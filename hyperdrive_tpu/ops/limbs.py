"""Shared limb-arithmetic core for TPU-native big-field arithmetic.

Both device fields — GF(2^255 - 19) (:mod:`.fe25519`) and the BLS12-381
base field GF(p_381) (:mod:`.fp381`) — use the same representation: a
field element is a vector of **13-bit limbs in int32**, value =
sum(l_i * 2^(13 i)). 13 bits is the sweet spot for hardware with no
64-bit integer multiply: a limb product fits in 26 bits, so a schoolbook
column accumulating ~20-30 products stays inside int32.

This module holds everything that is *not* specific to one modulus:

- limb packing/unpacking between Python ints and int32 arrays, for any
  limb count (:func:`to_limbs`, :func:`from_limbs`, vectorized
  :func:`to_limbs_flat`);
- the sequential scan carry (:func:`carry_scan`) and the vectorized
  carry pass (:func:`carry_pass`), both signed-safe (arithmetic shift =
  floor division);
- the carry-out fold helper for pseudo-Mersenne moduli
  (:func:`fold_carry_out`), parameterized by the fold factor
  (2^260 = 608 mod 2^255-19 for fe25519);
- the subtraction-bias search (:func:`make_sub_bias`), parameterized by
  (modulus, limb count, slack bound);
- a Montgomery-CIOS multiplier factory (:func:`make_montgomery`) for
  moduli with no usable pseudo-Mersenne structure — BLS12-381's p has
  no sparse form, so folding 2^390 back down never converges; fp381
  instead keeps values in the Montgomery domain and interleaves the
  reduction into the product (one 13-bit digit of the Montgomery
  quotient per outer step, one vectorized carry pass per step to stay
  inside int32).

Everything here is shape-static and transparent to jit/vmap/shard_map.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

__all__ = [
    "LIMB_BITS",
    "LIMB_MASK",
    "to_limbs",
    "from_limbs",
    "to_limbs_flat",
    "carry_scan",
    "carry_pass",
    "carry_pass_keep_top",
    "fold_carry_out",
    "make_sub_bias",
    "make_montgomery",
]

#: Limb radix shared by every device field: 13 bits in an int32 lane.
LIMB_BITS = 13
LIMB_MASK = (1 << LIMB_BITS) - 1


# ----------------------------------------------------------------- packing


def to_limbs_flat(vals, n_limbs: int) -> np.ndarray:
    """[n] Python ints -> [n, n_limbs] int32 limbs, vectorized through a
    byte buffer + unpackbits (the per-int Python limb loop costs
    ~10us/value — 100ms for one Shamir launch's 11k shares — vs ~2ms
    here). Values must lie in [0, 2^(13 * n_limbs))."""
    n = len(vals)
    total_bits = n_limbs * LIMB_BITS
    nbytes = (total_bits + 7) // 8
    try:
        buf = b"".join(v.to_bytes(nbytes, "little") for v in vals)
    except OverflowError:
        raise ValueError("value out of limb range") from None
    u = np.frombuffer(buf, dtype=np.uint8).reshape(n, nbytes)
    spare = 8 * nbytes - total_bits
    if spare and (u[:, -1] >> (8 - spare)).any():
        raise ValueError("value out of limb range")
    bits = np.unpackbits(u, axis=1, bitorder="little")[:, :total_bits]
    weights = (1 << np.arange(LIMB_BITS, dtype=np.int32)).astype(np.int32)
    return (
        bits.reshape(n, n_limbs, LIMB_BITS).astype(np.int32) * weights
    ).sum(axis=2, dtype=np.int32)


def to_limbs(x, n_limbs: int) -> np.ndarray:
    """Python int(s) -> int32 limb array. Accepts a single int (-> shape
    [n_limbs]) or any nested sequence of ints (-> shape [..., n_limbs]).
    Values must lie in [0, 2^(13 * n_limbs))."""
    if isinstance(x, (int,)):
        if not 0 <= x < 1 << (LIMB_BITS * n_limbs):
            raise ValueError("value out of limb range")
        return np.array(
            [(x >> (LIMB_BITS * i)) & LIMB_MASK for i in range(n_limbs)],
            dtype=np.int32,
        )
    x = list(x)
    if x and isinstance(x[0], int):
        if any(v < 0 for v in x):
            raise ValueError("value out of limb range")
        return to_limbs_flat(x, n_limbs)
    return np.stack([to_limbs(v, n_limbs) for v in x])


def from_limbs(limbs) -> "int | list":
    """Inverse of :func:`to_limbs` (host-side; accepts device arrays).
    Signed-safe: negative limbs contribute negatively, so redundant
    signed representations round-trip to their exact integer value."""
    a = np.asarray(limbs)
    if a.ndim == 1:
        return sum(int(a[i]) << (LIMB_BITS * i) for i in range(a.shape[0]))
    return [from_limbs(row) for row in a]


# ----------------------------------------------------------------- carries


def carry_scan(x: jnp.ndarray):
    """One full sequential carry pass: limbs -> [0, 2^13), returning
    ``(limbs, carry_out_of_top)``. Works for signed inputs (arithmetic
    shift = floor division), so it also serves as the borrow-propagating
    comparison primitive (carry < 0 iff the value is negative).

    Implemented as a lax.scan along the limb axis so the traced graph is
    one step deep — an unrolled 39-step chain inside a scalar-mult loop
    made XLA compile times explode."""
    xs = jnp.moveaxis(x, -1, 0)  # [K, ...batch]

    def step(carry, col):
        c = col + carry
        return c >> LIMB_BITS, c & LIMB_MASK

    carry, cols = lax.scan(step, jnp.zeros_like(xs[0]), xs)
    return jnp.moveaxis(cols, 0, -1), carry


def carry_pass(x: jnp.ndarray):
    """One vectorized carry pass: one shift/mask over the whole limb
    axis, every limb's carry moved up one position in a single slice
    shift. Returns ``(limbs, carry_out_of_top)``. Signed-safe (masked
    residues are non-negative; carries are floor quotients)."""
    c = x >> LIMB_BITS
    r = x & LIMB_MASK
    shifted = jnp.concatenate([jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1)
    return r + shifted, c[..., -1]


def carry_pass_keep_top(x: jnp.ndarray) -> jnp.ndarray:
    """Vectorized carry pass for fields with *no* carry-out fold
    (Montgomery representation): limbs below the top are masked to
    [0, 2^13) with carries shifted up one position; the top limb stays
    unmasked and absorbs the final carry. Callers guarantee the value
    bound keeps the top limb far inside int32 (for fp381, |value| <
    2^388 means |top| < 2^11 + carry)."""
    c = x[..., :-1] >> LIMB_BITS
    r = x[..., :-1] & LIMB_MASK
    return jnp.concatenate(
        [r[..., :1], r[..., 1:] + c[..., :-1], x[..., -1:] + c[..., -1:]],
        axis=-1,
    )


def fold_carry_out(x: jnp.ndarray, carry: jnp.ndarray, factor: int) -> jnp.ndarray:
    """Fold a (small) carry that left the top limb back into limb 0 with
    the given pseudo-Mersenne factor, then ripple the micro-carry. Only
    meaningful for moduli where 2^(13 * n_limbs) reduces to a small
    constant (608 for 2^255 - 19); fp381 has no such factor and uses
    :func:`make_montgomery` instead."""
    x = x.at[..., 0].add(carry * factor)
    # One micro ripple is enough: carry*factor < 2^23 adds at most 2^10
    # carry units into limb 1, which has headroom.
    c = x[..., 0]
    x = x.at[..., 0].set(c & LIMB_MASK)
    x = x.at[..., 1].add(c >> LIMB_BITS)
    return x


# ---------------------------------------------------------------- sub bias


def make_sub_bias(p_int: int, n_limbs: int, slack_max: int) -> np.ndarray:
    """A multiple of ``p_int`` whose (redundant) limb decomposition
    dominates any invariant-satisfying operand limb-wise, so
    ``a + bias - b`` has every limb non-negative *before* carrying.
    Non-negative pre-carry limbs are what lets subtraction normalize
    with a single vectorized carry pass instead of a sequential
    borrow-propagating scan.

    Construction: take the natural base-2^13 digits d_i of c*p and lend
    2^13 from each limb i+1 to limb i (m_0 = d_0 + 2^13, m_i = d_i +
    2^13 - 1 for interior limbs, m_top = d_top - 1, where d_top is the
    untruncated top digit). Searching c finds digits big enough that
    every m_i >= slack_max (the operand limb maximum)."""
    for c in range(40, 4096):
        v = c * p_int
        d = [(v >> (LIMB_BITS * i)) & LIMB_MASK for i in range(n_limbs - 1)]
        d.append(v >> (LIMB_BITS * (n_limbs - 1)))
        m = [d[0] + (1 << LIMB_BITS)]
        m += [d[i] + (1 << LIMB_BITS) - 1 for i in range(1, n_limbs - 1)]
        m.append(d[n_limbs - 1] - 1)
        if all(slack_max <= mi < (1 << 16) for mi in m):
            assert sum(mi << (LIMB_BITS * i) for i, mi in enumerate(m)) == v
            return np.array(m, dtype=np.int32)
    raise AssertionError("no subtraction bias found")


# -------------------------------------------------------------- Montgomery


class Montgomery:
    """Montgomery-CIOS multiplication over 13-bit int32 limbs for a
    modulus with no pseudo-Mersenne structure.

    R = 2^(13 n). Values live in the Montgomery domain (x̄ = x*R mod p);
    :meth:`mul` computes ā*b̄/R = (a*b)*R — the domain is closed under
    products. Conversion in/out happens host-side via :meth:`encode` /
    :meth:`decode` (the device never needs R^2: packing is a host int
    multiply).

    The CIOS loop interleaves reduction into the product: per outer step
    i it accumulates a_i * b and m_i * p into a running (n+1)-limb
    accumulator t, where m_i = (t_0 * n0') mod 2^13 zeroes t's low limb
    (n0' = -p^{-1} mod 2^13), then divides by 2^13 via a one-limb shift.
    One vectorized carry pass per step keeps every column inside int32:

    - operand limbs (signed) have magnitude <= ~2^13.01 after a pass, so
      the per-column step adds |a_i*b_j| + m*p_j <= 2*8193^2 ~= 1.35e8;
    - the accumulator limb steady state is |t_j| <= 8192 + 1.35e8/2^13
      ~= 2.5e4, keeping columns < 1.4e8 << 2^31.

    Signed operands are handled for free (arithmetic shifts are floor
    divisions; m is computed from the masked low limb, which is a
    correct residue for negative t_0 too), which is what lets the field
    layer above skip subtraction biases entirely: sub is a plain limb
    subtraction + carry pass, and every value carries a signed magnitude
    bound |v| < 2^(13 n - 2) that :meth:`mul` contracts back below
    |ab|/R + p per product.
    """

    def __init__(self, p_int: int, n_limbs: int):
        base = 1 << LIMB_BITS
        if p_int % 2 == 0:
            raise ValueError("Montgomery requires an odd modulus")
        if p_int >= 1 << (LIMB_BITS * n_limbs):
            raise ValueError("modulus exceeds limb capacity")
        self.p_int = p_int
        self.n_limbs = n_limbs
        self.r_int = 1 << (LIMB_BITS * n_limbs)
        self.r_mod_p = self.r_int % p_int
        self.r_inv = pow(self.r_int, -1, p_int)
        self.n0p = (-pow(p_int, -1, base)) % base
        self.p_limbs = to_limbs(p_int, n_limbs)

    # -- host-side domain conversion

    def encode(self, x: int) -> int:
        """Standard -> Montgomery domain (host int)."""
        return (x % self.p_int) * self.r_mod_p % self.p_int

    def decode(self, x: int) -> int:
        """Montgomery -> standard domain (host int). Accepts the signed
        redundant values :func:`from_limbs` produces."""
        return x * self.r_inv % self.p_int

    # -- device kernel

    def mul(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        """CIOS product ā*b̄/R on [..., n_limbs] int32 arrays. Operand
        contract: |value| < 2^(13 n - 2) with limb magnitudes <= ~2^13.2
        (what :func:`carry_pass` outputs). Output value is bounded by
        |a*b|/R + p with limbs <= ~2^13.01 after the two closing passes."""
        n = self.n_limbs
        p = jnp.asarray(self.p_limbs, dtype=jnp.int32)
        batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
        t0 = jnp.zeros((*batch, n + 1), dtype=jnp.int32)

        # The outer CIOS loop runs as a fori_loop rather than a Python
        # unroll: one traced step instead of n keeps the XLA graph ~n
        # times smaller, which is what makes the point-arithmetic
        # kernels stacked on top (12+ muls per G1 add, dozens of adds
        # per launch) compile in seconds instead of tens of minutes.
        def step(i, t):
            a_i = lax.dynamic_slice_in_dim(a, i, 1, axis=-1)
            t = t.at[..., :n].add(a_i * b)
            m = ((t[..., 0] & LIMB_MASK) * self.n0p) & LIMB_MASK
            t = t.at[..., :n].add(m[..., None] * p)
            # t_0 is now a multiple of 2^13: shift one limb down, exact.
            carry0 = t[..., 0] >> LIMB_BITS
            t = jnp.concatenate(
                [t[..., 1:], jnp.zeros_like(t[..., :1])], axis=-1
            )
            t = t.at[..., 0].add(carry0)
            # One vectorized pass bounds the next step's columns. The
            # top slot (virtual limb n) accumulates the pass carry; it
            # is consumed by the next shift-down.
            c = t[..., :n] >> LIMB_BITS
            r = t[..., :n] & LIMB_MASK
            return jnp.concatenate(
                [r[..., :1], r[..., 1:] + c[..., :-1], t[..., n:] + c[..., -1:]],
                axis=-1,
            )

        t = lax.fori_loop(0, n, step, t0)
        out = t[..., :n]
        # |result| < |ab|/R + p < 2^(13 n - 5): the top slot is exactly
        # zero once limbs settle, and the value bound keeps the top limb
        # tiny, so the closing passes leave it unmasked (no fold exists
        # to absorb a carry-out).
        out = carry_pass_keep_top(out)
        out = carry_pass_keep_top(out)
        return out


def make_montgomery(p_int: int, n_limbs: int) -> Montgomery:
    """Build the Montgomery context for (modulus, limb count)."""
    return Montgomery(p_int, n_limbs)
