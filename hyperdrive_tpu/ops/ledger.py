"""Batched transaction apply as one padded segment-sum/scatter-add kernel.

The execution layer's hot loop (exec/ledger.py host reference) walks a
block twice per sender: once to total outflows, once to apply the
surviving transactions — O(T) Python dispatch per block. Here the whole
block is four dense int32 vectors (kind, sender, recipient, amount) plus
the signature mask, and the apply is one fused device program:

  1. segment-sum the per-sender outflows (balance outflow for TRANSFER/
     STAKE, stake outflow for UNSTAKE) with ``.at[].add`` scatters,
  2. gather each tx's sender solvency back (block-atomic per sender:
     a sender whose *total* asks exceed its funds has ALL its txs in
     the block rejected — order-independence is what makes the
     vectorized form bit-identical to any serial schedule),
  3. scatter-add the applied deltas into balances/stakes.

Everything is int32. Callers bound amounts (``exec.ExecutionConfig
.amount_cap``) and seed balances so that worst-case per-block flow —
``txs_per_block * amount_cap`` — stays far below 2^31; the exec layer
asserts this bound host-side, the kernel does not re-check.

Shapes are padded to the ``TX_BUCKETS`` ladder (ops/bucketing.py) so XLA
compiles one executable per bucket; pad rows carry ``sig_ok=False`` and
``amount=0`` and are algebraically inert.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from hyperdrive_tpu.ops.bucketing import bucket_for

__all__ = [
    "TX_BUCKETS",
    "KIND_TRANSFER",
    "KIND_STAKE",
    "KIND_UNSTAKE",
    "apply_block_jax",
    "apply_block",
    "pad_block",
]

#: Padded-launch ladder for the tx axis. Same doctrine as the Ed25519
#: packer: one executable per bucket, beyond the top round to its
#: multiple (bench runs 1k/16k/64k blocks, so the ladder tops at 64k).
TX_BUCKETS = (256, 1024, 4096, 16384, 65536)

#: Transaction kinds. TRANSFER moves balance sender->recipient; STAKE
#: converts sender balance into sender stake; UNSTAKE converts sender
#: stake back into sender balance. Recipient is ignored for kinds 1/2.
KIND_TRANSFER = 0
KIND_STAKE = 1
KIND_UNSTAKE = 2


def apply_block_jax(balances, stakes, kind, sender, recipient, amount, sig_ok):
    """One block of transactions against the ledger, order-independent.

    Args (all device arrays):
      balances, stakes: [A] int32 — pre-block state.
      kind, sender, recipient, amount: [T] int32 — the block, padded.
      sig_ok: [T] bool — signature verified AND row is a real tx.

    Returns ``(new_balances, new_stakes, applied)`` where ``applied`` is
    the [T] bool mask of transactions that actually executed (signature
    good AND the sender could cover its block-total outflows).
    """
    ok_i = sig_ok.astype(jnp.int32)
    amt = amount * ok_i
    is_transfer = (kind == KIND_TRANSFER).astype(jnp.int32)
    is_stake = (kind == KIND_STAKE).astype(jnp.int32)
    is_unstake = (kind == KIND_UNSTAKE).astype(jnp.int32)

    # 1. Per-sender asks, summed over the whole block (segment-sum as a
    #    scatter-add over the account axis).
    zero = jnp.zeros_like(balances)
    out_bal = zero.at[sender].add(amt * (is_transfer + is_stake))
    out_stk = zero.at[sender].add(amt * is_unstake)

    # 2. Block-atomic solvency: every tx of an overdrawn sender dies.
    sender_ok = (balances >= out_bal) & (stakes >= out_stk)
    applied = sig_ok & sender_ok[sender]
    aamt = amount * applied.astype(jnp.int32)

    # 3. Applied deltas, one signed scatter per (state, index) pair:
    #    the sender's balance move is -a for TRANSFER/STAKE and +a for
    #    UNSTAKE, its stake move is +a for STAKE and -a for UNSTAKE,
    #    and only TRANSFER credits the recipient — three scatters
    #    total instead of one per kind-axis combination (the scatter
    #    is the serial part of the CPU lowering, so fusing the deltas
    #    is most of the large-block win).
    new_bal = (
        balances
        .at[sender].add(aamt * (is_unstake - is_transfer - is_stake))
        .at[recipient].add(aamt * is_transfer)
    )
    new_stk = stakes.at[sender].add(aamt * (is_stake - is_unstake))
    return new_bal, new_stk, applied


@functools.cache
def _jitted():
    # No donation: the CPU backend can't honor it and warns per compile.
    return jax.jit(apply_block_jax)


def pad_block(kind, sender, recipient, amount, sig_ok, bucket: int | None = None):
    """Pad host tx arrays up the ``TX_BUCKETS`` ladder.

    Pad rows are ``sig_ok=False, amount=0, sender=recipient=0`` — inert
    through the kernel. Returns the five padded np arrays.
    """
    n = len(kind)
    b = bucket if bucket is not None else bucket_for(max(n, 1), TX_BUCKETS)
    pad = b - n

    def _p(a, dtype):
        a = np.asarray(a, dtype=dtype)
        return np.pad(a, (0, pad)) if pad else a

    return (
        _p(kind, np.int32),
        _p(sender, np.int32),
        _p(recipient, np.int32),
        _p(amount, np.int32),
        _p(sig_ok, bool),
    )


def apply_block(balances, stakes, kind, sender, recipient, amount, sig_ok):
    """Host-convenience wrapper: pad to the ladder, run the jitted
    kernel, slice the applied mask back to the true length. State
    arrays round-trip as np.int32; inputs may be lists or arrays."""
    n = len(kind)
    k, s, r, a, ok = pad_block(kind, sender, recipient, amount, sig_ok)
    nb, ns, applied = _jitted()(
        jnp.asarray(np.asarray(balances, dtype=np.int32)),
        jnp.asarray(np.asarray(stakes, dtype=np.int32)),
        jnp.asarray(k), jnp.asarray(s), jnp.asarray(r), jnp.asarray(a),
        jnp.asarray(ok),
    )
    return (
        np.asarray(nb, dtype=np.int32),
        np.asarray(ns, dtype=np.int32),
        np.asarray(applied)[:n],
    )
