"""Batched transaction apply as one padded segment-sum/scatter-add kernel.

The execution layer's hot loop (exec/ledger.py host reference) walks a
block twice per sender: once to total outflows, once to apply the
surviving transactions — O(T) Python dispatch per block. Here the whole
block is four dense int32 vectors (kind, sender, recipient, amount) plus
the signature mask, and the apply is one fused device program:

  1. segment-sum the per-sender outflows (balance outflow for TRANSFER/
     STAKE, stake outflow for UNSTAKE) with ``.at[].add`` scatters,
  2. gather each tx's sender solvency back (block-atomic per sender:
     a sender whose *total* asks exceed its funds has ALL its txs in
     the block rejected — order-independence is what makes the
     vectorized form bit-identical to any serial schedule),
  3. scatter-add the applied deltas into balances/stakes.

Everything is int32. Callers bound amounts (``exec.ExecutionConfig
.amount_cap``) and seed balances so that worst-case per-block flow —
``txs_per_block * amount_cap`` — stays far below 2^31; the exec layer
asserts this bound host-side, the kernel does not re-check.

Shapes are padded to the ``TX_BUCKETS`` ladder (ops/bucketing.py) so XLA
compiles one executable per bucket; pad rows carry ``sig_ok=False`` and
``amount=0`` and are algebraically inert.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from hyperdrive_tpu.ops import rootmix
from hyperdrive_tpu.ops.bucketing import bucket_for
from hyperdrive_tpu.ops.rootmix import (
    ROOT_WORDS,
    fold_root_np,
    mix_matrix,
    state_digest_np,
)

__all__ = [
    "TX_BUCKETS",
    "KIND_TRANSFER",
    "KIND_STAKE",
    "KIND_UNSTAKE",
    "ROOT_WORDS",
    "apply_block_jax",
    "apply_block",
    "pad_block",
    "mix_matrix",
    "state_digest_np",
    "fold_root_np",
    "apply_block_chain_jax",
    "apply_block_chain_cols_jax",
    "apply_block_chain_merkle_cols_jax",
    "pack_block_cols",
]

#: Padded-launch ladder for the tx axis. Same doctrine as the Ed25519
#: packer: one executable per bucket, beyond the top round to its
#: multiple. Every power of four plus the 32k rung: a 32k-tx block is
#: the e2e bench's mid size, and without its own rung it would run the
#: 64k-shaped kernel — double the scatter work for padding that is
#: algebraically inert but not free.
TX_BUCKETS = (256, 1024, 4096, 16384, 32768, 65536)

#: Transaction kinds. TRANSFER moves balance sender->recipient; STAKE
#: converts sender balance into sender stake; UNSTAKE converts sender
#: stake back into sender balance. Recipient is ignored for kinds 1/2.
KIND_TRANSFER = 0
KIND_STAKE = 1
KIND_UNSTAKE = 2


def apply_block_jax(balances, stakes, kind, sender, recipient, amount, sig_ok):
    """One block of transactions against the ledger, order-independent.

    Args (all device arrays):
      balances, stakes: [A] int32 — pre-block state.
      kind, sender, recipient, amount: [T] int32 — the block, padded.
      sig_ok: [T] bool — signature verified AND row is a real tx.

    Returns ``(new_balances, new_stakes, applied)`` where ``applied`` is
    the [T] bool mask of transactions that actually executed (signature
    good AND the sender could cover its block-total outflows).
    """
    a = balances.shape[0]
    ok_i = sig_ok.astype(jnp.int32)
    amt = amount * ok_i
    is_transfer = (kind == KIND_TRANSFER).astype(jnp.int32)
    is_stake = (kind == KIND_STAKE).astype(jnp.int32)
    is_unstake = (kind == KIND_UNSTAKE).astype(jnp.int32)

    # The scatters ARE the serial part of the CPU lowering, so both
    # passes run as ONE scatter each over a concatenated [2A] account
    # axis (balances in [:A], stakes in [A:]) instead of one scatter
    # per (state, index) pair — five scatters become two, measurably
    # faster at every bucket.

    # 1. Per-sender asks, summed over the whole block (segment-sum as a
    #    scatter-add): a tx asks from its sender's balance for
    #    TRANSFER/STAKE and from its sender's stake for UNSTAKE.
    asks = jnp.zeros(2 * a, dtype=balances.dtype).at[
        sender + a * is_unstake
    ].add(amt)

    # 2. Block-atomic solvency: every tx of an overdrawn sender dies.
    sender_ok = (balances >= asks[:a]) & (stakes >= asks[a:])
    applied = sig_ok & sender_ok[sender]
    aamt = amount * applied.astype(jnp.int32)

    # 3. Applied deltas: the sender's balance move is -a for TRANSFER/
    #    STAKE and +a for UNSTAKE, its stake move is +a for STAKE and
    #    -a for UNSTAKE, and only TRANSFER credits the recipient —
    #    three index lanes concatenated into the one [2A] scatter.
    state = jnp.concatenate([balances, stakes])
    new = state.at[
        jnp.concatenate([sender, recipient, a + sender])
    ].add(jnp.concatenate([
        aamt * (is_unstake - is_transfer - is_stake),
        aamt * is_transfer,
        aamt * (is_stake - is_unstake),
    ]))
    return new[:a], new[a:], applied


@functools.cache
def _jitted():
    # No donation: the CPU backend can't honor it and warns per compile.
    return jax.jit(apply_block_jax)


# --------------------------------------------------------------------------
# Device-resident state root (PR 16): the jnp twin of ops/rootmix.py,
# fused into the apply launch — state words, digest reduction, and the
# chain fold all wrap mod 2^32 exactly as the numpy host twin does, so
# the running root never leaves the device between heights and still
# chains byte-equal to the host reference.


def _state_words_jax(balances, stakes):
    def words(v):
        lo = v.astype(jnp.uint32)
        hi = jnp.right_shift(v, 31).astype(jnp.uint32)
        return jnp.stack([lo, hi], axis=1).reshape(-1)

    return jnp.concatenate([words(balances), words(stakes)])


def _fold_root_jax(root_words, height_u32, digest_words):
    k = jnp.arange(rootmix.ROOT_WORDS, dtype=jnp.uint32)
    x = (
        root_words * jnp.uint32(rootmix.FOLD_PREV)
        + digest_words
        + height_u32 * jnp.uint32(rootmix.FOLD_HEIGHT)
        + k
    )
    x = x ^ jnp.right_shift(x, 16)
    x = x * jnp.uint32(rootmix.FMIX_A)
    x = x ^ jnp.right_shift(x, 15)
    x = x * jnp.uint32(rootmix.FMIX_B)
    x = x ^ jnp.right_shift(x, 16)
    return x


def apply_block_chain_jax(
    balances, stakes, root_words, height_u32,
    kind, sender, recipient, amount, sig_ok, mix,
):
    """The fused pipeline step: apply one block AND fold the new state
    into the running root, all on device — the inter-height host hop of
    the sha256 chain becomes one extra reduction inside the same launch.

    Args beyond :func:`apply_block_jax`:
      root_words: [ROOT_WORDS] uint32 — the running chained root.
      height_u32: uint32 scalar — the height being applied.
      mix: [4*A, ROOT_WORDS] uint32 — :func:`mix_matrix` for this width.

    Returns ``(new_balances, new_stakes, applied_count, new_root)``
    where ``applied_count`` is a device int32 scalar (NOT fetched here:
    the executor accumulates it and materializes per window flush).
    """
    new_bal, new_stk, applied = apply_block_jax(
        balances, stakes, kind, sender, recipient, amount, sig_ok
    )
    w = _state_words_jax(new_bal, new_stk)
    digest = (w[:, None] * mix).sum(axis=0, dtype=jnp.uint32)
    new_root = _fold_root_jax(root_words, height_u32, digest)
    count = applied.astype(jnp.int32).sum()
    return new_bal, new_stk, count, new_root


@functools.cache
def _jitted_chain():
    return jax.jit(apply_block_chain_jax)


def apply_block_chain_cols_jax(balances, stakes, root_words, height_u32, cols, mix):
    """:func:`apply_block_chain_jax` taking the block as ONE packed
    [5, T] int32 matrix (kind, sender, recipient, amount, sig_ok rows —
    :func:`pack_block_cols`). Five separate host->device transfers per
    height cost ~1ms of fixed ``device_put`` dispatch on the CPU
    backend; one contiguous buffer costs one."""
    return apply_block_chain_jax(
        balances, stakes, root_words, height_u32,
        cols[0], cols[1], cols[2], cols[3], cols[4].astype(bool), mix,
    )


@functools.cache
def _jitted_chain_cols():
    return jax.jit(apply_block_chain_cols_jax)


def apply_block_chain_merkle_cols_jax(
    balances, stakes, root_words, tree, height_u32, cols, mix
):
    """The Merkleized pipeline step (PR 17): apply one packed block,
    incrementally update the hash tree from the block's own scatter
    targets, and fold digest + Merkle root into the running chained
    root — all one launch, so per-account provability costs no extra
    dispatch over the PR 16 chain.

    Args beyond :func:`apply_block_chain_cols_jax`:
      tree: tuple of uint32 [p >> d, NODE_WORDS] levels
            (ops/merkle.py ``build_tree_jax``).

    Returns ``(new_bal, new_stk, count, new_root, digest, new_tree)``
    — ``digest`` is the post-block state digest (the proof witness),
    ``new_tree`` the updated level tuple. The Merkle root is
    ``new_tree[-1][0]``.

    The dirty set is the sender and recipient columns verbatim — pad
    rows point at account 0 and rejected rows leave state unchanged,
    so their leaf recomputations are idempotent no-ops. When the block
    touches at least as many lanes as the tree has leaves, the full
    log-depth rebuild is cheaper than per-path scatters; the choice is
    made at trace time (both branches fixed-shape).
    """
    from hyperdrive_tpu.ops import merkle

    new_bal, new_stk, applied = apply_block_jax(
        balances, stakes, cols[0], cols[1], cols[2], cols[3],
        cols[4].astype(bool),
    )
    w = _state_words_jax(new_bal, new_stk)
    digest = (w[:, None] * mix).sum(axis=0, dtype=jnp.uint32)
    if 2 * cols.shape[1] >= tree[0].shape[0]:
        new_tree = merkle.build_tree_jax(new_bal, new_stk)
    else:
        dirty = jnp.concatenate([cols[1], cols[2]])
        new_tree = merkle.update_tree_jax(tree, new_bal, new_stk, dirty)
    folded = merkle.fold_merkle_jax(digest, new_tree[-1][0])
    new_root = _fold_root_jax(root_words, height_u32, folded)
    count = applied.astype(jnp.int32).sum()
    return new_bal, new_stk, count, new_root, digest, new_tree


@functools.cache
def _jitted_chain_merkle_cols():
    return jax.jit(apply_block_chain_merkle_cols_jax)


def pack_block_cols(kind, sender, recipient, amount, sig_ok=None,
                    bucket: int | None = None) -> np.ndarray:
    """Pack a block into the [5, bucket] int32 matrix
    :func:`apply_block_chain_cols_jax` consumes — rows (kind, sender,
    recipient, amount, sig_ok as 0/1), pad columns inert (sig_ok=0,
    amount=0). ``sig_ok=None`` admits every real row (the unsigned
    semantics)."""
    n = len(kind)
    b = bucket if bucket is not None else bucket_for(max(n, 1), TX_BUCKETS)
    out = np.zeros((5, b), dtype=np.int32)
    out[0, :n] = kind
    out[1, :n] = sender
    out[2, :n] = recipient
    out[3, :n] = amount
    if sig_ok is None:
        out[4, :n] = 1
    else:
        out[4, :n] = np.asarray(sig_ok, dtype=np.int32)
    return out


def pad_block(kind, sender, recipient, amount, sig_ok, bucket: int | None = None):
    """Pad host tx arrays up the ``TX_BUCKETS`` ladder.

    Pad rows are ``sig_ok=False, amount=0, sender=recipient=0`` — inert
    through the kernel. Returns the five padded np arrays.
    """
    n = len(kind)
    b = bucket if bucket is not None else bucket_for(max(n, 1), TX_BUCKETS)
    pad = b - n

    def _p(a, dtype):
        a = np.asarray(a, dtype=dtype)
        return np.pad(a, (0, pad)) if pad else a

    return (
        _p(kind, np.int32),
        _p(sender, np.int32),
        _p(recipient, np.int32),
        _p(amount, np.int32),
        _p(sig_ok, bool),
    )


def apply_block(balances, stakes, kind, sender, recipient, amount, sig_ok):
    """Host-convenience wrapper: pad to the ladder, run the jitted
    kernel, slice the applied mask back to the true length. State
    arrays round-trip as np.int32; inputs may be lists or arrays."""
    n = len(kind)
    k, s, r, a, ok = pad_block(kind, sender, recipient, amount, sig_ok)
    nb, ns, applied = _jitted()(
        jnp.asarray(np.asarray(balances, dtype=np.int32)),
        jnp.asarray(np.asarray(stakes, dtype=np.int32)),
        jnp.asarray(k), jnp.asarray(s), jnp.asarray(r), jnp.asarray(a),
        jnp.asarray(ok),
    )
    return (
        np.asarray(nb, dtype=np.int32),
        np.asarray(ns, dtype=np.int32),
        np.asarray(applied)[:n],
    )
