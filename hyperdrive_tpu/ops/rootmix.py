"""The chained state-root reduction, host side (jax-free).

The execution layer's root chain (PR 16) replaces the per-block host
``sha256(state)`` hop with a fixed-shape uint32 reduction over the
packed ledger leaves, chained height to height with a 32-bit mix
finalizer:

  digest_k(state) = sum_i words(state)_i * M[i, k]        (mod 2^32)
  root_h          = fmix(root_{h-1} * C1 + digest + h * C2 + k)

``words`` splits each account's 8-byte little-endian signed packing
into (lo, hi) uint32 pairs — hi is the int32 sign extension — so the
reduction covers exactly the bytes ``exec.ledger.pack_state`` would
have hashed. ``M`` is a deterministic per-shape odd-constant matrix
and ``fmix`` the lowbias32 finalizer pair.

This module is the NUMPY twin: the host reference executor, checkpoint
verification, and the chaos soak (which must stay jax-free) chain
through these functions. ``ops/ledger.py`` implements the identical
arithmetic in jnp fused into the device apply launch; both wrap mod
2^32 bit-identically, which is the whole parity contract.

The reduction is linear-algebraic, NOT a cryptographic hash: the
genesis root stays sha256 and the running chain is re-derived from
fetched state at checkpoints (``HostLedgerExecutor.host_verify``) and
in the parity CLIs — ROBUSTNESS.md "State-root doctrine" states the
rule.
"""

from __future__ import annotations

import functools

import numpy as np

__all__ = [
    "ROOT_WORDS",
    "mix_matrix",
    "state_digest_np",
    "fold_root_np",
    "root_bytes",
    "root_words",
]

#: The running root is 8 little-endian uint32 words = 32 bytes, so root
#: width (and every ``len(value) == 64`` commit-record assertion) is
#: unchanged from the sha256 chain it replaces.
ROOT_WORDS = 8

_M32 = 0xFFFFFFFF

#: Chain-fold multipliers (golden-ratio / murmur-family odd constants)
#: and the lowbias32 finalizer pair. Shared by the numpy and jnp twins.
FOLD_PREV = 0x9E3779B1
FOLD_HEIGHT = 0x85EBCA77
FMIX_A = 0x7FEB352D
FMIX_B = 0x846CA68B


@functools.lru_cache(maxsize=8)
def mix_matrix(n_words: int) -> np.ndarray:
    """The per-shape multiplier matrix M[n_words, ROOT_WORDS]: odd
    deterministic uint32 constants from a splitmix-style sequence, so
    every state word feeds every root word. Pure function of the word
    count — both executors derive the identical matrix for one account
    width."""
    out = np.empty(n_words * ROOT_WORDS, dtype=np.uint32)
    for i in range(n_words * ROOT_WORDS):
        z = (i * 0x9E3779B9 + 0x243F6A88) & _M32
        z ^= z >> 16
        z = (z * 0x21F0AAAD) & _M32
        z ^= z >> 15
        z = (z * 0x735A2D97) & _M32
        z ^= z >> 15
        out[i] = z | 1
    return out.reshape(n_words, ROOT_WORDS)


def _state_words(balances, stakes) -> np.ndarray:
    """int32 state -> interleaved (lo, hi) uint32 words, mirroring the
    8-byte-LE signed packing word-for-word (hi = sign extension)."""

    def words(v):
        v = np.asarray(v, dtype=np.int32)
        lo = v.astype(np.uint32)
        hi = (v >> 31).astype(np.uint32)
        return np.stack([lo, hi], axis=1).reshape(-1)

    return np.concatenate([words(balances), words(stakes)])


def state_digest_np(balances, stakes) -> np.ndarray:
    """Host twin of the device digest: uint32[ROOT_WORDS]."""
    w = _state_words(balances, stakes)
    m = mix_matrix(w.shape[0])
    return (w[:, None] * m).sum(axis=0, dtype=np.uint32)


def fold_root_np(prev_words, height: int, digest_words) -> np.ndarray:
    """Chain ``digest_words`` into ``prev_words`` at ``height`` (host
    twin of the device fold — identical mod-2^32 arithmetic)."""
    r = np.asarray(prev_words, dtype=np.uint32)
    d = np.asarray(digest_words, dtype=np.uint32)
    k = np.arange(ROOT_WORDS, dtype=np.uint32)
    # Scalar term in Python ints: numpy warns on scalar uint overflow
    # (array ops wrap silently, which is what the rest relies on).
    hterm = np.uint32((height * FOLD_HEIGHT) & _M32)
    x = (
        r * np.uint32(FOLD_PREV)
        + d
        + hterm
        + k
    ).astype(np.uint32)
    x ^= x >> np.uint32(16)
    x = (x * np.uint32(FMIX_A)).astype(np.uint32)
    x ^= x >> np.uint32(15)
    x = (x * np.uint32(FMIX_B)).astype(np.uint32)
    x ^= x >> np.uint32(16)
    return x


def root_bytes(words) -> bytes:
    """uint32[ROOT_WORDS] -> the canonical 32-byte little-endian root."""
    return np.asarray(words, dtype=np.uint32).astype("<u4").tobytes()


def root_words(root: bytes) -> np.ndarray:
    """32-byte root -> uint32[ROOT_WORDS] (the chain-fold input form)."""
    return np.frombuffer(root, dtype="<u4").copy()
