"""Batched Ed25519 verification as one Pallas TPU kernel.

Same equation, semantics, and host packing as the XLA kernel
(:func:`hyperdrive_tpu.ops.ed25519_jax.verify_kernel` — reference cites and
the signed-window design live there); what changes is WHERE the
intermediates live and HOW the lanes are used:

- **Limb-major layout** ``[20, BLK]``: the batch rides the 128-wide lane
  axis and the 20 limbs ride sublanes (padded to 24). The XLA kernel's
  ``[B, 20]`` tensors put limbs on lanes — 20 of 128 used — and XLA's
  layout assignment keeps enough of the computation in that shape that the
  vector units run mostly empty. Measured on v5e (bench.py, 64k-signature
  launches, pipelined): 535.1k sigs/s vs the XLA kernel's 70.9k — 7.5x —
  for exactly that reason.
- **VMEM residency**: the whole 64-window ladder — accumulator, the
  9-entry per-signature table, every field-op intermediate — stays in
  VMEM/registers for a block of 256 signatures; the only HBM traffic is
  the packed inputs in and one acceptance row out.

Mosaic constraints shaped the code (kept as-is rather than papered over):

- ``jnp .at[].add/.set`` lower to ``scatter``, which Mosaic cannot lower —
  every row update is expressed as concatenation splicing (:func:`_upd`).
- Array literals cannot be captured by the kernel — all constants (the
  subtraction bias, 2d, p digits, the fixed-base table) enter as inputs
  with broadcast BlockSpecs.
- A straight-line 8-addition table build (36 loop-invariant live arrays)
  SIGABRTs the Mosaic compiler; building the table with a ``fori_loop``
  that writes each entry into VMEM scratch compiles fine and is how the
  per-signature table is carried across the window loop.

The field ops mirror :mod:`hyperdrive_tpu.ops.fe25519` limb-for-limb with
the limb axis leading; the bound walks there apply verbatim (the pass /
fold structure is identical, only the axis moved). Differential tests
enforce bit-exact agreement with the host oracle and the XLA kernel.
"""

from __future__ import annotations

import functools
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hyperdrive_tpu.crypto import ed25519 as host_ed
from hyperdrive_tpu.ops import fe25519 as fe
from hyperdrive_tpu.ops.ed25519_jax import _b_niels_np, _recode_signed

__all__ = [
    "verify_pallas",
    "make_pallas_verify_fn",
    "wire_verify_pallas",
    "make_pallas_wire_verify_fn",
    "semiwire_verify_pallas",
    "make_pallas_semiwire_verify_fn",
    "pallas_backend_ok",
    "resolve_backend",
]

N = fe.N_LIMBS
_LB = fe.LIMB_BITS
_MASK = fe.LIMB_MASK
_F260 = fe.FOLD_260
_F255 = fe.FOLD_255
_TSH = fe.TOP_SHIFT
_TMASK = fe.TOP_MASK

_BLOCK = 256  # lanes per grid step; best in the measured v5e sweep
# (single-shot 16k batches: 303k sigs/s at block 128/512/1024, 324k at 256)

_SUB_BIAS_COL = fe._SUB_BIAS.reshape(N, 1)
_K2D_COL = fe.to_limbs((2 * host_ed.D) % host_ed.P).reshape(N, 1)
_P_COL = fe.to_limbs(fe.P_INT).reshape(N, 1)
_2P_COL = fe.to_limbs(2 * fe.P_INT).reshape(N, 1)

class _TraceConsts(threading.local):
    """Kernel-trace-scoped constants (loaded from refs at kernel entry;
    see the module doc for why they cannot be captured as literals).
    Thread-local so concurrent traces cannot read each other's tracers,
    and cleared when the kernel body finishes so no tracer outlives its
    trace."""

    def __init__(self):
        self.vals = {}

    def __getitem__(self, k):
        return self.vals[k]

    def __setitem__(self, k, v):
        self.vals[k] = v

    def clear(self):
        self.vals.clear()


_C = _TraceConsts()


# --------------------- limb-major field ops: [20, B], limb axis leading ---


def _upd(x, a, b, v):
    """Replace rows [a:b) of x (static indices) via concatenation."""
    parts = []
    if a > 0:
        parts.append(x[:a])
    parts.append(v)
    if b < x.shape[0]:
        parts.append(x[b:])
    return jnp.concatenate(parts, axis=0)


def _pass_L(x):
    c = x >> _LB
    r = x & _MASK
    shifted = jnp.concatenate([jnp.zeros_like(c[:1]), c[:-1]], axis=0)
    return r + shifted, c[-1:]


def _pass_fold_L(x):
    x, c = _pass_L(x)
    return _upd(x, 0, 1, x[0:1] + c * _F260)


def _fold_top_L(x):
    hi = x[N - 1 : N] >> _TSH
    x = _upd(x, N - 1, N, x[N - 1 : N] & _TMASK)
    c0 = x[0:1] + hi * _F255
    x = _upd(x, 1, 2, x[1:2] + (c0 >> _LB))
    return _upd(x, 0, 1, c0 & _MASK)


def _carry_tail_L(x, c):
    c0 = x[0:1] + c * _F260
    x = _upd(x, 1, 2, x[1:2] + (c0 >> _LB))
    x = _upd(x, 0, 1, c0 & _MASK)
    return _fold_top_L(x)


def add_L(a, b):
    x, c = _pass_L(a + b)
    return _carry_tail_L(x, c)


def sub_L(a, b):
    x, c = _pass_L(a + (_C["bias"] - b))
    return _carry_tail_L(x, c)


def neg_L(a):
    x, c = _pass_L(_C["bias"] - a)
    return _carry_tail_L(x, c)


def _reduce_cols_L(cols):
    cols, c1 = _pass_L(cols)
    low = cols[:N]
    high = cols[N:]
    low = _upd(low, 0, N - 1, low[: N - 1] + high * _F260)
    low = _upd(low, 19, 20, low[19:20] + c1 * _F260)
    low = _pass_fold_L(low)
    low = _pass_fold_L(low)
    return _fold_top_L(low)


def mul_L(a, b):
    batch = jnp.broadcast_shapes(a.shape[1:], b.shape[1:])
    zrow = jnp.zeros((1, *batch), dtype=jnp.int32)
    cols = None
    for i in range(N):
        prod = jnp.broadcast_to(a[i : i + 1] * b, (N, *batch))
        padded = jnp.concatenate(
            [zrow] * i + [prod] + [zrow] * (N - 1 - i), axis=0
        )
        cols = padded if cols is None else cols + padded
    return _reduce_cols_L(cols)


def sqr_L(a):
    a2 = a + a
    batch = a.shape[1:]
    zrow = jnp.zeros((1, *batch), dtype=jnp.int32)
    cols = None
    for i in range(N):
        head = (
            jnp.concatenate([a[i : i + 1], a2[i + 1 :]], axis=0)
            if i + 1 < N
            else a[i : i + 1]
        )
        row = a[i : i + 1] * head
        padded = jnp.concatenate(
            [zrow] * (2 * i) + [row] + [zrow] * (N - 1 - i), axis=0
        )
        cols = padded if cols is None else cols + padded
    return _reduce_cols_L(cols)


def mul_small_L(a, k):
    x = _pass_fold_L(a * jnp.int32(k))
    x = _pass_fold_L(x)
    x = _pass_fold_L(x)
    return _fold_top_L(x)


def _sel_rows(mask1b, a, b):
    return jnp.where(mask1b, a, b)


# ------------------------------------------------ point ops (limb-major) --


def madd_L(p, n, need_t):
    x1, y1, z1, t1 = p
    yp2, ym2, t2d2 = n
    a = mul_L(sub_L(y1, x1), ym2)
    b = mul_L(add_L(y1, x1), yp2)
    c = mul_L(t1, t2d2)
    d = mul_small_L(z1, 2)
    e = sub_L(b, a)
    f = sub_L(d, c)
    g = add_L(d, c)
    h = add_L(b, a)
    out = (mul_L(e, f), mul_L(g, h), mul_L(f, g))
    return (*out, mul_L(e, h)) if need_t else out


def padd_L(p, n, need_t):
    x1, y1, z1, t1 = p
    yp2, ym2, t2d2, z2 = n
    a = mul_L(sub_L(y1, x1), ym2)
    b = mul_L(add_L(y1, x1), yp2)
    c = mul_L(t1, t2d2)
    d = mul_small_L(mul_L(z1, z2), 2)
    e = sub_L(b, a)
    f = sub_L(d, c)
    g = add_L(d, c)
    h = add_L(b, a)
    out = (mul_L(e, f), mul_L(g, h), mul_L(f, g))
    return (*out, mul_L(e, h)) if need_t else out


def dbl_L(p3, need_t):
    x1, y1, z1 = p3
    a = sqr_L(x1)
    b = sqr_L(y1)
    c = mul_small_L(sqr_L(z1), 2)
    d = neg_L(a)
    e = sub_L(sub_L(sqr_L(add_L(x1, y1)), a), b)
    g = add_L(d, b)
    f = sub_L(g, c)
    h = sub_L(d, b)
    out = (mul_L(e, f), mul_L(g, h), mul_L(f, g))
    return (*out, mul_L(e, h)) if need_t else out


def _nsqr_L(x, n):
    if n < 4:
        for _ in range(n):
            x = sqr_L(x)
        return x
    return lax.fori_loop(0, n, lambda _, v: sqr_L(v), x)


def _pow22523_L(a):
    """a^((p-5)/8) = a^(2^252 - 3), limb-major (fe25519.pow22523's chain
    with the limb axis leading)."""
    z2 = sqr_L(a)
    z8 = _nsqr_L(z2, 2)
    z9 = mul_L(a, z8)
    z11 = mul_L(z2, z9)
    z22 = sqr_L(z11)
    z_5_0 = mul_L(z9, z22)
    z_10_0 = mul_L(_nsqr_L(z_5_0, 5), z_5_0)
    z_20_0 = mul_L(_nsqr_L(z_10_0, 10), z_10_0)
    z_40_0 = mul_L(_nsqr_L(z_20_0, 20), z_20_0)
    z_50_0 = mul_L(_nsqr_L(z_40_0, 10), z_10_0)
    z_100_0 = mul_L(_nsqr_L(z_50_0, 50), z_50_0)
    z_200_0 = mul_L(_nsqr_L(z_100_0, 100), z_100_0)
    z_250_0 = mul_L(_nsqr_L(z_200_0, 50), z_50_0)
    return mul_L(_nsqr_L(z_250_0, 2), a)


def _is_zero_mod_p_L(d):
    """True per lane iff d (a sub_L output: value < 2^256) is 0 mod p —
    i.e. its fully-carried digits equal 0, p, or 2p (3p > 2^256).

    Carry settling: after the first pass carries are <= 1; a ripple can
    then crawl at most one limb per pass, so N further passes guarantee
    canonical digits. The x608 fold term is live only while a top carry
    exists (value < 2^256 keeps the top digit < 2^9 once settled)."""
    x = d
    for _ in range(N + 2):
        x, c = _pass_L(x)
        x = _upd(x, 0, 1, x[0:1] + c * _F260)
    z0 = jnp.all(x == 0, axis=0, keepdims=True)
    zp = jnp.all(x == _C["pdig"], axis=0, keepdims=True)
    z2p = jnp.all(x == _C["p2dig"], axis=0, keepdims=True)
    return z0 | zp | z2p


def _settle_digits_L(x):
    """Carry-settle to EXACT base-2^13 digits of the represented value
    (< 2^256 by the public invariant, so q below is at most 2). Same
    settling argument as :func:`_is_zero_mod_p_L`."""
    for _ in range(N + 2):
        x, c = _pass_L(x)
        x = _upd(x, 0, 1, x[0:1] + c * _F260)
    return x


def _ge_digits_L(x, cdig):
    """Lexicographic x >= c on settled digit arrays ([N, B] vs [N, 1])."""
    res = x[0:1] >= cdig[0:1]
    for i in range(1, N):
        gt = x[i : i + 1] > cdig[i : i + 1]
        eq = x[i : i + 1] == cdig[i : i + 1]
        res = gt | (eq & res)
    return res


def _parity_L(x):
    """[1, B] canonical parity bit of a field element (< 2^256): settle to
    exact digits, count the p-subtractions q in {0, 1, 2} needed to reach
    [0, p), and flip the digit parity per subtraction (p is odd)."""
    xs = _settle_digits_L(x)
    q = _ge_digits_L(xs, _C["pdig"]).astype(jnp.int32) + _ge_digits_L(
        xs, _C["p2dig"]
    ).astype(jnp.int32)
    return (xs[0:1] + q) & 1


def _decompress_L(y, sign):
    """RFC 8032 x-recovery, limb-major: y [N, B] (bit 255 cleared, y < p
    guaranteed by the wire packer), sign [1, B] int32 -> (x [N, B],
    ok [1, B] bool). Mirrors ed25519_wire.decompress_device case-for-case
    (the jnp/XLA twin); differential tests enforce bit-exact agreement
    with the host oracle's _recover_x."""
    blk = y.shape[1]
    row = lax.broadcasted_iota(jnp.int32, (N, blk), 0)
    one = (row == 0).astype(jnp.int32)
    y2 = sqr_L(y)
    u = sub_L(y2, one)
    # Const column ([N, 1]) second: mul_L slices its FIRST operand per
    # limb, and a [1, 1] slice would need a both-axes vector broadcast
    # Mosaic does not implement.
    v = add_L(mul_L(y2, _C["d"]), one)
    v2 = sqr_L(v)
    uv3 = mul_L(u, mul_L(v2, v))
    uv7 = mul_L(uv3, sqr_L(v2))
    x = mul_L(uv3, _pow22523_L(uv7))
    vx2 = mul_L(v, sqr_L(x))
    ok_direct = _is_zero_mod_p_L(sub_L(vx2, u))
    ok_flip = _is_zero_mod_p_L(add_L(vx2, u))
    x = _sel_rows(
        ok_flip & jnp.logical_not(ok_direct), mul_L(x, _C["sqrtm1"]), x
    )
    ok = ok_direct | ok_flip
    x_zero = _is_zero_mod_p_L(x)
    ok = ok & jnp.logical_not(x_zero & (sign == 1))
    x = _sel_rows(_parity_L(x) != sign, neg_L(x), x)
    return x, ok


# -------------------------------------------------------------- the kernel


def _verify_kernel_body(*refs):
    try:
        _verify_kernel_inner(*refs)
    finally:
        _C.clear()


def _verify_kernel_inner(ax_ref, ay_ref, at_ref, rx_ref, ry_ref,
                         sd_ref, kd_ref, bias_ref, k2d_ref,
                         pdig_ref, p2dig_ref, _d_ref, _sqrtm1_ref,
                         byp_ref, bym_ref, bt2_ref,
                         ok_ref, tbl_ref):
    # (_d_ref/_sqrtm1_ref unused here: all three kernels share ONE const
    # block — see _consts — so the tuple/ref alignment cannot drift.)
    ax, ay, at = ax_ref[:], ay_ref[:], at_ref[:]
    rx, ry = rx_ref[:], ry_ref[:]

    _C["bias"] = bias_ref[:]
    _C["pdig"] = pdig_ref[:]
    _C["p2dig"] = p2dig_ref[:]
    k2d = k2d_ref[:]
    byp_c, bym_c, bt2_c = byp_ref[:], bym_ref[:], bt2_ref[:]

    ok_ref[:] = _ladder_ok(
        ax, ay, at, rx, ry, sd_ref, kd_ref, tbl_ref, k2d,
        byp_c, bym_c, bt2_c,
    ).astype(jnp.int32)


def _ladder_ok(ax, ay, at, rx, ry, sd_ref, kd_ref, tbl_ref, k2d,
               byp_c, bym_c, bt2_c):
    """The shared joint-Horner ladder + projective R check: [s]B + [k]A'
    == R on pre-decompressed limb-major coordinates (A' = -A). Used by
    both the packed-input kernel and the wire kernel (which decompresses
    A and R in-kernel first). Returns the [1, B] bool acceptance row."""
    blk = ax.shape[1]

    row = lax.broadcasted_iota(jnp.int32, (N, blk), 0)
    one = (row == 0).astype(jnp.int32)
    zero = jnp.zeros((N, blk), dtype=jnp.int32)

    # [0..8]A' into VMEM scratch (see module doc: straight-line SIGABRTs).
    a_niels = (add_L(ay, ax), sub_L(ay, ax), mul_L(at, k2d))

    def build(v, prev):
        sx, sy, sz, st = prev
        tbl_ref[pl.ds(v, 1), 0] = add_L(sy, sx)[None]
        tbl_ref[pl.ds(v, 1), 1] = sub_L(sy, sx)[None]
        tbl_ref[pl.ds(v, 1), 2] = mul_L(st, k2d)[None]
        tbl_ref[pl.ds(v, 1), 3] = sz[None]
        return madd_L(prev, a_niels, need_t=True)

    lax.fori_loop(0, 9, build, (zero, one, one, zero))

    tb = [
        (byp_c[:, v : v + 1], bym_c[:, v : v + 1], bt2_c[:, v : v + 1])
        for v in range(9)
    ]

    def sel_a(digit):  # [1, BLK] signed -> projective niels entry
        sign = digit < 0
        mag = jnp.abs(digit)
        yp = zero
        ym = zero
        t2 = zero
        z = zero
        for v in range(9):
            m = mag == v
            yp = jnp.where(m, tbl_ref[v, 0], yp)
            ym = jnp.where(m, tbl_ref[v, 1], ym)
            t2 = jnp.where(m, tbl_ref[v, 2], t2)
            z = jnp.where(m, tbl_ref[v, 3], z)
        return (
            _sel_rows(sign, ym, yp),
            _sel_rows(sign, yp, ym),
            _sel_rows(sign, neg_L(t2), t2),
            z,
        )

    def sel_b(digit):  # [1, BLK] signed -> affine niels entry
        sign = digit < 0
        mag = jnp.abs(digit)
        yp = zero
        ym = zero
        t2 = zero
        for v in range(9):
            m = mag == v
            yp = jnp.where(m, jnp.broadcast_to(tb[v][0], (N, blk)), yp)
            ym = jnp.where(m, jnp.broadcast_to(tb[v][1], (N, blk)), ym)
            t2 = jnp.where(m, jnp.broadcast_to(tb[v][2], (N, blk)), t2)
        return (
            _sel_rows(sign, ym, yp),
            _sel_rows(sign, yp, ym),
            _sel_rows(sign, neg_L(t2), t2),
        )

    def body(i, acc3):
        w = 63 - i
        for _ in range(3):
            acc3 = dbl_L(acc3, need_t=False)
        acc4 = dbl_L(acc3, need_t=True)
        kdw = kd_ref[pl.ds(w, 1), :]
        sdw = sd_ref[pl.ds(w, 1), :]
        acc4 = padd_L(acc4, sel_a(kdw), need_t=True)
        return madd_L(acc4, sel_b(sdw), need_t=False)

    px, py, pz = lax.fori_loop(0, 64, body, (zero, one, one))

    ok_x = _is_zero_mod_p_L(sub_L(px, mul_L(rx, pz)))
    ok_y = _is_zero_mod_p_L(sub_L(py, mul_L(ry, pz)))
    return ok_x & ok_y


def _wire_kernel_body(*refs):
    try:
        _wire_kernel_inner(*refs)
    finally:
        _C.clear()


def _wire_kernel_inner(ay_ref, asign_ref, ry_ref, rsign_ref,
                       sd_ref, kd_ref, bias_ref, k2d_ref,
                       pdig_ref, p2dig_ref, d_ref, sqrtm1_ref,
                       byp_ref, bym_ref, bt2_ref, ok_ref, tbl_ref):
    """Wire-input variant: decompress A and R in-kernel (the host ships
    raw 32-byte encodings — see ops.ed25519_wire), negate A, then run the
    shared ladder."""
    _C["bias"] = bias_ref[:]
    _C["pdig"] = pdig_ref[:]
    _C["p2dig"] = p2dig_ref[:]
    _C["d"] = d_ref[:]
    _C["sqrtm1"] = sqrtm1_ref[:]
    k2d = k2d_ref[:]
    byp_c, bym_c, bt2_c = byp_ref[:], bym_ref[:], bt2_ref[:]

    ay = ay_ref[:]
    ry = ry_ref[:]
    ax, ok_a = _decompress_L(ay, asign_ref[:])
    rx, ok_r = _decompress_L(ry, rsign_ref[:])
    nax = neg_L(ax)
    nat = mul_L(nax, ay)

    ok = _ladder_ok(
        nax, ay, nat, rx, ry, sd_ref, kd_ref, tbl_ref, k2d,
        byp_c, bym_c, bt2_c,
    )
    ok_ref[:] = (ok & ok_a & ok_r).astype(jnp.int32)


def _semiwire_kernel_body(*refs):
    try:
        _semiwire_kernel_inner(*refs)
    finally:
        _C.clear()


def _semiwire_kernel_inner(ax_ref, ay_ref, at_ref, ry_ref, rsign_ref,
                           sd_ref, kd_ref, bias_ref, k2d_ref,
                           pdig_ref, p2dig_ref, d_ref, sqrtm1_ref,
                           byp_ref, bym_ref, bt2_ref, ok_ref, tbl_ref):
    """Indexed-A wire variant: A arrives pre-decompressed and pre-negated
    (gathered from the resident validator table OUTSIDE the kernel — the
    gather is an XLA op on device-resident tensors, no host transfer);
    only R is decompressed in-kernel."""
    _C["bias"] = bias_ref[:]
    _C["pdig"] = pdig_ref[:]
    _C["p2dig"] = p2dig_ref[:]
    _C["d"] = d_ref[:]
    _C["sqrtm1"] = sqrtm1_ref[:]
    k2d = k2d_ref[:]
    byp_c, bym_c, bt2_c = byp_ref[:], bym_ref[:], bt2_ref[:]

    ry = ry_ref[:]
    rx, ok_r = _decompress_L(ry, rsign_ref[:])
    ok = _ladder_ok(
        ax_ref[:], ay_ref[:], at_ref[:], rx, ry,
        sd_ref, kd_ref, tbl_ref, k2d, byp_c, bym_c, bt2_c,
    )
    ok_ref[:] = (ok & ok_r).astype(jnp.int32)


def _b_niels_cols():
    yp, ym, t2 = _b_niels_np(9)
    return (
        np.asarray(yp).T.copy(),
        np.asarray(ym).T.copy(),
        np.asarray(t2).T.copy(),
    )


_D_COL = fe.to_limbs(host_ed.D).reshape(N, 1)
_SQRTM1_COL = fe.to_limbs(host_ed.SQRT_M1).reshape(N, 1)

#: Number of shared const inputs (the [N, 1] columns + [N, 9] tables).
_N_C1, _N_C9 = 6, 3


def _consts():
    """The ONE const block every kernel receives, in the ONE order every
    ``*_kernel_inner`` declares its const refs: (bias, k2d, pdig, p2dig,
    d, sqrtm1, byp, bym, bt2). Single-sourced so the tuple and the three
    kernels' ref lists cannot drift — a positional mismatch here would
    corrupt crypto verdicts silently."""
    byp, bym, bt2 = _b_niels_cols()
    return (
        jnp.asarray(_SUB_BIAS_COL, dtype=jnp.int32),
        jnp.asarray(_K2D_COL, dtype=jnp.int32),
        jnp.asarray(_P_COL, dtype=jnp.int32),
        jnp.asarray(_2P_COL, dtype=jnp.int32),
        jnp.asarray(_D_COL, dtype=jnp.int32),
        jnp.asarray(_SQRTM1_COL, dtype=jnp.int32),
        jnp.asarray(byp, dtype=jnp.int32),
        jnp.asarray(bym, dtype=jnp.int32),
        jnp.asarray(bt2, dtype=jnp.int32),
    )


def _specs(block):
    """(spec20, spec64, spec1, const_specs) for one block size."""
    return (
        pl.BlockSpec((N, block), lambda i: (0, i)),
        pl.BlockSpec((64, block), lambda i: (0, i)),
        pl.BlockSpec((1, block), lambda i: (0, i)),
        [pl.BlockSpec((N, 1), lambda i: (0, 0))] * _N_C1
        + [pl.BlockSpec((N, 9), lambda i: (0, 0))] * _N_C9,
    )


def _pallas_verify_call(body, block, interpret, in_specs, inputs):
    """Shared pallas_call scaffolding: every verify kernel has the same
    output row, grid, scratch table, and trailing const block."""
    bsz = inputs[0].shape[-1]
    _, _, spec1, const_specs = _specs(block)
    ok = pl.pallas_call(
        body,
        out_shape=jax.ShapeDtypeStruct((1, bsz), jnp.int32),
        grid=(bsz // block,),
        in_specs=list(in_specs) + const_specs,
        out_specs=spec1,
        scratch_shapes=[pltpu.VMEM((9, 4, N, block), jnp.int32)],
        interpret=interpret,
    )(*inputs, *_consts())
    return ok[0].astype(bool)


def _check_block(bsz, block, padder: str):
    if bsz % block != 0:
        # The grid floor-divides; a ragged batch would leave the tail
        # lanes UNWRITTEN and return garbage as crypto verdicts.
        raise ValueError(
            f"batch {bsz} is not a multiple of block {block}; "
            f"use {padder}(), which pads"
        )


def _pad_to_block(block, arrays):
    """Zero-pad each array's leading axis up to a multiple of ``block``
    (callers slice the verdict row back; pad-lane outcomes are
    discarded)."""
    bsz = arrays[0].shape[0]
    padded = ((bsz + block - 1) // block) * block
    if padded == bsz:
        return tuple(arrays)
    return tuple(
        jnp.concatenate(
            [jnp.asarray(a),
             jnp.zeros((padded - bsz, *a.shape[1:]), dtype=a.dtype)]
        )
        for a in arrays
    )


@functools.lru_cache(maxsize=None)
def make_pallas_verify_fn(block: int = _BLOCK, interpret: bool = False):
    """Jitted ``(ax..k_nib) -> bool[B]`` with the XLA kernel's signature:
    inputs are the batch-major [B, 20] / [B, 64] tensors the packer emits
    (transpose + signed recode happen inside the jit, on device). B must
    be a multiple of ``block`` — :func:`verify_pallas` pads."""

    @jax.jit
    def run(ax, ay, at, rx, ry, s_nib, k_nib):
        _check_block(ax.shape[0], block, "verify_pallas")
        sd = _recode_signed(s_nib)  # [64, B]
        kd = _recode_signed(k_nib)
        spec20, spec64, _, _ = _specs(block)
        return _pallas_verify_call(
            _verify_kernel_body, block, interpret,
            [spec20] * 5 + [spec64] * 2,
            (ax.T, ay.T, at.T, rx.T, ry.T, sd, kd),
        )

    return run


@functools.lru_cache(maxsize=None)
def make_pallas_wire_verify_fn(block: int = _BLOCK, interpret: bool = False):
    """Jitted wire-path verify ``(a_rows, r_rows, s_rows, k_rows) ->
    bool[B]`` — inputs are the [B, 32] uint8 rows the wire packer emits
    (ops.ed25519_wire.Ed25519WireHost); byte->limb/nibble unpacking and
    the signed recode run on device inside the jit, point decompression
    runs inside the Mosaic kernel. B must be a multiple of ``block`` —
    :func:`wire_verify_pallas` pads."""
    from hyperdrive_tpu.ops.ed25519_wire import (
        limbs_from_rows,
        nibbles_from_rows,
    )

    @jax.jit
    def run(a_rows, r_rows, s_rows, k_rows):
        _check_block(a_rows.shape[0], block, "wire_verify_pallas")
        ay, a_sign = limbs_from_rows(a_rows)
        ry, r_sign = limbs_from_rows(r_rows)
        sd = _recode_signed(nibbles_from_rows(s_rows))  # [64, B]
        kd = _recode_signed(nibbles_from_rows(k_rows))
        spec20, spec64, spec1, _ = _specs(block)
        return _pallas_verify_call(
            _wire_kernel_body, block, interpret,
            [spec20, spec1, spec20, spec1] + [spec64] * 2,
            (ay.T, a_sign[None, :], ry.T, r_sign[None, :], sd, kd),
        )

    return run


@functools.lru_cache(maxsize=None)
def make_pallas_semiwire_verify_fn(block: int = _BLOCK,
                                   interpret: bool = False):
    """Jitted indexed-A wire verify ``(idx, r_rows, s_rows, k_rows,
    tnax, tay, tnat, tvalid) -> bool[B]``: A coordinates gather from the
    device-resident validator table (see ops.ed25519_wire.ValidatorTable)
    — the gather and byte unpacking run as XLA ops inside the jit, the
    R decompression + ladder inside the Mosaic kernel."""
    from hyperdrive_tpu.ops.ed25519_wire import (
        limbs_from_rows,
        nibbles_from_rows,
    )

    @jax.jit
    def run(idx, r_rows, s_rows, k_rows, tnax, tay, tnat, tvalid):
        _check_block(idx.shape[0], block, "semiwire_verify_pallas")
        nax = jnp.take(tnax, idx, axis=0)
        ay = jnp.take(tay, idx, axis=0)
        nat = jnp.take(tnat, idx, axis=0)
        ok_t = jnp.take(tvalid, idx, axis=0)
        ry, r_sign = limbs_from_rows(r_rows)
        sd = _recode_signed(nibbles_from_rows(s_rows))
        kd = _recode_signed(nibbles_from_rows(k_rows))
        spec20, spec64, spec1, _ = _specs(block)
        ok = _pallas_verify_call(
            _semiwire_kernel_body, block, interpret,
            [spec20] * 3 + [spec20, spec1] + [spec64] * 2,
            (nax.T, ay.T, nat.T, ry.T, r_sign[None, :], sd, kd),
        )
        return ok & ok_t

    return run


def semiwire_verify_pallas(idx, r_rows, s_rows, k_rows,
                           tnax, tay, tnat, tvalid,
                           block: int = _BLOCK, interpret: bool = False):
    """Padding wrapper around :func:`make_pallas_semiwire_verify_fn`
    (pad lanes index slot 0 with zero wire bytes; verdicts sliced off)."""
    bsz = idx.shape[0]
    idx, r_rows, s_rows, k_rows = _pad_to_block(
        block, (idx, r_rows, s_rows, k_rows)
    )
    fn = make_pallas_semiwire_verify_fn(block=block, interpret=interpret)
    return fn(idx, r_rows, s_rows, k_rows, tnax, tay, tnat, tvalid)[:bsz]


def wire_verify_pallas(a_rows, r_rows, s_rows, k_rows,
                       block: int = _BLOCK, interpret: bool = False):
    """Drop-in equivalent of ``wire_verify_kernel`` on the Pallas path:
    pads the batch to a multiple of ``block``, runs, slices the mask.
    Padding rows are all-zero wire bytes; their verdicts are discarded by
    the final slice, so their decode outcome is irrelevant."""
    bsz = a_rows.shape[0]
    a_rows, r_rows, s_rows, k_rows = _pad_to_block(
        block, (a_rows, r_rows, s_rows, k_rows)
    )
    fn = make_pallas_wire_verify_fn(block=block, interpret=interpret)
    return fn(a_rows, r_rows, s_rows, k_rows)[:bsz]


def pallas_backend_ok(devices=None) -> bool:
    """True when the target devices compile Mosaic kernels (real TPU —
    including the axon remote-compile platform). ``devices``: the devices
    the kernel will actually run on (e.g. ``mesh.devices.flat``); defaults
    to the process default backend. CPU/interpret is only for tests: the
    interpreter is orders of magnitude too slow for real windows."""
    try:
        if devices is not None:
            plats = {d.platform for d in np.asarray(devices).flat}
            return plats <= {"tpu", "axon"} and bool(plats)
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover - no backend at all
        return False


def resolve_backend(backend=None, devices=None) -> str:
    """Normalize a backend choice to "pallas" or "xla".

    ``backend``: "pallas"/"xla" pass through; None or "auto" selects
    "pallas" when ``devices`` (or the default backend) are Mosaic-capable.
    The one resolution rule shared by every consumer (TpuBatchVerifier,
    the sharded mesh step, bench.py) so the selection logic cannot drift."""
    if backend in (None, "auto"):
        return "pallas" if pallas_backend_ok(devices) else "xla"
    if backend not in ("pallas", "xla"):
        raise ValueError(f"unknown backend {backend!r}")
    return backend


def verify_pallas(ax, ay, at, rx, ry, s_nib, k_nib,
                  block: int = _BLOCK, interpret: bool = False):
    """Drop-in equivalent of ``verify_kernel`` on the Pallas path: pads the
    batch to a multiple of ``block``, runs the kernel, slices the mask.

    Shares ``verify_kernel``'s PRECONDITION: scalar nibbles must encode
    values < 2^253 (guaranteed by the packer; the signed recode drops the
    final carry, so an out-of-range raw scalar would verify as
    ``scalar - 2^256`` instead of being rejected)."""
    bsz = ax.shape[0]
    ax, ay, at, rx, ry, s_nib, k_nib = _pad_to_block(
        block, (ax, ay, at, rx, ry, s_nib, k_nib)
    )
    fn = make_pallas_verify_fn(block=block, interpret=interpret)
    return fn(ax, ay, at, rx, ry, s_nib, k_nib)[:bsz]
