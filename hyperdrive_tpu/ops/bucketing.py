"""Static-shape bucket selection, shared by every padded device launch.

XLA compiles one executable per input shape, so variable-size batches are
padded up to a small ladder of precompiled bucket sizes; batches beyond
the largest bucket round up to its next multiple (large launches amortize
the padding, and chunked callers split on the largest bucket anyway).
One policy, one place — the Ed25519 packer, the vote grid, and any future
padded launch must agree or they recompile/pad inconsistently.
"""

from __future__ import annotations

import math

__all__ = ["bucket_for"]


def bucket_for(n: int, buckets) -> int:
    """Smallest bucket holding ``n``, else the next multiple of the
    largest. ``buckets`` must be sorted ascending and non-empty."""
    for b in buckets:
        if n <= b:
            return b
    top = buckets[-1]
    return math.ceil(n / top) * top
