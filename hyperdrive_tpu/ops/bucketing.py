"""Static-shape bucket selection, shared by every padded device launch.

XLA compiles one executable per input shape, so variable-size batches are
padded up to a small ladder of precompiled bucket sizes; batches beyond
the largest bucket round up to its next multiple (large launches amortize
the padding, and chunked callers split on the largest bucket anyway).
One policy, one place — the Ed25519 packer, the vote grid, and any future
padded launch must agree or they recompile/pad inconsistently.
"""

from __future__ import annotations

import math

__all__ = ["bucket_for", "launch_target", "would_spill"]


def bucket_for(n: int, buckets) -> int:
    """Smallest bucket holding ``n``, else the next multiple of the
    largest. ``buckets`` must be sorted ascending and non-empty."""
    for b in buckets:
        if n <= b:
            return b
    top = buckets[-1]
    return math.ceil(n / top) * top


def launch_target(buckets, default: int = 4096) -> int:
    """Preferred lanes-per-launch: the ladder's largest bucket (chunked
    callers split on it, coalescing callers aim to fill it), or
    ``default`` when the verifier exposes no ladder (HostVerifier).
    The one number the Ed25519 chunker, the settle-pass grouping, and
    the devsched slot-close rule must agree on."""
    return buckets[-1] if buckets else default


def would_spill(rows: int, add: int, buckets) -> bool:
    """True when growing a padded batch from ``rows`` by ``add`` lanes
    crosses a bucket boundary. Padded launches cost by bucket, not by
    fill — the devsched spill rule drains the queue rather than cross
    (harness/sim.py speculative settle); any coalescer sharing the
    ladder should make the same call here."""
    if not buckets or not rows:
        return False
    return bucket_for(rows + add, buckets) > bucket_for(rows, buckets)
