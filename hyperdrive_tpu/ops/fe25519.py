"""GF(2^255 - 19) arithmetic on int32 limb vectors (TPU-native).

Design (SURVEY.md section 7.3 hard part #1): TPUs have no 64-bit integer
multiply, so a field element is **20 limbs of 13 bits** in int32, value =
sum(l_i * 2^(13 i)), capacity 260 bits. With normalized limbs (< 2^13):

- a limb product is < 2^26, and a schoolbook column accumulates at most 20
  products, staying < 2^30.4 — comfortably inside int32. (Normalization
  leaves slack on low limbs — public results bound their limbs by
  ``SLACK_MAX`` = 9,400, not 2^13 — and the worst real column bound is
  20 * SLACK_MAX^2 = 1.767e9 < 2^31, still safe);
- 2^260 = 608 (mod p), so columns 20..39 of a product fold back into
  columns 0..19 with a single multiply by 608;
- bits 255..259 fold with a multiply by 19 (2^255 = 19 mod p), which keeps
  every public result under the invariant **value < 2^256** with all limbs
  in [0, SLACK_MAX].

Every function operates on arrays shaped ``[..., 20]`` (any batch prefix),
contains only static shapes and static Python loops over limb indices, and
is transparent to jit/vmap/shard_map. Negative intermediates (subtraction)
are handled by signed carries: numpy/XLA right-shift on int32 is
arithmetic, so ``c >> 13`` is a floor division and ``c & 0x1FFF`` is the
non-negative residue.

The Python-int reference for every operation is the host crypto module
(:mod:`hyperdrive_tpu.crypto.ed25519`); differential tests enforce exact
agreement.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from hyperdrive_tpu.ops import limbs as _limbs

__all__ = [
    "N_LIMBS",
    "LIMB_BITS",
    "LIMB_MASK",
    "P_INT",
    "to_limbs",
    "from_limbs",
    "zeros_like_batch",
    "add",
    "sub",
    "neg",
    "mul",
    "sqr",
    "mul_small",
    "inv",
    "pow22523",
    "canonical",
    "eq",
    "is_zero",
    "select",
    "ZERO",
    "ONE",
]

N_LIMBS = 20
LIMB_BITS = _limbs.LIMB_BITS
LIMB_MASK = _limbs.LIMB_MASK

P_INT = 2**255 - 19
#: 2^260 mod p — the fold factor for columns >= 20.
FOLD_260 = 608
#: 2^255 mod p — the fold factor for bits >= 255 inside limb 19.
FOLD_255 = 19
#: Bit position of 2^255 inside limb 19 (19 * 13 = 247; 255 - 247 = 8).
TOP_SHIFT = 8
TOP_MASK = (1 << TOP_SHIFT) - 1

#: Invariant slack: public results have limbs in [0, SLACK_MAX]. The bound
#: comes from :func:`_reduce_cols`'s two-fold-pass tail (worst chain value
#: 9,383 — see the bound walk there); 9,400 adds margin while keeping the
#: schoolbook column bound 20 * SLACK_MAX^2 = 1.767e9 < 2^31 int32-safe.
SLACK_MAX = 9_400


# The bias search, limb packing, and carry primitives are shared with
# fp381 through :mod:`hyperdrive_tpu.ops.limbs`; this module pins the
# GF(2^255-19) parameters (20 limbs, SLACK_MAX domination bound).
_SUB_BIAS = _limbs.make_sub_bias(P_INT, N_LIMBS, SLACK_MAX)


def _to_limbs_flat(vals) -> np.ndarray:
    """[n] Python ints -> [n, 20] int32 limbs (vectorized; see
    :func:`hyperdrive_tpu.ops.limbs.to_limbs_flat`)."""
    return _limbs.to_limbs_flat(vals, N_LIMBS)


def to_limbs(x) -> np.ndarray:
    """Python int(s) -> int32 limb array. Accepts a single int (-> shape
    [20]) or any nested sequence of ints (-> shape [..., 20]). Values must
    lie in [0, 2^260)."""
    return _limbs.to_limbs(x, N_LIMBS)


def from_limbs(limbs) -> "int | list":
    """Inverse of :func:`to_limbs` (host-side; accepts device arrays)."""
    return _limbs.from_limbs(limbs)


ZERO = to_limbs(0)
ONE = to_limbs(1)
_P_LIMBS = to_limbs(P_INT)


def zeros_like_batch(batch_shape) -> jnp.ndarray:
    return jnp.zeros((*batch_shape, N_LIMBS), dtype=jnp.int32)


# ------------------------------------------------------------------ carries


#: Sequential scan carry (shared; see :func:`limbs.carry_scan`).
_carry = _limbs.carry_scan

#: Pseudo-Mersenne carry-out fold (shared; see :func:`limbs.fold_carry_out`).
_fold_carry_out = _limbs.fold_carry_out


def _fold_top(x: jnp.ndarray) -> jnp.ndarray:
    """Fold bits 255..259 (the high bits of limb 19) back via x19 -> 19 *
    (x19 >> 8), establishing value < 2^256. Input limbs must be
    non-negative with limb 19 < 2^23 (so hi * 19 stays within the micro
    ripple's headroom); callers arrive here with limbs <= 2^13 + small."""
    hi = x[..., N_LIMBS - 1] >> TOP_SHIFT
    x = x.at[..., N_LIMBS - 1].set(x[..., N_LIMBS - 1] & TOP_MASK)
    x = x.at[..., 0].add(hi * FOLD_255)
    c = x[..., 0]
    x = x.at[..., 0].set(c & LIMB_MASK)
    x = x.at[..., 1].add(c >> LIMB_BITS)
    return x


def _normalize(x: jnp.ndarray) -> jnp.ndarray:
    """Carry + top-fold: limbs in [0, 2^13), value < 2^256."""
    x, carry = _carry(x)
    x = _fold_carry_out(x, carry, FOLD_260)
    x = _fold_top(x)
    return x


# -------------------------------------------------- vectorized carry passes
#
# The scan in :func:`_carry` is exact but sequential: 20 (or 39) dependent
# steps per normalization, each touching one limb column. The hot path
# instead uses *vectorized* passes — one shift/mask over the whole limb
# axis, with every limb's carry moved up one position in a single slice
# shift. Because all pre-carry limbs on the hot path are provably
# non-negative (schoolbook columns of non-negative limbs; sums; the
# dominating subtraction bias), carries are non-negative and a constant
# number of passes restores the invariant — no borrow can ripple.


#: One vectorized carry pass (shared; see :func:`limbs.carry_pass`).
_pass = _limbs.carry_pass


def _pass_fold(x: jnp.ndarray) -> jnp.ndarray:
    """Carry pass on a 20-limb array, folding the 2^260 carry-out back
    into limb 0 (x608)."""
    x, c = _pass(x)
    return x.at[..., 0].add(c * FOLD_260)


# ---------------------------------------------------------------- operators


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(a + b) mod-ish p: normalized, value < 2^256.

    Pre-carry limbs are <= 2 * SLACK_MAX < 2^15; one pass leaves limbs
    <= 2^13 + 2, one micro-fold absorbs the (<=2) 2^260 carry."""
    x, c = _pass(a + b)
    x = _fold_carry_out(x, c, FOLD_260)
    return _fold_top(x)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(a - b) mod-ish p via the limb-dominating bias: every pre-carry
    limb of ``a + bias - b`` is non-negative, so a single vectorized pass
    normalizes (no borrow propagation possible)."""
    bias = jnp.asarray(_SUB_BIAS, dtype=jnp.int32)
    x, c = _pass(a + (bias - b))
    x = _fold_carry_out(x, c, FOLD_260)
    return _fold_top(x)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    bias = jnp.asarray(_SUB_BIAS, dtype=jnp.int32)
    x, c = _pass(bias - a)
    x = _fold_carry_out(x, c, FOLD_260)
    return _fold_top(x)


def _reduce_cols(cols: jnp.ndarray) -> jnp.ndarray:
    """Shared reduction tail of :func:`mul`/:func:`sqr`: take the 39 product
    columns (each <= 20 * SLACK_MAX^2 = 1.767e9 < 2^31 — the callers' bound
    analyses guarantee this), normalize to 20 invariant limbs, value < 2^256.

    Bound walk (operand limbs <= SLACK_MAX = 9,400, so cols <= 1.767e9):
    one pass leaves limbs <= 8,191 + (1.767e9 >> 13) = 223,913 with top
    carry c1 <= 215,722; the x608 fold of columns 20..38 (c1 as virtual
    column 39 into 19) keeps every column <= 223,913 * 609 < 2^27.03.
    Fold-pass A: limbs <= 8,191 + (2^27.02 >> 13) = 24,836, and its top
    carry (<= 16,037) folds x608 into limb 0 <= 9,758,687 < 2^23.3.
    Fold-pass B: limb 1 <= 8,191 + (9,758,687 >> 13) = 9,382, all others
    <= 8,194, top carry <= 3 folds to limb 0 <= 10,015. The top fold then
    masks limb 0 and ripples <= 1 into limb 1: final limbs <= 9,383 —
    inside SLACK_MAX, closing the invariant."""
    cols, c1 = _pass(cols)

    low = cols[..., :N_LIMBS]
    high = cols[..., N_LIMBS:]  # columns 20..38 fold x608 into 0..18
    low = low.at[..., : N_LIMBS - 1].add(high * FOLD_260)
    # Virtual column 39 (the pass's top carry) folds to column 19.
    low = low.at[..., 19].add(c1 * FOLD_260)

    low = _pass_fold(low)
    low = _pass_fold(low)
    return _fold_top(low)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook product with modular folding. Inputs must satisfy the
    invariant (limbs <= SLACK_MAX); output does too, value < 2^256.

    Bound chain: products <= SLACK_MAX^2 < 2^26.4, columns accumulate <= 20
    of them -> <= 1.767e9 < 2^31 (int32-safe), meeting
    :func:`_reduce_cols`'s contract."""
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    cols = jnp.zeros((*batch, 2 * N_LIMBS - 1), dtype=jnp.int32)
    for i in range(N_LIMBS):
        cols = cols.at[..., i : i + N_LIMBS].add(a[..., i : i + 1] * b)
    return _reduce_cols(cols)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    """Squaring: symmetric schoolbook — cross products a_i*a_j (i < j)
    appear twice, so accumulate a_i * (a_i, 2a_{i+1}, ..., 2a_19) per row,
    halving the multiply work of :func:`mul`.

    Bound: the worst column sums 10 doubled cross products (col 19:
    (0,19)..(9,10)) <= 10 * 2 * SLACK_MAX^2 = 1.767e9 < 2^31 — int32-safe,
    meeting :func:`_reduce_cols`'s contract."""
    a2 = a + a
    batch = a.shape[:-1]
    cols = jnp.zeros((*batch, 2 * N_LIMBS - 1), dtype=jnp.int32)
    for i in range(N_LIMBS):
        row = jnp.concatenate([a[..., i : i + 1], a2[..., i + 1 :]], axis=-1)
        cols = cols.at[..., 2 * i : i + N_LIMBS].add(a[..., i : i + 1] * row)
    return _reduce_cols(cols)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small constant (k < 2^17 keeps products in int32)."""
    if not 0 <= k < (1 << 17):
        raise ValueError("constant too large for int32 limb products")
    x = _pass_fold(a * jnp.int32(k))
    x = _pass_fold(x)
    x = _pass_fold(x)
    return _fold_top(x)


def inv(a: jnp.ndarray) -> jnp.ndarray:
    """a^(p-2) via the standard curve25519 addition chain (254 squarings,
    11 multiplies)."""

    def nsqr(x, n):
        # fori_loop keeps the traced graph one squaring deep instead of n
        # deep — essential for compile times (n reaches 100 here).
        if n < 4:
            for _ in range(n):
                x = sqr(x)
            return x
        return lax.fori_loop(0, n, lambda _, v: sqr(v), x)

    z2 = sqr(a)  # 2
    z8 = nsqr(z2, 2)  # 8
    z9 = mul(a, z8)  # 9
    z11 = mul(z2, z9)  # 11
    z22 = sqr(z11)  # 22
    z_5_0 = mul(z9, z22)  # 2^5 - 2^0
    z_10_5 = nsqr(z_5_0, 5)
    z_10_0 = mul(z_10_5, z_5_0)
    z_20_10 = nsqr(z_10_0, 10)
    z_20_0 = mul(z_20_10, z_10_0)
    z_40_20 = nsqr(z_20_0, 20)
    z_40_0 = mul(z_40_20, z_20_0)
    z_50_10 = nsqr(z_40_0, 10)
    z_50_0 = mul(z_50_10, z_10_0)
    z_100_50 = nsqr(z_50_0, 50)
    z_100_0 = mul(z_100_50, z_50_0)
    z_200_100 = nsqr(z_100_0, 100)
    z_200_0 = mul(z_200_100, z_100_0)
    z_250_50 = nsqr(z_200_0, 50)
    z_250_0 = mul(z_250_50, z_50_0)
    z_255_5 = nsqr(z_250_0, 5)
    return mul(z_255_5, z11)  # z^(2^255 - 21) = z^(p-2)


def pow22523(a: jnp.ndarray) -> jnp.ndarray:
    """a^((p-5)/8) = a^(2^252 - 3) — the exponent of the combined
    square-root/division trick used by point decompression (RFC 8032
    §5.1.3): x = u*v^3 * (u*v^7)^((p-5)/8). Same addition chain as
    :func:`inv` up to the tail."""

    def nsqr(x, n):
        if n < 4:
            for _ in range(n):
                x = sqr(x)
            return x
        return lax.fori_loop(0, n, lambda _, v: sqr(v), x)

    z2 = sqr(a)  # 2
    z8 = nsqr(z2, 2)  # 8
    z9 = mul(a, z8)  # 9
    z11 = mul(z2, z9)  # 11
    z22 = sqr(z11)  # 22
    z_5_0 = mul(z9, z22)  # 2^5 - 2^0
    z_10_0 = mul(nsqr(z_5_0, 5), z_5_0)
    z_20_0 = mul(nsqr(z_10_0, 10), z_10_0)
    z_40_0 = mul(nsqr(z_20_0, 20), z_20_0)
    z_50_0 = mul(nsqr(z_40_0, 10), z_10_0)
    z_100_0 = mul(nsqr(z_50_0, 50), z_50_0)
    z_200_0 = mul(nsqr(z_100_0, 100), z_100_0)
    z_250_0 = mul(nsqr(z_200_0, 50), z_50_0)
    return mul(nsqr(z_250_0, 2), a)  # 2^252 - 3


# ------------------------------------------------------------- canonical


def _cond_sub_p(x: jnp.ndarray) -> jnp.ndarray:
    """Subtract p if x >= p (constant-time select)."""
    p = jnp.asarray(_P_LIMBS, dtype=jnp.int32)
    t = x - p
    t, borrow = _carry(t)  # borrow < 0 iff x < p
    keep = borrow < 0
    return jnp.where(keep[..., None], x, t)


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Fully reduce to the unique representative in [0, p)."""
    x = _normalize(x)  # value < 2^256 < 2p + eps
    x = _cond_sub_p(x)
    x = _cond_sub_p(x)
    return x


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field equality (handles redundant representations)."""
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == 0, axis=-1)


def select(mask: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Elementwise field-element select: mask ? a : b (mask shaped [...])."""
    return jnp.where(mask[..., None], a, b)
