"""Device-Merkleized account state: incremental hash tree + inclusion proofs.

PR 16's chained root proves whole-state equality only — nobody can check
ONE account without refetching all of them. This module Merkleizes the
packed account vector: a binary hash tree over the 8-byte-LE account
leaves, built as log-depth fixed-shape reductions in the one-launch
idiom (leaves padded to a power of two, every level one elementwise
combine), so each committed root supports O(log n) per-account
inclusion proofs.

The perf core is the **incremental update**: per-block apply marks the
dirty leaves straight from the scatter targets (sender + recipient
columns — pad rows point at account 0, and recomputing a clean leaf is
idempotent, so no mask is needed) and recomputes only the touched
root-paths: O(k log n) scatter/gather work instead of the O(n) full
rebuild, fused into the same launch as the block apply and the chain
fold (ops/ledger.py) so the Merkle root rides the device-resident root
chain with no extra dispatch.

Node arithmetic is NODE_WORDS uint32 lanes through the lowbias32
finalizer, mod 2^32, shared bit-identically by the NUMPY twin here
(host reference executor, light clients, chaos soak — all jax-free)
and the jnp twin (``*_jax``) the device kernel fuses. Like the root
chain it feeds (``fold_merkle`` -> ``fold_root``), this is
linear-algebraic, NOT a cryptographic hash — ROBUSTNESS.md
"Proof-serving doctrine" states the trust envelope.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from hyperdrive_tpu.ops.rootmix import (
    ROOT_WORDS,
    FMIX_A,
    FMIX_B,
    fold_root_np,
    root_bytes,
    root_words,
)

__all__ = [
    "NODE_WORDS",
    "MerkleProof",
    "tree_depth",
    "leaf_count",
    "build_tree_np",
    "update_tree_np",
    "merkle_root_np",
    "merkle_bytes",
    "fold_merkle_np",
    "prove_np",
    "fold_path_np",
    "verify_inclusion",
    "build_tree_jax",
    "update_tree_jax",
    "fold_merkle_jax",
]

_M32 = 0xFFFFFFFF

#: Every tree node is 4 little-endian uint32 words = 16 bytes, half the
#: chain-root width: a depth-17 proof (131072 accounts) is 272 bytes of
#: siblings, and the per-level device combine stays a 4-lane elementwise
#: op.
NODE_WORDS = 4

#: Leaf/combine multipliers (murmur3 c1/c2 and finalizer-family odd
#: constants, disjoint from the rootmix chain-fold set so a leaf can
#: never alias a fold term). Shared by the numpy and jnp twins.
LEAF_FOLD = 0xCC9E2D51
LEAF_IDX = 0x1B873593
SIB_LEFT = 0x85EBCA6B
SIB_RIGHT = 0xC2B2AE35
MERKLE_FOLD = 0x27D4EB2F


def leaf_count(accounts: int) -> int:
    """Leaves are padded to the next power of two (min 1) so every
    level halves exactly — the fixed-shape ladder of the build."""
    return 1 if accounts <= 1 else 1 << (accounts - 1).bit_length()


def tree_depth(accounts: int) -> int:
    """Number of combine levels (== sibling-path length) for a ledger
    of ``accounts`` accounts."""
    return (leaf_count(accounts) - 1).bit_length()


# ------------------------------------------------------------- numpy twin


def _fmix_np(x: np.ndarray) -> np.ndarray:
    x = np.asarray(x, dtype=np.uint32)
    x = x ^ (x >> np.uint32(16))
    x = (x * np.uint32(FMIX_A)).astype(np.uint32)
    x = x ^ (x >> np.uint32(15))
    x = (x * np.uint32(FMIX_B)).astype(np.uint32)
    x = x ^ (x >> np.uint32(16))
    return x


def leaf_words_np(idx, balances, stakes) -> np.ndarray:
    """Leaf nodes for accounts ``idx``: the (lo, hi) uint32 words of the
    8-byte-LE signed balance and stake (hi = sign extension, exactly the
    ``pack_state`` bytes) salted by account index and lane, finalized.
    Returns uint32[K, NODE_WORDS]."""
    idx = np.asarray(idx, dtype=np.uint32)
    b = np.asarray(balances, dtype=np.int32)
    s = np.asarray(stakes, dtype=np.int32)
    w = np.stack(
        [
            b.astype(np.uint32),
            (b >> 31).astype(np.uint32),
            s.astype(np.uint32),
            (s >> 31).astype(np.uint32),
        ],
        axis=-1,
    )
    k = np.arange(NODE_WORDS, dtype=np.uint32)
    return _fmix_np(
        w * np.uint32(LEAF_FOLD) + idx[:, None] * np.uint32(LEAF_IDX) + k
    )


def combine_np(left, right) -> np.ndarray:
    """Parent nodes from child pairs — position-asymmetric (left and
    right multiply by different constants) so a swapped sibling can
    never reproduce the parent. uint32[K, NODE_WORDS] each side."""
    left = np.asarray(left, dtype=np.uint32)
    right = np.asarray(right, dtype=np.uint32)
    k = np.arange(NODE_WORDS, dtype=np.uint32)
    return _fmix_np(
        left * np.uint32(SIB_LEFT) + right * np.uint32(SIB_RIGHT) + k
    )


def build_tree_np(balances, stakes) -> list:
    """Full O(n) rebuild: list of levels, leaves first, uint32
    [p >> d, NODE_WORDS] each, root level last ([1, NODE_WORDS]).
    Pad leaves are real leaves of zero-balance zero-stake accounts at
    their padded index — deterministic and never dirtied."""
    b = np.asarray(balances, dtype=np.int32)
    s = np.asarray(stakes, dtype=np.int32)
    p = leaf_count(b.shape[0])
    if p != b.shape[0]:
        b = np.pad(b, (0, p - b.shape[0]))
        s = np.pad(s, (0, p - s.shape[0]))
    levels = [leaf_words_np(np.arange(p, dtype=np.uint32), b, s)]
    while levels[-1].shape[0] > 1:
        cur = levels[-1]
        levels.append(combine_np(cur[0::2], cur[1::2]))
    return levels


def update_tree_np(tree: list, balances, stakes, dirty_idx) -> list:
    """Incremental O(k log n) update IN PLACE: recompute the dirty
    leaves from post-block state and walk only the touched root-paths
    up. Duplicate / already-clean indices are idempotent (a clean leaf
    recomputes to itself), so callers pass raw scatter targets.
    Returns ``tree`` for chaining."""
    idx = np.unique(np.asarray(dirty_idx, dtype=np.int64))
    b = np.asarray(balances, dtype=np.int32)
    s = np.asarray(stakes, dtype=np.int32)
    tree[0][idx] = leaf_words_np(idx.astype(np.uint32), b[idx], s[idx])
    for d in range(1, len(tree)):
        idx = np.unique(idx >> 1)
        child = tree[d - 1]
        tree[d][idx] = combine_np(child[2 * idx], child[2 * idx + 1])
    return tree


def merkle_root_np(tree) -> np.ndarray:
    """uint32[NODE_WORDS] — the tree's root node."""
    return np.asarray(tree[-1][0], dtype=np.uint32)


def merkle_bytes(words) -> bytes:
    """uint32[NODE_WORDS] -> the canonical 16-byte little-endian form
    (the obs/report rendering; the wire carries the words)."""
    return np.asarray(words, dtype=np.uint32).astype("<u4").tobytes()


def fold_merkle_np(digest_words, merkle_words) -> np.ndarray:
    """Chain the Merkle root into the state digest BEFORE the height
    fold: digest'_k = fmix(digest_k * C + merkle_{k mod 4} + k). Both
    executors fold this way, so ``root_h`` commits the tree and the
    flat digest together and a light client can rebind a proof to the
    certificate chain with O(1) extra witness words."""
    d = np.asarray(digest_words, dtype=np.uint32)
    m = np.asarray(merkle_words, dtype=np.uint32)
    k = np.arange(ROOT_WORDS, dtype=np.uint32)
    return _fmix_np(d * np.uint32(MERKLE_FOLD) + m[k % NODE_WORDS] + k)


def prove_np(tree, account: int) -> tuple:
    """O(log n) sibling path for ``account``, leaf level upward: a
    tuple of NODE_WORDS-int tuples, one per level below the root."""
    sibs = []
    i = int(account)
    for d in range(len(tree) - 1):
        sibs.append(tuple(int(w) for w in tree[d][i ^ 1]))
        i >>= 1
    return tuple(sibs)


def fold_path_np(leaf, account: int, siblings) -> np.ndarray:
    """Walk a sibling path from ``leaf`` back to the Merkle root —
    the light-client side of :func:`prove_np`. uint32[NODE_WORDS]."""
    cur = np.asarray(leaf, dtype=np.uint32).reshape(1, NODE_WORDS)
    i = int(account)
    for sib in siblings:
        sib = np.asarray(sib, dtype=np.uint32).reshape(1, NODE_WORDS)
        cur = combine_np(cur, sib) if i % 2 == 0 else combine_np(sib, cur)
        i >>= 1
    return cur[0]


@dataclass(frozen=True)
class MerkleProof:
    """Everything a stateless client needs to check one account against
    a trusted chained root: the claimed height, the previous chained
    root and post-block state digest as O(1) witness words, and the
    O(log n) sibling path. The client recomputes

      root'_h = fold_root(prev_root, h, fold_merkle(digest, path(leaf)))

    and compares against the certificate-chain root — zero trust in
    the serving replica."""

    height: int
    account: int
    balance: int
    stake: int
    prev_root: bytes  # 32 bytes — root_{h-1}
    digest: tuple  # ROOT_WORDS ints — post-block state digest
    siblings: tuple  # depth × NODE_WORDS-int tuples, leaf level first


#: Paths longer than this are rejected before any arithmetic — 2^64
#: accounts bounds every honest tree, so an attacker can't stall a
#: client with a mile-long forged path.
MAX_DEPTH = 64


def verify_inclusion(root: bytes, account: int, balance: int, stake: int,
                     proof: MerkleProof) -> bool:
    """True iff ``proof`` binds (account, balance, stake) into the
    trusted chained root ``root``. Detects stale roots (old-height
    witness against a fresh root), forged siblings, truncated paths,
    and wrong-leaf values — each perturbs the recomputed fold."""
    if (
        not isinstance(proof, MerkleProof)
        or proof.height < 1
        or account < 0
        or len(proof.prev_root) != 32
        or len(proof.digest) != ROOT_WORDS
        or len(proof.siblings) > MAX_DEPTH
        or account >> len(proof.siblings)
    ):
        return False
    leaf = leaf_words_np(
        np.asarray([account], dtype=np.uint32), [balance], [stake]
    )[0]
    mroot = fold_path_np(leaf, account, proof.siblings)
    folded = fold_merkle_np(
        np.asarray(proof.digest, dtype=np.uint32), mroot
    )
    r = fold_root_np(root_words(proof.prev_root), proof.height, folded)
    return root_bytes(r) == root


# --------------------------------------------------------------- jnp twin
#
# Imported lazily by ops/ledger.py's fused kernel; everything below
# mirrors the numpy twin mod 2^32 bit-for-bit. Kept in one module so a
# constant can never drift between the twins.


def _fmix_jax(x):
    import jax.numpy as jnp

    x = x ^ jnp.right_shift(x, 16)
    x = x * jnp.uint32(FMIX_A)
    x = x ^ jnp.right_shift(x, 15)
    x = x * jnp.uint32(FMIX_B)
    x = x ^ jnp.right_shift(x, 16)
    return x


def _leaf_words_jax(idx_u32, balances, stakes):
    import jax.numpy as jnp

    w = jnp.stack(
        [
            balances.astype(jnp.uint32),
            jnp.right_shift(balances, 31).astype(jnp.uint32),
            stakes.astype(jnp.uint32),
            jnp.right_shift(stakes, 31).astype(jnp.uint32),
        ],
        axis=-1,
    )
    k = jnp.arange(NODE_WORDS, dtype=jnp.uint32)
    return _fmix_jax(
        w * jnp.uint32(LEAF_FOLD)
        + idx_u32[:, None] * jnp.uint32(LEAF_IDX)
        + k
    )


def _combine_jax(left, right):
    import jax.numpy as jnp

    k = jnp.arange(NODE_WORDS, dtype=jnp.uint32)
    return _fmix_jax(
        left * jnp.uint32(SIB_LEFT) + right * jnp.uint32(SIB_RIGHT) + k
    )


def build_tree_jax(balances, stakes):
    """Full rebuild on device: log-depth fixed-shape strided combines.
    Returns the tuple-of-levels pytree the fused kernel threads."""
    import jax.numpy as jnp

    a = balances.shape[0]
    p = leaf_count(a)
    if p != a:
        balances = jnp.pad(balances, (0, p - a))
        stakes = jnp.pad(stakes, (0, p - a))
    levels = [
        _leaf_words_jax(jnp.arange(p, dtype=jnp.uint32), balances, stakes)
    ]
    while levels[-1].shape[0] > 1:
        cur = levels[-1]
        levels.append(_combine_jax(cur[0::2], cur[1::2]))
    return tuple(levels)


def update_tree_jax(tree, balances, stakes, dirty_idx):
    """Incremental update on device: one [K] leaf scatter plus one
    [K] gather-combine-scatter per level — O(k log n) work in the same
    launch as the block apply. Duplicate dirty indices scatter
    identical values (each recomputed from the same post-block state),
    so the result is deterministic without a dedup pass the device
    can't shape. Returns a new tuple of levels (functional)."""
    idx = dirty_idx.astype("int32")
    new0 = tree[0].at[idx].set(
        _leaf_words_jax(idx.astype("uint32"), balances[idx], stakes[idx])
    )
    levels = [new0]
    for d in range(1, len(tree)):
        idx = idx // 2
        child = levels[-1]
        levels.append(
            tree[d]
            .at[idx]
            .set(_combine_jax(child[2 * idx], child[2 * idx + 1]))
        )
    return tuple(levels)


def fold_merkle_jax(digest_words, merkle_words):
    import jax.numpy as jnp

    k = jnp.arange(ROOT_WORDS, dtype=jnp.uint32)
    return _fmix_jax(
        digest_words * jnp.uint32(MERKLE_FOLD)
        + merkle_words[k % NODE_WORDS]
        + k
    )
