"""Test doubles and edge-case-biased random generators.

Capability parity with the reference's ``process/processutil`` package:
callback-struct mocks for every DI seam (nil-safe: unset callbacks are
no-ops) and random generators where roughly a third of draws are adversarial
edge cases (-1, 0, int64 extremes, all-zero / all-0xFF values) —
reference: processutil/processutil.go:135-353.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Optional

from hyperdrive_tpu.messages import Precommit, Prevote, Propose
from hyperdrive_tpu.state import State
from hyperdrive_tpu.types import (
    INT64_MAX,
    INVALID_ROUND,
    NIL_VALUE,
    Height,
    Round,
    Signatory,
    Step,
    Value,
)

__all__ = [
    "BroadcasterCallbacks",
    "CommitterCallback",
    "MockProposer",
    "MockValidator",
    "MockScheduler",
    "CatcherCallbacks",
    "TimerCallbacks",
    "random_height",
    "random_round",
    "random_step",
    "random_value",
    "random_good_value",
    "random_signatory",
    "random_state",
    "random_propose",
    "random_prevote",
    "random_precommit",
]


# ------------------------------------------------------------------- mocks


@dataclass
class BroadcasterCallbacks:
    """Nil-safe broadcast hooks (reference: processutil/processutil.go:12-44)."""

    on_propose: Optional[Callable[[Propose], None]] = None
    on_prevote: Optional[Callable[[Prevote], None]] = None
    on_precommit: Optional[Callable[[Precommit], None]] = None

    def broadcast_propose(self, propose: Propose) -> None:
        if self.on_propose is not None:
            self.on_propose(propose)

    def broadcast_prevote(self, prevote: Prevote) -> None:
        if self.on_prevote is not None:
            self.on_prevote(prevote)

    def broadcast_precommit(self, precommit: Precommit) -> None:
        if self.on_precommit is not None:
            self.on_precommit(precommit)


@dataclass
class CommitterCallback:
    """Commit hook returning (new_f, new_scheduler)
    (reference: processutil/processutil.go:47-58)."""

    on_commit: Optional[Callable[[Height, Value], tuple[int, object]]] = None

    def commit(self, height: Height, value: Value):
        if self.on_commit is not None:
            return self.on_commit(height, value)
        return 0, None


@dataclass
class MockProposer:
    """Fixed- or callback-valued proposer
    (reference: processutil/processutil.go:61-75)."""

    value: Optional[Value] = None
    fn: Optional[Callable[[Height, Round], Value]] = None

    def propose(self, height: Height, round: Round) -> Value:
        if self.fn is not None:
            return self.fn(height, round)
        return self.value if self.value is not None else NIL_VALUE


@dataclass
class MockValidator:
    """Constant or callback validity predicate
    (reference: processutil/processutil.go:78-95)."""

    ok: bool = True
    fn: Optional[Callable[[Height, Round, Value], bool]] = None

    def valid(self, height: Height, round: Round, value: Value) -> bool:
        if self.fn is not None:
            return self.fn(height, round, value)
        return self.ok


@dataclass
class MockScheduler:
    """Always elects one signatory."""

    whoami: Signatory = b"\x00" * 32

    def schedule(self, height: Height, round: Round) -> Signatory:
        return self.whoami


@dataclass
class CatcherCallbacks:
    """Nil-safe misbehaviour hooks (reference: processutil/processutil.go:98-130)."""

    on_double_propose: Optional[Callable[[Propose, Propose], None]] = None
    on_double_prevote: Optional[Callable[[Prevote, Prevote], None]] = None
    on_double_precommit: Optional[Callable[[Precommit, Precommit], None]] = None
    on_out_of_turn_propose: Optional[Callable[[Propose], None]] = None

    def catch_double_propose(self, new: Propose, existing: Propose) -> None:
        if self.on_double_propose is not None:
            self.on_double_propose(new, existing)

    def catch_double_prevote(self, new: Prevote, existing: Prevote) -> None:
        if self.on_double_prevote is not None:
            self.on_double_prevote(new, existing)

    def catch_double_precommit(self, new: Precommit, existing: Precommit) -> None:
        if self.on_double_precommit is not None:
            self.on_double_precommit(new, existing)

    def catch_out_of_turn_propose(self, propose: Propose) -> None:
        if self.on_out_of_turn_propose is not None:
            self.on_out_of_turn_propose(propose)


@dataclass
class TimerCallbacks:
    """Records or forwards timeout scheduling requests."""

    on_propose: Optional[Callable[[Height, Round], None]] = None
    on_prevote: Optional[Callable[[Height, Round], None]] = None
    on_precommit: Optional[Callable[[Height, Round], None]] = None

    def timeout_propose(self, height: Height, round: Round) -> None:
        if self.on_propose is not None:
            self.on_propose(height, round)

    def timeout_prevote(self, height: Height, round: Round) -> None:
        if self.on_prevote is not None:
            self.on_prevote(height, round)

    def timeout_precommit(self, height: Height, round: Round) -> None:
        if self.on_precommit is not None:
            self.on_precommit(height, round)


# -------------------------------------------------------------- generators
# ~30% of draws are adversarial edge cases, mirroring the reference's
# distribution (processutil/processutil.go:135-353).


def random_height(rng: random.Random) -> Height:
    r = rng.random()
    if r < 0.1:
        return -1
    if r < 0.2:
        return 0
    if r < 0.3:
        return INT64_MAX
    return rng.randint(1, 1 << 40)


def random_round(rng: random.Random) -> Round:
    r = rng.random()
    if r < 0.1:
        return INVALID_ROUND
    if r < 0.2:
        return 0
    if r < 0.3:
        return INT64_MAX
    return rng.randint(0, 1 << 40)


def random_step(rng: random.Random) -> Step:
    r = rng.random()
    if r < 0.25:
        return Step.PROPOSING
    if r < 0.5:
        return Step.PREVOTING
    if r < 0.75:
        return Step.PRECOMMITTING
    # An out-of-range step is representable in Go; here Step is a real enum,
    # so the worst legal draw is the highest step.
    return Step.PRECOMMITTING


def random_value(rng: random.Random) -> Value:
    r = rng.random()
    if r < 0.15:
        return NIL_VALUE
    if r < 0.3:
        return b"\xff" * 32
    return rng.randbytes(32)


def random_good_value(rng: random.Random) -> Value:
    """A uniformly random non-nil value."""
    while True:
        v = rng.randbytes(32)
        if v != NIL_VALUE:
            return v


def random_signatory(rng: random.Random) -> Signatory:
    return rng.randbytes(32)


def random_propose(rng: random.Random) -> Propose:
    return Propose(
        height=random_height(rng),
        round=random_round(rng),
        valid_round=random_round(rng),
        value=random_value(rng),
        sender=random_signatory(rng),
    )


def random_prevote(rng: random.Random) -> Prevote:
    return Prevote(
        height=random_height(rng),
        round=random_round(rng),
        value=random_value(rng),
        sender=random_signatory(rng),
    )


def random_precommit(rng: random.Random) -> Precommit:
    return Precommit(
        height=random_height(rng),
        round=random_round(rng),
        value=random_value(rng),
        sender=random_signatory(rng),
    )


def random_state(rng: random.Random) -> State:
    st = State(
        current_height=random_height(rng),
        current_round=random_round(rng),
        current_step=random_step(rng),
        locked_value=random_value(rng),
        locked_round=random_round(rng),
        valid_value=random_value(rng),
        valid_round=random_round(rng),
    )
    for _ in range(rng.randint(0, 4)):
        rnd = rng.randint(0, 100)
        st.propose_logs[rnd] = random_propose(rng)
        st.propose_is_valid[rnd] = rng.random() < 0.5
    for _ in range(rng.randint(0, 4)):
        rnd = rng.randint(0, 100)
        votes = {}
        for _ in range(rng.randint(0, 4)):
            pv = random_prevote(rng)
            votes[pv.sender] = pv
        st.prevote_logs[rnd] = votes
    for _ in range(rng.randint(0, 4)):
        rnd = rng.randint(0, 100)
        votes = {}
        for _ in range(rng.randint(0, 4)):
            pc = random_precommit(rng)
            votes[pc.sender] = pc
        st.precommit_logs[rnd] = votes
    for _ in range(rng.randint(0, 4)):
        st.once_flags[rng.randint(0, 100)] = rng.randint(0, 7)
    for _ in range(rng.randint(0, 4)):
        st.trace_logs[rng.randint(0, 100)] = {
            random_signatory(rng) for _ in range(rng.randint(0, 4))
        }
    # The logs above were populated directly; bring the derived tallies in
    # sync so the state behaves like one built through add_prevote/precommit.
    st.rebuild_counts()
    return st
