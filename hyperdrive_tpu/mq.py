"""Per-sender message queues sorted by (height, round), with bounded capacity.

Capability parity with the reference's ``mq/mq.go``: every sender gets a
dedicated queue kept in ascending (height, round) order (FIFO among equal
keys), bounded at ``max_capacity`` messages to stop far-future flooding from
exhausting memory; :meth:`MessageQueue.consume` drains everything at or below
a height through per-type callbacks, applying a sender whitelist. Queues do
no deduplication and are not safe for concurrent use (the replica serializes
access).

TPU extension: :meth:`MessageQueue.drain_window` pops up to ``window`` ready
messages *without* dispatching them, so the replica can hand the whole window
to the batched signature Verifier in one device launch and then feed the
survivors to the Process in order — the "batched drain" of SURVEY.md §7.1(4).
"""

from __future__ import annotations

import heapq
from bisect import bisect_right
from typing import Callable, Iterable

from hyperdrive_tpu.messages import Precommit, Prevote, Propose
from hyperdrive_tpu.types import Height, Signatory

__all__ = ["MessageQueue", "DEFAULT_MAX_CAPACITY"]

#: Default per-sender capacity (reference: mq/opt.go:19).
DEFAULT_MAX_CAPACITY = 1000

Message = Propose | Prevote | Precommit


class MessageQueue:
    """Sorted, bounded, per-sender buffering of consensus messages."""

    __slots__ = ("max_capacity", "_queues")

    def __init__(self, max_capacity: int = DEFAULT_MAX_CAPACITY):
        self.max_capacity = int(max_capacity)
        self._queues: dict[Signatory, list[Message]] = {}

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    # ------------------------------------------------------------------ insert

    def insert_propose(self, propose: Propose) -> None:
        """Assumes the sender was already authenticated and filtered
        (reference: mq/mq.go:85-86)."""
        self._insert(propose)

    def insert_prevote(self, prevote: Prevote) -> None:
        self._insert(prevote)

    def insert_precommit(self, precommit: Precommit) -> None:
        self._insert(precommit)

    def _insert(self, msg: Message) -> None:
        q = self._queues.setdefault(msg.sender, [])
        # Insert after all entries with the same (height, round) so equal-key
        # messages stay FIFO (reference: sort.Search semantics, mq/mq.go:117-127).
        idx = bisect_right(q, (msg.height, msg.round), key=lambda m: (m.height, m.round))
        q.insert(idx, msg)
        # Drop the far-future tail when over capacity (reference: mq/mq.go:139-142).
        if len(q) > self.max_capacity:
            del q[self.max_capacity :]

    # ----------------------------------------------------------------- consume

    def consume(
        self,
        height: Height,
        propose: Callable[[Propose], None],
        prevote: Callable[[Prevote], None],
        precommit: Callable[[Precommit], None],
        procs_allowed: Iterable[Signatory],
    ) -> int:
        """Dispatch and drop every queued message with height <= ``height``.

        Returns the number of messages *consumed* — including messages
        dropped by the whitelist, which still count (reference: mq/mq.go:36-66
        increments ``n`` before the whitelist check returns).
        """
        allowed = (
            procs_allowed
            if isinstance(procs_allowed, (set, frozenset, dict))
            else set(procs_allowed)
        )
        # Two-phase drain: detach each sender's eligible prefix *before*
        # dispatching it, so callbacks that reentrantly insert messages (a
        # synchronous loopback broadcaster) cannot corrupt the iteration.
        # The Go reference is immune only because broadcasts hop through a
        # channel; the synchronous driving mode must be safe on its own.
        n = 0
        for sender in list(self._queues.keys()):
            q = self._queues.get(sender)
            if not q:
                continue
            i = 0
            while i < len(q) and q[i].height <= height:
                i += 1
            if not i:
                continue
            batch = q[:i]
            del q[:i]
            n += len(batch)
            if sender not in allowed:
                continue
            for msg in batch:
                if isinstance(msg, Propose):
                    propose(msg)
                elif isinstance(msg, Prevote):
                    prevote(msg)
                else:
                    precommit(msg)
        return n

    def drain_window(self, height: Height, window: int) -> list[Message]:
        """Pop up to ``window`` messages with height <= ``height``, in
        **global ascending (height, round) order across senders**, without
        dispatching them.

        This is the wide input for the batched TPU Verifier: the caller
        verifies the window as one launch and feeds survivors to the
        Process. Whitelisting is the caller's job (it already is for
        :meth:`consume`'s callback contract).

        Ordering contract: a capped window always contains the globally
        smallest (height, round) keys among eligible messages, merged
        across the per-sender queues (stable: FIFO within a sender, and
        senders tie-break in queue-creation order). This means the Process
        can never be fed a later round before an earlier one within a
        window — the interleave a per-message consume loop would produce —
        so batching changes *when* rules fire, never the key order votes
        arrive in.
        """
        # k-way merge of the per-sender eligible prefixes. Entries carry
        # (key..., sender_order, index) so heap comparison never reaches
        # the non-comparable queue object and equal keys stay deterministic.
        heap: list[tuple[int, int, int, int, list]] = []
        for order, q in enumerate(self._queues.values()):
            if q and q[0].height <= height:
                heap.append((q[0].height, q[0].round, order, 0, q))
        heapq.heapify(heap)

        out: list[Message] = []
        taken: dict[int, tuple[list, int]] = {}
        while heap and len(out) < window:
            h, r, order, i, q = heapq.heappop(heap)
            out.append(q[i])
            taken[order] = (q, i + 1)
            i += 1
            if i < len(q) and q[i].height <= height:
                heapq.heappush(heap, (q[i].height, q[i].round, order, i, q))
        for q, count in taken.values():
            del q[:count]
        return out

    # -------------------------------------------------------------------- drop

    def drop_messages_below_height(self, height: Height) -> None:
        """Forget everything below ``height`` (resync support; reference:
        mq/mq.go:70-83)."""
        for sender, q in self._queues.items():
            i = 0
            while i < len(q) and q[i].height < height:
                i += 1
            if i:
                del q[:i]
