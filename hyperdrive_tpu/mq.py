"""Per-sender message queues sorted by (height, round), with bounded capacity.

Capability parity with the reference's ``mq/mq.go``: every sender gets a
dedicated queue kept in ascending (height, round) order (FIFO among equal
keys), bounded at ``max_capacity`` messages to stop far-future flooding from
exhausting memory; :meth:`MessageQueue.consume` drains everything at or below
a height through per-type callbacks, applying a sender whitelist. Queues do
no deduplication and are not safe for concurrent use (the replica serializes
access).

TPU extension: :meth:`MessageQueue.drain_window` pops up to ``window`` ready
messages *without* dispatching them, so the replica can hand the whole window
to the batched signature Verifier in one device launch and then feed the
survivors to the Process in order — the "batched drain" of SURVEY.md §7.1(4).
"""

from __future__ import annotations

import heapq
from bisect import bisect_right
from typing import Callable, Iterable

from hyperdrive_tpu.messages import Precommit, Prevote, Propose
from hyperdrive_tpu.obs.recorder import NULL_BOUND
from hyperdrive_tpu.types import Height, Signatory

__all__ = ["MessageQueue", "DEFAULT_MAX_CAPACITY"]

#: Default per-sender capacity (reference: mq/opt.go:19).
DEFAULT_MAX_CAPACITY = 1000

Message = Propose | Prevote | Precommit


class MessageQueue:
    """Sorted, bounded, per-sender buffering of consensus messages.

    A persistent head-heap indexes each non-empty sender queue by its head
    (height, round) key, so :meth:`consume` and :meth:`drain_window` cost
    O(eligible log senders) instead of scanning every sender — the flush
    loop runs after *every* handled message (replica/replica.go:148), so a
    full scan per flush is O(n) per message and dominates at n=256.
    Heap entries are lazily invalidated: ``_head_key`` records the key each
    sender is currently registered under; popped entries that disagree are
    stale and dropped.
    """

    __slots__ = (
        "max_capacity",
        "_queues",
        "_order",
        "_heads",
        "_head_key",
        "obs",
        "admission",
    )

    def __init__(self, max_capacity: int = DEFAULT_MAX_CAPACITY):
        self.max_capacity = int(max_capacity)
        #: Flight-recorder handle (obs/recorder.py); the owning replica
        #: rebinds it. Only the overflow branch ever touches it.
        self.obs = NULL_BOUND
        #: Optional AdmissionGate (load/backpressure.py). When set, every
        #: insert consults it before buffering — under pressure the queue
        #: sheds classified traffic instead of growing toward the far-
        #: future capacity drop. None = admit everything (the default).
        self.admission = None
        self._queues: dict[Signatory, list[Message]] = {}
        #: sender -> stable tiebreak index (queue-creation order).
        self._order: dict[Signatory, int] = {}
        #: lazy min-heap of (height, round, order, sender) head keys.
        self._heads: list[tuple[Height, int, int, Signatory]] = []
        #: sender -> the (height, round, order) its live heap entry carries.
        self._head_key: dict[Signatory, tuple[Height, int, int]] = {}

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _register_head(self, sender: Signatory) -> None:
        """(Re)register ``sender``'s current queue head in the heap."""
        q = self._queues.get(sender)
        if not q:
            self._head_key.pop(sender, None)
            return
        key = (q[0].height, q[0].round, self._order[sender])
        if self._head_key.get(sender) != key:
            self._head_key[sender] = key
            heapq.heappush(self._heads, (*key, sender))

    def _pop_eligible_sender(self, height: Height):
        """Pop the sender with the smallest head key <= ``height``; returns
        (sender, queue) or None. Discards stale entries as it goes."""
        while self._heads:
            h, r, order, sender = self._heads[0]
            if self._head_key.get(sender) != (h, r, order):
                heapq.heappop(self._heads)  # stale
                continue
            if h > height:
                return None
            heapq.heappop(self._heads)
            del self._head_key[sender]
            return sender, self._queues[sender]
        return None

    def _peek_head(self):
        """The smallest live head key (height, round, order), or None."""
        while self._heads:
            h, r, order, sender = self._heads[0]
            if self._head_key.get(sender) != (h, r, order):
                heapq.heappop(self._heads)  # stale
                continue
            return (h, r, order)
        return None

    # ------------------------------------------------------------------ insert

    def insert_propose(self, propose: Propose) -> None:
        """Assumes the sender was already authenticated and filtered
        (reference: mq/mq.go:85-86)."""
        self._insert(propose)

    def insert_prevote(self, prevote: Prevote) -> None:
        self._insert(prevote)

    def insert_precommit(self, precommit: Precommit) -> None:
        self._insert(precommit)

    def order_of(self, sender: Signatory) -> int:
        """Stable per-sender tie-break index, registered on first use.
        Shared with the replica's burst fast lane so lane and queue
        messages from one sender sort under one identity."""
        o = self._order.get(sender)
        if o is None:
            o = self._order[sender] = len(self._order)
        return o

    def _insert(self, msg: Message) -> None:
        if self.admission is not None and not self.admission.admit(msg):
            return
        q = self._queues.get(msg.sender)
        if q is None:
            q = self._queues[msg.sender] = []
            self.order_of(msg.sender)
        # Fast path: consensus traffic arrives overwhelmingly in ascending
        # (height, round) order, so most inserts are appends — skip the
        # binary search (and its per-probe key lambda) entirely.
        if not q:
            q.append(msg)
            idx = 0
        else:
            last = q[-1]
            if (last.height, last.round) <= (msg.height, msg.round):
                q.append(msg)
                idx = len(q) - 1
            else:
                # Insert after all entries with the same (height, round) so
                # equal-key messages stay FIFO (reference: sort.Search
                # semantics, mq/mq.go:117-127).
                idx = bisect_right(
                    q,
                    (msg.height, msg.round),
                    key=lambda m: (m.height, m.round),
                )
                q.insert(idx, msg)
        # Drop the far-future tail when over capacity (reference: mq/mq.go:139-142).
        if len(q) > self.max_capacity:
            if self.obs is not NULL_BOUND:
                dropped = q[self.max_capacity]
                self.obs.emit(
                    "mq.drop",
                    dropped.height,
                    dropped.round,
                    len(q) - self.max_capacity,
                )
            del q[self.max_capacity :]
        if idx == 0:
            self._register_head(msg.sender)

    # ----------------------------------------------------------------- consume

    def consume(
        self,
        height: Height,
        propose: Callable[[Propose], None],
        prevote: Callable[[Prevote], None],
        precommit: Callable[[Precommit], None],
        procs_allowed: Iterable[Signatory],
    ) -> int:
        """Dispatch and drop every queued message with height <= ``height``.

        Returns the number of messages *consumed* — including messages
        dropped by the whitelist, which still count (reference: mq/mq.go:36-66
        increments ``n`` before the whitelist check returns).
        """
        allowed = (
            procs_allowed
            if isinstance(procs_allowed, (set, frozenset, dict))
            else set(procs_allowed)
        )
        # Two-phase drain: detach every eligible prefix *before* dispatching,
        # so callbacks that reentrantly insert messages (a synchronous
        # loopback broadcaster) cannot corrupt the iteration. The Go
        # reference is immune only because broadcasts hop through a channel;
        # the synchronous driving mode must be safe on its own.
        n = 0
        batches: list[list[Message]] = []
        while True:
            popped = self._pop_eligible_sender(height)
            if popped is None:
                break
            sender, q = popped
            i = 0
            while i < len(q) and q[i].height <= height:
                i += 1
            batch = q[:i]
            del q[:i]
            self._register_head(sender)
            n += len(batch)
            if sender in allowed:
                batches.append(batch)
        for batch in batches:
            for msg in batch:
                if isinstance(msg, Propose):
                    propose(msg)
                elif isinstance(msg, Prevote):
                    prevote(msg)
                else:
                    precommit(msg)
        return n

    def drain_window(self, height: Height, window: int) -> list[Message]:
        """Pop up to ``window`` messages with height <= ``height``, in
        **global ascending (height, round) order across senders**, without
        dispatching them.

        This is the wide input for the batched TPU Verifier: the caller
        verifies the window as one launch and feeds survivors to the
        Process. Whitelisting is the caller's job (it already is for
        :meth:`consume`'s callback contract).

        Ordering contract: a capped window always contains the globally
        smallest (height, round) keys among eligible messages, merged
        across the per-sender queues (stable: FIFO within a sender, and
        senders tie-break in queue-creation order). This means the Process
        can never be fed a later round before an earlier one within a
        window — the interleave a per-message consume loop would produce —
        so batching changes *when* rules fire, never the key order votes
        arrive in.
        """
        # k-way merge over the persistent head-heap: pop the smallest-headed
        # sender, take its run of messages while they stay eligible and
        # ahead of the next-best head, then re-register its new head.
        out: list[Message] = []
        while len(out) < window:
            popped = self._pop_eligible_sender(height)
            if popped is None:
                break
            sender, q = popped
            my_order = self._order[sender]
            nxt = self._peek_head()
            i = 0
            while i < len(q) and len(out) < window and q[i].height <= height:
                if nxt is not None and (q[i].height, q[i].round, my_order) > nxt:
                    break
                out.append(q[i])
                i += 1
            del q[:i]
            self._register_head(sender)
        return out

    def has_eligible(self, height: Height) -> bool:
        """True iff some queued message has height <= ``height`` — an O(1)
        peek the burst settle uses to skip the drain/merge machinery for
        replicas with an empty backlog (the common case)."""
        head = self._peek_head()
        return head is not None and head[0] <= height

    def drain_all(self, height: Height) -> list[Message]:
        """Pop EVERY eligible message (height <= ``height``) in the same
        global ascending (height, round) order as :meth:`drain_window`.

        The burst drain: one settle pass takes a replica's whole backlog,
        so per-message heap maintenance is pure overhead — this does one
        scan over the sender queues plus one C-level sort of the eligible
        runs (timsort exploits the per-sender sortedness), which profiles
        several times faster than the k-way merge at superstep batch sizes.
        """
        runs: list[tuple[int, list[Message]]] = []
        for sender, q in self._queues.items():
            if not q or q[0].height > height:
                continue
            i = 0
            while i < len(q) and q[i].height <= height:
                i += 1
            runs.append((self._order[sender], q[:i]))
            del q[:i]
            self._register_head(sender)
        if not runs:
            return []
        if len(runs) == 1:
            return runs[0][1]
        # (h, r, sender-order, run-seq) is unique per message, so the bare
        # tuple sort never falls through to comparing messages, and it
        # reproduces drain_window's contract exactly: global (h, r) order,
        # FIFO within a sender, senders tie-broken by creation order.
        keyed = [
            (m.height, m.round, order, j, m)
            for order, run in runs
            for j, m in enumerate(run)
        ]
        keyed.sort()
        return [t[4] for t in keyed]

    # -------------------------------------------------------------------- drop

    def drop_messages_below_height(self, height: Height) -> None:
        """Forget everything below ``height`` (resync support; reference:
        mq/mq.go:70-83)."""
        for sender, q in self._queues.items():
            i = 0
            while i < len(q) and q[i].height < height:
                i += 1
            if i:
                del q[:i]
                self._register_head(sender)

    def clear(self) -> None:
        """Forget every queued message — the crash-restart revive path
        (Replica.restore): buffered messages are volatile state that
        died with the process. The ``_order`` tie-break map is kept: it
        is derived from the whitelist registration order at construction,
        not from traffic, and a restored replica must keep draining in
        the same deterministic order as the rest of the network."""
        self._queues.clear()
        self._heads.clear()
        self._head_key.clear()
