"""Loopback-TCP binding of the Broadcaster seam.

The reference leaves networking entirely to the embedding application —
the ``Broadcaster`` DI interface IS the whole communication backend
contract (broadcast to all incl. self, eventual delivery, no ordering;
reference: process/process.go:47-60), and its tests wire it to an
in-memory queue (replica/replica_test.go:174-208). This module turns that
seam into a PROOF over real sockets: a full-mesh, length-framed TCP
transport driving threaded replicas with real wall-clock
:class:`~hyperdrive_tpu.timer.LinearTimer` timeouts — consensus across OS
process boundaries with no shared memory.

Scope (deliberate): the control plane for small messages. Bulk tensor
traffic (vote batches, signature limbs) belongs on ICI/DCN device
collectives (:mod:`hyperdrive_tpu.parallel`); this transport carries the
consensus envelopes a deployment would gossip over its host network.

Wire format: 4-byte little-endian length + the signed message envelope
(:func:`hyperdrive_tpu.messages.marshal_message`). Malformed frames from
a peer are dropped (DoS-safe: the codec never raises past the budget, and
a framing error closes only that peer's connection).
"""

from __future__ import annotations

import queue
import random
import socket
import struct
import threading

from hyperdrive_tpu.analysis.annotations import wire_codec, wire_entry
from hyperdrive_tpu.analysis.sanitizer import maybe_wire_reader
from hyperdrive_tpu.codec import SerdeError, Writer
from hyperdrive_tpu.messages import (
    Precommit,
    Propose,
    Prevote,
    marshal_message,
    unmarshal_message,
)
from hyperdrive_tpu.obs.tracectx import (
    TRACE_MAGIC,
    note_recv as note_trace_recv,
    split_frame as split_trace_frame,
)
from hyperdrive_tpu.utils.log import get_logger, kv as _kv

__all__ = [
    "TcpBroadcaster",
    "TcpNode",
    "encode_frame",
    "reconnect_schedule",
    "FlightRecorder",
    "replay_flight",
]

_LEN = struct.Struct("<I")
_MAX_FRAME = 1 << 20  # 1 MiB: far above any consensus envelope
#: Per-peer outbound buffer (frames). A peer that stays unreachable longer
#: than this many broadcasts sees the oldest frames dropped — best-effort,
#: matching the reference's trust model where eventual delivery is the
#: embedding network's promise, not the library's
#: (process/process.go:47-60).
_PEER_QUEUE = 4096


@wire_codec(tag="msg.envelope", max_bytes=_MAX_FRAME)
def encode_frame(msg) -> bytes:
    w = Writer()
    marshal_message(msg, w)
    payload = w.data()
    return _LEN.pack(len(payload)) + payload


def reconnect_schedule(seed: int, key, *, base: float = 0.05,
                       factor: float = 2.0, cap: float = 2.0,
                       jitter: float = 0.5):
    """Seeded exponential-backoff delays for one peer's dialer.

    Yields connect-retry sleeps: an exponential ramp from ``base``
    (×``factor`` per failed attempt) HARD-CLAMPED at ``cap``, then
    stretched by up to ``jitter`` (cap-before-jitter, the
    :mod:`hyperdrive_tpu.timer` shaping convention — jitter widens the
    spread instead of vanishing at the cap, so a mesh of nodes retrying
    a rebooted peer never thundering-herds it). Every yield is
    therefore in ``[delay, delay * (1 + jitter)]`` with ``delay <=
    cap`` — the ceiling is a spec'd bound, not an emergent one, and the
    ramp is computed incrementally so a long outage never evaluates an
    unbounded ``factor ** attempt``. Deterministic per ``(seed, key)``:
    the test suite asserts the exact schedule, and a node re-creates
    the generator after each successful connect so every outage
    replays the same bounded ramp. The ceiling is configurable per
    node (``TcpNode(backoff={"cap": ...})``).
    """
    if base <= 0.0 or cap < base:
        raise ValueError(
            f"backoff needs 0 < base <= cap, got base={base} cap={cap}"
        )
    if factor < 1.0 or jitter < 0.0:
        raise ValueError(
            f"backoff needs factor >= 1 and jitter >= 0, got "
            f"factor={factor} jitter={jitter}"
        )
    # String seeding hashes through SHA-512 inside random.seed — stable
    # across processes (tuple seeding is deprecated, and hash() of the
    # host string is randomized per process).
    rng = random.Random(f"reconnect:{seed}:{key!r}")
    delay = base
    while True:
        yield delay * (1.0 + jitter * rng.random())
        delay = min(cap, delay * factor)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class TcpNode:
    """One process's endpoint of the full-mesh broadcast transport.

    Hosts any number of local replicas. ``broadcast`` serializes once,
    delivers to every LOCAL replica directly (the Broadcaster contract
    includes the sender), and writes the frame to every remote peer's
    connection. Inbound frames are decoded and delivered to every local
    replica. Peer connections are dialed lazily with retries, so nodes
    may start in any order.
    """

    def __init__(self, listen_port: int = 0, host: str = "127.0.0.1",
                 obs=None, admission=None, registry=None, seed: int = 0,
                 backoff=None, trace=None):
        from hyperdrive_tpu.obs.recorder import NULL_BOUND

        self._host = host
        #: Optional :class:`~hyperdrive_tpu.obs.tracectx.TraceSource`:
        #: when set, every outbound frame carries a 21-byte causal
        #: stamp ahead of the envelope (emitting ``trace.send``) and
        #: inbound stamped frames are stripped + marked ``trace.recv``.
        #: Unstamped peers interoperate unchanged — the stamp magic
        #: byte cannot begin a legal envelope.
        self.trace = trace
        #: Reconnect-backoff shaping overrides (``base`` / ``factor`` /
        #: ``cap`` / ``jitter`` kwargs of :func:`reconnect_schedule`).
        #: The cap is a per-node deployment knob: a LAN mesh wants a
        #: tight ceiling (sub-second reconnects), a WAN deployment a
        #: generous one. Validated eagerly — a bad shape fails at node
        #: construction, not on the first outage.
        self.backoff = dict(backoff or {})
        next(reconnect_schedule(int(seed), None, **self.backoff))
        #: Flight-recorder handle for wire anomalies (oversize frames,
        #: malformed envelopes, shed backlog). The node is multithreaded,
        #: so callers must pass a handle bound to a threadsafe Recorder.
        self.obs = obs if obs is not None else NULL_BOUND
        self._obs_null = NULL_BOUND
        #: Optional AdmissionGate (load/backpressure.py) applied to WIRE
        #: ingress only: frames decoded off peer connections pass through
        #: it before delivery, attributed to the sending peer for
        #: fairness; a node's own broadcasts self-deliver ungated (a
        #: replica never sheds its own votes). Build the gate with
        #: ``threadsafe=True`` — read loops run one thread per peer.
        self.admission = admission
        #: Optional metrics Registry: shed/stale frames count here by
        #: class so overload runs are diagnosable from exported metrics
        #: alone (``wire.frame.shed`` labeled counter).
        self.registry = registry
        #: Seed for the per-peer reconnect backoff schedules.
        self.seed = int(seed)
        #: Wire-path epoch state (epochs.py key rotation): the current
        #: table generation, verifiers to rotate on epoch switch, and
        #: retired signatory -> first-stale-height bounds. Frames signed
        #: under a retired generation are counted and dropped — never
        #: fatal to the peer's connection (a laggard peer is lagging,
        #: not hostile).
        self.generation = 0
        self.retired: dict = {}
        self.stale_frames = 0
        #: Wire-anomaly counters (guarded by ``_lock``): frames dropped
        #: for a malformed envelope / an oversize length header. The
        #: chaos soak's frame-fuzz leg asserts on these — a mutated
        #: frame must land HERE, never in a crashed read thread.
        self.malformed_frames = 0
        self.oversize_frames = 0
        self._verifiers: list = []
        self._replicas: list = []
        #: peer key -> outbound frame queue, drained by a dedicated sender
        #: thread per peer — a dead or slow peer can never stall the
        #: broadcasting replica threads or the other peers.
        self._peer_queues: dict[tuple[str, int], queue.Queue] = {}
        #: peer key -> frames shed from that peer's backlog (``_PEER_QUEUE``
        #: overflow). Best-effort delivery makes shedding legitimate, but a
        #: silently starving peer is an operational blind spot: the count is
        #: inspectable here, exported via obs (``transport.peer.dropped``,
        #: detail = running count), and the FIRST drop per peer logs at
        #: WARNING. Guarded by ``_lock`` (any replica thread may broadcast).
        self.dropped_frames: dict[tuple[str, int], int] = {}
        self._log = get_logger("hyperdrive_tpu.transport")
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._accepted: list[socket.socket] = []
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, listen_port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._threads = [
            threading.Thread(target=self._accept_loop, daemon=True)
        ]

    # ------------------------------------------------------------ lifecycle

    def add_replica(self, replica) -> None:
        """Register a local threaded replica (its async ``propose``/
        ``prevote``/``precommit`` inbox methods receive every delivered
        message)."""
        self._replicas.append(replica)

    def add_peer(self, host: str, port: int) -> None:
        key = (host, port)
        if key in self._peer_queues:
            return
        q: queue.Queue = queue.Queue(maxsize=_PEER_QUEUE)
        self._peer_queues[key] = q
        self._threads.append(
            threading.Thread(
                target=self._send_loop, args=(key, q), daemon=True
            )
        )

    def register_wire_verifier(self, verifier) -> None:
        """Attach a wire-path signature verifier (e.g.
        :class:`~hyperdrive_tpu.ops.ed25519_wire.TpuWireVerifier`) whose
        key table must follow this node's epoch switches."""
        self._verifiers.append(verifier)

    def rotate_epoch(self, generation: int, table=None,
                     retired=None) -> None:
        """Epoch handoff on the socket path: install the new pubkey
        ``table`` (signatory -> key, or a verifier-native table) under
        ``generation`` on every registered wire verifier, and extend the
        retired-identity bounds so frames still signed under rotated-out
        keys are counted (``wire.frame.stale``) and dropped rather than
        failing verification mid-batch. Verifiers without
        ``install_table`` (NullVerifier deployments) just follow the
        generation number when they can."""
        with self._lock:
            self.generation = int(generation)
            if retired:
                self.retired.update(retired)
        for v in self._verifiers:
            if table is not None and hasattr(v, "install_table"):
                v.install_table(table, generation)
            elif hasattr(v, "set_generation"):
                v.set_generation(generation)
        if self.obs is not self._obs_null:
            self.obs.emit("epoch.switch", -1, -1, generation)

    def start(self) -> None:
        for t in self._threads:
            if not t.is_alive():
                try:
                    t.start()
                except RuntimeError:
                    pass  # already started (idempotent start)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        for q in self._peer_queues.values():
            try:
                q.put_nowait(None)  # wake the sender thread
            except queue.Full:
                pass
        with self._lock:
            for sock in self._accepted:
                try:
                    sock.close()
                except OSError:
                    pass
            self._accepted.clear()

    # ------------------------------------------------------------- inbound

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with self._lock:
                if self._stop.is_set():
                    conn.close()
                    continue
                self._accepted.append(conn)
            t = threading.Thread(
                target=self._read_loop, args=(conn,), daemon=True
            )
            t.start()

    def _read_loop(self, conn: socket.socket) -> None:
        try:
            peer = conn.getpeername()
        except OSError:
            peer = None
        with conn:
            while not self._stop.is_set():
                try:
                    head = _recv_exact(conn, _LEN.size)
                    if head is None:
                        return
                    (length,) = _LEN.unpack(head)
                    if length > _MAX_FRAME:
                        with self._lock:
                            self.oversize_frames += 1
                        if self.obs is not self._obs_null:
                            self.obs.emit("wire.frame.oversize", -1, -1,
                                          length)
                        return  # framing attack: drop the connection
                    payload = _recv_exact(conn, length)
                    if payload is None:
                        return
                except OSError:
                    return
                try:
                    ctx = None
                    if payload and payload[0] == TRACE_MAGIC:
                        ctx, payload = split_trace_frame(payload)
                    msg = unmarshal_message(
                        maybe_wire_reader("msg.envelope", payload,
                                          obs=self.obs)
                    )
                except SerdeError:
                    with self._lock:
                        self.malformed_frames += 1
                    if self.obs is not self._obs_null:
                        self.obs.emit("wire.frame.malformed", -1, -1,
                                      len(payload))
                    continue  # malformed envelope: drop the frame
                if ctx is not None and self.obs is not self._obs_null:
                    note_trace_recv(
                        self.obs, ctx, msg.height,
                        getattr(msg, "round", -1),
                    )
                if self._stop.is_set():
                    return
                self._deliver(msg, peer=peer)

    def _deliver(self, msg, peer=None, local: bool = False) -> None:
        # Timeouts are LOCAL, unauthenticated events (each replica's own
        # LinearTimer enqueues them directly); a Timeout arriving off the
        # wire is a forgery attempt — any peer could otherwise drive
        # honest replicas into premature round changes. Deliver only the
        # three signed consensus message types.
        t = type(msg)
        if not local:
            # Wire ingress only: a node's own broadcasts (local=True)
            # bypass both checks — they are signed under the current
            # generation by construction and must never shed.
            if self.retired:
                from hyperdrive_tpu.load.frames import (
                    STALE_GENERATION,
                    classify_frame,
                )

                cls, _ = classify_frame(msg, retired=self.retired)
                if cls is STALE_GENERATION:
                    with self._lock:
                        self.stale_frames += 1
                        count = self.stale_frames
                    if self.obs is not self._obs_null:
                        self.obs.emit(
                            "wire.frame.stale", msg.height,
                            getattr(msg, "round", -1), count,
                        )
                    if self.registry is not None:
                        self.registry.count("wire.frame.stale")
                    return  # counted, never fatal to the connection
            if self.admission is not None and not self.admission.admit(
                msg, peer
            ):
                return
        for r in self._replicas:
            if t is Propose:
                r.propose(msg, self._stop)
            elif t is Prevote:
                r.prevote(msg, self._stop)
            elif t is Precommit:
                r.precommit(msg, self._stop)

    # ------------------------------------------------------------- outbound

    def _send_loop(self, key, q: "queue.Queue") -> None:
        """One peer's sender: connect (retrying on a seeded exponential
        backoff with jitter — peers start in any order and may crash),
        then drain the frame queue. A dead peer costs nothing to anyone
        else: broadcasts just enqueue. The backoff schedule is
        deterministic per ``(seed, peer)`` (:func:`reconnect_schedule`)
        and resets after every successful connect, so a flapping peer
        pays the bounded ramp each outage instead of spinning at the
        old flat 100ms."""
        sock: socket.socket | None = None
        sched = reconnect_schedule(self.seed, key, **self.backoff)
        attempts = 0
        while not self._stop.is_set():
            item = q.get()
            if item is None or self._stop.is_set():
                break
            frame = item[1]
            while not self._stop.is_set():
                if sock is None:
                    try:
                        sock = socket.create_connection(key, timeout=5.0)
                        sock.setsockopt(
                            socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                        )
                    except OSError:
                        attempts += 1
                        if self._stop.wait(next(sched)):
                            break
                        continue
                    if attempts:
                        # Peer came (back) up after a retry ramp.
                        if self.obs is not self._obs_null:
                            self.obs.emit(
                                "transport.reconnect", -1, -1, attempts
                            )
                        if self.registry is not None:
                            self.registry.count("transport.reconnect")
                        sched = reconnect_schedule(
                            self.seed, key, **self.backoff
                        )
                        attempts = 0
                try:
                    sock.sendall(frame)
                    break
                except OSError:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def broadcast(self, msg) -> None:
        """Fan out to all: local replicas directly, remote peers via their
        sender queues (never blocks on a slow or dead peer). A full peer
        queue sheds priority-aware: under admission pressure (level >=
        SHED_LOW_PRIORITY) a new *prevote* frame is itself dropped —
        backlogged proposals and precommits are worth more than a fresh
        prevote — otherwise the oldest frame is evicted, exactly the old
        best-effort behavior. Every shed counts per peer
        (``dropped_frames``), per class in the Registry
        (``wire.frame.shed``), and emits the obs pair."""
        self._deliver(msg, local=True)
        frame = encode_frame(msg)
        if self.trace is not None:
            # Stamp INSIDE the length framing: strip encode_frame's
            # header, prefix the 21-byte trace context, re-frame.
            body = self.trace.stamp(
                frame[_LEN.size:], height=msg.height,
                round_=getattr(msg, "round", -1),
            )
            frame = _LEN.pack(len(body)) + body
        # Frames queue with the class they would shed under: prevotes are
        # the low-priority tier; everything else only ever sheds as
        # best-effort backlog eviction.
        cls = "low_priority" if type(msg) is Prevote else "backlog"
        level = 0
        ctrl = self.admission.controller if self.admission is not None \
            else None
        if ctrl is not None:
            level = ctrl.level
        worst = 0.0
        for key, q in self._peer_queues.items():
            if level >= 2 and cls == "low_priority":
                # SHED_LOW_PRIORITY or worse: a full queue drops the new
                # prevote instead of evicting older (higher-value) frames.
                try:
                    q.put_nowait((cls, frame))
                except queue.Full:
                    self._count_shed(key, cls)
                if ctrl is not None:
                    occ = q.qsize() / _PEER_QUEUE
                    if occ > worst:
                        worst = occ
                continue
            while True:
                try:
                    q.put_nowait((cls, frame))
                    break
                except queue.Full:
                    try:
                        old = q.get_nowait()  # shed the oldest frame
                    except queue.Empty:
                        continue
                    self._count_shed(
                        key, old[0] if old is not None else "backlog"
                    )
            if ctrl is not None:
                occ = q.qsize() / _PEER_QUEUE
                if occ > worst:
                    worst = occ
        if ctrl is not None:
            ctrl.note_peer_occupancy(worst)

    def _count_shed(self, key, cls: str) -> None:
        """Account one shed outbound frame: per-peer counter, labeled
        Registry counter, WARNING on the peer's first drop, obs pair."""
        with self._lock:
            count = self.dropped_frames.get(key, 0) + 1
            self.dropped_frames[key] = count
        if count == 1:
            self._log.warning(
                "peer backlog overflow %s",
                _kv(peer=f"{key[0]}:{key[1]}", capacity=_PEER_QUEUE),
            )
        if self.registry is not None:
            self.registry.count("wire.frame.shed", label=cls)
        if self.obs is not self._obs_null:
            self.obs.emit("wire.frame.shed", -1, -1, cls)
            self.obs.emit("transport.peer.dropped", -1, -1, count)


@wire_codec(tag="flight.record", max_bytes=_MAX_FRAME)
class FlightRecorder:
    """One replica's consumption log: every input the replica's event
    loop consumed — votes, local timeouts, resets — in consumption order.

    This extends the sim's seeded record/replay (the reference's
    failure.dump workflow, replica/replica_test.go:850-928) to the
    DEPLOYMENT path, where inputs arrive over sockets and wall-clock
    timers and are otherwise unreproducible. The replica is the
    serialization point (one event loop consumes everything), so its log
    is a complete causal record: replaying it into a fresh in-process
    replica with the same deterministic DI set reproduces the replica's
    whole trajectory — no sockets, no timers, no other processes.

    Thread-safety: ``record`` runs on the owning replica's event-loop
    thread only (the single-writer discipline every replica component
    shares); ``dump`` may run on any thread after the loop stops.

    Format: per record, a one-byte kind tag — 0 = message envelope
    (:func:`hyperdrive_tpu.messages.marshal_message`, signatures
    included), 1 = height reset (height + signatory list) — then the
    4-byte-length-framed body.
    """

    KIND_MSG = 0
    KIND_RESET = 1

    def __init__(self):
        self.frames: list[bytes] = []

    def record(self, msg) -> None:
        from hyperdrive_tpu.replica import ResetHeight

        if isinstance(msg, ResetHeight):
            w = Writer()
            w.i64(msg.height)
            w.u32(len(msg.signatories))
            for s in msg.signatories:
                w.raw(s)
            self.frames.append(
                bytes([self.KIND_RESET]) + _LEN.pack(len(w.data()))
                + w.data()
            )
            return
        w = Writer()
        marshal_message(msg, w)
        self.frames.append(
            bytes([self.KIND_MSG]) + _LEN.pack(len(w.data())) + w.data()
        )

    def dump(self, path) -> None:
        with open(path, "wb") as f:
            for frame in self.frames:
                f.write(frame)

    @staticmethod
    @wire_entry
    def load(path) -> list:
        """Decode a dumped flight log back into input objects (messages
        and :class:`~hyperdrive_tpu.replica.ResetHeight`), in recorded
        order.

        A partial trailing frame — the expected shape when the recording
        process was killed mid-write, which is precisely the run worth
        replaying — ends the log cleanly: the intact prefix is returned.
        A corrupt frame BODY (unknown kind, malformed envelope) still
        raises SerdeError; truncation is survivable, corruption is not.
        """
        from hyperdrive_tpu.replica import ResetHeight

        out = []
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        n = len(data)
        while off < n:
            if n - off < 5:
                break  # partial header: killed mid-write
            kind = data[off]
            (length,) = _LEN.unpack(data[off + 1 : off + 5])
            body = data[off + 5 : off + 5 + length]
            if len(body) != length:
                break  # partial body: killed mid-write
            off += 5 + length
            if kind == FlightRecorder.KIND_MSG:
                out.append(unmarshal_message(
                    maybe_wire_reader("msg.envelope", body)
                ))
            elif kind == FlightRecorder.KIND_RESET:
                r = maybe_wire_reader("flight.record", body)
                height = r.i64()
                sigs = tuple(r.raw() for _ in range(r.u32()))
                out.append(ResetHeight(height, sigs))
            else:
                raise SerdeError(f"unknown flight record kind {kind}")
        return out


def replay_flight(path, replica) -> None:
    """Re-drive a fresh replica through a dumped flight log, offline.

    ``replica`` must be built with the same deterministic DI set the
    recorded run used (proposer, validator, committer semantics, same
    signatory whitelist and, for signed runs, an equivalent verifier —
    the log holds raw pre-verification inputs, signatures included).
    Broadcasts during replay go wherever the fresh replica's broadcaster
    points (a no-op or a sink: every self-delivered broadcast the live
    run consumed is already IN the log); timers may be None — recorded
    Timeout events stand in for the wall clock.
    """
    replica.start()
    for msg in FlightRecorder.load(path):
        replica.handle(msg)


class TcpBroadcaster:
    """Per-replica Broadcaster facade over a shared :class:`TcpNode`,
    signing each outbound message when a keypair is supplied (the wire
    envelope carries the detached signature)."""

    def __init__(self, node: TcpNode, keypair=None):
        self._node = node
        self._kp = keypair

    def _send(self, msg) -> None:
        if self._kp is not None:
            msg = self._kp.sign_message(msg)
        self._node.broadcast(msg)

    def broadcast_propose(self, propose: Propose) -> None:
        self._send(propose)

    def broadcast_prevote(self, prevote: Prevote) -> None:
        self._send(prevote)

    def broadcast_precommit(self, precommit: Precommit) -> None:
        self._send(precommit)
