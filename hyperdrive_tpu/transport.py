"""Loopback-TCP binding of the Broadcaster seam.

The reference leaves networking entirely to the embedding application —
the ``Broadcaster`` DI interface IS the whole communication backend
contract (broadcast to all incl. self, eventual delivery, no ordering;
reference: process/process.go:47-60), and its tests wire it to an
in-memory queue (replica/replica_test.go:174-208). This module turns that
seam into a PROOF over real sockets: a full-mesh, length-framed TCP
transport driving threaded replicas with real wall-clock
:class:`~hyperdrive_tpu.timer.LinearTimer` timeouts — consensus across OS
process boundaries with no shared memory.

Scope (deliberate): the control plane for small messages. Bulk tensor
traffic (vote batches, signature limbs) belongs on ICI/DCN device
collectives (:mod:`hyperdrive_tpu.parallel`); this transport carries the
consensus envelopes a deployment would gossip over its host network.

Wire format: 4-byte little-endian length + the signed message envelope
(:func:`hyperdrive_tpu.messages.marshal_message`). Malformed frames from
a peer are dropped (DoS-safe: the codec never raises past the budget, and
a framing error closes only that peer's connection).
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time

from hyperdrive_tpu.codec import Reader, SerdeError, Writer
from hyperdrive_tpu.messages import (
    Precommit,
    Propose,
    Prevote,
    marshal_message,
    unmarshal_message,
)
from hyperdrive_tpu.utils.log import get_logger, kv as _kv

__all__ = [
    "TcpBroadcaster",
    "TcpNode",
    "encode_frame",
    "FlightRecorder",
    "replay_flight",
]

_LEN = struct.Struct("<I")
_MAX_FRAME = 1 << 20  # 1 MiB: far above any consensus envelope
#: Per-peer outbound buffer (frames). A peer that stays unreachable longer
#: than this many broadcasts sees the oldest frames dropped — best-effort,
#: matching the reference's trust model where eventual delivery is the
#: embedding network's promise, not the library's
#: (process/process.go:47-60).
_PEER_QUEUE = 4096


def encode_frame(msg) -> bytes:
    w = Writer()
    marshal_message(msg, w)
    payload = w.data()
    return _LEN.pack(len(payload)) + payload


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class TcpNode:
    """One process's endpoint of the full-mesh broadcast transport.

    Hosts any number of local replicas. ``broadcast`` serializes once,
    delivers to every LOCAL replica directly (the Broadcaster contract
    includes the sender), and writes the frame to every remote peer's
    connection. Inbound frames are decoded and delivered to every local
    replica. Peer connections are dialed lazily with retries, so nodes
    may start in any order.
    """

    def __init__(self, listen_port: int = 0, host: str = "127.0.0.1",
                 obs=None):
        from hyperdrive_tpu.obs.recorder import NULL_BOUND

        self._host = host
        #: Flight-recorder handle for wire anomalies (oversize frames,
        #: malformed envelopes, shed backlog). The node is multithreaded,
        #: so callers must pass a handle bound to a threadsafe Recorder.
        self.obs = obs if obs is not None else NULL_BOUND
        self._obs_null = NULL_BOUND
        self._replicas: list = []
        #: peer key -> outbound frame queue, drained by a dedicated sender
        #: thread per peer — a dead or slow peer can never stall the
        #: broadcasting replica threads or the other peers.
        self._peer_queues: dict[tuple[str, int], queue.Queue] = {}
        #: peer key -> frames shed from that peer's backlog (``_PEER_QUEUE``
        #: overflow). Best-effort delivery makes shedding legitimate, but a
        #: silently starving peer is an operational blind spot: the count is
        #: inspectable here, exported via obs (``transport.peer.dropped``,
        #: detail = running count), and the FIRST drop per peer logs at
        #: WARNING. Guarded by ``_lock`` (any replica thread may broadcast).
        self.dropped_frames: dict[tuple[str, int], int] = {}
        self._log = get_logger("hyperdrive_tpu.transport")
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._accepted: list[socket.socket] = []
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, listen_port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._threads = [
            threading.Thread(target=self._accept_loop, daemon=True)
        ]

    # ------------------------------------------------------------ lifecycle

    def add_replica(self, replica) -> None:
        """Register a local threaded replica (its async ``propose``/
        ``prevote``/``precommit`` inbox methods receive every delivered
        message)."""
        self._replicas.append(replica)

    def add_peer(self, host: str, port: int) -> None:
        key = (host, port)
        if key in self._peer_queues:
            return
        q: queue.Queue = queue.Queue(maxsize=_PEER_QUEUE)
        self._peer_queues[key] = q
        self._threads.append(
            threading.Thread(
                target=self._send_loop, args=(key, q), daemon=True
            )
        )

    def start(self) -> None:
        for t in self._threads:
            if not t.is_alive():
                try:
                    t.start()
                except RuntimeError:
                    pass  # already started (idempotent start)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        for q in self._peer_queues.values():
            try:
                q.put_nowait(None)  # wake the sender thread
            except queue.Full:
                pass
        with self._lock:
            for sock in self._accepted:
                try:
                    sock.close()
                except OSError:
                    pass
            self._accepted.clear()

    # ------------------------------------------------------------- inbound

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with self._lock:
                if self._stop.is_set():
                    conn.close()
                    continue
                self._accepted.append(conn)
            t = threading.Thread(
                target=self._read_loop, args=(conn,), daemon=True
            )
            t.start()

    def _read_loop(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    head = _recv_exact(conn, _LEN.size)
                    if head is None:
                        return
                    (length,) = _LEN.unpack(head)
                    if length > _MAX_FRAME:
                        if self.obs is not self._obs_null:
                            self.obs.emit("wire.frame.oversize", -1, -1,
                                          length)
                        return  # framing attack: drop the connection
                    payload = _recv_exact(conn, length)
                    if payload is None:
                        return
                except OSError:
                    return
                try:
                    msg = unmarshal_message(Reader(payload))
                except SerdeError:
                    if self.obs is not self._obs_null:
                        self.obs.emit("wire.frame.malformed", -1, -1,
                                      len(payload))
                    continue  # malformed envelope: drop the frame
                if self._stop.is_set():
                    return
                self._deliver(msg)

    def _deliver(self, msg) -> None:
        # Timeouts are LOCAL, unauthenticated events (each replica's own
        # LinearTimer enqueues them directly); a Timeout arriving off the
        # wire is a forgery attempt — any peer could otherwise drive
        # honest replicas into premature round changes. Deliver only the
        # three signed consensus message types.
        t = type(msg)
        for r in self._replicas:
            if t is Propose:
                r.propose(msg, self._stop)
            elif t is Prevote:
                r.prevote(msg, self._stop)
            elif t is Precommit:
                r.precommit(msg, self._stop)

    # ------------------------------------------------------------- outbound

    def _send_loop(self, key, q: "queue.Queue") -> None:
        """One peer's sender: connect (retrying with backoff — peers start
        in any order and may crash), then drain the frame queue. A dead
        peer costs nothing to anyone else: broadcasts just enqueue."""
        sock: socket.socket | None = None
        while not self._stop.is_set():
            frame = q.get()
            if frame is None or self._stop.is_set():
                break
            while not self._stop.is_set():
                if sock is None:
                    try:
                        sock = socket.create_connection(key, timeout=5.0)
                        sock.setsockopt(
                            socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                        )
                    except OSError:
                        time.sleep(0.1)
                        continue
                try:
                    sock.sendall(frame)
                    break
                except OSError:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def broadcast(self, msg) -> None:
        """Fan out to all: local replicas directly, remote peers via their
        sender queues (never blocks on a slow or dead peer; a full queue
        drops the oldest frame — see _PEER_QUEUE)."""
        self._deliver(msg)
        frame = encode_frame(msg)
        for key, q in self._peer_queues.items():
            while True:
                try:
                    q.put_nowait(frame)
                    break
                except queue.Full:
                    try:
                        q.get_nowait()  # shed the oldest frame
                    except queue.Empty:
                        continue
                    with self._lock:
                        count = self.dropped_frames.get(key, 0) + 1
                        self.dropped_frames[key] = count
                    if count == 1:
                        self._log.warning(
                            "peer backlog overflow %s",
                            _kv(peer=f"{key[0]}:{key[1]}",
                                capacity=_PEER_QUEUE),
                        )
                    if self.obs is not self._obs_null:
                        self.obs.emit("wire.frame.shed", -1, -1)
                        self.obs.emit(
                            "transport.peer.dropped", -1, -1, count
                        )


class FlightRecorder:
    """One replica's consumption log: every input the replica's event
    loop consumed — votes, local timeouts, resets — in consumption order.

    This extends the sim's seeded record/replay (the reference's
    failure.dump workflow, replica/replica_test.go:850-928) to the
    DEPLOYMENT path, where inputs arrive over sockets and wall-clock
    timers and are otherwise unreproducible. The replica is the
    serialization point (one event loop consumes everything), so its log
    is a complete causal record: replaying it into a fresh in-process
    replica with the same deterministic DI set reproduces the replica's
    whole trajectory — no sockets, no timers, no other processes.

    Thread-safety: ``record`` runs on the owning replica's event-loop
    thread only (the single-writer discipline every replica component
    shares); ``dump`` may run on any thread after the loop stops.

    Format: per record, a one-byte kind tag — 0 = message envelope
    (:func:`hyperdrive_tpu.messages.marshal_message`, signatures
    included), 1 = height reset (height + signatory list) — then the
    4-byte-length-framed body.
    """

    KIND_MSG = 0
    KIND_RESET = 1

    def __init__(self):
        self.frames: list[bytes] = []

    def record(self, msg) -> None:
        from hyperdrive_tpu.replica import ResetHeight

        if isinstance(msg, ResetHeight):
            w = Writer()
            w.i64(msg.height)
            w.u32(len(msg.signatories))
            for s in msg.signatories:
                w.raw(s)
            self.frames.append(
                bytes([self.KIND_RESET]) + _LEN.pack(len(w.data()))
                + w.data()
            )
            return
        w = Writer()
        marshal_message(msg, w)
        self.frames.append(
            bytes([self.KIND_MSG]) + _LEN.pack(len(w.data())) + w.data()
        )

    def dump(self, path) -> None:
        with open(path, "wb") as f:
            for frame in self.frames:
                f.write(frame)

    @staticmethod
    def load(path) -> list:
        """Decode a dumped flight log back into input objects (messages
        and :class:`~hyperdrive_tpu.replica.ResetHeight`), in recorded
        order.

        A partial trailing frame — the expected shape when the recording
        process was killed mid-write, which is precisely the run worth
        replaying — ends the log cleanly: the intact prefix is returned.
        A corrupt frame BODY (unknown kind, malformed envelope) still
        raises SerdeError; truncation is survivable, corruption is not.
        """
        from hyperdrive_tpu.replica import ResetHeight

        out = []
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        n = len(data)
        while off < n:
            if n - off < 5:
                break  # partial header: killed mid-write
            kind = data[off]
            (length,) = _LEN.unpack(data[off + 1 : off + 5])
            body = data[off + 5 : off + 5 + length]
            if len(body) != length:
                break  # partial body: killed mid-write
            off += 5 + length
            if kind == FlightRecorder.KIND_MSG:
                out.append(unmarshal_message(Reader(body)))
            elif kind == FlightRecorder.KIND_RESET:
                r = Reader(body)
                height = r.i64()
                sigs = tuple(r.raw() for _ in range(r.u32()))
                out.append(ResetHeight(height, sigs))
            else:
                raise SerdeError(f"unknown flight record kind {kind}")
        return out


def replay_flight(path, replica) -> None:
    """Re-drive a fresh replica through a dumped flight log, offline.

    ``replica`` must be built with the same deterministic DI set the
    recorded run used (proposer, validator, committer semantics, same
    signatory whitelist and, for signed runs, an equivalent verifier —
    the log holds raw pre-verification inputs, signatures included).
    Broadcasts during replay go wherever the fresh replica's broadcaster
    points (a no-op or a sink: every self-delivered broadcast the live
    run consumed is already IN the log); timers may be None — recorded
    Timeout events stand in for the wall clock.
    """
    replica.start()
    for msg in FlightRecorder.load(path):
        replica.handle(msg)


class TcpBroadcaster:
    """Per-replica Broadcaster facade over a shared :class:`TcpNode`,
    signing each outbound message when a keypair is supplied (the wire
    envelope carries the detached signature)."""

    def __init__(self, node: TcpNode, keypair=None):
        self._node = node
        self._kp = keypair

    def _send(self, msg) -> None:
        if self._kp is not None:
            msg = self._kp.sign_message(msg)
        self._node.broadcast(msg)

    def broadcast_propose(self, propose: Propose) -> None:
        self._send(propose)

    def broadcast_prevote(self, prevote: Prevote) -> None:
        self._send(prevote)

    def broadcast_precommit(self, precommit: Precommit) -> None:
        self._send(precommit)
