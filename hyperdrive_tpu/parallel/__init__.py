"""SPMD scaling over a jax.sharding.Mesh.

The reference has no distributed communication backend of its own — the
Broadcaster seam is the entire contract, and tests wire it to an in-memory
queue (SURVEY.md section 2.3). The TPU-native equivalent: votes are
tensors, so the wide work (signature verification + quorum tallies) shards
across chips with ``shard_map`` and combines with XLA collectives over
ICI/DCN, while the host network stays the control path exactly where the
reference assumes an external network.
"""

# Lazy exports (PEP 562): the mesh/multihost members need jax at import
# time, but the multi-tenant serving layer (parallel/service.py) and its
# chaos/CLI consumers must be importable jax-free. Attribute access
# resolves the owning submodule on first touch.

_EXPORTS = {
    "grid_pack": "hyperdrive_tpu.parallel.mesh",
    "grid_pack_wire": "hyperdrive_tpu.parallel.mesh",
    "make_mesh": "hyperdrive_tpu.parallel.mesh",
    "make_sharded_step": "hyperdrive_tpu.parallel.mesh",
    "sharded_chalwire_tally": "hyperdrive_tpu.parallel.mesh",
    "sharded_verify_tally": "hyperdrive_tpu.parallel.mesh",
    "global_window_from_local": "hyperdrive_tpu.parallel.multihost",
    "init_distributed": "hyperdrive_tpu.parallel.multihost",
    "make_hybrid_mesh": "hyperdrive_tpu.parallel.multihost",
    "replicate_to_all_hosts": "hyperdrive_tpu.parallel.multihost",
    "ShardVerifyService": "hyperdrive_tpu.parallel.service",
    "ServicePort": "hyperdrive_tpu.parallel.service",
    "RemoteServiceClient": "hyperdrive_tpu.parallel.service",
    "TenantShard": "hyperdrive_tpu.parallel.service",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        )
    import importlib

    value = getattr(importlib.import_module(module), name)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
