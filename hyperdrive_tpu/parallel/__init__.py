"""SPMD scaling over a jax.sharding.Mesh.

The reference has no distributed communication backend of its own — the
Broadcaster seam is the entire contract, and tests wire it to an in-memory
queue (SURVEY.md section 2.3). The TPU-native equivalent: votes are
tensors, so the wide work (signature verification + quorum tallies) shards
across chips with ``shard_map`` and combines with XLA collectives over
ICI/DCN, while the host network stays the control path exactly where the
reference assumes an external network.
"""

from hyperdrive_tpu.parallel.mesh import (
    grid_pack,
    grid_pack_wire,
    make_mesh,
    make_sharded_step,
    sharded_chalwire_tally,
    sharded_verify_tally,
)
from hyperdrive_tpu.parallel.multihost import (
    global_window_from_local,
    init_distributed,
    make_hybrid_mesh,
    replicate_to_all_hosts,
)

__all__ = [
    "grid_pack",
    "grid_pack_wire",
    "make_mesh",
    "make_sharded_step",
    "sharded_chalwire_tally",
    "sharded_verify_tally",
    "global_window_from_local",
    "init_distributed",
    "make_hybrid_mesh",
    "replicate_to_all_hosts",
]
