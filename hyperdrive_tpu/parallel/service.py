"""The multi-tenant verify service: continuous batching as a deployment.

M independent shard-consensus instances (each its own committee, its own
chain) funnel verify/tally windows into ONE
:class:`~hyperdrive_tpu.devsched.DeviceWorkQueue` — inference-server
continuous batching applied to consensus: the drain loop coalesces
whatever is pending across ALL tenants into the next launch, so the
measured ~107 ms launch+sync floor is amortized across every instance
instead of paid per shard (BENCH_r11; PAPERS.md "ACE Runtime" makes the
serving-system framing, arXiv:2302.00418 shows verify throughput is the
binding resource).

Three layers, all host-side and jax-free (the device enters only through
whatever verifier the caller hands in):

- :class:`ShardVerifyService` — the shared verifier + queue + per-tenant
  accounting (certificates, watermarks, telemetry tracks). The drain
  policy seam (devsched/policy.py) rides the queue, so a firehose tenant
  cannot monopolize launch occupancy.
- :class:`ServicePort` / :class:`RemoteServiceClient` — cross-process
  batching over the transport's length-framed TCP machinery: replicas in
  OTHER processes ship packed precommit windows to the host that owns
  the device queue and get their futures resolved by certificate frames
  back (O(1) proof, not 2f+1 signatures). Ingress reuses the
  admission/backpressure doctrine from ``load/``: duplicate and
  stale-height windows shed at pressure, CRITICAL_ONLY turns submits
  away with a busy status, and nothing is ever silently dropped — every
  request is answered.
- :class:`TenantShard` — one instance's drive loop: sign a window,
  submit (locally or through a client), count the quorum, mint/verify
  the certificate, record the commit. The same class runs both sides of
  the wire, which is what makes local-vs-remote digest parity a single
  assertion.

``python -m hyperdrive_tpu.parallel serve`` runs the deployment shape;
``benches/multitenant_bench.py`` measures it.
"""

from __future__ import annotations

import hashlib
import queue as queue_mod
import socket
import struct
import threading
import time

from hyperdrive_tpu.analysis.annotations import wire_codec
from hyperdrive_tpu.analysis.sanitizer import maybe_wire_reader
from hyperdrive_tpu.certificates import (
    marshal_certificate,
    unmarshal_certificate,
)
from hyperdrive_tpu.codec import SerdeError, Writer
from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.messages import Precommit
from hyperdrive_tpu.obs.recorder import NULL_BOUND
from hyperdrive_tpu.obs.tracectx import (
    TRACE_MAGIC,
    note_recv as note_trace_recv,
    split_frame as split_trace_frame,
)
from hyperdrive_tpu.ops.merkle import MAX_DEPTH, MerkleProof
from hyperdrive_tpu.transport import _LEN, _MAX_FRAME, _recv_exact

__all__ = [
    "ShardVerifyService",
    "ServicePort",
    "RemoteServiceClient",
    "RemoteFuture",
    "TenantShard",
    "STATUS_COMMITTED",
    "STATUS_NO_QUORUM",
    "STATUS_SHED",
    "STATUS_UNKNOWN_TENANT",
    "STATUS_NO_STATE",
    "TAG_QUERY",
    "TAG_METRICS",
    "encode_query",
    "encode_proof",
    "decode_proof",
    "encode_metrics_request",
    "encode_metrics_reply",
    "decode_metrics_reply",
    "encode_hello_ack",
    "decode_hello_ack",
]

# ------------------------------------------------------------ wire format
#
# Same 4-byte little-endian length framing as transport.py, distinct
# payload tags (this port speaks windows and certificates, not consensus
# envelopes). All payloads go through codec.Writer/Reader so adversarial
# bytes raise SerdeError instead of crashing the port.

TAG_HELLO = 1
TAG_SUBMIT = 2
TAG_RESULT = 3
#: Proof query (request) / proof answer (response) — the trustless read
#: path. Result frames keep TAG_RESULT byte-for-byte, so a v15-era
#: client and this port interoperate on the submit path unchanged
#: (tests/test_service.py pins the cross-version roundtrip).
TAG_QUERY = 4
#: Live-metrics scrape (request) / Prometheus snapshot (response) — the
#: observability read path. Classed WITH proof queries at the admission
#: gate (first-shed at SHED_LOW_PRIORITY), so a scrape storm can never
#: displace consensus traffic.
TAG_METRICS = 5

STATUS_COMMITTED = 0
STATUS_NO_QUORUM = 1
STATUS_SHED = 2
STATUS_UNKNOWN_TENANT = 3
#: Query answered before the tenant's first certificate landed (no
#: settled basis to prove against yet) — retryable, like SHED.
STATUS_NO_STATE = 4

STATUS_NAMES = ("committed", "no_quorum", "shed", "unknown_tenant",
                "no_state")

#: Committee width cap for HELLO (matches the certificate bitmap cap).
_MAX_SIGNATORIES = 4096
#: Rows per submitted window — far above any committee's 2f+1 burst.
_MAX_ROWS = 65536
#: Tenant-name cap for HELLO: a name is an identifier, not a payload.
_MAX_NAME = 256
#: Widest per-row detached signature (Ed25519 64, BLS G2 96).
_MAX_ROW_SIG = 96


@wire_codec(tag="service.hello", max_bytes=1 << 18)
def encode_hello(name: str, signatories, f: int, t0: float = 0.0) -> bytes:
    """``t0`` (optional trailing f64) is the client's wall-clock send
    stamp: the port echoes it back in the hello-ack so the client can
    estimate the server's clock offset NTP-style (``obs merge`` aligns
    per-process journals on those estimates). Pre-echo clients simply
    omit it — :func:`decode_request` reads 0.0 and the ack degrades to
    a no-offset handshake."""
    w = Writer()
    w.u8(TAG_HELLO)
    w.raw(name.encode("utf-8"))
    w.u32(int(f))
    w.u32(len(signatories))
    for s in signatories:
        w.bytes32(s)
    if t0:
        w.f64(float(t0))
    return w.data()


@wire_codec(tag="service.hello.ack", max_bytes=64)
def encode_hello_ack(t0: float, t1: float, origin: int) -> bytes:
    """The port's answer to HELLO: the client's echoed send stamp, the
    server's own receive stamp, and the server's trace origin id. From
    ``(t0, t1, t3=now)`` the client estimates the server clock offset
    as ``t1 - (t0 + t3) / 2`` — half the round trip cancels out."""
    w = Writer()
    w.u8(TAG_HELLO)
    w.f64(float(t0))
    w.f64(float(t1))
    w.u32(int(origin))
    return w.data()


@wire_codec(tag="service.hello.ack", max_bytes=64)
def decode_hello_ack(payload: bytes):
    """Client-side decode: ``(t0, t1, origin)``."""
    r = maybe_wire_reader("service.hello.ack", payload)
    if r.u8() != TAG_HELLO:
        raise SerdeError("expected a hello-ack frame")
    t0 = r.f64()
    t1 = r.f64()
    origin = r.u32()
    if not r.done():
        raise SerdeError("trailing bytes after hello-ack frame")
    return t0, t1, origin


@wire_codec(tag="service.submit", max_bytes=_MAX_FRAME)
def encode_submit(req_id: int, height: int, round: int, value: bytes,
                  rows, generation: int = 0) -> bytes:
    """``rows``: signed :class:`~hyperdrive_tpu.messages.Precommit`s (or
    bare ``(sender, signature)`` pairs) for ONE (height, round, value)
    window. The digest is recomputed server-side from the header, so the
    wire carries 32 + ~68 bytes per row, not the whole envelope."""
    w = Writer()
    w.u8(TAG_SUBMIT)
    w.u64(req_id)
    w.i64(height)
    w.i64(round)
    w.bytes32(value)
    w.u32(int(generation))
    w.u32(len(rows))
    for row in rows:
        if isinstance(row, tuple):
            sender, sig = row
        else:
            sender, sig = row.sender, row.signature
        w.bytes32(sender)
        w.raw(sig)
    return w.data()


@wire_codec(tag="service.result", max_bytes=_MAX_FRAME)
def encode_result(req_id: int, status: int, nrows: int, mask,
                  cert=None, root=None) -> bytes:
    """``root`` (32 bytes or None) rides between the mask and the
    certificate tail: a serving host with an execution ledger attached
    for the tenant stamps the committed frame with the chained state
    root its executor derived at that height, so the O(1) certificate
    answer vouches for ledger state, not just the agreed value."""
    w = Writer()
    w.u8(TAG_RESULT)
    w.u64(req_id)
    w.u8(int(status))
    w.u32(int(nrows))
    bitmap = bytearray(-(-nrows // 8)) if nrows else bytearray()
    for i, ok in enumerate(mask or ()):
        if ok:
            bitmap[i >> 3] |= 1 << (i & 7)
    w.raw(bytes(bitmap))
    w.raw(root or b"")
    if cert is not None:
        cw = Writer()
        marshal_certificate(cert, cw)
        w.raw(cw.data())
    else:
        w.raw(b"")
    return w.data()


@wire_codec(tag="service.query", max_bytes=64)
def encode_query(req_id: int, account: int) -> bytes:
    """A stateless client's proof request: ONE account id. The answer
    (:func:`encode_proof`) is self-contained — the client needs nothing
    but the certificate-chain root it already trusts."""
    w = Writer()
    w.u8(TAG_QUERY)
    w.u64(req_id)
    w.u32(int(account))
    return w.data()


@wire_codec(tag="service.metrics", max_bytes=64)
def encode_metrics_request(req_id: int) -> bytes:
    """A live-metrics scrape: request the Registry's Prometheus
    snapshot over the service port. Carries nothing but the request id
    — the cheapest frame in the protocol, and the first one shed."""
    w = Writer()
    w.u8(TAG_METRICS)
    w.u64(req_id)
    return w.data()


@wire_codec(tag="service.metrics.reply", max_bytes=1 << 18)
def encode_metrics_reply(req_id: int, status: int, text: str = "") -> bytes:
    """ONE metrics answer: the rendered Prometheus exposition text (or
    an empty body for refusals). The 256 KiB budget bounds what a
    Byzantine server can make a scraper buffer."""
    w = Writer()
    w.u8(TAG_METRICS)
    w.u64(req_id)
    w.u8(int(status))
    if status == STATUS_COMMITTED:
        w.raw(text.encode("utf-8"))
    return w.data()


@wire_codec(tag="service.metrics.reply", max_bytes=1 << 18)
def decode_metrics_reply(payload: bytes):
    """Client-side decode: ``(req_id, status, text_or_None)``."""
    r = maybe_wire_reader("service.metrics.reply", payload)
    if r.u8() != TAG_METRICS:
        raise SerdeError("expected a metrics reply frame")
    req_id = r.u64()
    status = r.u8()
    if status != STATUS_COMMITTED:
        if not r.done():
            raise SerdeError("trailing bytes after metrics status")
        return req_id, status, None
    text = r.raw().decode("utf-8", "replace")
    if not r.done():
        raise SerdeError("trailing bytes after metrics reply")
    return req_id, status, text


@wire_codec(tag="service.proof", max_bytes=4096)
def encode_proof(req_id: int, status: int, proof=None) -> bytes:
    """ONE proof frame: leaf values, the O(1) chain witness (previous
    root + state digest), and the O(log n) sibling path — everything
    :func:`~hyperdrive_tpu.ops.merkle.verify_inclusion` needs against a
    trusted root, with zero trust in the serving replica. Non-committed
    statuses carry no body."""
    w = Writer()
    w.u8(TAG_QUERY)
    w.u64(req_id)
    w.u8(int(status))
    if status != STATUS_COMMITTED:
        return w.data()
    w.i64(proof.height)
    w.u32(proof.account)
    w.i64(proof.balance)
    w.i64(proof.stake)
    w.bytes32(proof.prev_root)
    w.raw(struct.pack("<8I", *proof.digest))
    w.u32(len(proof.siblings))
    w.raw(b"".join(struct.pack("<4I", *sib) for sib in proof.siblings))
    return w.data()


@wire_codec(tag="service.proof", max_bytes=4096)
def decode_proof(payload: bytes):
    """Client-side decode: ``(req_id, status, proof_or_None)``. Raises
    SerdeError on malformed bytes, trailing garbage, or a path deeper
    than MAX_DEPTH — a Byzantine server cannot make the client loop or
    allocate unboundedly."""
    r = maybe_wire_reader("service.proof", payload)
    if r.u8() != TAG_QUERY:
        raise SerdeError("expected a proof frame")
    req_id = r.u64()
    status = r.u8()
    if status != STATUS_COMMITTED:
        if not r.done():
            raise SerdeError("trailing bytes after proof status")
        return req_id, status, None
    height = r.i64()
    account = r.u32()
    balance = r.i64()
    stake = r.i64()
    prev_root = r.bytes32()
    digest_raw = r.raw()
    if len(digest_raw) != 32:
        raise SerdeError(
            f"proof digest must be 32 bytes, got {len(digest_raw)}"
        )
    depth = r.u32()
    if depth > MAX_DEPTH:
        raise SerdeError(f"proof path deeper than {MAX_DEPTH}: {depth}")
    sib_raw = r.raw()
    if len(sib_raw) != 16 * depth:
        raise SerdeError("sibling bytes disagree with the path depth")
    proof = MerkleProof(
        height=height,
        account=account,
        balance=balance,
        stake=stake,
        prev_root=prev_root,
        digest=struct.unpack("<8I", digest_raw),
        siblings=tuple(
            struct.unpack_from("<4I", sib_raw, 16 * i)
            for i in range(depth)
        ),
    )
    if not r.done():
        raise SerdeError("trailing bytes after proof frame")
    return req_id, status, proof


#: First frame byte -> budget family for the shared request decoder:
#: each request kind is charged against ITS OWN registered budget, so a
#: 256 KiB hello cannot hide behind the wider submit allowance.
_REQUEST_FAMILIES = {
    TAG_HELLO: "service.hello",
    TAG_SUBMIT: "service.submit",
    TAG_QUERY: "service.query",
    TAG_METRICS: "service.metrics",
}


@wire_codec(tag="service.hello", max_bytes=1 << 18)
@wire_codec(tag="service.submit", max_bytes=_MAX_FRAME)
@wire_codec(tag="service.query", max_bytes=64)
@wire_codec(tag="service.metrics", max_bytes=64)
def decode_request(payload: bytes):
    """Server-side decode: ``("hello", name, f, signatories, t0)``,
    ``("submit", req_id, height, round, value, generation, rows)`` with
    ``rows`` as ``(sender, signature)`` pairs,
    ``("query", req_id, account)``, or ``("metrics", req_id)``. Raises
    SerdeError on anything malformed, over the width caps, or carrying
    trailing garbage — a truncated or padded frame is rejected typed,
    never half-decoded."""
    if not payload:
        raise SerdeError("empty service frame")
    family = _REQUEST_FAMILIES.get(payload[0])
    if family is None:
        raise SerdeError(f"unknown service frame tag: {payload[0]}")
    r = maybe_wire_reader(family, payload)
    tag = r.u8()
    if tag == TAG_HELLO:
        name_raw = r.raw()
        if len(name_raw) > _MAX_NAME:
            raise SerdeError(f"tenant name too long: {len(name_raw)}")
        name = name_raw.decode("utf-8", "replace")
        f = r.u32()
        n = r.u32()
        if n > _MAX_SIGNATORIES:
            raise SerdeError(f"committee too wide: {n}")
        sigs = [r.bytes32() for _ in range(n)]
        # Pre-echo hellos end here; echo-era clients append their
        # wall-clock send stamp (the offset-estimation seed).
        t0 = 0.0 if r.done() else r.f64()
        if not r.done():
            raise SerdeError("trailing bytes after hello frame")
        return ("hello", name, f, sigs, t0)
    if tag == TAG_SUBMIT:
        req_id = r.u64()
        height = r.i64()
        rnd = r.i64()
        value = r.bytes32()
        generation = r.u32()
        n = r.u32()
        if n > _MAX_ROWS:
            raise SerdeError(f"window too wide: {n} rows")
        rows = []
        for _ in range(n):
            sender = r.bytes32()
            sig = r.raw()
            if len(sig) > _MAX_ROW_SIG:
                raise SerdeError(f"row signature too wide: {len(sig)}")
            rows.append((sender, sig))
        if not r.done():
            raise SerdeError("trailing bytes after submit frame")
        return ("submit", req_id, height, rnd, value, generation, rows)
    if tag == TAG_METRICS:
        req = ("metrics", r.u64())
        if not r.done():
            raise SerdeError("trailing bytes after metrics frame")
        return req
    req = ("query", r.u64(), r.u32())
    if not r.done():
        raise SerdeError("trailing bytes after query frame")
    return req


@wire_codec(tag="service.result", max_bytes=_MAX_FRAME)
def decode_result(payload: bytes):
    """Client-side decode:
    ``(req_id, status, mask, cert_or_None, root_or_None)``. The bitmap
    must be exactly ``ceil(n/8)`` wide (the canonical encoding) and the
    frame must end where the certificate tail ends."""
    r = maybe_wire_reader("service.result", payload)
    if r.u8() != TAG_RESULT:
        raise SerdeError("expected a result frame")
    req_id = r.u64()
    status = r.u8()
    n = r.u32()
    if n > _MAX_ROWS:
        raise SerdeError(f"result mask too wide: {n} rows")
    bitmap = r.raw()
    if len(bitmap) != -(-n // 8):
        raise SerdeError("result bitmap width disagrees with its row count")
    mask = [bool(bitmap[i >> 3] >> (i & 7) & 1) for i in range(n)]
    root = r.raw() or None
    if root is not None and len(root) != 32:
        raise SerdeError(f"state root must be 32 bytes, got {len(root)}")
    cert_bytes = r.raw()
    cert = unmarshal_certificate(
        maybe_wire_reader("cert.quorum", cert_bytes)
    ) if cert_bytes else None
    if not r.done():
        raise SerdeError("trailing bytes after result frame")
    return req_id, status, mask, cert, root


# ---------------------------------------------------------------- service


class ShardVerifyService:
    """One verifier + one async device-work queue, shared by every
    replica a host runs: the multi-tenant batching seam.

    A host that runs many replicas (one per shard/tenant it serves) must
    NOT let each of them launch its own verify — per-launch sync cost
    multiplied by tenant count is exactly the bill devsched exists to
    split. Every tenant submits into the same
    :class:`~hyperdrive_tpu.devsched.DeviceWorkQueue`, so windows from
    all of them coalesce into ONE launch per drain: the sync floor is
    paid once per pipeline slot per HOST, not per replica.

    ``policy`` installs a tenant-aware drain policy
    (:class:`~hyperdrive_tpu.devsched.DeficitRoundRobin`) on the queue;
    the default keeps the digest-neutral FIFO drain. ``cert_keep``
    bounds per-tenant certificate retention: entries more than
    ``cert_keep`` heights below the tenant's committed-height watermark
    are retired on accept, so a long-running service stays O(tenants),
    not O(heights). ``remote_port()`` opens the cross-process submit
    path (:class:`ServicePort`).

    The service is deliberately mesh-agnostic — it batches the *launch
    schedule*, while :func:`~hyperdrive_tpu.parallel.multihost.
    make_hybrid_mesh` shapes the *launch itself*; a pod host composes
    both (sharded verify kernels fed by a coalesced queue).
    """

    def __init__(self, verifier, queue=None, max_depth: int = 8,
                 obs=None, tracer=None, devtel=None, policy=None,
                 cert_keep=None, registry=None):
        from hyperdrive_tpu.devsched import DeviceWorkQueue

        #: Optional metrics :class:`~hyperdrive_tpu.obs.metrics.
        #: Registry` — the live metrics plane: when set, the remote
        #: port answers TAG_METRICS scrapes with its rendered
        #: Prometheus snapshot (admission-gated with the read path).
        self.registry = registry

        self.verifier = verifier
        self.queue = (
            queue
            if queue is not None
            else DeviceWorkQueue(max_depth=max_depth, obs=obs,
                                 tracer=tracer, devtel=devtel,
                                 policy=policy)
        )
        if devtel is not None:
            # An externally-built queue adopts the service's probe (the
            # same late-binding the sim applies to its queue).
            self.queue.devtel = devtel
        if policy is not None and self.queue.policy is None:
            self.queue.policy = policy
        self.obs = obs if obs is not None else self.queue.obs
        self._launcher = self.queue.verify_launcher(verifier)
        #: Commands submitted per tenant key (observability).
        self.tenants: dict = {}
        #: Tenant key -> small stable int track id (first-submit order):
        #: what the launch probe records as each command's origin, so
        #: journal events and registry labels agree on the tenant axis.
        #: Ids are never reused, even after :meth:`retire_tenant` — a
        #: revived tenant must not inherit a dead one's track.
        self.tenant_ids: dict = {}
        self._next_tid = 0
        #: tenant -> {height -> QuorumCertificate}: O(1) commit proofs
        #: accepted through :meth:`accept_certificate`. A proof that
        #: fails the certifier's check never lands here.
        self.certificates: dict = {}
        #: tenant -> highest committed height accepted (the retirement
        #: watermark; also the remote port's stale-height reference).
        self.watermarks: dict = {}
        self.cert_keep = None if cert_keep is None else int(cert_keep)
        self.retired_certs = 0
        #: tenant -> HostLedgerExecutor (see :meth:`attach_execution`).
        self.executors: dict = {}
        #: tenant -> {height -> 32-byte chained state root}.
        self.state_roots: dict = {}
        #: tenant -> :class:`~hyperdrive_tpu.exec.ledger.ProofBasis`:
        #: the frozen snapshot proof queries answer from, refreshed in
        #: :meth:`accept_certificate` whenever the executor sits exactly
        #: at the certified height with no open speculation. Queries
        #: never touch the live executor — it may be speculated ahead
        #: of the last certificate by the time a query lands.
        self.proof_bases: dict = {}

    def _tenant_id(self, tenant) -> int:
        tid = self.tenant_ids.get(tenant)
        if tid is None:
            tid = self.tenant_ids[tenant] = self._next_tid
            self._next_tid += 1
        return tid

    def certifier(self, signatories, f, obs=None):
        """A :class:`~hyperdrive_tpu.certificates.Certifier` for one
        tenant, transcript-bound to this service's shared launcher — its
        certificates commit to the coalesced launch that verified the
        quorum, whichever tenants co-submitted into it."""
        from hyperdrive_tpu.certificates import Certifier

        return Certifier(
            signatories, f,
            transcript_source=lambda: self._launcher.last_transcript,
            obs=obs,
        )

    def attach_execution(self, tenant, config, genesis_stakes=()):
        """Give ``tenant`` a replicated ledger on this host: every
        certificate accepted for it advances a deterministic
        :class:`~hyperdrive_tpu.exec.ledger.HostLedgerExecutor` and
        records the chained state root, so the O(1) certificate frame a
        shard gets back can vouch for ledger state, not just the
        committed value. The host executor is deliberate — the serving
        path stays jax-free, and host/device parity is enforced by the
        exec CLI smoke, so the root is the same either route. Returns
        the executor (tests read ``roots`` off it directly)."""
        from hyperdrive_tpu.exec.ledger import HostLedgerExecutor

        ex = HostLedgerExecutor(config, genesis_stakes=genesis_stakes)
        self.executors[tenant] = ex
        self.state_roots[tenant] = {}
        return ex

    def speculate_height(self, tenant, height: int) -> bool:
        """Tenant windows ride the speculative pipeline (PR 16): apply
        ``height``'s block at SUBMIT time under the exact unsigned
        guess, so by the time the quorum certificate lands,
        :meth:`accept_certificate`'s ``advance_to`` is a cached read —
        the window's verify latency and its block apply overlap instead
        of stacking. Exact speculation cannot mismatch (there is no
        guessed mask to be wrong), so the rollback machinery stays out
        of the serving path; signed-tx configs are excluded because
        their admission mask is only known after verification. Only the
        strictly-next height speculates — out-of-order or duplicate
        submits are a no-op (``advance_to`` still catches any gap).
        Returns True when the height was speculatively applied."""
        ex = self.executors.get(tenant)
        if (
            ex is None
            or ex.config.sign_txs
            or height != ex.height + 1
        ):
            return False
        ex.speculate(height, None)
        return True

    def accept_certificate(self, tenant, certifier, cert) -> bool:
        """Cross-tenant commit-proof exchange: re-verify ``cert`` in
        O(1) against ``certifier`` (quorum weight + binding; no
        signatures re-checked, no vote set re-gossiped) and register it
        under ``tenant`` on success. This replaces shipping the 2f+1
        precommits a remote shard would otherwise need to trust the
        commit."""
        from hyperdrive_tpu.obs.devtel import NULL_DEVTEL

        devtel = self.queue.devtel
        t0 = devtel.now() if devtel is not NULL_DEVTEL else 0.0
        ok = certifier.verify(cert)
        if devtel is not NULL_DEVTEL:
            # Per-tenant commit latency: the O(1) proof re-check that
            # finalizes a remote shard's commit locally. Rejected proofs
            # land in their own histogram — a forged or stale cert must
            # not pollute the committed-path p95/p99.
            devtel.tenant_latency(
                self._tenant_id(tenant),
                devtel.now() - t0,
                "commit" if ok else "commit_rejected",
            )
        if not ok:
            return False
        certs = self.certificates.setdefault(tenant, {})
        certs[cert.height] = cert
        ex = self.executors.get(tenant)
        if ex is not None:
            # Pin the root the frame will carry. When the height rode
            # the speculative pipeline (speculate_height at submit),
            # this confirms-in-passing and reads the cached root; a gap
            # or a non-speculative tenant is caught up deterministically
            # from the block source.
            self.state_roots[tenant][cert.height] = ex.advance_to(
                cert.height
            )
            if ex.height == cert.height and not ex._spec:
                # Freeze the newly-certified height for proof serving.
                # When the executor already ran ahead (pipelined
                # speculation), the basis simply lags one certificate —
                # clients verify against the trusted root at the
                # proof's own height, so a lagging basis is still a
                # sound answer.
                self.proof_bases[tenant] = ex.proof_basis()
        wm = self.watermarks.get(tenant, 0)
        if cert.height > wm:
            wm = self.watermarks[tenant] = cert.height
        if self.cert_keep is not None:
            floor = wm - self.cert_keep
            if floor > 0:
                stale = [h for h in certs if h <= floor]
                for h in stale:
                    del certs[h]
                if stale:
                    self.retired_certs += len(stale)
                    if self.obs is not NULL_BOUND:
                        self.obs.emit(
                            "service.tenant.retire", wm,
                            self._tenant_id(tenant), len(stale),
                        )
        return True

    def retire_tenant(self, tenant) -> int:
        """Drop every table entry for a departed tenant; returns how
        many certificates were released. The tenant's track id is
        retired with it (never reused)."""
        released = len(self.certificates.pop(tenant, ()))
        self.tenants.pop(tenant, None)
        tid = self.tenant_ids.pop(tenant, None)
        self.watermarks.pop(tenant, None)
        self.proof_bases.pop(tenant, None)
        if released:
            self.retired_certs += released
        if tid is not None and self.obs is not NULL_BOUND:
            self.obs.emit("service.tenant.retire", -1, tid, released)
        return released

    def submit(self, tenant, items, generation: int = 0):
        """Enqueue one tenant's verify batch; returns its
        :class:`~hyperdrive_tpu.devsched.DeviceFuture`. ``tenant`` is an
        opaque accounting key (replica id, shard id). ``generation``
        tags the batch with its epoch pubkey-table generation
        (epochs.py): tenants on different generations — mid-rotation,
        some tenants already switched — still share the queue, but
        their windows coalesce per generation, never into a mixed-key
        launch."""
        self.tenants[tenant] = self.tenants.get(tenant, 0) + 1
        tid = self._tenant_id(tenant)
        fut = self.queue.submit(
            self._launcher, items, generation,
            origin=tid, rows=len(items),
        )
        from hyperdrive_tpu.obs.devtel import NULL_DEVTEL

        devtel = self.queue.devtel
        if devtel is not NULL_DEVTEL:
            # Per-tenant verify latency: submit -> resolution, on the
            # probe's (injectable) clock, into a labeled mergeable
            # histogram (tenant.verify.latency{label=<tid>}).
            t0 = devtel.now()

            def _observe(f, devtel=devtel, t0=t0, tid=tid):
                devtel.tenant_latency(tid, devtel.now() - t0, "verify")

            fut.add_done_callback(_observe)
        return fut

    def rotate(self, generation: int, table=None) -> None:
        """Propagate an epoch rotation to the shared verifier: installs
        ``table`` when the verifier holds resident state
        (:meth:`~hyperdrive_tpu.ops.ed25519_wire.TpuWireVerifier.
        install_table` double-buffers it) and records the generation on
        transcript-binding verifiers. Tenants then pass ``generation``
        to :meth:`submit`; in-flight commands keep their old tag."""
        if table is not None and hasattr(self.verifier, "install_table"):
            self.verifier.install_table(table, generation)
        elif hasattr(self.verifier, "set_generation"):
            self.verifier.set_generation(generation)

    def flusher(self, validators, **kwargs):
        """A queue-backed :class:`~hyperdrive_tpu.tallyflush.
        DeviceTallyFlusher` for one tenant replica. Every flusher built
        here shares this service's queue (and verifier), which is the
        whole point: co-located replicas' flush windows coalesce."""
        from hyperdrive_tpu.tallyflush import DeviceTallyFlusher

        return DeviceTallyFlusher(
            self.verifier, validators, queue=self.queue, **kwargs
        )

    def remote_port(self, host: str = "127.0.0.1", port: int = 0,
                    controller=None, obs=None, trace=None) -> "ServicePort":
        """Open the cross-process submit path: replicas in other
        processes connect a :class:`RemoteServiceClient` here and their
        windows coalesce into the same launches as local tenants'."""
        return ServicePort(
            self, host=host, port=port, controller=controller, obs=obs,
            trace=trace,
        )

    def drain(self) -> int:
        """Resolve every tenant's pending commands (one coalesced
        launch); the host event loop's idle hook."""
        return self.queue.drain()

    def close(self) -> int:
        return self.queue.close()


# ----------------------------------------------------------- tenant shard


class TenantShard:
    """One shard-consensus instance's drive loop against a service.

    Deliberately smaller than a full :class:`~hyperdrive_tpu.harness.
    sim.Simulation`: the serving benchmark measures the VERIFY/COMMIT
    data path (window → coalesced launch → quorum → certificate), so the
    shard models exactly that — a deterministic committee
    (``KeyRing.deterministic`` under a per-tenant namespace) emitting
    one full precommit window per height. ``sign=False`` swaps real
    Ed25519 signatures for fixed nonzero bytes (the NullVerifier /
    chaos leg, jax- and crypto-free).

    ``commit_digest()`` is the cross-run equality handle: the same
    canonical fold the sim's ``SimulationResult.commit_digest`` uses,
    over this tenant's committed (height, value) pairs — shared-service
    vs per-tenant-queue vs remote-over-TCP runs must all agree on it.
    """

    def __init__(self, name: str, n_validators: int = 4, f=None,
                 target_height: int = 8, sign: bool = True,
                 time_fn=None, execution=None):
        self.name = str(name)
        #: Optional :class:`~hyperdrive_tpu.exec.ExecutionConfig`:
        #: attach_local registers it with the service so committed
        #: certificate frames carry the tenant's chained state root.
        self.execution = execution
        self.ring = KeyRing.deterministic(
            n_validators, namespace=b"tenant/" + self.name.encode()
        )
        self.f = (n_validators - 1) // 3 if f is None else int(f)
        self.target_height = int(target_height)
        self.sign = bool(sign)
        self.time_fn = time_fn if time_fn is not None else time.perf_counter
        self.certifier = None
        self.service = None
        self.client = None
        self.generation = 0
        #: height -> committed value (32 bytes), in acceptance order.
        self.commits: dict = {}
        #: height -> 32-byte state root the committed frame carried
        #: (execution-attached tenants only).
        self.state_roots: dict = {}
        #: Per-commit submit->finalize latency (seconds on time_fn).
        self.commit_latencies: list = []
        self.rejected = 0
        self.shed_retries = 0
        self.next_height = 1
        self._inflight = 0

    # ---------------------------------------------------------- windows

    def value_at(self, height: int) -> bytes:
        return hashlib.sha256(
            f"{self.name}:{height}".encode()
        ).digest()

    def window(self, height: int) -> list:
        """The full committee's signed precommits for ``height``."""
        value = self.value_at(height)
        rows = []
        for kp in self.ring.pairs:
            pc = Precommit(
                height=height, round=0, value=value, sender=kp.public
            )
            rows.append(
                kp.sign_message(pc) if self.sign
                else pc.with_signature(b"\x01" * 64)
            )
        return rows

    @property
    def done(self) -> bool:
        return len(self.commits) >= self.target_height

    @property
    def inflight(self) -> int:
        return self._inflight

    def commit_digest(self) -> str:
        h = hashlib.sha256()
        for height in sorted(self.commits):
            h.update(int(height).to_bytes(8, "little"))
            h.update(self.commits[height])
        return h.hexdigest()

    # ------------------------------------------------------- local drive

    def attach_local(self, service: ShardVerifyService,
                     generation: int = 0) -> "TenantShard":
        self.service = service
        self.generation = int(generation)
        self.certifier = service.certifier(self.ring.signatories, self.f)
        if self.execution is not None:
            service.attach_execution(self.name, self.execution)
        return self

    def pump(self, max_inflight: int = 2) -> int:
        """Submit up to ``max_inflight`` outstanding height windows into
        the attached local service; commits finalize inside the queue's
        drain via done-callbacks. Returns how many windows were
        submitted. The caller owns the drain cadence (that IS the
        continuous-batching knob)."""
        submitted = 0
        while (
            self.next_height <= self.target_height
            and self._inflight < max_inflight
        ):
            height = self.next_height
            self.next_height += 1
            self._inflight += 1
            value = self.value_at(height)
            rows = self.window(height)
            items = [(pc.sender, pc.digest(), pc.signature) for pc in rows]
            t0 = self.time_fn()
            fut = self.service.submit(self.name, items, self.generation)
            # Execution-attached tenants ride the speculative pipeline:
            # the height's block applies now, overlapping the window's
            # verify, and the certificate accept reads the cached root.
            self.service.speculate_height(self.name, height)
            fut.add_done_callback(
                lambda f, height=height, value=value, rows=rows, t0=t0:
                self._finalize(f, height, value, rows, t0)
            )
            submitted += 1
        return submitted

    def _finalize(self, fut, height, value, rows, t0) -> None:
        self._inflight -= 1
        mask = fut.result()
        signers = [pc.sender for pc, ok in zip(rows, mask) if ok]
        if len(set(signers)) < 2 * self.f + 1:
            self.rejected += 1
            return
        cert = self.certifier.observe_commit(height, 0, value, signers)
        if self.service.accept_certificate(self.name, self.certifier, cert):
            self.commits[height] = value
            root = self.service.state_roots.get(self.name, {}).get(height)
            if root is not None:
                self.state_roots[height] = root
            self.commit_latencies.append(self.time_fn() - t0)
        else:
            self.rejected += 1

    # ------------------------------------------------------ remote drive

    def attach_remote(self, client: "RemoteServiceClient",
                      generation: int = 0) -> "TenantShard":
        """Bind to a service in ANOTHER process: introduces the
        committee over the wire, and builds a local certifier — its
        :meth:`~hyperdrive_tpu.certificates.Certifier.verify` is fully
        self-contained (binding recomputation, no transcript state), so
        server-minted certificates finalize here in O(1)."""
        from hyperdrive_tpu.certificates import Certifier

        self.client = client
        self.generation = int(generation)
        self.certifier = Certifier(self.ring.signatories, self.f)
        client.hello(self.name, self.ring.signatories, self.f)
        return self

    @staticmethod
    def verify_balance(proof, trusted_root: bytes) -> bool:
        """The light-client check: does ``proof`` bind its (account,
        balance, stake) leaf into ``trusted_root`` — a chained state
        root this shard already holds from its own certificate chain?
        Pure recomputation (ops/merkle.py ``verify_inclusion``); the
        serving replica is trusted for nothing."""
        from hyperdrive_tpu.ops.merkle import verify_inclusion

        return verify_inclusion(
            trusted_root, proof.account, proof.balance, proof.stake,
            proof,
        )

    def run_remote(self, max_inflight: int = 4, timeout: float = 30.0,
                   max_shed_retries: int = 1024) -> None:
        """Drive every height through the attached client. Keeps
        ``max_inflight`` windows on the wire so the serving host can
        coalesce them with other tenants' work; a shed (busy) answer
        re-submits the same height — backpressure is flow control here,
        never data loss."""
        pending: dict = {}
        while not self.done:
            while (
                self.next_height <= self.target_height
                and len(pending) < max_inflight
            ):
                height = self.next_height
                self.next_height += 1
                pending[height] = self._remote_submit(height)
            if not pending:
                break
            height = min(pending)
            fut, value, t0 = pending.pop(height)
            status, mask, cert = fut.result(timeout)
            if status == STATUS_SHED:
                self.shed_retries += 1
                if self.shed_retries > max_shed_retries:
                    raise RuntimeError(
                        f"tenant {self.name}: height {height} shed "
                        f"{max_shed_retries} times"
                    )
                pending[height] = self._remote_submit(height)
                continue
            if (
                status == STATUS_COMMITTED
                and cert is not None
                and cert.height == height
                and self.certifier.verify(cert)
            ):
                self.commits[height] = value
                if fut.root is not None:
                    self.state_roots[height] = fut.root
                self.commit_latencies.append(self.time_fn() - t0)
            else:
                self.rejected += 1

    def _remote_submit(self, height: int):
        value = self.value_at(height)
        rows = self.window(height)
        t0 = self.time_fn()
        fut = self.client.submit(
            height, 0, value, rows, generation=self.generation
        )
        return (fut, value, t0)


# ------------------------------------------------------------ server port


class _RemoteConn:
    """One accepted connection's state: socket, bounded sender queue,
    and — after HELLO — the tenant identity, its certifier, and its
    admission gate."""

    __slots__ = (
        "sock", "outbox", "tenant", "f", "certifier", "gate",
        "send_drops", "closed",
    )

    def __init__(self, sock):
        self.sock = sock
        self.outbox = queue_mod.Queue(maxsize=4096)
        self.tenant = None
        self.f = 0
        self.certifier = None
        self.gate = None
        self.send_drops = 0
        self.closed = False


class ServicePort:
    """The cross-process submit path of one :class:`ShardVerifyService`.

    Socket I/O runs on daemon threads (an accept loop plus one
    reader/sender pair per connection — the transport.py shape), but
    every decision touches the service on the owner's drive loop:
    readers park decoded requests in an inbox, and :meth:`pump` —
    called from the same thread that drains the queue — admits,
    submits, and resolves. The queue's single-writer discipline is
    preserved by construction.

    Admission reuses the ``load/`` doctrine verbatim: a
    :class:`~hyperdrive_tpu.load.backpressure.BackpressureController`
    watching the shared queue sets the level, and each tenant's
    :class:`~hyperdrive_tpu.load.backpressure.AdmissionGate` sheds
    duplicate/stale precommit rows at SHED_DUPLICATES and above (the
    gate's ``height_fn`` is the tenant's committed watermark, so replays
    of finalized heights classify stale). At CRITICAL_ONLY the port
    answers ``STATUS_SHED`` without touching the queue — the client
    retries, so overload is flow control, not loss.
    """

    def __init__(self, service: ShardVerifyService,
                 host: str = "127.0.0.1", port: int = 0,
                 controller=None, obs=None, trace=None):
        from hyperdrive_tpu.load.backpressure import BackpressureController

        self.service = service
        self.obs = obs if obs is not None else service.obs
        #: Optional :class:`~hyperdrive_tpu.obs.tracectx.TraceSource`:
        #: when set, every answer frame carries a causal stamp and the
        #: hello-ack advertises this origin id for offset estimation.
        #: Inbound stamped requests are stripped + marked ``trace.recv``
        #: regardless (stamp recognition costs one byte compare).
        self.trace = trace
        if controller is None:
            controller = BackpressureController()
            controller.watch(service.queue)
        self.controller = controller
        self._inbox: queue_mod.Queue = queue_mod.Queue()
        self._conns: list = []
        self._lock = threading.Lock()
        self._closed = False
        #: Remote windows submitted into the queue and not yet resolved.
        self.inflight = 0
        #: Lifetime counters (tests / the serve report).
        self.remote_submits = 0
        self.remote_resolves = 0
        self.remote_sheds = 0
        self.remote_queries = 0
        self.query_sheds = 0
        self.metrics_serves = 0
        self.metrics_sheds = 0
        self.bad_frames = 0
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(64)
        self._srv = srv
        self.address = srv.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="svcport-accept", daemon=True
        )
        self._accept_thread.start()

    # --------------------------------------------------------- io threads

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _RemoteConn(sock)
            with self._lock:
                self._conns.append(conn)
            threading.Thread(
                target=self._read_loop, args=(conn,),
                name="svcport-read", daemon=True,
            ).start()
            threading.Thread(
                target=self._send_loop, args=(conn,),
                name="svcport-send", daemon=True,
            ).start()

    def _read_loop(self, conn: _RemoteConn) -> None:
        sock = conn.sock
        try:
            while True:
                header = _recv_exact(sock, _LEN.size)
                if header is None:
                    return
                (n,) = _LEN.unpack(header)
                if n > _MAX_FRAME:
                    return
                payload = _recv_exact(sock, n)
                if payload is None:
                    return
                self._inbox.put((conn, payload))
        except OSError:
            return
        finally:
            conn.closed = True

    def _send_loop(self, conn: _RemoteConn) -> None:
        while True:
            frame = conn.outbox.get()
            if frame is None:
                return
            try:
                conn.sock.sendall(frame)
            except OSError:
                conn.closed = True
                return

    def _send(self, conn: _RemoteConn, payload: bytes) -> None:
        if self.trace is not None:
            payload = self.trace.stamp(payload)
        try:
            conn.outbox.put_nowait(_LEN.pack(len(payload)) + payload)
        except queue_mod.Full:
            conn.send_drops += 1

    # -------------------------------------------------------- drive loop

    def pump(self, max_requests: int = 64) -> int:
        """Process up to ``max_requests`` parked requests on the
        caller's (drive-loop) thread. Submitted windows resolve at the
        next queue drain, whose done-callbacks send the certificate
        frames back. Returns how many requests were handled."""
        handled = 0
        while handled < max_requests:
            try:
                conn, payload = self._inbox.get_nowait()
            except queue_mod.Empty:
                break
            handled += 1
            try:
                ctx = None
                if payload and payload[0] == TRACE_MAGIC:
                    ctx, payload = split_trace_frame(payload)
                req = decode_request(payload)
            except SerdeError:
                self.bad_frames += 1
                continue
            if ctx is not None and self.obs is not NULL_BOUND:
                note_trace_recv(
                    self.obs, ctx,
                    req[2] if req[0] == "submit" else -1,
                )
            if req[0] == "hello":
                self._handle_hello(conn, *req[1:])
            elif req[0] == "query":
                self._handle_query(conn, *req[1:])
            elif req[0] == "metrics":
                self._handle_metrics(conn, *req[1:])
            else:
                self._handle_submit(conn, *req[1:])
        return handled

    def _handle_hello(self, conn, name, f, signatories,
                      t0: float = 0.0) -> None:
        from hyperdrive_tpu.load.backpressure import AdmissionGate

        conn.tenant = name
        conn.f = int(f)
        # The port's obs handle rides into the certifier so cert.emit
        # marks land in the journal at the minted height — the
        # critical-path report's "cert" milestone.
        conn.certifier = self.service.certifier(
            signatories, f, obs=self.obs
        )
        watermarks = self.service.watermarks
        conn.gate = AdmissionGate(
            self.controller,
            height_fn=lambda name=name: watermarks.get(name, 0) + 1,
        )
        # Echo handshake: hand back the client's send stamp plus our
        # own wall-clock so it can place this process on its offset
        # graph. Answered for every hello — a pre-echo client's read
        # loop drops the unexpected frame as a typed decode miss.
        origin = self.trace.origin if self.trace is not None else 0
        self._send(
            conn, encode_hello_ack(t0, time.time(), origin)
        )

    def _handle_metrics(self, conn, req_id) -> None:
        """One TAG_METRICS request → ONE Prometheus-text frame (or a
        status-only refusal). Scrapes ride the tenant's admission gate
        classed WITH proof queries: at SHED_LOW_PRIORITY and above the
        port answers STATUS_SHED without rendering anything — the
        observability plane is the first load shed, never a reason
        consensus traffic queues."""
        from hyperdrive_tpu.load.frames import MetricsFrame

        if conn.tenant is None:
            self._send(
                conn,
                encode_metrics_reply(req_id, STATUS_UNKNOWN_TENANT),
            )
            return
        self.controller.poll()
        if not conn.gate.admit(MetricsFrame(), peer=conn.tenant):
            self.metrics_sheds += 1
            if self.obs is not NULL_BOUND:
                self.obs.emit("metrics.shed", -1, -1, conn.tenant)
            self._send(conn, encode_metrics_reply(req_id, STATUS_SHED))
            return
        registry = self.service.registry
        if registry is None:
            self._send(
                conn, encode_metrics_reply(req_id, STATUS_NO_STATE)
            )
            return
        from hyperdrive_tpu.obs.metrics import to_prometheus

        # Refresh the service-posture gauges at scrape time so every
        # answer reflects live state (a pull-model scrape, not a stale
        # copy). Commit latency lands in the registry on each resolve.
        registry.set_gauge("service.queue.depth",
                           self.service.queue.depth)
        registry.set_gauge("service.queue.launches",
                           self.service.queue.launches)
        registry.set_gauge("service.queue.coalesced",
                           self.service.queue.coalesced)
        registry.set_gauge("service.remote.submits", self.remote_submits)
        registry.set_gauge("service.remote.resolves",
                           self.remote_resolves)
        registry.set_gauge("service.remote.sheds", self.remote_sheds)
        registry.set_gauge("service.metrics.serves", self.metrics_serves)
        registry.set_gauge("service.metrics.sheds", self.metrics_sheds)
        text = to_prometheus(registry.snapshot())
        self.metrics_serves += 1
        if self.obs is not NULL_BOUND:
            self.obs.emit("metrics.serve", -1, -1, len(text))
        self._send(
            conn, encode_metrics_reply(req_id, STATUS_COMMITTED, text)
        )

    def _handle_query(self, conn, req_id, account) -> None:
        """One TAG_QUERY request → ONE proof frame (or a status-only
        refusal). Queries ride the tenant's admission gate as the
        ``query`` shed class: at SHED_LOW_PRIORITY and above the port
        answers STATUS_SHED without touching any ledger state, so a
        read storm degrades reads first and never queues ahead of
        certificates. Serving itself reads the frozen
        :class:`~hyperdrive_tpu.exec.ledger.ProofBasis` — O(log n)
        numpy indexing, no executor locks, no speculation hazard."""
        from hyperdrive_tpu.load.frames import QueryFrame

        if conn.tenant is None:
            self._send(conn, encode_proof(req_id, STATUS_UNKNOWN_TENANT))
            return
        self.controller.poll()
        if not conn.gate.admit(QueryFrame(account=account),
                               peer=conn.tenant):
            self.query_sheds += 1
            if self.obs is not NULL_BOUND:
                self.obs.emit("proof.shed", -1, -1, conn.tenant)
            self._send(conn, encode_proof(req_id, STATUS_SHED))
            return
        basis = self.service.proof_bases.get(conn.tenant)
        if basis is None or not 0 <= account < basis.accounts:
            self._send(conn, encode_proof(req_id, STATUS_NO_STATE))
            return
        payload = encode_proof(
            req_id, STATUS_COMMITTED, basis.prove(account)
        )
        self.remote_queries += 1
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "proof.serve", basis.height, -1,
                "account=%d bytes=%d" % (account, len(payload)),
            )
        self._send(conn, payload)

    def _handle_submit(self, conn, req_id, height, rnd, value,
                       generation, rows) -> None:
        from hyperdrive_tpu.load.backpressure import CRITICAL_ONLY

        if conn.tenant is None:
            self._send(
                conn,
                encode_result(req_id, STATUS_UNKNOWN_TENANT, len(rows), ()),
            )
            return
        if self.controller.poll() >= CRITICAL_ONLY:
            # Panic level: answer busy without touching the queue. The
            # client re-submits — certificates/windows are never lost,
            # merely deferred (the load/ doctrine's never-drop-quorum
            # rule, expressed as flow control).
            self.remote_sheds += 1
            if self.obs is not NULL_BOUND:
                self.obs.emit(
                    "service.remote.shed", height, rnd, conn.tenant
                )
            self._send(
                conn, encode_result(req_id, STATUS_SHED, len(rows), ())
            )
            return
        precommits = [
            Precommit(
                height=height, round=rnd, value=value, sender=sender,
                signature=sig,
            )
            for sender, sig in rows
        ]
        admitted_idx = [
            i for i, pc in enumerate(precommits)
            if conn.gate.admit(pc, peer=conn.tenant)
        ]
        if rows and not admitted_idx:
            # Every row shed (duplicate window / stale height): busy-
            # answer so the client backs off and retries or moves on.
            self.remote_sheds += 1
            if self.obs is not NULL_BOUND:
                self.obs.emit(
                    "service.remote.shed", height, rnd, conn.tenant
                )
            self._send(
                conn, encode_result(req_id, STATUS_SHED, len(rows), ())
            )
            return
        items = [
            (precommits[i].sender, precommits[i].digest(),
             precommits[i].signature)
            for i in admitted_idx
        ]
        self.remote_submits += 1
        self.inflight += 1
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "service.remote.submit", height, rnd, len(items)
            )
        t_sub = time.time()
        fut = self.service.submit(conn.tenant, items, generation)
        fut.add_done_callback(
            lambda f, conn=conn, req_id=req_id, height=height, rnd=rnd,
            value=value, rows=rows, admitted_idx=admitted_idx,
            t_sub=t_sub:
            self._resolve(
                f, conn, req_id, height, rnd, value, rows, admitted_idx,
                t_sub,
            )
        )

    def _resolve(self, fut, conn, req_id, height, rnd, value, rows,
                 admitted_idx, t_sub=None) -> None:
        """Queue-drain callback: fold the launch verdict back into a
        full-window mask, mint the certificate if the quorum stands,
        and answer with ONE O(1) certificate frame — never the 2f+1
        signatures."""
        self.inflight -= 1
        verdict = [] if fut.cancelled() else fut.result()
        mask = [False] * len(rows)
        for i, ok in zip(admitted_idx, verdict):
            mask[i] = bool(ok)
        signers = [rows[i][0] for i in range(len(rows)) if mask[i]]
        status = STATUS_NO_QUORUM
        cert = None
        if len(set(signers)) >= 2 * conn.f + 1:
            cert = conn.certifier.observe_commit(height, rnd, value, signers)
            if self.service.accept_certificate(
                conn.tenant, conn.certifier, cert
            ):
                status = STATUS_COMMITTED
            else:
                cert = None
        self.remote_resolves += 1
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "service.remote.resolve", height, rnd,
                STATUS_NAMES[status],
            )
        # The finality-SLO source: per-tenant submit→certificate wall
        # time, same histogram name the device-telemetry leg uses so
        # slo.evaluate_slos reads one series either way.
        registry = self.service.registry
        if (registry is not None and t_sub is not None
                and status == STATUS_COMMITTED):
            registry.observe(
                "tenant.commit.latency", time.time() - t_sub,
                label=conn.tenant,
            )
        root = None
        if status == STATUS_COMMITTED:
            root = self.service.state_roots.get(
                conn.tenant, {}
            ).get(height)
        self._send(
            conn,
            encode_result(req_id, status, len(rows), mask, cert,
                          root=root),
        )

    def close(self) -> None:
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.outbox.put_nowait(None)
            except queue_mod.Full:
                pass
            try:
                conn.sock.close()
            except OSError:
                pass


# ----------------------------------------------------------- client side


class RemoteFuture:
    """Resolution handle for one remote window: a thread event the
    client's reader sets when the certificate frame lands."""

    __slots__ = ("_event", "status", "mask", "cert", "root", "proof",
                 "text")

    def __init__(self):
        self._event = threading.Event()
        self.status = None
        self.mask = None
        self.cert = None
        #: 32-byte chained state root the committed frame carried, or
        #: None (execution-attached tenants only). Deliberately outside
        #: :meth:`result`'s tuple so root-less deployments keep their
        #: 3-tuple unpack.
        self.root = None
        #: :class:`~hyperdrive_tpu.ops.merkle.MerkleProof` for a
        #: TAG_QUERY request (None on submit futures and refusals).
        self.proof = None
        #: Prometheus exposition text for a TAG_METRICS request (None
        #: on every other future and on refusals).
        self.text = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float = 30.0):
        """``(status, mask, cert_or_None)``; raises TimeoutError if the
        serving host never answers (a closed port fails loudly, it does
        not hang the tenant forever)."""
        if not self._event.wait(timeout):
            raise TimeoutError("remote verify window timed out")
        return self.status, self.mask, self.cert

    def proof_result(self, timeout: float = 30.0):
        """``(status, proof_or_None)`` for a TAG_QUERY request."""
        if not self._event.wait(timeout):
            raise TimeoutError("remote proof query timed out")
        return self.status, self.proof

    def metrics_result(self, timeout: float = 30.0):
        """``(status, text_or_None)`` for a TAG_METRICS request."""
        if not self._event.wait(timeout):
            raise TimeoutError("remote metrics scrape timed out")
        return self.status, self.text


class RemoteServiceClient:
    """One remote tenant's connection to a :class:`ServicePort`.

    ``submit`` is async (returns a :class:`RemoteFuture`); a daemon
    reader thread resolves futures as result frames arrive, so a tenant
    can keep several windows on the wire — which is exactly what lets
    the serving host coalesce them with other tenants' work."""

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 obs=None, trace=None):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(None)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        #: Flight-recorder handle for ``trace.recv`` / ``trace.offset``
        #: marks (the reader thread emits, so bind a threadsafe
        #: Recorder) and :class:`~hyperdrive_tpu.obs.tracectx.
        #: TraceSource` for stamping outbound requests.
        self.obs = obs if obs is not None else NULL_BOUND
        self.trace = trace
        #: server trace-origin id -> estimated clock offset (seconds,
        #: ``server_clock - local_clock``) from the hello-ack echo.
        self.clock_offsets: dict = {}
        self._send_lock = threading.Lock()
        self._pending_lock = threading.Lock()
        self._pending: dict = {}
        self._next_req = 1
        self._reader = threading.Thread(
            target=self._read_loop, name="svcclient-read", daemon=True
        )
        self._reader.start()

    def hello(self, name: str, signatories, f: int) -> None:
        self._send(encode_hello(name, signatories, f, t0=time.time()))

    def submit(self, height: int, round: int, value: bytes, rows,
               generation: int = 0) -> RemoteFuture:
        fut = RemoteFuture()
        with self._pending_lock:
            req_id = self._next_req
            self._next_req += 1
            self._pending[req_id] = fut
        self._send(
            encode_submit(req_id, height, round, value, rows, generation)
        )
        return fut

    def query(self, account: int) -> RemoteFuture:
        """Request an O(log n) inclusion proof for ``account`` at the
        tenant's latest certified height. Resolve with
        :meth:`RemoteFuture.proof_result`; STATUS_SHED / STATUS_NO_STATE
        answers are retryable, exactly like shed submits."""
        fut = RemoteFuture()
        with self._pending_lock:
            req_id = self._next_req
            self._next_req += 1
            self._pending[req_id] = fut
        self._send(encode_query(req_id, account))
        return fut

    def metrics(self) -> RemoteFuture:
        """Scrape the serving host's metrics Registry: one TAG_METRICS
        request → the rendered Prometheus snapshot. Resolve with
        :meth:`RemoteFuture.metrics_result`; STATUS_SHED answers are
        retryable — and by doctrine the FIRST thing shed under load."""
        fut = RemoteFuture()
        with self._pending_lock:
            req_id = self._next_req
            self._next_req += 1
            self._pending[req_id] = fut
        self._send(encode_metrics_request(req_id))
        return fut

    def _send(self, payload: bytes) -> None:
        if self.trace is not None:
            payload = self.trace.stamp(payload)
        frame = _LEN.pack(len(payload)) + payload
        with self._send_lock:
            self.sock.sendall(frame)

    def _read_loop(self) -> None:
        try:
            while True:
                header = _recv_exact(self.sock, _LEN.size)
                if header is None:
                    return
                (n,) = _LEN.unpack(header)
                if n > _MAX_FRAME:
                    return
                payload = _recv_exact(self.sock, n)
                if payload is None:
                    return
                try:
                    if payload and payload[0] == TRACE_MAGIC:
                        ctx, payload = split_trace_frame(payload)
                        if self.obs is not NULL_BOUND:
                            note_trace_recv(self.obs, ctx)
                    text = None
                    if payload and payload[0] == TAG_QUERY:
                        req_id, status, proof = decode_proof(payload)
                        mask = cert = root = None
                    elif payload and payload[0] == TAG_HELLO:
                        self._note_offset(*decode_hello_ack(payload))
                        continue
                    elif payload and payload[0] == TAG_METRICS:
                        req_id, status, text = decode_metrics_reply(
                            payload
                        )
                        mask = cert = root = proof = None
                    else:
                        req_id, status, mask, cert, root = decode_result(
                            payload
                        )
                        proof = None
                except SerdeError:
                    continue
                with self._pending_lock:
                    fut = self._pending.pop(req_id, None)
                if fut is not None:
                    fut.status = status
                    fut.mask = mask
                    fut.cert = cert
                    fut.root = root
                    fut.proof = proof
                    fut.text = text
                    fut._event.set()
        except OSError:
            return

    def _note_offset(self, t0: float, t1: float, origin: int) -> None:
        """Fold one hello-ack echo into the offset table: NTP-style,
        ``offset ≈ t1 - (t0 + t3) / 2`` — the server's receive stamp
        against the midpoint of the round trip. A pre-echo server (t0
        never stamped) or an untraced port (origin 0) contributes
        nothing."""
        if not t0 or not origin:
            return
        t3 = time.time()
        offset = t1 - (t0 + t3) / 2.0
        self.clock_offsets[origin] = offset
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "trace.offset", -1, -1, f"{origin}:{offset:.6f}"
            )

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
