"""Sharded verification + tally: the multi-chip consensus data path.

Domain decomposition (SURVEY.md sections 2.3, 5):

- **validator axis** (``val``): votes land sharded by sender across chips —
  the data-parallel axis. Each chip verifies its shard's signatures
  locally; per-round tallies are partial sums combined with one ``psum``
  over the ICI ring. This is the moral equivalent of the reference's
  replicated-state-machine parallelism, with the O(n) map scans replaced
  by local reductions + one collective.
- **round axis** (``hr``): independent in-flight (height, round) pairs —
  the pipeline-like axis. Rounds never need cross-round communication, so
  sharding them is embarrassingly parallel; it exists to scale the number
  of simultaneously-open consensus instances (SURVEY.md section 5
  "long-context analogue").

The full step = batched Ed25519 verify of every vote in the window +
masked quorum tallies + threshold flags, compiled once under ``jit`` with
``shard_map`` inside.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hyperdrive_tpu.ops import fe25519 as fe
from hyperdrive_tpu.ops import tally as tally_ops
from hyperdrive_tpu.ops.ed25519_jax import verify_kernel

__all__ = [
    "make_mesh",
    "sharded_verify_tally",
    "sharded_chalwire_tally",
    "make_sharded_step",
    "grid_pack",
    "grid_pack_wire",
]


def make_mesh(devices=None, hr: int = 1, val: int | None = None) -> Mesh:
    """Build a 2D ('hr', 'val') mesh over the given (or all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if val is None:
        val = n // hr
    if hr * val != n:
        raise ValueError(f"hr*val must equal device count ({hr}*{val} != {n})")
    arr = np.array(devices).reshape(hr, val)
    return Mesh(arr, axis_names=("hr", "val"))


def _pick_kernel(backend: str | None, mesh: Mesh):
    """Resolve the per-shard verify kernel: the Pallas ladder when the
    MESH'S devices are Mosaic-capable (7x the XLA kernel — see
    ops/ed25519_pallas.py), the XLA kernel elsewhere (CPU meshes in tests
    and the dryrun — which can coexist with a TPU default backend, so the
    decision keys off the mesh, not the process default)."""
    from hyperdrive_tpu.ops.ed25519_pallas import resolve_backend

    if resolve_backend(backend, devices=mesh.devices) == "pallas":
        from hyperdrive_tpu.ops.ed25519_pallas import _BLOCK, verify_pallas

        def kernel(ax, ay, at, rx, ry, s_nib, k_nib):
            # Match the block to the per-shard local batch so fine-grained
            # hr x val splits don't pad every shard to 256 lanes (up to 4x
            # the ladder work), clamped at >=128 — sub-128 blocks are
            # below the TPU tile width; verify_pallas pads a smaller
            # batch up to one block.
            block = min(_BLOCK, max(ax.shape[0], 128))
            return verify_pallas(
                ax, ay, at, rx, ry, s_nib, k_nib, block=block
            )

        return kernel
    return verify_kernel


def _tally_psum(ok, vote_vals, target_vals, f):
    """Local masked tallies, then one collective over the validator
    axis — the one definition every sharded step's tail shares."""
    counts = tally_ops.tally_counts(vote_vals, ok, target_vals)
    counts = {k: lax.psum(v, axis_name="val") for k, v in counts.items()}
    flags = tally_ops.quorum_flags(counts, f)
    return counts, flags, ok


def _local_step(ax, ay, at, rx, ry, s_nib, k_nib, vote_vals, target_vals, f,
                *, kernel=verify_kernel):
    """Per-shard work: verify local signatures, tally locally, psum.

    Shapes (local shard): ax.. [R, V, 20], nibbles [R, V, 64],
    vote_vals [R, V, 8], target_vals [R, 8], f scalar int32.
    """
    r_l, v_l = ax.shape[0], ax.shape[1]

    def flat(a):
        return a.reshape((r_l * v_l,) + a.shape[2:])

    ok = kernel(
        flat(ax), flat(ay), flat(at), flat(rx), flat(ry),
        flat(s_nib), flat(k_nib),
    ).reshape(r_l, v_l)
    return _tally_psum(ok, vote_vals, target_vals, f)


def sharded_verify_tally(mesh: Mesh, backend: str | None = None):
    """Compile the full verify+tally step over ``mesh``.

    Input global shapes: signature arrays [R, V, ...] sharded (hr, val);
    target values [R, 8] sharded (hr,); f replicated. Outputs: counts and
    flags [R] sharded over 'hr' (replicated over 'val' after the psum),
    and the verification mask [R, V].

    ``backend``: None (auto — Pallas ladder on TPU, XLA kernel on CPU
    meshes), or "pallas"/"xla" explicitly. The per-shard local batch must
    be a multiple of the Pallas block or small enough to pad (the
    verify_pallas wrapper pads ragged shards).
    """
    spec_rv = P("hr", "val")
    spec_r = P("hr")
    kernel = _pick_kernel(backend, mesh)

    shard_fn = jax.shard_map(
        partial(_local_step, kernel=kernel),
        mesh=mesh,
        in_specs=(
            spec_rv, spec_rv, spec_rv, spec_rv, spec_rv,  # ax..ry
            spec_rv, spec_rv,  # nibbles
            spec_rv,  # vote values
            spec_r,  # target values
            P(),  # f
        ),
        out_specs=(
            {"matching": spec_r, "nil": spec_r, "total": spec_r},
            {
                "quorum_matching": spec_r,
                "quorum_nil": spec_r,
                "quorum_any": spec_r,
                "skip_eligible": spec_r,
            },
            spec_rv,
        ),
        check_vma=False,
    )
    return jax.jit(shard_fn)


def sharded_chalwire_tally(mesh: Mesh, backend: str | None = None):
    """The round-4 wire format, multi-chip: the 68 B/lane challenge-on-
    device pipeline sharded over ('hr', 'val').

    Lanes land sharded by (round, validator); the validator table
    (decompressed coords + compressed encodings, ~73 KB at 256
    validators) is REPLICATED — it is consensus configuration, not data.
    Each shard gathers its pubkeys by global index, derives
    k = SHA-512(R||A||M) mod L locally (per-round digests broadcast to
    the shard's lanes — zero per-lane transfer), decompresses R, runs
    the ladder, tallies locally, and one psum over 'val' combines the
    quorum counts. Two sharded executables with k staying device-
    resident and sharded between them — the same hash/ladder split as
    the single-chip path (see ed25519_wire.make_chalwire_verify_fn for
    why they must not fuse).

    Input global shapes: idx [R, V] int32, r_rows/s_rows [R, V, 32]
    uint8 sharded (hr, val); m_round [R, 32] uint8 sharded (hr,); the
    five ValidatorTable.arrays_chal() tensors replicated; vote_vals
    [R, V, 8] (hr, val); target_vals [R, 8] (hr,); f replicated.
    Outputs match :func:`sharded_verify_tally`.
    """
    from hyperdrive_tpu.ops.ed25519_wire import (
        challenge_from_round,
        semiwire_verify_kernel,
    )

    spec_rv = P("hr", "val")
    spec_r = P("hr")
    kernel = _pick_kernel(backend, mesh)

    def chal_local(idx, r_rows, m_round, trows):
        r_l, v_l = idx.shape
        k = challenge_from_round(
            idx.reshape(-1), r_rows.reshape(r_l * v_l, 32), m_round,
            trows, v_l,
        )
        return k.reshape(r_l, v_l, 32)

    # hdlint: disable=HD002 factory-local jit captured by the returned closure; compiled once per mesh
    chal_fn = jax.jit(jax.shard_map(
        chal_local,
        mesh=mesh,
        in_specs=(spec_rv, spec_rv, spec_r, P()),
        out_specs=spec_rv,
        check_vma=False,
    ))

    def ladder_local(idx, r_rows, s_rows, k_rows, tnax, tay, tnat, tvalid,
                     vote_vals, target_vals, f):
        r_l, v_l = idx.shape
        ok = semiwire_verify_kernel(
            idx.reshape(-1),
            r_rows.reshape(r_l * v_l, 32),
            s_rows.reshape(r_l * v_l, 32),
            k_rows.reshape(r_l * v_l, 32),
            tnax, tay, tnat, tvalid,
            kernel=kernel,
        ).reshape(r_l, v_l)
        return _tally_psum(ok, vote_vals, target_vals, f)

    # hdlint: disable=HD002 factory-local jit captured by the returned closure; compiled once per mesh
    ladder_fn = jax.jit(jax.shard_map(
        ladder_local,
        mesh=mesh,
        in_specs=(
            spec_rv, spec_rv, spec_rv, spec_rv,  # idx, r, s, k
            P(), P(), P(), P(),  # table coords + valid (replicated)
            spec_rv, spec_r, P(),  # votes, targets, f
        ),
        out_specs=(
            {"matching": spec_r, "nil": spec_r, "total": spec_r},
            {
                "quorum_matching": spec_r,
                "quorum_nil": spec_r,
                "quorum_any": spec_r,
                "skip_eligible": spec_r,
            },
            spec_rv,
        ),
        check_vma=False,
    ))

    def step(idx, r_rows, s_rows, m_round, tnax, tay, tnat, tvalid, trows,
             vote_vals, target_vals, f):
        k_rows = chal_fn(idx, r_rows, m_round, trows)
        return ladder_fn(idx, r_rows, s_rows, k_rows, tnax, tay, tnat,
                         tvalid, vote_vals, target_vals, f)

    return step


def grid_pack(ring, rounds: int, validators: int, values, corrupt=()):
    """Sign one vote per (round, validator) and pack to [R, V, ...] arrays
    ready for :func:`sharded_verify_tally`.

    ``values``: one 32-byte proposal value per round (each vote's digest is
    ``values[r] + bytes([r])``). ``corrupt``: set of (r, v) pairs whose
    signature scalar s gets one bit flipped — the lane still *parses*
    (prevalid stays True; s remains < L except with negligible probability)
    so rejection exercises the device kernel, not the host packer.
    Returns (shaped_arrays, prevalid[R, V]).
    """
    from hyperdrive_tpu.crypto import ed25519 as host_ed
    from hyperdrive_tpu.ops.ed25519_jax import Ed25519BatchHost

    host = Ed25519BatchHost(buckets=(rounds * validators,))
    items = []
    for r in range(rounds):
        for v in range(validators):
            kp = ring[v]
            digest = values[r] + bytes([r])
            sig = host_ed.sign(kp.seed, digest)
            if (r, v) in corrupt:
                sig = sig[:40] + bytes([sig[40] ^ 1]) + sig[41:]
            items.append((kp.public, digest, sig))
    arrays, prevalid, n = host.pack(items)
    assert n == rounds * validators
    shaped = tuple(
        jnp.asarray(a).reshape(rounds, validators, *a.shape[1:]) for a in arrays
    )
    return shaped, prevalid.reshape(rounds, validators)


def grid_pack_wire(ring, rounds: int, validators: int, values, corrupt=()):
    """Sign one vote per (round, validator) and marshal to the sharded
    CHALLENGE wire format for :func:`sharded_chalwire_tally`.

    ``values``: one 32-byte value per round; the signing digest is the
    32-byte ``bytes([r]) + values[r][1:]`` (distinct per round, shared by
    the round's validators — the consensus digest shape). ``corrupt``:
    (r, v) pairs whose signature scalar gets one bit flipped (still
    parses; rejection exercises the device kernels). Returns
    ((idx [R,V], r_rows [R,V,32], s_rows [R,V,32], m_round [R,32]),
    table, prevalid [R,V])."""
    from hyperdrive_tpu.crypto import ed25519 as host_ed
    from hyperdrive_tpu.ops.ed25519_wire import (
        Ed25519WireHost,
        ValidatorTable,
    )

    table = ValidatorTable([ring[v].public for v in range(validators)])
    host = Ed25519WireHost(buckets=(rounds * validators,))
    m_round = np.zeros((rounds, 32), dtype=np.uint8)
    items = []
    for r in range(rounds):
        digest = bytes([r]) + values[r][1:]
        m_round[r] = np.frombuffer(digest, dtype=np.uint8)
        for v in range(validators):
            sig = host_ed.sign(ring[v].seed, digest)
            if (r, v) in corrupt:
                sig = sig[:40] + bytes([sig[40] ^ 1]) + sig[41:]
            items.append((ring[v].public, digest, sig))
    (idx, r_rows, s_rows, _), prevalid, n = host.pack_wire_challenge(
        items, table, with_m=False
    )
    assert n == rounds * validators
    shaped = (
        jnp.asarray(idx.reshape(rounds, validators)),
        jnp.asarray(r_rows.reshape(rounds, validators, 32)),
        jnp.asarray(s_rows.reshape(rounds, validators, 32)),
        jnp.asarray(m_round),
    )
    return shaped, table, prevalid.reshape(rounds, validators)


def make_sharded_step(mesh: Mesh):
    """Convenience: returns (step_fn, make_example_args) for benchmarking
    and the multi-chip dry run."""
    step = sharded_verify_tally(mesh)

    def example_args(rounds: int, validators: int, rng_seed: int = 0):
        """Dummy-but-well-shaped inputs (all-zero signatures verify False;
        shapes and sharding are what matter for a compile check)."""
        rnd = np.random.RandomState(rng_seed)
        z = lambda *s: jnp.zeros(s, dtype=jnp.int32)  # noqa: E731
        vote_vals = jnp.asarray(
            rnd.randint(0, 1 << 30, size=(rounds, validators, 8)), dtype=jnp.int32
        )
        target_vals = vote_vals[:, 0, :]
        return (
            z(rounds, validators, fe.N_LIMBS),
            z(rounds, validators, fe.N_LIMBS),
            z(rounds, validators, fe.N_LIMBS),
            z(rounds, validators, fe.N_LIMBS),
            z(rounds, validators, fe.N_LIMBS),
            z(rounds, validators, 64),
            z(rounds, validators, 64),
            vote_vals,
            target_vals,
            jnp.int32(validators // 3),
        )

    return step, example_args
