"""The multi-tenant serving CLI (CI's ``multitenant-smoke`` job).

Usage::

    python -m hyperdrive_tpu.parallel serve
        [--tenants M] [--heights H] [--validators V]
        [--policy drr|fifo] [--capacity-rows N] [--quantum-rows N]
        [--starve-after K] [--weights T=W,...]
        [--verifier host|null|device] [--max-depth D]
        [--listen] [--remote-tenants K] [--parity]
        [--journal FILE] [--origin N] [--json] [-o FILE]

    python -m hyperdrive_tpu.parallel tenant
        --connect HOST:PORT --name NAME
        [--validators V] [--heights H] [--unsigned] [--inflight N]
        [--journal FILE] [--origin N]

``--journal`` turns on the distributed flight recorder: the process
records a wall-clock journal (``time.time`` timestamps, so journals
from different processes share a clock domain up to offset), stamps
every outbound frame with a causal trace context, and saves the
journal (meta: its trace origin id) on exit. ``serve --journal`` hands
each spawned remote tenant its own journal path and origin, so one run
yields N+1 journals ready for ``python -m hyperdrive_tpu.obs merge``.

``serve`` runs the deployment shape of ROADMAP item 2: M independent
shard-consensus instances (each its own deterministic committee)
funneling verify windows into ONE continuously-batching
:class:`~hyperdrive_tpu.parallel.service.ShardVerifyService`. The drive
loop pumps every tenant, services the remote port, and drains the
shared queue — each drain is one coalesced launch covering whatever
every tenant had pending.

``--remote-tenants K`` spawns K child processes running the ``tenant``
subcommand against the port: REAL cross-process batching over TCP, with
commits finalized by O(1) certificate frames. ``--parity`` re-runs
every tenant on its own dedicated service afterwards and asserts the
commit digests match — continuous batching must change scheduling,
never results.

The ``serve`` path is jax-free unless ``--verifier device`` asks for
the compiled batch verifier; the ``tenant`` subcommand never imports
jax at all.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

from hyperdrive_tpu.parallel.service import (
    RemoteServiceClient,
    ShardVerifyService,
    TenantShard,
)


def _percentile(values, q: float):
    vals = sorted(values)
    if not vals:
        return None
    return vals[min(len(vals) - 1, int(q * len(vals)))]


def _journal_path_for_child(journal: str, i: int) -> str:
    """remote child i's journal path, derived from the serve journal
    (``foo.json`` -> ``foo.remote-0.json``)."""
    base, dot, ext = journal.rpartition(".")
    if not dot:
        return f"{journal}.remote-{i}"
    return f"{base}.remote-{i}.{ext}"


def _build_observer(origin: int):
    """One process's distributed-tracing kit: a threadsafe wall-clock
    Recorder (IO threads emit), its bound handle on the sim track, and
    the stamp mint."""
    from hyperdrive_tpu.obs.recorder import Recorder
    from hyperdrive_tpu.obs.tracectx import TraceSource

    rec = Recorder(time_fn=time.time, threadsafe=True)
    obs = rec.scoped(-1)
    return rec, obs, TraceSource(origin, obs=obs)


def _build_verifier(kind: str):
    if kind == "null":
        from hyperdrive_tpu.verifier import NullVerifier

        return NullVerifier()
    if kind == "device":
        from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

        return TpuBatchVerifier()
    from hyperdrive_tpu.verifier import HostVerifier

    return HostVerifier()


def _build_policy(args):
    if args.policy == "fifo":
        return None
    from hyperdrive_tpu.devsched import DeficitRoundRobin

    weights = {}
    if args.weights:
        for part in args.weights.split(","):
            name, _, w = part.partition("=")
            weights[name.strip()] = int(w)
    return DeficitRoundRobin(
        capacity_rows=args.capacity_rows,
        quantum_rows=args.quantum_rows,
        weights=weights or None,
        starve_after=args.starve_after,
    )


def _dedicated_digest(name: str, validators: int, heights: int,
                      sign: bool, verifier_kind: str) -> str:
    """The per-tenant-queue baseline: the same tenant driven through its
    own fresh service (own queue, own verifier instance) — what the
    shared run's digest must match exactly."""
    svc = ShardVerifyService(_build_verifier(verifier_kind), max_depth=0)
    shard = TenantShard(
        name, n_validators=validators, target_height=heights, sign=sign
    ).attach_local(svc)
    while not shard.done:
        if not shard.pump(max_inflight=2):
            break
        svc.drain()
    svc.close()
    return shard.commit_digest()


def serve(args) -> int:
    from hyperdrive_tpu.obs.devtel import DeviceTelemetry

    sign = args.verifier != "null"
    devtel = DeviceTelemetry(keep=4096)
    policy = _build_policy(args)
    flight_rec = obs = trace = registry = None
    if args.journal:
        from hyperdrive_tpu.obs.metrics import Registry

        flight_rec, obs, trace = _build_observer(args.origin)
        registry = Registry()
    service = ShardVerifyService(
        _build_verifier(args.verifier),
        max_depth=args.max_depth,
        devtel=devtel,
        policy=policy,
        obs=obs,
        registry=registry,
    )
    tenants = [
        TenantShard(
            f"tenant-{i}", n_validators=args.validators,
            target_height=args.heights, sign=sign,
        ).attach_local(service)
        for i in range(args.tenants)
    ]

    port = None
    children = []
    child_journals = []
    if args.listen or args.remote_tenants:
        port = service.remote_port(obs=obs, trace=trace)
        host, pnum = port.address
        for i in range(args.remote_tenants):
            cmd = [
                sys.executable, "-m", "hyperdrive_tpu.parallel", "tenant",
                "--connect", f"{host}:{pnum}",
                "--name", f"remote-{i}",
                "--validators", str(args.validators),
                "--heights", str(args.heights),
            ]
            if not sign:
                cmd.append("--unsigned")
            if args.journal:
                child_path = _journal_path_for_child(args.journal, i)
                child_journals.append(child_path)
                cmd += [
                    "--journal", child_path,
                    "--origin", str(args.origin + 1 + i),
                ]
            children.append(
                subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)
            )

    t_start = time.perf_counter()
    deadline = t_start + args.timeout
    while time.perf_counter() < deadline:
        submitted = sum(t.pump(max_inflight=2) for t in tenants)
        handled = port.pump() if port is not None else 0
        if service.queue.depth:
            service.drain()
        locals_done = all(t.done for t in tenants)
        remote_quiet = port is None or (
            port.inflight == 0
            and all(c.poll() is not None for c in children)
        )
        if locals_done and remote_quiet and not service.queue.depth:
            break
        if not submitted and not handled and not service.queue.depth:
            time.sleep(0.001)
    wall = time.perf_counter() - t_start
    service.drain()

    child_reports = []
    for c in children:
        out, _ = c.communicate(timeout=30)
        child_reports.append(json.loads(out) if out.strip() else {})
    if port is not None:
        port.close()
    service.close()

    # Coalescing evidence straight from the launch probe: launches whose
    # origin tuples span more than one tenant track, and — with remote
    # tenants — launches mixing a remote tenant's track with local ones.
    local_tids = {service.tenant_ids[t.name] for t in tenants}
    multi_origin = 0
    remote_coalesced = 0
    for rec in devtel.records:
        origins = set(rec.origins)
        if len(origins) > 1:
            multi_origin += 1
            if origins - local_tids and origins & local_tids:
                remote_coalesced += 1

    total_rows = sum(
        len(t.commits) * args.validators for t in tenants
    ) + sum(
        r.get("commits", 0) * args.validators for r in child_reports
    )
    latencies = [lat for t in tenants for lat in t.commit_latencies]
    parity_ok = None
    if args.parity:
        parity_ok = True
        for t in tenants:
            want = _dedicated_digest(
                t.name, args.validators, args.heights, sign, args.verifier
            )
            if t.commit_digest() != want:
                parity_ok = False
                print(
                    f"PARITY MISMATCH tenant={t.name}: shared "
                    f"{t.commit_digest()[:16]} != dedicated {want[:16]}",
                    file=sys.stderr,
                )
        for r in child_reports:
            if not r:
                continue
            want = _dedicated_digest(
                r["name"], args.validators, args.heights, sign,
                args.verifier,
            )
            if r.get("digest") != want:
                parity_ok = False
                print(
                    f"PARITY MISMATCH remote tenant={r['name']}: "
                    f"{str(r.get('digest'))[:16]} != local {want[:16]}",
                    file=sys.stderr,
                )

    summary = {
        "tenants": args.tenants,
        "remote_tenants": args.remote_tenants,
        "heights": args.heights,
        "validators": args.validators,
        "policy": args.policy,
        "verifier": args.verifier,
        "completed": all(t.done for t in tenants)
        and all(r.get("done") for r in child_reports if r),
        "wall_s": wall,
        "votes_per_s": (total_rows / wall) if wall > 0 else 0.0,
        "launches": service.queue.launches,
        "coalesced": service.queue.coalesced,
        "multi_origin_launches": multi_origin,
        "remote_coalesced_launches": remote_coalesced,
        "commit_latency_p50_s": _percentile(latencies, 0.50),
        "commit_latency_p95_s": _percentile(latencies, 0.95),
        "commit_latency_p99_s": _percentile(latencies, 0.99),
        "remote": None if port is None else {
            "submits": port.remote_submits,
            "resolves": port.remote_resolves,
            "sheds": port.remote_sheds,
            "metrics_serves": port.metrics_serves,
            "metrics_sheds": port.metrics_sheds,
            "children": child_reports,
        },
        "policy_stats": None if policy is None else {
            "deferred_total": policy.deferred_total,
            "forced_total": policy.forced_total,
            "max_deferrals": policy.max_deferrals,
        },
        "parity_ok": parity_ok,
    }
    if flight_rec is not None:
        flight_rec.save(args.journal, meta={"origin": args.origin})
        summary["journal"] = args.journal
        summary["journals"] = [args.journal] + child_journals
        summary["trace_origin"] = args.origin
        summary["trace_events"] = sum(
            1 for ev in flight_rec.snapshot() if ev[4].startswith("trace.")
        )
    text = json.dumps(summary, indent=None if args.json else 2)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    print(text)
    if not summary["completed"]:
        print("serve: tenants did not finish before --timeout",
              file=sys.stderr)
        return 1
    if args.parity and not parity_ok:
        return 1
    return 0


def tenant(args) -> int:
    host, _, pnum = args.connect.rpartition(":")
    rec = obs = trace = None
    if args.journal:
        rec, obs, trace = _build_observer(args.origin)
    client = RemoteServiceClient(
        host or "127.0.0.1", int(pnum), obs=obs, trace=trace
    )
    shard = TenantShard(
        args.name, n_validators=args.validators,
        target_height=args.heights, sign=not args.unsigned,
    ).attach_remote(client)
    t0 = time.perf_counter()
    shard.run_remote(max_inflight=args.inflight, timeout=args.timeout)
    client.close()
    report = {
        "name": shard.name,
        "done": shard.done,
        "commits": len(shard.commits),
        "digest": shard.commit_digest(),
        "wall_s": time.perf_counter() - t0,
        "rejected": shard.rejected,
        "shed_retries": shard.shed_retries,
        "commit_latency_p95_s": _percentile(shard.commit_latencies, 0.95),
    }
    if rec is not None:
        rec.save(args.journal, meta={"origin": args.origin})
        report["journal"] = args.journal
        report["trace_origin"] = args.origin
        report["clock_offsets"] = {
            str(o): off for o, off in sorted(client.clock_offsets.items())
        }
    print(json.dumps(report))
    return 0 if shard.done else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m hyperdrive_tpu.parallel")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser(
        "serve", help="run the continuously-batching multi-tenant service"
    )
    p.add_argument("--tenants", type=int, default=4)
    p.add_argument("--heights", type=int, default=16)
    p.add_argument("--validators", type=int, default=4)
    p.add_argument("--policy", choices=("drr", "fifo"), default="drr")
    p.add_argument("--capacity-rows", type=int, default=256)
    p.add_argument("--quantum-rows", type=int, default=64)
    p.add_argument("--starve-after", type=int, default=4)
    p.add_argument("--weights", default="",
                   help="per-tenant DRR weights, e.g. tenant-0=3,tenant-1=1")
    p.add_argument("--verifier", choices=("host", "null", "device"),
                   default="host")
    p.add_argument("--max-depth", type=int, default=0,
                   help="queue auto-drain depth (0 = drive loop drains)")
    p.add_argument("--listen", action="store_true",
                   help="open the remote submit port even with no children")
    p.add_argument("--remote-tenants", type=int, default=0,
                   help="spawn K remote tenant subprocesses over TCP")
    p.add_argument("--parity", action="store_true",
                   help="assert shared-service digests == per-tenant-queue")
    p.add_argument("--journal", default="",
                   help="record a causal-trace journal here (children get "
                        "derived paths); enables frame stamping and the "
                        "TAG_METRICS plane")
    p.add_argument("--origin", type=int, default=1,
                   help="this process's trace origin id (children get "
                        "origin+1..origin+K)")
    p.add_argument("--timeout", type=float, default=120.0)
    p.add_argument("--json", action="store_true",
                   help="single-line JSON summary")
    p.add_argument("-o", "--out", default="",
                   help="also write the summary JSON to this file")
    p.set_defaults(fn=serve)

    p = sub.add_parser(
        "tenant", help="drive one remote tenant against a serve port"
    )
    p.add_argument("--connect", required=True, help="HOST:PORT of the serve")
    p.add_argument("--name", required=True)
    p.add_argument("--validators", type=int, default=4)
    p.add_argument("--heights", type=int, default=16)
    p.add_argument("--unsigned", action="store_true")
    p.add_argument("--inflight", type=int, default=4)
    p.add_argument("--timeout", type=float, default=30.0)
    p.add_argument("--journal", default="",
                   help="record a causal-trace journal here")
    p.add_argument("--origin", type=int, default=2,
                   help="this process's trace origin id")
    p.set_defaults(fn=tenant)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
