"""Multi-host scale-out: DCN x ICI hybrid meshes and window distribution.

The reference delegates its network entirely to the embedding application
(`/root/reference/process/process.go:47-60` — the Broadcaster seam is the
whole backend contract). This module is the TPU-native analogue of "the
application brings the transport" for the *bulk data path*: when the
validator set outgrows one slice, votes are tensors, and tensor exchange
belongs on the accelerator fabric, not the host NICs.

Axis placement follows the bandwidth hierarchy (scaling-book recipe):

- ``val`` — the validator axis carries the ``psum`` quorum reductions
  (`mesh.py::_local_step`), so it must ride **ICI** (intra-slice ring,
  ~10x DCN bandwidth). It is always the *inner* (fast) mesh axis.
- ``hr`` — in-flight (height, round) pairs never communicate with each
  other, so the only cross-slice traffic on **DCN** is input/output
  distribution. It is the *outer* (slow) axis.

Control-plane messages (proposes, timeouts, ResetHeight) stay on host
networking exactly where the reference assumes an external network; only
the wide verify+tally tensors cross the fabric.

Single-host processes can build the same topology (the hybrid mesh
degrades to a plain 2D mesh), so every consumer — `sharded_verify_tally`,
`VoteGrid`, the dryrun — is topology-agnostic: axis names, not device
counts, are the contract.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.experimental import mesh_utils, multihost_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# The multi-tenant service grew into its own (jax-free) module; the
# import here keeps every historical ``multihost.ShardVerifyService``
# call site working.
from hyperdrive_tpu.parallel.service import ShardVerifyService

__all__ = [
    "init_distributed",
    "make_hybrid_mesh",
    "global_window_from_local",
    "replicate_to_all_hosts",
    "ShardVerifyService",
]


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    auto: bool = False,
) -> int:
    """Join (or skip joining) the multi-host JAX runtime.

    On a multi-host TPU pod each host process calls this once before any
    other JAX API, with the rendezvous coordinator's ``host:port`` and its
    own rank — or with ``auto=True`` to use JAX's cluster-environment
    detection. With neither, this returns immediately WITHOUT touching any
    JAX API: probing (e.g. ``jax.process_count()``) would initialize the
    local-only backend, silently foreclosing a later ``initialize`` call —
    so the no-op path costs nothing and burns nothing.

    Returns the process count after initialization (1 on the no-op path).
    """
    if coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    elif auto:
        jax.distributed.initialize()
    else:
        return 1
    return jax.process_count()


def make_hybrid_mesh(hr_dcn: int | None = None, val_ici: int | None = None) -> Mesh:
    """Build the 2D ('hr', 'val') mesh with DCN-aware device placement.

    ``hr_dcn`` — size of the 'hr' axis (defaults to the process count, so
    each host's slice owns a disjoint set of in-flight rounds and the
    round axis never crosses DCN except at the boundaries).
    ``val_ici`` — size of the 'val' axis (defaults to local device count,
    keeping every quorum psum inside one slice's ICI ring).

    Multi-process: delegates to ``mesh_utils.create_hybrid_device_mesh``,
    which groups devices by granule (process/slice) so the outer axis maps
    to DCN and the inner axis to ICI. Single-process (tests, the CPU
    mesh): the same shape is built from ``jax.devices()`` directly —
    topology-identical for compilation purposes, with the grouping then
    only a layout hint.
    """
    n_proc = jax.process_count()
    n_dev = len(jax.devices())
    if hr_dcn is None:
        hr_dcn = max(n_proc, 1)
    if val_ici is None:
        val_ici = n_dev // hr_dcn
    if hr_dcn * val_ici != n_dev:
        raise ValueError(
            f"hr_dcn*val_ici must equal the global device count "
            f"({hr_dcn}*{val_ici} != {n_dev})"
        )
    if n_proc > 1:
        # The DCN granules (processes/slices) tile the 'hr' axis, so 'val'
        # psums never leave a slice. That requires hr_dcn to absorb the
        # whole process count; validate here with the constraint spelled
        # out rather than letting mesh_utils fail on a derived shape.
        if hr_dcn % n_proc != 0:
            raise ValueError(
                f"hr_dcn ({hr_dcn}) must be a multiple of the process "
                f"count ({n_proc}) so the 'val' axis — which carries the "
                f"quorum psums — stays inside one slice's ICI domain"
            )
        per_granule_hr = hr_dcn // n_proc
        # Check against the devices ACTUALLY attached to this process,
        # not the global-count average: on a misconfigured pod (uneven
        # device visibility, a host joined with the wrong topology) the
        # average can look right while the local slab cannot hold its
        # per-granule tile.
        local = jax.local_device_count()
        if per_granule_hr * val_ici != local:
            raise ValueError(
                f"per-process mesh {per_granule_hr}x{val_ici} does not "
                f"match the {local} devices attached to this process"
            )
        # Granule = process: 'hr' tiles one row-block per process, which
        # keeps 'val' on process-local (hence intra-slice) devices. This
        # also holds on CPU pods, whose devices carry process indices but
        # no slice indices (slice-granule grouping would see one slice).
        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(per_granule_hr, val_ici),
            dcn_mesh_shape=(n_proc, 1),
            process_is_granule=True,
        )
    else:
        arr = np.array(jax.devices()).reshape(hr_dcn, val_ici)
    return Mesh(arr, axis_names=("hr", "val"))


def global_window_from_local(mesh: Mesh, local_arrays, spec: P = P("hr", "val")):
    """Assemble per-host window shards into global device arrays.

    Each host packs only the votes of *its* rounds x validators (its
    ``[R/hr_dcn, V, ...]`` slab of the global ``[R, V, ...]`` window —
    host-side packing parallelizes across the pod for free) and passes the
    slab here; the result is a tuple of global ``jax.Array`` views ready
    for :func:`hyperdrive_tpu.parallel.mesh.sharded_verify_tally`. No data
    moves between hosts: every shard is already on the chips attached to
    the host that produced it.

    Single-process, this is just ``device_put`` with the mesh sharding —
    so tests and the dryrun exercise the identical call path.
    """
    arrays = tuple(local_arrays)
    if jax.process_count() > 1:
        return tuple(
            multihost_utils.host_local_array_to_global_array(a, mesh, spec)
            for a in arrays
        )
    # device_put takes numpy and jax.Array inputs alike; already-on-device
    # arrays reshard device-to-device without a host round-trip.
    shard = NamedSharding(mesh, spec)
    return tuple(jax.device_put(a, shard) for a in arrays)


def replicate_to_all_hosts(mesh: Mesh, value):
    """Replicate a small host value (e.g. the target proposal values or f)
    onto every device of the mesh — the broadcast side of the control
    plane.

    Multi-process this is a real broadcast from process 0
    (``multihost_utils.broadcast_one_to_all``): replication via
    local-to-global assembly would be undefined behavior if hosts ever
    disagreed on the bytes, and "every host already agrees" is exactly
    what a consensus framework must not assume about its own inputs."""
    if jax.process_count() > 1:
        agreed = multihost_utils.broadcast_one_to_all(np.asarray(value))
        return multihost_utils.host_local_array_to_global_array(
            agreed, mesh, P()
        )
    return jax.device_put(np.asarray(value), NamedSharding(mesh, P()))
