"""The mutable State of a consensus Process, with checkpoint serde.

Capability parity with the reference's ``process/state.go:35-279``: current
height/round/step, the locked and valid value/round pair, full per-round
message logs (proposes + validity, prevotes, precommits), once-flags, and
trace logs (unique signatories seen per round, powering the f+1 round-skip
rule L55). The whole State round-trips through the canonical codec so a
replica can be checkpointed after every method call and restored after a
crash (reference contract: process/state.go:18-20).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from hyperdrive_tpu.analysis.annotations import wire_codec
from hyperdrive_tpu.codec import Reader, SerdeError, Writer
from hyperdrive_tpu.messages import Precommit, Prevote, Propose
from hyperdrive_tpu.types import (
    DEFAULT_HEIGHT,
    DEFAULT_ROUND,
    INVALID_ROUND,
    NIL_VALUE,
    Step,
)

__all__ = ["State", "OnceFlag"]


class OnceFlag:
    """Bit flags guaranteeing per-round once-only events.

    Reference: ``process/process.go:929-938``.
    """

    TIMEOUT_PRECOMMIT_UPON_SUFFICIENT_PRECOMMITS = 1
    TIMEOUT_PREVOTE_UPON_SUFFICIENT_PREVOTES = 2
    PRECOMMIT_UPON_SUFFICIENT_PREVOTES = 4


# A sane upper bound on log sizes accepted while unmarshaling a checkpoint.
# (The byte budget is the real defense; this just gives clearer errors.)
_MAX_LOG_ENTRIES = 1 << 20


@wire_codec(tag="state.checkpoint", max_bytes=1 << 28)
@dataclass
class State:
    """Consensus-automaton state (paper L1 initialization block)."""

    current_height: int = DEFAULT_HEIGHT
    current_round: int = DEFAULT_ROUND
    current_step: Step = Step.PROPOSING
    locked_value: bytes = NIL_VALUE
    locked_round: int = INVALID_ROUND
    valid_value: bytes = NIL_VALUE
    valid_round: int = INVALID_ROUND

    # round -> Propose
    propose_logs: dict[int, Propose] = field(default_factory=dict)
    # round -> bool (validity of the stored propose)
    propose_is_valid: dict[int, bool] = field(default_factory=dict)
    # round -> {signatory -> Prevote}
    prevote_logs: dict[int, dict[bytes, Prevote]] = field(default_factory=dict)
    # round -> {signatory -> Precommit}
    precommit_logs: dict[int, dict[bytes, Precommit]] = field(default_factory=dict)
    # round -> OnceFlag bits
    once_flags: dict[int, int] = field(default_factory=dict)
    # round -> set of unique signatories seen this round (L55 round skip)
    trace_logs: dict[int, set[bytes]] = field(default_factory=dict)

    # Derived tallies: round -> {value -> count} over the vote logs,
    # maintained incrementally by :meth:`add_prevote`/:meth:`add_precommit`
    # so every quorum rule reads one dict lookup instead of scanning the
    # round's votes (the reference's four O(n) hot loops,
    # process/process.go:487-491, 574-579, 626-631, 696-701 — at n=256
    # those scans are the host bottleneck). Not serialized: rebuilt from
    # the logs on unmarshal, so the checkpoint format is unchanged.
    prevote_counts: dict[int, dict[bytes, int]] = field(default_factory=dict)
    precommit_counts: dict[int, dict[bytes, int]] = field(default_factory=dict)

    # ------------------------------------------------------------------ basics

    @classmethod
    def default_with_height(cls, height: int) -> "State":
        return cls(current_height=height)

    def clone(self) -> "State":
        """Deep copy (reference: process/state.go:89-134)."""
        return State(
            current_height=self.current_height,
            current_round=self.current_round,
            current_step=self.current_step,
            locked_value=self.locked_value,
            locked_round=self.locked_round,
            valid_value=self.valid_value,
            valid_round=self.valid_round,
            propose_logs=dict(self.propose_logs),
            propose_is_valid=dict(self.propose_is_valid),
            prevote_logs={r: dict(m) for r, m in self.prevote_logs.items()},
            precommit_logs={r: dict(m) for r, m in self.precommit_logs.items()},
            once_flags=dict(self.once_flags),
            trace_logs={r: set(s) for r, s in self.trace_logs.items()},
            prevote_counts={r: dict(c) for r, c in self.prevote_counts.items()},
            precommit_counts={r: dict(c) for r, c in self.precommit_counts.items()},
        )

    def equal(self, other: "State") -> bool:
        """Scalar-field equality; logs and once-flags are ignored
        (reference: process/state.go:139-149)."""
        return (
            self.current_height == other.current_height
            and self.current_round == other.current_round
            and self.current_step == other.current_step
            and self.locked_value == other.locked_value
            and self.locked_round == other.locked_round
            and self.valid_value == other.valid_value
            and self.valid_round == other.valid_round
        )

    def reset_for_new_height(self) -> None:
        """Wipe locks and logs when moving to the next height
        (reference: process/process.go:712-725)."""
        self.locked_value = NIL_VALUE
        self.locked_round = INVALID_ROUND
        self.valid_value = NIL_VALUE
        self.valid_round = INVALID_ROUND
        self.propose_logs = {}
        self.propose_is_valid = {}
        self.prevote_logs = {}
        self.precommit_logs = {}
        self.once_flags = {}
        self.trace_logs = {}
        self.prevote_counts = {}
        self.precommit_counts = {}

    # ------------------------------------------------------------ vote logging

    def add_prevote(self, prevote: Prevote):
        """Log a prevote, updating the round's tally and trace log.

        Returns the already-logged vote from the same sender (without
        mutating anything) if one exists — the caller decides whether that
        is a duplicate or equivocation — else None after inserting.
        """
        rnd = prevote.round
        votes = self.prevote_logs.get(rnd)
        if votes is None:
            votes = self.prevote_logs[rnd] = {}
        existing = votes.get(prevote.sender)
        if existing is not None:
            return existing
        votes[prevote.sender] = prevote
        counts = self.prevote_counts.get(rnd)
        if counts is None:
            counts = self.prevote_counts[rnd] = {}
        counts[prevote.value] = counts.get(prevote.value, 0) + 1
        trace = self.trace_logs.get(rnd)
        if trace is None:
            trace = self.trace_logs[rnd] = set()
        trace.add(prevote.sender)
        return None

    def add_precommit(self, precommit: Precommit):
        """Log a precommit; same contract as :meth:`add_prevote`."""
        rnd = precommit.round
        votes = self.precommit_logs.get(rnd)
        if votes is None:
            votes = self.precommit_logs[rnd] = {}
        existing = votes.get(precommit.sender)
        if existing is not None:
            return existing
        votes[precommit.sender] = precommit
        counts = self.precommit_counts.get(rnd)
        if counts is None:
            counts = self.precommit_counts[rnd] = {}
        counts[precommit.value] = counts.get(precommit.value, 0) + 1
        trace = self.trace_logs.get(rnd)
        if trace is None:
            trace = self.trace_logs[rnd] = set()
        trace.add(precommit.sender)
        return None

    def count_prevotes_for(self, round: int, value: bytes) -> int:
        """Prevotes at ``round`` whose value equals ``value`` — O(1) from
        the derived tally, with an O(V) log scan when the round has no
        tally dict (device-tally ingestion skips host tally maintenance —
        the vote grid answers the hot queries, and the rare declined query
        lands here)."""
        counts = self.prevote_counts.get(round)
        if counts is not None:
            return counts.get(value, 0)
        votes = self.prevote_logs.get(round)
        if not votes:
            return 0
        return sum(1 for v in votes.values() if v.value == value)

    def count_precommits_for(self, round: int, value: bytes) -> int:
        """Precommits at ``round``; same contract as
        :meth:`count_prevotes_for`."""
        counts = self.precommit_counts.get(round)
        if counts is not None:
            return counts.get(value, 0)
        votes = self.precommit_logs.get(round)
        if not votes:
            return 0
        return sum(1 for v in votes.values() if v.value == value)

    def rebuild_counts(self) -> None:
        """Recompute the derived tallies from the logs — for states whose
        logs were populated directly (unmarshal, test generators)."""
        self.prevote_counts = {}
        for rnd, votes in self.prevote_logs.items():
            counts = self.prevote_counts.setdefault(rnd, {})
            for v in votes.values():
                counts[v.value] = counts.get(v.value, 0) + 1
        self.precommit_counts = {}
        for rnd, votes in self.precommit_logs.items():
            counts = self.precommit_counts.setdefault(rnd, {})
            for v in votes.values():
                counts[v.value] = counts.get(v.value, 0) + 1

    # ------------------------------------------------------------------- serde

    def marshal(self, w: Writer) -> None:
        w.i64(self.current_height)
        w.i64(self.current_round)
        w.u8(int(self.current_step))
        w.bytes32(self.locked_value)
        w.i64(self.locked_round)
        w.bytes32(self.valid_value)
        w.i64(self.valid_round)

        w.u32(len(self.propose_logs))
        for rnd in sorted(self.propose_logs):
            w.i64(rnd)
            self.propose_logs[rnd].marshal(w)

        w.u32(len(self.propose_is_valid))
        for rnd in sorted(self.propose_is_valid):
            w.i64(rnd)
            w.bool(self.propose_is_valid[rnd])

        w.u32(len(self.prevote_logs))
        for rnd in sorted(self.prevote_logs):
            w.i64(rnd)
            votes = self.prevote_logs[rnd]
            w.u32(len(votes))
            for sig in sorted(votes):
                votes[sig].marshal(w)

        w.u32(len(self.precommit_logs))
        for rnd in sorted(self.precommit_logs):
            w.i64(rnd)
            votes = self.precommit_logs[rnd]
            w.u32(len(votes))
            for sig in sorted(votes):
                votes[sig].marshal(w)

        w.u32(len(self.once_flags))
        for rnd in sorted(self.once_flags):
            w.i64(rnd)
            w.u16(self.once_flags[rnd])

        w.u32(len(self.trace_logs))
        for rnd in sorted(self.trace_logs):
            w.i64(rnd)
            sigs = self.trace_logs[rnd]
            w.u32(len(sigs))
            for sig in sorted(sigs):
                w.bytes32(sig)

    @classmethod
    def unmarshal(cls, r: Reader) -> "State":
        st = cls()
        st.current_height = r.i64()
        st.current_round = r.i64()
        step = r.u8()
        try:
            st.current_step = Step(step)
        except ValueError as e:
            raise SerdeError(f"invalid step: {step}") from e
        st.locked_value = r.bytes32()
        st.locked_round = r.i64()
        st.valid_value = r.bytes32()
        st.valid_round = r.i64()

        def _count() -> int:
            n = r.u32()
            if n > _MAX_LOG_ENTRIES:
                raise SerdeError(f"log length {n} exceeds cap")
            return n

        for _ in range(_count()):
            rnd = r.i64()
            st.propose_logs[rnd] = Propose.unmarshal(r)
        for _ in range(_count()):
            rnd = r.i64()
            st.propose_is_valid[rnd] = r.bool()
        for _ in range(_count()):
            rnd = r.i64()
            votes: dict[bytes, Prevote] = {}
            for _ in range(_count()):
                v = Prevote.unmarshal(r)
                votes[v.sender] = v
            st.prevote_logs[rnd] = votes
        for _ in range(_count()):
            rnd = r.i64()
            pvotes: dict[bytes, Precommit] = {}
            for _ in range(_count()):
                v = Precommit.unmarshal(r)
                pvotes[v.sender] = v
            st.precommit_logs[rnd] = pvotes
        for _ in range(_count()):
            rnd = r.i64()
            st.once_flags[rnd] = r.u16()
        for _ in range(_count()):
            rnd = r.i64()
            sigs: set[bytes] = set()
            for _ in range(_count()):
                sigs.add(r.bytes32())
            st.trace_logs[rnd] = sigs
        st.rebuild_counts()
        return st
