# hdlint: scope=async
"""Async device-work scheduling: one queue, futures, coalesced launches.

The engine's dominant cost is no longer crypto — it is the serial
device round trip. BENCH config 4 measures a ~107 ms minimal
launch+fetch floor on a tunnel-attached chip against only ~27-36 ms of
dependent host work (``sub_crossover_note``), and before this package
every settle paid that floor blocking, once per height.

:class:`DeviceWorkQueue` replaces per-call blocking device access with
submitted commands returning :class:`DeviceFuture` handles. Pending
commands against the same launcher coalesce into ONE device launch at
the next drain — across replicas, heights, and (multi-tenant seam,
``parallel/multihost.py``) consensus instances — so the sync floor is
paid once per pipeline slot instead of once per call. On top of it the
sim harness pipelines consensus chained-HotStuff-style
(``Simulation(pipeline_heights=True)``): a replica enters height h+1's
propose/prevote while height h's verification is still in flight, with
commit finalization gated on the future's resolution.

Scope discipline (ANALYSIS.md HD006): inside devsched-managed async
scopes, futures are the ONLY device-access idiom — a raw blocking
``device_fetch`` would silently re-serialize the pipeline. Drains
(the one place blocking is the point) are marked ``@drain_point``.
"""

from hyperdrive_tpu.devsched.flusher import QueueFlusher
from hyperdrive_tpu.devsched.policy import DeficitRoundRobin, FifoDrainPolicy
from hyperdrive_tpu.devsched.queue import (
    DeviceFuture,
    DeviceWorkQueue,
    NullVerifyLauncher,
    SpeculationMismatch,
    VerifyLauncher,
)

__all__ = [
    "DeficitRoundRobin",
    "DeviceFuture",
    "DeviceWorkQueue",
    "FifoDrainPolicy",
    "NullVerifyLauncher",
    "QueueFlusher",
    "SpeculationMismatch",
    "VerifyLauncher",
]
