"""Devsched parity smoke: pipelined runs must commit the sequential chain.

Usage::

    python -m hyperdrive_tpu.devsched parity [--n N] [--heights H]
        [--seed S] [--device] [--buckets 64,256]

Runs the same scenario sequentially and pipelined and compares
:meth:`~hyperdrive_tpu.harness.sim.SimulationResult.commit_digest` —
byte-identical chains or exit 1. Two legs by default, both cheap enough
for a CI dryrun (no ladder compile):

- ``burst``: signed supersteps through the HostVerifier, sequential vs
  ``pipeline_heights=True`` (speculative settle, gated commits);
- ``lockstep``: unsigned delivery, blocking flush vs queue-backed
  :class:`~hyperdrive_tpu.devsched.QueueFlusher` replicas sharing one
  :class:`~hyperdrive_tpu.devsched.DeviceWorkQueue`.

``--device`` adds the compiled leg — TpuBatchVerifier + device tally
with a small bucket ladder — which is minutes of XLA compile on a cold
cache; CI keeps it out of the dryrun and the bench covers it instead.
HD_SANITIZE=1 in the environment arms the runtime consensus sanitizer.
"""

from __future__ import annotations

import argparse
import sys

from hyperdrive_tpu.devsched import DeviceWorkQueue, QueueFlusher
from hyperdrive_tpu.harness.sim import Simulation
from hyperdrive_tpu.verifier import NullVerifier


def _leg_burst(args):
    kw = dict(
        n=args.n, target_height=args.heights, seed=args.seed,
        sign=True, burst=True, observe=True,
    )
    seq = Simulation(**kw).run()
    sim = Simulation(pipeline_heights=True, **kw)
    pipe = sim.run()
    q = sim._sched
    return seq, pipe, q


def _leg_lockstep(args):
    kw = dict(
        n=args.n, target_height=args.heights, seed=args.seed,
        timeout=1.0, delivery_cost=1e-3, observe=True,
    )
    seq = Simulation(**kw).run()
    q = DeviceWorkQueue(max_depth=8)
    pipe = Simulation(
        devsched=q,
        flusher_for=lambda i, validators: QueueFlusher(NullVerifier(), q),
        **kw,
    ).run()
    return seq, pipe, q


def _leg_device(args):
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

    buckets = tuple(int(b) for b in args.buckets.split(","))
    kw = dict(
        n=args.n, target_height=args.heights, seed=args.seed,
        sign=True, burst=True, observe=True,
        dedup_verify=True, device_tally=True,
    )
    seq = Simulation(
        batch_verifier=TpuBatchVerifier(buckets=buckets), **kw
    ).run()
    sim = Simulation(
        batch_verifier=TpuBatchVerifier(buckets=buckets),
        pipeline_heights=True,
        **kw,
    )
    pipe = sim.run()
    return seq, pipe, sim._sched


def parity(args) -> int:
    legs = {"burst": _leg_burst, "lockstep": _leg_lockstep}
    if args.device:
        legs["device"] = _leg_device
    failed = 0
    for name, leg in legs.items():
        seq, pipe, q = leg(args)
        d_seq, d_pipe = seq.commit_digest(), pipe.commit_digest()
        ok = seq.completed and pipe.completed and d_seq == d_pipe
        print(
            f"{'ok' if ok else 'FAIL'} {name}: digest {d_seq[:16]} "
            f"{'==' if d_seq == d_pipe else '!='} {d_pipe[:16]} "
            f"sched={q.submitted} submitted / {q.launches} launches "
            f"({q.coalesced} coalesced)"
        )
        if not ok:
            failed += 1
        if q.coalesced == 0:
            print(f"FAIL {name}: queue never coalesced", file=sys.stderr)
            failed += 1
    if failed:
        print(f"parity FAILED: {failed} checks", file=sys.stderr)
        return 1
    print(f"parity ok: {len(legs)} legs, pipelined == sequential")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m hyperdrive_tpu.devsched")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser(
        "parity", help="pipelined-vs-sequential commit-digest smoke"
    )
    p.add_argument("--n", type=int, default=4)
    p.add_argument("--heights", type=int, default=6)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument(
        "--device", action="store_true",
        help="also run the compiled device-tally leg (slow: XLA compile)",
    )
    p.add_argument(
        "--buckets", default="64,256",
        help="device-leg verify bucket ladder (comma-separated)",
    )
    p.set_defaults(fn=parity)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
