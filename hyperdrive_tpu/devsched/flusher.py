# hdlint: scope=async
"""Queue-backed flushing for the host-automaton path.

:class:`QueueFlusher` is the minimal devsched client: it plugs into the
:class:`~hyperdrive_tpu.replica.Replica` ``flusher`` seam, drains the
replica's eligible window, submits its verification to the shared
:class:`~hyperdrive_tpu.devsched.DeviceWorkQueue`, and dispatches the
window into the automaton when the future resolves — by which point the
queue has coalesced every co-submitted window (other replicas, later
heights) into one launch. It is the no-grid sibling of
:class:`~hyperdrive_tpu.tallyflush.DeviceTallyFlusher`'s queue mode:
same schedule, no device tally — which keeps it free of any jax import,
so the chaos soak can run pipelined scenarios on the pure-host engine.
"""

from __future__ import annotations

from hyperdrive_tpu.analysis.annotations import async_scope
from hyperdrive_tpu.obs.recorder import NULL_BOUND

__all__ = ["QueueFlusher"]


class QueueFlusher:
    """Host-automaton flush through the async device-work queue.

    ``verifier``: anything with ``verify_signatures`` (coalesced into
    one call per drain) or nothing but transport trust (NullVerifier —
    the queue substitutes the accept-all launcher). Verdict semantics
    are identical to the blocking flush leg; only the schedule moves.
    """

    def __init__(self, verifier, queue, obs=None):
        self.verifier = verifier
        self.queue = queue
        self.obs = obs if obs is not None else NULL_BOUND
        self._inflight: list = []
        #: Windows submitted / dispatched (observability, tests).
        self.submitted = 0
        self.dispatched = 0

    @async_scope
    def flush(self, replica) -> None:
        """Drain the replica's queue to quiescence, one submitted window
        per pass; dispatch happens at the queue's next drain."""
        queue = self.queue
        launcher = queue.verify_launcher(self.verifier)
        while True:
            window = replica.mq.drain_window(
                replica.proc.current_height, replica.opts.verify_window
            )
            if not window:
                return
            if self.obs is not NULL_BOUND:
                self.obs.emit(
                    "flush.launch",
                    replica.proc.current_height,
                    replica.proc.current_round,
                    len(window),
                )
            fut = queue.submit(
                launcher,
                [(m.sender, m.digest(), m.signature) for m in window],
                origin=(
                    self.obs.replica
                    if self.obs is not NULL_BOUND else None
                ),
                rows=len(window),
            )
            self._inflight.append(fut)
            self.submitted += 1

            def dispatch(f, window=window, replica=replica):
                try:
                    self._inflight.remove(f)
                except ValueError:
                    pass
                # hdlint: disable=HD001 resolved futures hold a host list; the one device fetch happened inside the coalesced launch
                replica.dispatch_window(
                    window, [bool(ok) for ok in f.result()]
                )
                self.dispatched += 1
                # Dispatching may advance the height and make buffered
                # messages eligible; re-flush so those join the drain's
                # next cycle (the blocking leg loops to quiescence too).
                self.flush(replica)

            fut.add_done_callback(dispatch)

    def reset(self, replica=None) -> None:
        """Crash-restart recovery hook (``Replica.restore``): cancel the
        dead incarnation's in-flight windows — the revived replica must
        not have them dispatched on top of its checkpoint."""
        for fut in self._inflight:
            fut.cancel()
        self._inflight.clear()