# hdlint: scope=async
"""The async device-work queue: commands in, futures out, one coalesced
launch per drain.

Deterministic by construction — no wall clock, no threads, no
randomness: commands resolve in global submission order (which makes
per-submitter FIFO a corollary), and a fixed submission sequence always
produces the same launch grouping and the same results. The sim drives
drains from its virtual-clock loop, so pipelined runs replay exactly.

The scheduling model is an inference server's continuous batcher
applied to consensus: every pending command against the same launcher
coalesces into ONE device launch at the next drain, so N submitters
(replicas, heights, tenants) share one sync instead of paying one
each. ROADMAP item 3's multi-tenant verification service batches
through exactly this seam (:class:`~hyperdrive_tpu.parallel.multihost.
ShardVerifyService`).
"""

from __future__ import annotations

from hyperdrive_tpu.analysis.annotations import drain_point
from hyperdrive_tpu.obs.devtel import NULL_DEVTEL, CmdMeta
from hyperdrive_tpu.obs.recorder import NULL_BOUND

__all__ = [
    "DeviceFuture",
    "DeviceWorkQueue",
    "VerifyLauncher",
    "NullVerifyLauncher",
    "SpeculationMismatch",
]


class SpeculationMismatch(AssertionError):
    """A pipelined settle's speculative verdict diverged from the
    device's actual verdict at drain time.

    Speculation accepts exactly the parseable-and-signed rows; an
    honest network's signatures all verify, so a mismatch means a
    forged-but-well-formed signature was speculatively dispatched.
    What happens next depends on the layer. The SETTLE pipeline
    (harness/sim.py ``_settle_speculative``) fails LOUDLY with this
    exception: safety was never at risk — the mismatch is detected
    before commit finalization, which gates on this resolution — but
    the run aborts rather than silently diverging from the sequential
    trajectory. The EXECUTION pipeline (exec/ledger.py ``speculate``/
    ``resolve``) instead rolls the speculative apply back
    bit-identically and re-applies under the true mask — rollback
    machinery exists there because a ledger state, unlike a vote
    verdict, can be unwound from a snapshot.
    """


class DeviceFuture:
    """Handle for one submitted device command.

    Resolution happens at queue drains; ``result()`` forces a drain
    when called early (the blocking escape hatch — inside async scopes
    prefer ``add_done_callback``, which HD006 enforces)."""

    __slots__ = (
        "_queue", "_value", "_done", "_cancelled", "_callbacks",
        "seq", "launch_id",
    )

    def __init__(self, queue: "DeviceWorkQueue"):
        self._queue = queue
        self._value = None
        self._done = False
        self._cancelled = False
        self._callbacks: list = []
        #: Device-telemetry attribution (obs/devtel.py): the command's
        #: submission sequence number, and — once resolved — the id of
        #: the coalesced launch that carried it. Both stay None when
        #: the queue runs unprobed.
        self.seq = None
        self.launch_id = None

    def done(self) -> bool:
        return self._done

    def cancelled(self) -> bool:
        return self._cancelled

    def cancel(self) -> bool:
        """Cancel a not-yet-resolved command (crash-restart reset path:
        a revived replica must not apply its dead predecessor's
        in-flight settles). Returns False if already resolved."""
        if self._done:
            return False
        self._cancelled = True
        self._done = True
        self._callbacks.clear()
        return True

    def add_done_callback(self, cb) -> None:
        """``cb(future)`` runs at resolution (immediately if already
        resolved). Callbacks run inside the drain, in submission
        order; they may submit further commands, which join the same
        drain's next cycle."""
        if self._done:
            if not self._cancelled:
                cb(self)
            return
        self._callbacks.append(cb)

    @drain_point
    def result(self):
        """The command's result, forcing a queue drain if needed."""
        if not self._done:
            self._queue.drain()
        if self._cancelled:
            raise RuntimeError("command was cancelled")
        if not self._done:
            raise RuntimeError("drain did not resolve this future")
        return self._value

    def _resolve(self, value) -> None:
        self._value = value
        self._done = True
        cbs = self._callbacks
        self._callbacks = []
        for cb in cbs:
            cb(self)


class VerifyLauncher:
    """Coalesces verify commands into one ``verify_signatures`` call.

    A payload is a list of ``(pub, digest, sig)`` triples; the drain
    concatenates every pending payload into ONE batch — the verifier
    dedups, bucket-pads, and chunks internally, and multi-chunk batches
    already fetch one concatenated mask — then slices the result back
    per command. Coalescing is where the ladder economics come from:
    settle windows fill ~25% of a verify bucket alone, so four of them
    in one launch do the same protocol work in a quarter of the lanes.
    """

    kind = "verify"

    def __init__(self, verifier):
        self.verifier = verifier
        #: Transcript of the most recent coalesced launch (the verifier's
        #: RLC binder digest, or b"" for ladder/null verifiers) — what a
        #: certificates.Certifier binds when the quorum was established
        #: through the queued flush path rather than a blocking verify.
        self.last_transcript = b""

    def launch(self, payloads: list) -> list:
        items: list = []
        bounds: list = []
        for p in payloads:
            start = len(items)
            items.extend(p)
            bounds.append((start, len(items)))
        mask = self.verifier.verify_signatures(items)
        self.last_transcript = getattr(self.verifier, "last_transcript", b"")
        mask = mask.tolist() if hasattr(mask, "tolist") else list(mask)
        # Unsigned lanes can pass a padded launch vacuously; apply the
        # same presence filter the sync verify_batch wrappers do, so a
        # launcher verdict means exactly what a blocking verify meant.
        mask = [
            bool(ok) and bool(it[2]) for ok, it in zip(mask, items)
        ]
        return [mask[a:b] for a, b in bounds]


class NullVerifyLauncher:
    """Transport-trusting launcher: accept every row, exactly
    :class:`~hyperdrive_tpu.verifier.NullVerifier`'s ``verify_batch``
    semantics — so swapping a NullVerifier deployment from blocking to
    queued flushing changes scheduling, never verdicts. No device, no
    compile: the chaos soak exercises pipelined scheduling without a
    ladder compile (or any jax import at all)."""

    kind = "verify.null"

    #: No batch equation ran, so there is no transcript to bind.
    last_transcript = b""

    def launch(self, payloads: list) -> list:
        return [[True] * len(p) for p in payloads]


class DeviceWorkQueue:
    """One async device-command queue.

    ``submit(launcher, payload)`` enqueues and returns a
    :class:`DeviceFuture`; nothing touches the device until
    :meth:`drain` — where pending commands group by launcher (in first-
    submission order) and each group becomes ONE ``launcher.launch``
    call. ``max_depth > 0`` bounds in-flight commands by auto-draining
    on the submit that reaches the bound (the pipeline-slot size).

    ``obs``: a bound recorder handle (the sim passes its scoped(-2)
    devsched track); ``tracer``: metrics sink for ``sim.sched.*``.
    ``on_drain``: callback ``(resolved_count) -> None`` fired after
    every drain that resolved work — the sim's commit-finalization
    flush hooks here, so gated commits land the moment their settle's
    future does.

    ``policy``: a drain policy (devsched/policy.py) consulted once per
    drain cycle to partition pending commands into this cycle's
    launches vs next cycle's — tenant-aware fairness for the
    multi-tenant service. ``None`` (the default) keeps the historical
    FIFO-everything drain byte-identical.
    """

    def __init__(self, max_depth: int = 0, obs=None, tracer=None,
                 devtel=None, policy=None):
        self.max_depth = int(max_depth)
        self.obs = obs if obs is not None else NULL_BOUND
        self.tracer = tracer
        #: Launch probe (obs/devtel.py): NULL_DEVTEL = off, and every
        #: probe touch point below guards on that identity first.
        self.devtel = devtel if devtel is not None else NULL_DEVTEL
        self.on_drain = None
        #: Backpressure seam (load/backpressure.py): when a
        #: BackpressureController is attached (``controller.watch(q)``),
        #: every submit pushes the new depth and every drain pushes its
        #: resolved count + latency (timed by the controller's clock, so
        #: the queue itself stays wall-clock-free). None = no admission
        #: coupling, exactly the pre-backpressure behavior.
        self.controller = None
        self.policy = policy
        self._pending: list = []  # (launcher, payload, future, gen, meta)
        self._launchers: dict = {}  # id(verifier) -> VerifyLauncher
        self._draining = False
        self._closed = False
        #: Lifetime counters (observability / tests).
        self.submitted = 0
        self.launches = 0
        self.coalesced = 0

    # ------------------------------------------------------------ submit

    @property
    def depth(self) -> int:
        """Commands awaiting resolution."""
        return len(self._pending)

    def verify_launcher(self, verifier):
        """The shared per-verifier launcher — commands only coalesce
        within one launcher object, so every submitter against the same
        verifier must hold the same instance (memoized here). Verifiers
        without a ``verify_signatures`` entry (NullVerifier) get the
        transport-trusting launcher."""
        key = id(verifier)
        got = self._launchers.get(key)
        if got is None:
            got = (
                VerifyLauncher(verifier)
                if hasattr(verifier, "verify_signatures")
                else NullVerifyLauncher()
            )
            self._launchers[key] = got
        return got

    def submit(self, launcher, payload, generation: int = 0,
               origin=None, rows=None) -> DeviceFuture:
        """Enqueue one command; returns its future. Auto-drains when
        ``max_depth`` is reached (including the command just
        submitted), so a pipeline slot never grows unbounded.

        ``generation`` tags the command with its epoch-keyed pubkey
        table generation (epochs.py): commands only coalesce within one
        (launcher, generation) pair, so a drain spanning an epoch
        boundary SPLITS into one launch per generation instead of
        mixing two key tables in one batch. Generation-less callers
        (the default 0) coalesce exactly as before.

        ``origin`` / ``rows`` feed the launch probe when one is
        installed: the submitting track (replica index, tenant id, -1
        for the sim) and the command's requested lane count. Both are
        accounting-only — scheduling ignores them."""
        if self._closed:
            raise RuntimeError("queue is closed")
        fut = DeviceFuture(self)
        meta = None
        if self.devtel is not NULL_DEVTEL:
            if rows is None:
                rows = len(payload) if hasattr(payload, "__len__") else 0
            meta = self.devtel.command(origin, rows)
            fut.seq = meta.seq
        elif self.policy is not None:
            # The drain policy reads origin/rows off the command meta;
            # synthesize a probe-free one when no devtel is installed
            # (fairness must not require telemetry).
            if rows is None:
                rows = len(payload) if hasattr(payload, "__len__") else 0
            meta = CmdMeta(self.submitted, 0.0, origin, rows)
        self._pending.append((launcher, payload, fut, generation, meta))
        self.submitted += 1
        if self.controller is not None:
            self.controller.note_depth(len(self._pending))
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "sched.submit", -1, -1,
                getattr(launcher, "kind", None),
            )
        if self.max_depth and len(self._pending) >= self.max_depth:
            if not self._draining:
                self.drain()
        return fut

    # ------------------------------------------------------------- drain

    @drain_point
    def drain(self) -> int:
        """Resolve every pending command; returns how many resolved.

        Each cycle snapshots the pending list, groups it by launcher
        preserving first-submission order, runs ONE launch per group,
        and resolves the group's futures in submission order (their
        callbacks run here). Callbacks may submit more work — the loop
        runs until the queue is quiet. Re-entrant calls (a callback
        resolving a future early) are satisfied by the outer drain.
        """
        if self._draining:
            return 0
        self._draining = True
        resolved = 0
        ctrl = self.controller
        t0 = None
        if ctrl is not None and ctrl.time_fn is not None:
            t0 = ctrl.time_fn()
        try:
            while self._pending:
                batch = self._pending
                self._pending = []
                policy = self.policy
                if policy is not None:
                    live = [c for c in batch if not c[2].cancelled()]
                    batch, deferred = policy.select(live)
                    if deferred:
                        # Deferred commands rejoin pending FIRST, so
                        # work submitted by this cycle's callbacks
                        # queues behind them — age order survives.
                        self._pending.extend(deferred)
                        if self.obs is not NULL_BOUND:
                            self.obs.emit(
                                "tenant.drain.deferred", -1, -1,
                                len(deferred),
                            )
                    if policy.last_forced and self.obs is not NULL_BOUND:
                        self.obs.emit(
                            "tenant.drain.forced", -1, -1,
                            policy.last_forced,
                        )
                    if not batch:
                        continue
                groups: dict = {}
                order: list = []
                for cmd in batch:
                    if cmd[2].cancelled():
                        continue
                    # Coalesce per (launcher, table generation): an
                    # epoch boundary inside one drain yields one launch
                    # per generation — keys never mix within a batch.
                    key = (id(cmd[0]), cmd[3])
                    if key not in groups:
                        groups[key] = []
                        order.append(key)
                    groups[key].append(cmd)
                devtel = self.devtel
                if devtel is not NULL_DEVTEL and len(order) > 1:
                    # Generation splits: extra launches the SAME
                    # launcher pays because its commands straddled an
                    # epoch boundary (distinct-launcher groups are
                    # ordinary fan-out, not splits).
                    per_launcher: dict = {}
                    for k in order:
                        per_launcher[k[0]] = per_launcher.get(k[0], 0) + 1
                    gen_splits = sum(
                        v - 1 for v in per_launcher.values()
                    )
                    if gen_splits:
                        devtel.splits(gen_splits)
                for key in order:
                    cmds = groups[key]
                    launcher = cmds[0][0]
                    if self.obs is not NULL_BOUND and len(cmds) > 1:
                        self.obs.emit(
                            "sched.coalesce", -1, -1, len(cmds)
                        )
                    if self.tracer is not None:
                        self.tracer.observe(
                            "sim.sched.coalesce", len(cmds)
                        )
                    self.launches += 1
                    self.coalesced += len(cmds) - 1
                    if key[1] and hasattr(launcher, "set_generation"):
                        # Generation-aware launchers swap their double-
                        # buffered table before the coalesced launch.
                        launcher.set_generation(key[1])
                    rec = None
                    if devtel is not NULL_DEVTEL:
                        rec = devtel.launch_begin(
                            getattr(launcher, "kind", "launch"),
                            key[1],
                            [c[4] for c in cmds],
                        )
                    payloads = [c[1] for c in cmds]
                    if rec is not None:
                        devtel.mark_pack(rec)
                    try:
                        results = launcher.launch(payloads)
                    except BaseException:
                        if rec is not None:
                            devtel.launch_end(rec)
                        raise
                    if rec is not None:
                        devtel.mark_dispatch(rec)
                        devtel.launch_lanes(rec, launcher)
                    if len(results) != len(cmds):
                        if rec is not None:
                            devtel.launch_end(rec)
                        raise RuntimeError(
                            f"launcher {launcher!r} returned "
                            f"{len(results)} results for {len(cmds)} "
                            "commands"
                        )
                    try:
                        for (_, _, fut, _, _), res in zip(cmds, results):
                            if rec is not None:
                                fut.launch_id = rec.launch_id
                            if not fut.cancelled():
                                fut._resolve(res)
                            resolved += 1
                    finally:
                        # Closed in a finally so a callback raising
                        # (SpeculationMismatch) still seals the record
                        # and removes the fetch probe.
                        if rec is not None:
                            devtel.launch_end(rec)
        finally:
            self._draining = False
        if resolved:
            if ctrl is not None:
                ctrl.note_drain(
                    resolved,
                    (ctrl.time_fn() - t0) if t0 is not None else 0.0,
                )
            if self.obs is not NULL_BOUND:
                self.obs.emit("sched.drain", -1, -1, resolved)
            if self.tracer is not None:
                self.tracer.observe("sim.sched.drain", resolved)
            if self.on_drain is not None:
                self.on_drain(resolved)
        return resolved

    def close(self) -> int:
        """Final drain, then reject further submits (shutdown: no
        command may be silently dropped — drain-on-shutdown is part of
        the queue contract, property-tested)."""
        resolved = self.drain()
        self._closed = True
        return resolved
