# hdlint: scope=async
"""Tenant-aware drain policies: who rides the next coalesced launch.

The default :class:`~hyperdrive_tpu.devsched.queue.DeviceWorkQueue`
drain is FIFO-everything: every pending command coalesces into the next
launch. That is optimal for throughput — one sync covers all tenants —
but it has no answer to a firehose tenant: a shard submitting 100x
everyone else's rows makes every launch huge, and every OTHER tenant's
commit latency inherits the firehose's launch time. An inference
server meets the same problem with continuous batching plus a
fair scheduler; this module is that scheduler for the verify queue.

A policy is consulted once per drain *cycle* (the queue's
``while pending`` loop): it partitions the pending command list into
``(selected, deferred)``. Selected commands form this cycle's launches;
deferred commands rejoin the pending list and are reconsidered next
cycle — still inside the same ``drain()`` call, so nothing leaks past a
drain, the queue's drain-on-close contract is untouched, and a bounded
``capacity_rows`` turns one monster drain into a train of bounded
launches with fair seating.

Two policies:

- ``None`` / :class:`FifoDrainPolicy` — select everything, defer
  nothing: **byte-identical scheduling to the policy-less queue**
  (digest-neutral; the default).
- :class:`DeficitRoundRobin` — weighted deficit round-robin over
  per-tenant pending rows (Shreedhar & Varghese), with a starvation
  bound: a command deferred ``starve_after`` consecutive cycles is
  force-selected into the next launch regardless of deficit or
  capacity, so the worst-case wait is ``starve_after`` launches — a
  spec'd bound the chaos soak asserts
  (:meth:`~hyperdrive_tpu.chaos.monitor.InvariantMonitor.
  check_tenant_fairness`).

Deterministic by construction, like the queue itself: no wall clock, no
randomness — selection depends only on the submission sequence, so
fixed-seed runs replay byte-identically.
"""

from __future__ import annotations

__all__ = ["FifoDrainPolicy", "DeficitRoundRobin"]


def _rows(cmd) -> int:
    """Row weight of one pending command tuple (launcher, payload,
    future, generation, meta). Probed queues carry the submitter's row
    count in meta; unprobed queues fall back to payload length. Zero-row
    commands weigh 1 so deficit accounting always makes progress."""
    meta = cmd[4]
    if meta is not None:
        return max(1, int(meta.rows))
    payload = cmd[1]
    n = len(payload) if hasattr(payload, "__len__") else 1
    return max(1, n)


def _origin(cmd):
    """The submitting tenant's track id (``DeviceWorkQueue.submit``'s
    ``origin``), or None for origin-less submitters — which share one
    round-robin seat rather than bypassing fairness."""
    meta = cmd[4]
    return meta.origin if meta is not None else None


class FifoDrainPolicy:
    """Explicit spelling of the default: everything launches now.

    Exists so ``policy=FifoDrainPolicy()`` and ``policy=None`` are
    interchangeable (tests assert scheduling equality) and so callers
    can treat "which policy" as data rather than an if."""

    name = "fifo"
    starve_after = 0

    def __init__(self):
        self.deferred_total = 0
        self.forced_total = 0
        self.max_deferrals = 0
        self.last_deferred = 0
        self.last_forced = 0

    def select(self, batch):
        self.last_deferred = 0
        self.last_forced = 0
        return batch, []


class DeficitRoundRobin:
    """Weighted deficit round-robin over per-tenant pending rows.

    ``capacity_rows`` bounds the rows selected per drain cycle (the
    launch-size envelope the sync floor is amortized over);
    ``quantum_rows`` is the per-visit deficit credit (scaled by the
    tenant's ``weights`` entry, default 1); ``starve_after`` is the
    starvation bound in cycles.

    Selection each cycle:

    1. **Forced lane** — commands already deferred ``starve_after``
       times are selected first, capacity notwithstanding (the bound is
       a guarantee, not a goal).
    2. **DRR lane** — visit tenants in first-seen ring order starting
       one past last cycle's start; each visit credits the tenant's
       deficit and takes queued commands head-first while the deficit
       covers their rows and cycle capacity remains. A tenant's unspent
       deficit carries to its next visit; an emptied tenant's deficit
       resets (classic DRR — credit must not accrue while idle).
    3. Everything else defers to the next cycle and its deferral count
       rises; ``max_deferrals`` records the lifetime worst, which the
       starvation bound caps at ``starve_after``.

    Progress is guaranteed: a non-empty batch always selects at least
    one command (an over-capacity command that nothing else displaces is
    taken alone rather than spinning).
    """

    name = "drr"

    def __init__(self, capacity_rows: int = 256, quantum_rows: int = 64,
                 weights=None, starve_after: int = 4):
        if capacity_rows < 1:
            raise ValueError(f"capacity_rows must be >= 1, got {capacity_rows}")
        if quantum_rows < 1:
            raise ValueError(f"quantum_rows must be >= 1, got {quantum_rows}")
        if starve_after < 1:
            raise ValueError(f"starve_after must be >= 1, got {starve_after}")
        self.capacity_rows = int(capacity_rows)
        self.quantum_rows = int(quantum_rows)
        self.weights = dict(weights) if weights else {}
        self.starve_after = int(starve_after)
        #: Per-tenant deficit credit (rows), carried across cycles.
        self._deficit: dict = {}
        #: Tenants in first-seen order (the round-robin ring) + cursor.
        self._ring: list = []
        self._ring_pos: dict = {}
        self._cursor = 0
        #: future-id -> consecutive deferral count for pending commands.
        self._defers: dict = {}
        #: Lifetime counters (tests, chaos invariants, the soak report).
        self.deferred_total = 0
        self.forced_total = 0
        self.max_deferrals = 0
        self.last_deferred = 0
        self.last_forced = 0

    def weight(self, origin) -> int:
        return max(1, int(self.weights.get(origin, 1)))

    def _seat(self, origin) -> None:
        if origin not in self._ring_pos:
            self._ring_pos[origin] = len(self._ring)
            self._ring.append(origin)

    def select(self, batch):
        self.last_deferred = 0
        self.last_forced = 0
        if not batch:
            return [], []
        selected: list = []
        budget = self.capacity_rows
        queues: dict = {}
        for cmd in batch:
            fid = id(cmd[2])
            if self._defers.get(fid, 0) >= self.starve_after:
                # Forced lane: the starvation bound fires.
                self._defers.pop(fid, None)
                selected.append(cmd)
                budget -= _rows(cmd)
                self.last_forced += 1
                self.forced_total += 1
                continue
            origin = _origin(cmd)
            self._seat(origin)
            queues.setdefault(origin, []).append(cmd)
        # DRR lane: ring visits from a rotating start, credit + take.
        ring = self._ring
        if ring and budget > 0:
            start = self._cursor % len(ring)
            self._cursor = (self._cursor + 1) % len(ring)
            progressed = True
            while budget > 0 and progressed:
                progressed = False
                for step in range(len(ring)):
                    origin = ring[(start + step) % len(ring)]
                    q = queues.get(origin)
                    if not q:
                        continue
                    credit = self._deficit.get(origin, 0) + (
                        self.quantum_rows * self.weight(origin)
                    )
                    while q and budget > 0:
                        need = _rows(q[0])
                        if credit < need or need > budget:
                            break
                        cmd = q.pop(0)
                        credit -= need
                        budget -= need
                        self._defers.pop(id(cmd[2]), None)
                        selected.append(cmd)
                        progressed = True
                    # Classic DRR: an emptied tenant forfeits its credit.
                    self._deficit[origin] = 0 if not q else credit
                    if budget <= 0:
                        break
        deferred: list = []
        for origin in ring:
            q = queues.get(origin)
            if q:
                deferred.extend(q)
        if not selected and deferred:
            # Progress guarantee: take the oldest submission alone
            # (an over-capacity command becomes its own launch).
            cmd = min(deferred, key=batch.index)
            deferred.remove(cmd)
            self._defers.pop(id(cmd[2]), None)
            selected.append(cmd)
        if len(deferred) > 1:
            # Re-queue in original submission order so per-tenant FIFO
            # and cross-tenant age ordering survive the detour.
            index = {id(c[2]): i for i, c in enumerate(batch)}
            deferred.sort(key=lambda c: index[id(c[2])])
        for cmd in deferred:
            fid = id(cmd[2])
            n = self._defers.get(fid, 0) + 1
            self._defers[fid] = n
            if n > self.max_deferrals:
                self.max_deferrals = n
        self.last_deferred = len(deferred)
        self.deferred_total += len(deferred)
        return selected, deferred
