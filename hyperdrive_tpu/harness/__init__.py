"""Deterministic in-process network simulation for consensus testing.

The capability analogue of the reference's ``replica/replica_test.go``
harness (an in-memory global message queue, lock-step delivery, seeded
scenarios, fault and Byzantine injection, and record/replay of failing
interleavings) — redesigned around a virtual clock so runs are fast and
bit-reproducible instead of sleeping real time.
"""

from hyperdrive_tpu.harness.sim import (
    Simulation,
    SimulationResult,
    ScenarioRecord,
    VirtualClock,
)

__all__ = ["Simulation", "SimulationResult", "ScenarioRecord", "VirtualClock"]
