"""The simulator: virtual time, seeded adversaries, record/replay.

How it maps to the reference harness (replica/replica_test.go):

- One global message queue of ``(to, msg)`` records; every broadcast
  appends one record per replica, including the sender
  (reference: 174-208). Delivery is strictly one message at a time, so the
  whole distributed execution is a serialized, recordable interleaving
  (reference: 228-323).
- Timeouts go through a :class:`VirtualClock` instead of real sleeps: when
  the network drains, the clock jumps to the next deadline and the fired
  timeout enters the queue addressed to its owner (the reference used
  real-time sleeping timers; virtual time preserves the semantics and makes
  runs instant and deterministic).
- Faults: replicas can be killed at a chosen delivery step (reference:
  574-589 kills via context cancel); Byzantine replicas take custom
  proposer/validator behaviours (reference: 603-682).
- Every delivered message is recorded into a :class:`ScenarioRecord` that
  serializes through the canonical codec; a failing run can be dumped to
  disk and replayed message-for-message (reference: Scenario + failure.dump
  + REPLAY_MODE, 850-928/1049-1078).
"""

from __future__ import annotations

import hashlib
import heapq
import random
import time
from itertools import repeat

import numpy as np
from dataclasses import dataclass, field, replace
from typing import Callable, Optional

from hyperdrive_tpu.analysis.annotations import wire_codec, wire_entry
from hyperdrive_tpu.analysis.sanitizer import maybe_wire_reader
from hyperdrive_tpu.batch import WindowColumns
from hyperdrive_tpu.codec import Reader, SerdeError, Writer
from hyperdrive_tpu.messages import (
    Precommit,
    Prevote,
    Propose,
    Timeout,
    marshal_message,
    unmarshal_message,
)
from hyperdrive_tpu.obs.recorder import NULL_BOUND as _OBS_NULL
from hyperdrive_tpu.overlay.runtime import OverlayFrame, OverlayTick
from hyperdrive_tpu.replica import (
    Replica,
    ReplicaOptions,
    ResetHeight,
    merge_drain,
)
from hyperdrive_tpu.scheduler import RoundRobin
from hyperdrive_tpu.testutil import (
    BroadcasterCallbacks,
    CatcherCallbacks,
    CommitterCallback,
    MockProposer,
    MockValidator,
)
from hyperdrive_tpu.timer import VirtualTimer
from hyperdrive_tpu.types import Height, Value

__all__ = ["VirtualClock", "ScenarioRecord", "SimulationResult", "Simulation"]


class VirtualClock:
    """A deterministic event clock: deadlines in a heap, time advances only
    when the simulator asks for the next due event."""

    def __init__(self):
        self.now = 0.0
        self._seq = 0
        self._heap: list[tuple[float, int, object, object]] = []

    def schedule(self, delay: float, event, handler) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (self.now + delay, self._seq, event, handler))

    def pending(self) -> int:
        return len(self._heap)

    def fire_next(self):
        """Jump to the earliest deadline; return (event, handler).

        Delivery pacing can push ``now`` past a pending deadline — firing
        must never move time backwards (monotonicity keeps the tracer's
        latencies and subsequently scheduled deadlines coherent)."""
        deadline, _, event, handler = heapq.heappop(self._heap)
        self.now = max(self.now, deadline)
        return event, handler

    def prune(self, keep) -> int:
        """Drop scheduled events failing ``keep(event)``; returns the drop
        count. Happy-path consensus never drains the queue, so timeouts
        for long-committed heights pile up forever (~255/height at n=256 —
        2.5M dead heap entries over a 10k-height run); the driver prunes
        them once the heap gets large."""
        kept = [e for e in self._heap if keep(e[2])]
        dropped = len(self._heap) - len(kept)
        if dropped:
            heapq.heapify(kept)
            self._heap = kept
        return dropped


@wire_codec(tag="scenario.record", max_bytes=1 << 30)
@dataclass
class ScenarioRecord:
    """A reproducible account of one simulated run
    (reference: Scenario struct, replica_test.go:850-860)."""

    seed: int
    n: int
    f: int
    target_height: Height
    signatories: list[bytes] = field(default_factory=list)
    #: Every delivered (to, message) in delivery order.
    messages: list[tuple[int, object]] = field(default_factory=list)
    #: Burst-mode runs record each superstep's delivered-message count so
    #: replay reproduces the same window boundaries (empty = lock-step run).
    bursts: list[int] = field(default_factory=list)
    #: Whether burst windows were fed through Process.ingest (batched rule
    #: cascade) or per-message dispatch — replay must match, or timeout
    #: schedules and evidence can diverge from the recorded run.
    batch_ingest: bool = True
    #: Chaos lifecycle operations, ``(kind, pos, replica, aux)`` with
    #: kind one of OP_CRASH / OP_RESTORE / OP_RESYNC, ``pos`` the
    #: delivered-message count when the op fired (replay applies every op
    #: with pos <= j before delivering message j), and ``aux`` the resync
    #: height for RESTORE/RESYNC (0 for CRASH). Dropped/blocked/delayed
    #: messages never enter the record, so replay needs no knowledge of
    #: the FaultPlan — only of when replicas died, revived, and jumped.
    lifecycle: list[tuple[int, int, int, int]] = field(default_factory=list)
    #: Epoch configuration, ``(epoch_length, committee_size,
    #: rekey_per_epoch, seed, stakes)`` for dynamic-validator-set runs
    #: (epochs.py) or None. Replay rebuilds the identical EpochSchedule
    #: from these five values — elections and re-keys are deterministic
    #: functions of them plus the committed boundary values, which the
    #: message stream reproduces.
    epochs: "tuple | None" = None
    #: Execution-layer configuration (exec/__init__.py
    #: ``ExecutionConfig.as_ints()``) or None. Block content, admission
    #: masks, and the chained state roots are all deterministic
    #: functions of these ints plus committed heights, so replay
    #: re-derives the full ledger trajectory — root-extended commit
    #: values included — with no stored state.
    execution: "tuple | None" = None

    OP_CRASH = 0
    OP_RESTORE = 1
    OP_RESYNC = 2

    #: Format magic+version; bump on any envelope/layout change so stale
    #: dumps are rejected with a clear error instead of desynchronizing.
    #: v3 appends the burst-size trailer (v2 dumps still load); v4 appends
    #: the batch_ingest flag. Pre-v4 dumps load as batch_ingest=False:
    #: batched ingestion did not exist then, so every old record was
    #: captured under per-message dispatch. v5 appends the chaos
    #: lifecycle-op trailer (pre-v5 dumps load with no lifecycle ops).
    #: v6 appends the epoch-config trailer (pre-v6 dumps load with no
    #: epochs — dynamic validator sets did not exist then). v7 appends
    #: the execution-layer trailer (pre-v7 dumps load with no execution
    #: — blocks were opaque digests then).
    MAGIC = 0x48594456  # "HYDV"
    VERSION = 7

    def marshal(self, w: Writer) -> None:
        w.u32(self.MAGIC)
        w.u32(self.VERSION)
        w.u64(self.seed)
        w.u32(self.n)
        w.u32(self.f)
        w.i64(self.target_height)
        w.u32(len(self.signatories))
        for s in self.signatories:
            w.bytes32(s)
        w.u32(len(self.messages))
        for to, msg in self.messages:
            w.u32(to)
            marshal_message(msg, w)
        w.u32(len(self.bursts))
        for b in self.bursts:
            w.u32(b)
        w.bool(self.batch_ingest)
        w.u32(len(self.lifecycle))
        for kind, pos, replica, aux in self.lifecycle:
            w.u32(kind)
            w.u32(pos)
            w.u32(replica)
            w.i64(aux)
        w.bool(self.epochs is not None)
        if self.epochs is not None:
            epoch_length, committee, rekey, eseed, stakes = self.epochs
            w.u32(epoch_length)
            w.u32(committee)
            w.u32(rekey)
            w.u64(eseed)
            w.u32(len(stakes))
            for s in stakes:
                w.u64(s)
        w.bool(self.execution is not None)
        if self.execution is not None:
            # Length-prefixed u64 fields: a future config int extends
            # the trailer without another version bump.
            w.u32(len(self.execution))
            for v in self.execution:
                w.u64(int(v))

    @classmethod
    def unmarshal(cls, r: Reader) -> "ScenarioRecord":
        magic = r.u32()
        if magic != cls.MAGIC:
            raise SerdeError(f"not a scenario dump (magic {magic:#x})")
        version = r.u32()
        if version not in (2, 3, 4, 5, 6, cls.VERSION):
            raise SerdeError(
                f"scenario dump version {version} unsupported "
                f"(expected {cls.VERSION})"
            )
        rec = cls(seed=r.u64(), n=r.u32(), f=r.u32(), target_height=r.i64())
        nsigs = r.u32()
        if nsigs > 1 << 20:
            raise SerdeError("signatory count too large")
        rec.signatories = [r.bytes32() for _ in range(nsigs)]
        nmsgs = r.u32()
        if nmsgs > 1 << 24:
            raise SerdeError("message count too large")
        # Intern equal messages: live runs deliver ONE broadcast object to
        # all receivers, and downstream fast paths (identity-keyed dedup
        # verification, digest memoization) lean on that. Restore the
        # shared-object invariant for replayed dumps, where each delivery
        # would otherwise deserialize to a distinct object. Message
        # equality excludes the signature (compare=False), so it is keyed
        # explicitly — same-content deliveries with different signatures
        # must stay distinct objects or replayed verdicts could flip.
        interned: dict = {}
        rec.messages = []
        for _ in range(nmsgs):
            to = r.u32()
            msg = unmarshal_message(r)
            # Timeout deliveries carry no signature; key them by value
            # alone (their dataclass equality covers every field).
            key = (msg, getattr(msg, "signature", None))
            rec.messages.append((to, interned.setdefault(key, msg)))
        if version >= 3:
            nb = r.u32()
            if nb > 1 << 24:
                raise SerdeError("burst count too large")
            rec.bursts = [r.u32() for _ in range(nb)]
        if version >= 4:
            rec.batch_ingest = r.bool()
        else:
            rec.batch_ingest = False
        if version >= 5:
            nops = r.u32()
            if nops > 1 << 20:
                raise SerdeError("lifecycle op count too large")
            rec.lifecycle = [
                (r.u32(), r.u32(), r.u32(), r.i64()) for _ in range(nops)
            ]
        if version >= 6 and r.bool():
            epoch_length = r.u32()
            committee = r.u32()
            rekey = r.u32()
            eseed = r.u64()
            nstakes = r.u32()
            if nstakes > 1 << 20:
                raise SerdeError("stake count too large")
            rec.epochs = (
                epoch_length, committee, rekey, eseed,
                tuple(r.u64() for _ in range(nstakes)),
            )
        if version >= 7 and r.bool():
            nvals = r.u32()
            if nvals > 64:
                raise SerdeError("execution trailer too large")
            rec.execution = tuple(r.u64() for _ in range(nvals))
        return rec

    def dump(self, path: str) -> None:
        w = Writer(rem=1 << 30)
        self.marshal(w)
        with open(path, "wb") as fh:
            fh.write(w.data())

    @classmethod
    @wire_entry
    def load(cls, path: str) -> "ScenarioRecord":
        with open(path, "rb") as fh:
            return cls.unmarshal(maybe_wire_reader(
                "scenario.record", fh.read(), rem=1 << 30
            ))


class _Discard:
    """Zero-cost message sink for record=False runs (append is a no-op,
    so the hot loops keep a single unconditional call either way)."""

    __slots__ = ()

    def append(self, item) -> None:
        pass

    def append_broadcast(self, msg, live) -> None:
        pass


_DISCARD = _Discard()


class RecordedMessages:
    """The delivery log, list-compatible but broadcast-compact.

    A shared-superstep broadcast reaches every live replica; storing one
    op ``(msg, live)`` instead of ``len(live)`` per-delivery tuples cuts
    the recorder's memory and append cost by ~n (at 256 replicas a
    100-height run holds ~51k ops instead of ~13M tuples). The flat
    per-delivery view — what replay, serde, and equality consume — is
    materialized lazily on first indexed access; the run phase only ever
    appends. ``live`` lists are shared by reference across one
    superstep's ops and must not be mutated afterwards (the run loop
    rebuilds the list each superstep).
    """

    __slots__ = ("_ops", "_len", "_flat")

    _TARGETED = None  # sentinel 'live' meaning a single (to, msg) delivery

    def __init__(self):
        self._ops: list = []
        self._len = 0
        self._flat = None

    def append(self, item) -> None:
        """One targeted delivery: item = (to, msg)."""
        if self._flat is not None:
            self._flat.append(item)
        self._ops.append((item, self._TARGETED))
        self._len += 1

    def append_broadcast(self, msg, live) -> None:
        """One broadcast delivered to every replica in ``live`` (in
        order) — recorded as a single op."""
        if self._flat is not None:
            self._flat.extend((i, msg) for i in live)
        self._ops.append((msg, live))
        self._len += len(live)

    def _materialize(self) -> list:
        flat = self._flat
        if flat is None:
            flat = []
            for head, live in self._ops:
                if live is self._TARGETED:
                    flat.append(head)
                else:
                    flat.extend((i, head) for i in live)
            self._flat = flat
        return flat

    def __len__(self) -> int:
        return self._len

    def __iter__(self):
        return iter(self._materialize())

    def __getitem__(self, idx):
        return self._materialize()[idx]

    def __eq__(self, other) -> bool:
        if isinstance(other, RecordedMessages):
            other = other._materialize()
        if isinstance(other, list):
            return self._materialize() == other
        return NotImplemented

    def __repr__(self) -> str:
        return f"RecordedMessages({self._len} deliveries)"


@dataclass
class SimulationResult:
    completed: bool
    steps: int
    virtual_time: float
    heights: list[Height]
    commits: list[dict[Height, Value]]
    record: "ScenarioRecord | None"  # None when the run had record=False
    alive: list[bool]
    #: Per-replica certificate chain digests (certificates=True runs
    #: only): ``Certifier.chain_digest()`` in replica order — the O(1)
    #: commit-proof sibling of :meth:`commit_digest` for pipelined ==
    #: sequential and cross-replica equality checks.
    cert_digests: "list[str] | None" = None
    #: The Simulation behind a :meth:`Simulation.replay` result (live
    #: ``run()`` callers already hold theirs). The chaos replay CLI
    #: re-verifies the epoch-proof chain off the replayed certifiers.
    sim: "Simulation | None" = field(default=None, repr=False, compare=False)

    def assert_safety(self) -> None:
        """All replicas — including ones that later died — must agree
        byte-for-byte wherever their commit maps overlap (reference
        assertion: replica_test.go:418-423). A dead replica's commits from
        before its death are still evidence: a fork committed pre-kill must
        fail the check."""
        maps = self.commits
        # Sorted: set-union order is hash-seed dependent, and everything
        # downstream of this walk (first-failure reporting, digesting)
        # must be replay-stable run to run.
        for h in sorted(set().union(*[set(c) for c in maps])) if maps else ():
            vals = {c[h] for c in maps if h in c}
            assert len(vals) <= 1, f"safety violation at height {h}: {vals}"

    def commit_digest(self, up_to: int | None = None) -> str:
        """Canonical digest of the network's agreed chain: SHA-256 over
        the height-sorted (height, value) pairs of the merged commit
        maps (:meth:`assert_safety` certifies the merge is fork-free).
        Two runs that committed the same chain produce the same hex
        digest regardless of replica count, delivery schedule, or hash
        seed — the regression handle for determinism tests.

        ``up_to`` bounds the digest to heights <= that value: two runs
        to the same target can legitimately overshoot by different
        amounts (whoever drains the final queue first commits one more
        height before the driver stops), so cross-run equality checks
        compare the chains up to the shared target, not the ragged
        tail."""
        import hashlib

        self.assert_safety()
        merged: dict = {}
        for c in self.commits:
            merged.update(c)
        if up_to is not None:
            merged = {k: v for k, v in merged.items() if k <= up_to}
        h = hashlib.sha256()
        for height in sorted(merged):
            v = merged[height]
            h.update(int(height).to_bytes(8, "little"))
            h.update(len(v).to_bytes(4, "little"))
            h.update(v)
        return h.hexdigest()


class Simulation:
    """Build and run one n-replica scenario."""

    def __init__(
        self,
        n: int,
        target_height: Height,
        seed: int = 1,
        timeout: float = 1.0,
        timeout_scaling: float = 0.5,
        max_capacity: int = 1000,
        reorder: bool = False,
        drop_rate: float = 0.0,
        kill_at_step: Optional[dict[int, int]] = None,
        offline: Optional[set[int]] = None,
        byzantine_proposer: Optional[dict[int, Callable[[Height, int], Value]]] = None,
        byzantine_validator: Optional[dict[int, Callable[[Height, int, Value], bool]]] = None,
        verifier_for: Optional[Callable[[int], object]] = None,
        signatories: Optional[list[bytes]] = None,
        sign: bool = False,
        delivery_cost: float = 0.0,
        burst: bool = False,
        batch_verifier=None,
        dedup_verify: bool = False,
        batch_ingest: Optional[bool] = None,
        device_tally: bool = False,
        tally_mesh=None,
        tally_check=None,
        payload_bytes: int = 0,
        dedup_reconstruct: bool = True,
        reconstructor=None,
        record: bool = True,
        shared_superstep: Optional[bool] = None,
        small_window_host: Optional[bool] = None,
        fused_min_window: int = 0,
        columnar_ingest: Optional[bool] = None,
        pipeline_verify: Optional[bool] = None,
        route_hysteresis: int = 32,
        pipeline_heights: Optional[bool] = None,
        pipeline_depth: int = 6,
        devsched=None,
        flusher_for: Optional[Callable[[int, list], object]] = None,
        observe: bool = False,
        obs_capacity: int = 65536,
        chaos=None,
        certificates: bool = False,
        bls_certificates=False,
        epochs=None,
        catchup_every: Optional[int] = None,
        catchup_lag: Optional[int] = None,
        load=None,
        overlay=None,
        execution=None,
        exec_speculate: Optional[bool] = None,
        fused_exec_drain: Optional[bool] = None,
        dedup_exec: Optional[bool] = None,
    ):
        """``sign=True`` gives every replica a deterministic Ed25519 keypair
        (identity = public key), signs every broadcast message, and installs
        a :class:`~hyperdrive_tpu.verifier.HostVerifier` on each replica
        unless ``verifier_for`` overrides it — authenticated consensus end
        to end, the host baseline of BASELINE.md config 4.

        ``burst=True`` switches delivery from lock-step (one message, one
        flush) to supersteps: every pending delivery is buffered into its
        destination's queue, then the whole network settles through the
        two-phase drain/dispatch protocol — all replicas' windows are
        signature-checked in ONE ``batch_verifier`` launch per settle pass
        (:class:`~hyperdrive_tpu.ops.ed25519_jax.TpuBatchVerifier` for the
        device path, :class:`~hyperdrive_tpu.verifier.HostVerifier` for the
        host baseline). This is the batched replica driving mode of
        SURVEY.md §7.1(4): per-message interleaving becomes per-burst, each
        replica still sees its messages in global (height, round) order, and
        burst boundaries are recorded for exact replay.

        ``dedup_verify=True`` verifies each distinct (sender, digest,
        signature) once per settle launch and fans the verdict out to every
        receiver. One simulated chip then performs one replica's
        verification load (each broadcast checked once), which is the
        per-chip work of a real deployment where every validator owns its
        chip; with it off, the single chip redundantly re-verifies each
        broadcast for all n receivers — n× the deployment's per-chip load.
        Acceptance decisions are identical either way (verification is
        deterministic), so safety/replay semantics do not change.

        ``payload_bytes > 0`` turns on the MPC payload path (BASELINE
        config 5): every proposed value carries a (2f+1)-of-n Shamir share
        bundle for a payload of that many bytes, validators accept only
        proposals whose bundle matches the value commitment, and on every
        commit the committer reconstructs the payload from k shares via
        the adaptive router
        (:class:`~hyperdrive_tpu.ops.shamir.AdaptiveReconstructor` —
        commit-sized batches ride its cached-weight host leg; pass
        ``reconstructor=`` to pin a backend, e.g. BatchReconstructor for
        the device kernel), recording it in
        ``self.reconstructed[replica][height]``.
        ``dedup_reconstruct`` mirrors dedup_verify: reconstruct each
        distinct committed value once per chip (the per-replica load of a
        real deployment) instead of once per simulated replica."""
        self.n = n
        self.f = n // 3
        self.target_height = target_height
        self.seed = seed
        self.rng = random.Random(seed)
        self.reorder = reorder
        self.drop_rate = drop_rate
        #: Virtual seconds charged per delivered message (the reference
        #: harness paces deliveries at 1 ms, replica_test.go:291; 0 = free
        #: delivery). With pacing on, per-height latency histograms measure
        #: something real and stay deterministic.
        self.delivery_cost = delivery_cost
        self.kill_at_step = dict(kill_at_step or {})
        self.offline = set(offline or ())
        self.clock = VirtualClock()
        # One shared tracer across all replicas, on virtual time: metrics
        # (round latencies, verify occupancy, equivocation counts) are
        # deterministic and replay-identical.
        from hyperdrive_tpu.utils import Tracer

        # The sim is single-threaded; skip the tracer's per-call locking.
        self.tracer = Tracer(time_fn=lambda: self.clock.now, threadsafe=False)
        # Flight recorder (obs/recorder.py): a bounded, deterministic event
        # journal on the same virtual clock, so fixed-seed runs are
        # digest-identical (OBSERVABILITY.md). Off by default — NULL_RECORDER
        # hands every replica the shared no-op handle, keeping disabled
        # recording at one identity check per emit site.
        from hyperdrive_tpu.obs.recorder import NULL_RECORDER, Recorder

        self.obs = (
            Recorder(
                capacity=obs_capacity,
                time_fn=lambda: self.clock.now,
                threadsafe=False,
            )
            if observe
            else NULL_RECORDER
        )
        #: Sim-level emit handle (replica = -1): settle/verify/tally launch
        #: events that belong to the harness, not any one replica.
        self._obs_sim = self.obs.scoped(-1)
        # Metrics registry + device-telemetry probe (obs/metrics.py,
        # obs/devtel.py), both on the virtual clock so the snapshot is
        # digest-identical across fixed-seed runs. The registry always
        # exists (it is where metrics_snapshot() folds the tracer in);
        # the launch probe only when observing — NULL_DEVTEL keeps the
        # unobserved queue at one pointer compare per submit/drain.
        from hyperdrive_tpu.obs.devtel import NULL_DEVTEL, DeviceTelemetry
        from hyperdrive_tpu.obs.metrics import Registry

        self.registry = Registry(time_fn=lambda: self.clock.now)
        self.devtel = (
            DeviceTelemetry(
                recorder=self.obs,
                registry=self.registry,
                time_fn=lambda: self.clock.now,
            )
            if observe
            else NULL_DEVTEL
        )
        # The delivery queue is consumed via a head index (O(1) per step;
        # list.pop(0) would make 256-replica x 10k-height runs quadratic).
        self.queue: list[tuple[int, object]] = []
        self._qhead = 0
        # ``record=False`` turns off the replay recorder: every delivered
        # message is otherwise retained for the dump/replay workflow, which
        # at depth dominates memory (a 1,000-height 256-replica run holds
        # 131M deliveries, ~12 GB — BENCH.md config 4 dedup_run_deep).
        # Unrecorded runs report ``result.record = None`` so a dump
        # attempt fails loudly instead of replaying an empty scenario.
        self._record_on = record
        self.record = ScenarioRecord(
            seed=seed, n=n, f=self.f, target_height=target_height
        )
        # Live runs record through the broadcast-compact log (one op per
        # broadcast instead of one tuple per delivery); loaded dumps keep
        # plain lists — the two compare equal element-for-element.
        self.record.messages = RecordedMessages()

        self.burst = burst
        self.batch_verifier = batch_verifier
        # An observed run adopts a device verifier's recorder handle so
        # its kernel-side occupancy probes (verify.occupancy.*) land in
        # the same journal as the queue's launch records; an explicitly
        # pre-bound handle wins.
        if observe and batch_verifier is not None:
            from hyperdrive_tpu.obs.recorder import NULL_BOUND

            if getattr(batch_verifier, "obs", None) is NULL_BOUND:
                batch_verifier.obs = self._obs_sim
        #: certificates=True: every replica's Process carries a
        #: certificates.Certifier minting a constant-size
        #: QuorumCertificate at each commit (transcript-bound to the
        #: settle layer's batch verifier when one is installed); chain
        #: digests land in SimulationResult.cert_digests.
        self.certificates_on = bool(certificates) or bool(bls_certificates)
        #: bls_certificates: False | True | "device". Truthy implies
        #: certificates=True and installs the deterministic BLS committee
        #: keyring on every certifier, so each commit's certificate
        #: carries the 48-byte aggregate signature a light client can
        #: check with zero transcript trust. "device" routes the G1
        #: aggregation through the ops.g1 bitmask kernel (fixed launch
        #: width = committee size rounded up to a power of two); True
        #: keeps the host fold — digests are identical either way.
        self.bls_certificates = bls_certificates
        self._bls_keyring: "dict | None" = None
        self.certifiers: list = []
        self.dedup_verify = dedup_verify
        #: Small-window host routing for device-backed verifiers: a
        #: propose settle is 1-2 signatures, and on a tunnel-attached
        #: chip ANY device sync costs ~100 ms — the host verifies such a
        #: window in well under a millisecond with bit-identical verdicts
        #: (differentially tested). This is the AdaptiveVerifier insight
        #: applied at the settle layer; vote-bearing windows stay on
        #: device. ``small_window_host`` is a differential-testing knob
        #: (like ``shared_superstep``/``batch_ingest``): None = auto (on
        #: for fused-capable device verifiers), False forces every window
        #: — however small — through the device backend so e2e tests can
        #: exercise the device verify path at miniature scales, True
        #: demands the routing (error if there is no batch verifier to
        #: route around, rather than silently doing nothing).
        self._small_win_host = None
        if small_window_host is True and batch_verifier is None:
            raise ValueError(
                "small_window_host=True requires a batch_verifier to "
                "route small windows away from"
            )
        if batch_verifier is not None and (
            small_window_host is True
            or (
                small_window_host is None
                and hasattr(batch_verifier, "fused_inner")
            )
        ):
            from hyperdrive_tpu.verifier import HostVerifier

            self._small_win_host = HostVerifier()
        #: Shared-superstep fast path: with no per-delivery adversary
        #: (reorder/drops), every live replica receives the identical
        #: broadcast sequence, so the superstep keeps ONE shared broadcast
        #: list — one queue entry, one sort, one verify per broadcast
        #: instead of one per delivery. Per-replica state stays honest:
        #: each replica still filters, inserts, and cascades its own copy
        #: of the window. Trajectories, records, and replays are identical
        #: to the per-delivery path (the expansion happens at record time).
        #: ``shared_superstep`` (like ``batch_ingest``) is a differential-
        #: testing knob: None = auto (on whenever eligible), False forces
        #: the per-delivery path so equivalence can be asserted run-for-run.
        self._shared_mode = (
            burst and not reorder and drop_rate == 0.0
            if shared_superstep is None
            else bool(shared_superstep)
        )
        if self._shared_mode and not (burst and not reorder and drop_rate == 0.0):
            raise ValueError(
                "shared_superstep=True requires burst mode with no "
                "per-delivery adversary (reorder/drop_rate)"
            )
        self._shared: list = []
        #: Burst mode defaults to batched window ingestion (one rule
        #: cascade per window — see Process.ingest); pass False to force
        #: per-message dispatch for differential comparison.
        self.batch_ingest = burst if batch_ingest is None else batch_ingest
        if self.batch_ingest and not burst:
            raise ValueError("batch_ingest requires burst=True")
        self.record.batch_ingest = self.batch_ingest
        #: Device-resident quorum tallies (ops.votegrid): scatter accepted
        #: votes into per-replica vote tensors and feed the rule cascade
        #: the device counts. Behavior-neutral by construction (counts
        #: equal the host counters wherever the TallyView answers), so no
        #: record flag is needed — replays without a grid are identical.
        self.device_tally = device_tally
        #: Optional callable (view, proc) -> view, used by tests to wrap
        #: every TallyView in a host-vs-device equality checker.
        self._tally_check = tally_check
        #: Per-settle crossover routing for the fused device path: a
        #: vote-bearing settle whose shared window holds fewer than this
        #: many messages is handled entirely on the host (aggregated host
        #: verification + host-counter cascade) instead of paying the
        #: fused launch's device sync. On a tunnel-attached chip the sync
        #: floor is ~100 ms — the host verifies ~1000 signatures in that
        #: time — so sub-crossover settles are faster on host by
        #: construction; the device grid is poisoned for the affected
        #: heights (counts would be incomplete) and re-engages at the
        #: next height. 0 = always fuse (the round-3 behavior). This is
        #: AdaptiveVerifier's measured-crossover insight applied to the
        #: whole settle, not just the verify leg.
        self._fused_min_window = int(fused_min_window)
        #: Columnar settle fast path: lockstep windows ingest through ONE
        #: WindowColumns extraction shared by every replica instead of
        #: per-replica attribute access over message objects
        #: (Process.ingest_insert_cols). Differential-testing knob like
        #: ``batch_ingest``: None = auto (on whenever ingestion is
        #: batched), False forces the per-object window path so parity
        #: can be asserted run-for-run.
        self.columnar_ingest = (
            self.batch_ingest
            if columnar_ingest is None
            else bool(columnar_ingest)
        )
        if self.columnar_ingest and not self.batch_ingest:
            raise ValueError("columnar_ingest requires batched ingestion")
        #: Double-buffered settle (redundant verify mode): chunk the
        #: pass's windows into replica groups and enqueue group g+1's
        #: pack+verify launches before fetching group g's mask, so the
        #: device round trip runs under group g's host insert+cascade.
        #: None/True = on (it degrades to the serial path when there is
        #: nothing to overlap), False forces the single-launch schedule.
        self._pipeline_verify = (
            True if pipeline_verify is None else bool(pipeline_verify)
        )
        #: Router hysteresis window N (0 = off): when >= 95% of the last
        #: N routed settles went to the host, the grid's per-settle
        #: poison/scatter upkeep is dropped entirely (the workload is
        #: host-shaped; upkeep was the remaining device-path tax) and the
        #: grid rebuilds — claimed at the current height, fully dirty —
        #: when a fused-sized settle re-engages it.
        self._route_hyst_n = int(route_hysteresis)
        #: Chained height pipelining (ROADMAP item 5, chained-HotStuff
        #: shape): settles dispatch IMMEDIATELY on a speculative verdict
        #: (parseable-and-signed — identical to the device's verdict for
        #: every honest signature) while the actual verification rides
        #: the async device-work queue (hyperdrive_tpu/devsched); a
        #: replica enters height h+1's propose/prevote while height h's
        #: verify launch is still in flight. Commit finalization is
        #: GATED on the future's resolution: _on_commit buffers until
        #: the covering drain confirms the speculation, so no commit is
        #: externally visible on an unverified window — and a
        #: divergence (a forged-but-well-formed signature) raises
        #: SpeculationMismatch instead of rolling back. The device sync
        #: floor (~107 ms on a tunnel-attached chip, BENCH config 4) is
        #: then paid once per pipeline slot (``pipeline_depth`` settles
        #: coalesced into one launch) instead of once per height.
        #: None = off (the sequential trajectory stays the default and
        #: the differential baseline).
        self._pipeline_heights = bool(pipeline_heights or False)
        self._pipeline_depth = int(pipeline_depth)
        if self._pipeline_heights:
            if not burst:
                raise ValueError(
                    "pipeline_heights requires burst mode (settles are "
                    "the unit of pipelining; lock-step replicas "
                    "pipeline through a queue-backed flusher instead)"
                )
            if batch_verifier is None and not sign:
                raise ValueError(
                    "pipeline_heights pipelines the batch_verifier's "
                    "launches; pass one (or sign=True, which installs "
                    "a HostVerifier default)"
                )
            if payload_bytes:
                raise ValueError(
                    "pipeline_heights defers commit finalization past "
                    "the height, but payload reconstruction reads the "
                    "committed height's propose logs at commit time — "
                    "run the payload path sequentially"
                )
        #: The async device-work queue. Externally injectable
        #: (``devsched=``): lock-step chaos runs hand the sim the queue
        #: their replicas' flushers submit through, so the delivery
        #: loop drains it before firing timeouts — virtual time never
        #: jumps over in-flight device work. Pipelined burst runs that
        #: don't pass one get their own.
        self._sched = devsched
        if self._sched is None and self._pipeline_heights:
            from hyperdrive_tpu.devsched import DeviceWorkQueue

            self._sched = DeviceWorkQueue(
                max_depth=self._pipeline_depth,
                obs=self.obs.scoped(-2),
                tracer=self.tracer,
                devtel=self.devtel,
            )
        if self._sched is not None:
            self._sched.on_drain = self._on_sched_drain
            # An externally-built queue adopts this run's observability
            # seams unless its builder already bound some: sched.* events
            # land on the devsched track (-2) and sim.sched.* metrics on
            # the run's tracer, same as a sim-built queue.
            from hyperdrive_tpu.obs.recorder import NULL_BOUND

            if self._sched.obs is NULL_BOUND:
                self._sched.obs = self.obs.scoped(-2)
            if self._sched.tracer is None:
                self._sched.tracer = self.tracer
            if self._sched.devtel is NULL_DEVTEL:
                self._sched.devtel = self.devtel
        #: Per-replica flusher factory ``(i, signatories) -> flusher``
        #: for LOCK-STEP pipelining: queue-backed flushers (devsched
        #: QueueFlusher / DeviceTallyFlusher with ``queue=``) submit
        #: through the injected ``devsched`` queue and the delivery loop
        #: drains it whenever the network quiesces — so every replica's
        #: windows coalesce into one launch per drain. Chaos scenarios
        #: use this seam to keep settles in flight across partitions
        #: and crash-restarts.
        self._flusher_for = flusher_for
        if flusher_for is not None and burst:
            raise ValueError(
                "flusher_for wires per-replica flushers for lock-step "
                "delivery; burst mode settles through the aggregated "
                "harness path (use pipeline_heights there)"
            )
        #: Commit finalizations gated on in-flight speculation:
        #: (replica, height, value, covering future) in commit order,
        #: flushed by _on_sched_drain once the covering futures
        #: resolve. The future carries the launch probe's attribution
        #: (launch_id) so the finalize event links commit -> launch.
        self._gated_commits: list = []
        self._spec_inflight = 0
        #: The most recent speculative-settle future: what a commit
        #: raised while speculation is in flight is gated on.
        self._spec_last_fut = None
        #: Rows accumulated in the open pipeline slot — the row-aware
        #: drain trigger (_settle_speculative) closes the slot just
        #: before a submission would spill into a larger verify bucket,
        #: because a spilled launch costs the BIGGER bucket's full lane
        #: count (4096 lanes ≈ 4× the 1024 launch) for the same work.
        self._spec_rows = 0
        if device_tally and not (burst and self.batch_ingest):
            raise ValueError(
                "device_tally requires burst=True with batched ingestion"
            )
        if batch_verifier is not None and not burst:
            raise ValueError("batch_verifier requires burst=True")
        if burst and verifier_for is not None:
            raise ValueError(
                "burst mode verifies at the network settle layer; pass "
                "batch_verifier instead of per-replica verifier_for"
            )
        self.ring = None
        if sign:
            from hyperdrive_tpu.crypto.keys import KeyRing
            from hyperdrive_tpu.verifier import HostVerifier

            self.ring = KeyRing.deterministic(n, namespace=b"sim-%d" % seed)
            if signatories is not None and signatories != self.ring.signatories:
                raise ValueError(
                    "sign=True derives identities from the keyring; a "
                    "signatories override that differs from the ring's "
                    "public keys would make every signature verification "
                    "fail (replay a signed dump with the same seed instead)"
                )
            self.signatories = self.ring.signatories
            if burst:
                if batch_verifier is None:
                    self.batch_verifier = HostVerifier()
            elif verifier_for is None and overlay is None:
                # Overlay runs verify at the dissemination layer instead
                # (once network-wide, batched per aggregation level);
                # installing per-replica verifiers would re-verify every
                # delivered constituent n times over.
                verifier_for = lambda i: HostVerifier()  # noqa: E731
        else:
            self.signatories = signatories or [
                hashlib.sha256(b"sim-replica-%d-%d" % (seed, i)).digest()
                for i in range(n)
            ]
        #: Dynamic validator sets (epochs.py): pass ``epochs=EpochConfig``
        #: to partition heights into epochs, elect a stake-weighted
        #: committee at every boundary commit, and rotate keys. Identities
        #: are derived per (pool index, key generation); ``_identity[i]``
        #: tracks replica i's CURRENT signatory (rekeys replace it) while
        #: ``self.signatories`` stays the generation-0 pool for record /
        #: replay compatibility. ``_retired`` maps a retired identity to
        #: the first height where votes under it are stale — shared by
        #: reference with every replica (the stale-vote admission check).
        self.epoch_schedule = None
        self.epoch = 0
        self._identity = list(self.signatories)
        self._retired: dict = {}
        self._replica_epoch = [0] * n
        if epochs is not None:
            if burst:
                raise ValueError(
                    "epochs advance on lock-step boundary commits; use "
                    "burst=False (the settle layer rotates per-launch "
                    "table generations instead — see tallyflush)"
                )
            if sign:
                raise ValueError(
                    "epochs derive identities per (index, generation); "
                    "the deterministic keyring has no generation axis — "
                    "run epoch scenarios unsigned"
                )
            if payload_bytes:
                raise ValueError(
                    "payload reconstruction pins k = 2f+1 at "
                    "construction; epoch-rotated thresholds are not "
                    "supported on the payload path"
                )
            from hyperdrive_tpu.epochs import (
                EpochSchedule,
                default_signatory,
            )

            stakes = tuple(epochs.stakes) or (1,) * n
            if len(stakes) != n:
                raise ValueError(
                    f"epochs.stakes has {len(stakes)} entries for "
                    f"{n} replicas"
                )
            ns = b"sim-%d" % seed
            sig_fn = (
                lambda idx, gen, _ns=ns: default_signatory(
                    idx, gen, namespace=_ns
                )
            )
            self.epoch_schedule = EpochSchedule(
                stakes,
                epochs.committee_size or n,
                epochs.epoch_length,
                epochs.seed or seed,
                rekey_per_epoch=epochs.rekey_per_epoch,
                signatory_fn=sig_fn,
            )
            derived = [sig_fn(i, 0) for i in range(n)]
            if signatories is not None and list(signatories) != derived:
                raise ValueError(
                    "epochs derive identities from the schedule's "
                    "signatory function; a signatories override that "
                    "differs would desynchronize elections (replay an "
                    "epoch dump with the same seed instead)"
                )
            self.signatories = derived
            self._identity = list(derived)
            self.record.epochs = (
                int(epochs.epoch_length),
                int(epochs.committee_size or n),
                int(epochs.rekey_per_epoch),
                int(epochs.seed or seed),
                stakes,
            )
        self.record.signatories = list(self.signatories)
        self._max_capacity = max_capacity
        #: Sender -> tie-break index for the shared-lane sort; seeded with
        #: the whitelist so it matches every replica's pre-registered mq
        #: order map (replica.py registers signatories at construction).
        self._order_pos = {s: v for v, s in enumerate(self.signatories)}
        if device_tally:
            from hyperdrive_tpu.ops.votegrid import VoteGrid

            # 4 round slots: covers the happy path plus three retry
            # rounds on device; deeper rounds (rare) fall back to the
            # authoritative host counters. Halving the slot window halves
            # the grid tensors and every launch's transfer.
            # ``tally_mesh``: shard the grid's validator axis over a
            # ('hr', 'val') device mesh — sharded CONSENSUS, not just a
            # sharded kernel: every settle's scatter routes rows by global
            # validator index and the quorum counts psum over the mesh
            # before the rule cascade consumes them.
            self.vote_grid = VoteGrid(
                n, len(self.signatories), r_slots=4, mesh=tally_mesh
            )
            self._grid_height = [-1] * n
            self._grid_dirty: list[set] = [set() for _ in range(n)]
            #: Router hysteresis state: engaged = the grid receives its
            #: per-settle upkeep (scatter bookkeeping, poison marks).
            #: Disengaged (a host-shaped run of settles) skips that
            #: upkeep entirely; _reengage_grid rebuilds before the next
            #: device-routed settle touches the grid.
            self._grid_engaged = True
            self._route_hist: list = []
            self._route_hyst_thresh = -(-95 * self._route_hyst_n // 100)
            self._sender_pos = {
                s: v for v, s in enumerate(self.signatories)
            }
            #: Fused verify+scatter+tally (ONE device round trip per
            #: settle, same as the verify-only baseline): available when
            #: the verifier exposes its traceable kernel and the run
            #: dedups verification (shared verdicts = shared scatter).
            self._fused_ok = (
                self._shared_mode
                and tally_mesh is None  # fused launcher is single-chip
                and dedup_verify
                and hasattr(self.batch_verifier, "fused_inner")
                and hasattr(getattr(self.batch_verifier, "host", None),
                            "pack")
            )
            if self._fused_ok:
                self.vote_grid.attach_fused(self.batch_verifier.fused_inner)
        self.payload_bytes = payload_bytes
        self.dedup_reconstruct = dedup_reconstruct
        self._bundle_cache: dict[Value, bytes] = {}
        self._recon_cache: dict[Value, bytes] = {}
        if payload_bytes:
            from hyperdrive_tpu.ops.shamir import AdaptiveReconstructor

            self.k = 2 * self.f + 1
            #: Commit-path reconstruction routes host/device by block
            #: count. In-harness, commit batches (~16 blocks) sit far
            #: below the provisional crossover AND below calibrate_at, so
            #: every sim commit rides the cached-weight host leg on the
            #: provisional threshold — the measured calibration only
            #: triggers on wide batches (benches, bulk resync). Pass
            #: ``reconstructor=`` to pin a specific backend — e.g.
            #: BatchReconstructor() to force every commit through the
            #: device kernel (the pinned e2e test does).
            self.reconstructor = (
                reconstructor
                if reconstructor is not None
                else AdaptiveReconstructor()
            )
            #: Per-replica height -> reconstructed payload bytes.
            self.reconstructed: list[dict[Height, bytes]] = [
                dict() for _ in range(n)
            ]
        self.commits: list[dict[Height, Value]] = [dict() for _ in range(n)]
        self.alive = [i not in self.offline for i in range(n)]
        # Incremental completion tracking: a replica leaves the pending set
        # when it commits the target height (or dies), so the per-step
        # completion check is O(1) instead of O(n).
        self._pending_replicas = {i for i in range(n) if self.alive[i]}
        self.caught: list[tuple[str, int]] = []

        #: Chaos engine (hyperdrive_tpu/chaos): a seeded FaultPlan
        #: interpreted per delivery in the lock-step loop. Faults draw
        #: from a dedicated RNG stream (not ``self.rng``) so enabling
        #: chaos never perturbs the trajectory machinery existing seeds
        #: pin down. The checkpoint store / capture set exist even
        #: without a plan: replay of a chaos record restores crash
        #: victims from checkpoints it re-derives at the recorded commit
        #: points (identical delivery stream -> identical Process bytes).
        #: Laggard catch-up sweep tuning (PR 4 constants, promoted):
        #: ``catchup_every`` delivery steps between sweeps, ``catchup_lag``
        #: tolerated height lag before a laggard is jumped forward. None =
        #: the module defaults (unchanged behavior); a tighter sweep
        #: bounds rejoin latency at the cost of more resync churn.
        self._catchup_every = (
            _CATCHUP_EVERY if catchup_every is None else int(catchup_every)
        )
        self._catchup_lag = (
            _CATCHUP_LAG if catchup_lag is None else int(catchup_lag)
        )
        if self._catchup_every < 1:
            raise ValueError("catchup_every must be >= 1")
        if self._catchup_lag < 0:
            raise ValueError("catchup_lag must be >= 0")
        #: Open-loop overload injection (load/generator.py LoadProfile):
        #: schedule arrivals are checked against the virtual clock at
        #: every delivered vote, and each due arrival re-delivers that
        #: vote inline as a gossip duplicate — consuming NO steps, NO
        #: virtual time, and NO rng draws, so the real message schedule
        #: (timeouts, chaos ticks, reorder swaps) is bit-identical to
        #: the unloaded run and behavior-neutral shedding keeps commit
        #: digests equal. Injected deliveries ARE recorded, so replay
        #: reproduces the loaded run exactly.
        self._load = None
        self.load_controller = None
        if load is not None:
            if burst:
                raise ValueError(
                    "open-loop load injects per delivery; use lock-step "
                    "mode (burst=False)"
                )
            if delivery_cost <= 0.0:
                raise ValueError(
                    "load arrivals are scheduled on the virtual clock, "
                    "and without delivery pacing a busy network never "
                    "advances it — pass delivery_cost > 0"
                )
            from hyperdrive_tpu.load.generator import LoadRuntime

            self._load = LoadRuntime(load)
        self._chaos = chaos
        self._chaos_monitor = None
        from hyperdrive_tpu.utils.checkpoint import CheckpointStore

        self._ckpt_store = CheckpointStore()
        self._ckpt_capture: set[int] = set()
        if chaos is not None:
            if burst:
                raise ValueError(
                    "chaos faults apply per delivery; use lock-step mode "
                    "(burst=False)"
                )
            chaos.validate(n)
            if chaos.partitions and delivery_cost <= 0.0:
                raise ValueError(
                    "partitions are scheduled on the virtual clock, and "
                    "without delivery pacing a busy network never "
                    "advances it — pass delivery_cost > 0 (the reference "
                    "harness paces at 1 ms)"
                )
            self._chaos_rng = random.Random((seed << 1) ^ 0x43484F53)
            self._chaos_links = {
                (lf.src, lf.dst): lf for lf in chaos.links
            }
            self._chaos_parts = [_PartitionRT(p) for p in chaos.partitions]
            self._chaos_crashes = {c.replica: c for c in chaos.crashes}
            self._chaos_restores: dict[int, int] = {}
            self._ckpt_capture = set(self._chaos_crashes)

        #: Aggregation overlay (overlay/): votes disseminate along a
        #: seeded binomial tree as partial-aggregate frames instead of
        #: all-to-all fan-out. Constituent votes are still delivered and
        #: recorded per message, so dumps replay through the ordinary
        #: record-driven path with no overlay wiring at all.
        self._overlay = None
        self._overlay_coalesce = False
        if overlay is not None:
            if burst:
                raise ValueError(
                    "the overlay disseminates per delivery on the shared "
                    "virtual clock; use lock-step mode (burst=False)"
                )
            if load is not None:
                raise ValueError(
                    "open-loop load injection bypasses the overlay's "
                    "broadcast path; run overload and overlay scenarios "
                    "separately"
                )
            if drop_rate or reorder:
                raise ValueError(
                    "the seeded drop/reorder adversary acts on the raw "
                    "queue and would desynchronize frame bookkeeping; "
                    "use chaos link faults with overlay instead"
                )
            if delivery_cost <= 0.0:
                raise ValueError(
                    "overlay level windows ride the virtual clock, and "
                    "without delivery pacing a busy network never "
                    "advances it — pass delivery_cost > 0"
                )
            if verifier_for is not None:
                raise ValueError(
                    "overlay runs verify once at the dissemination layer "
                    "(replicas get verifier=None); per-replica "
                    "verifier_for would re-verify every constituent"
                )
            if epochs is not None and (epochs.committee_size or n) != n:
                raise ValueError(
                    "overlay coverage masks index validator slots 1:1 "
                    "with replicas; partial committees are not supported "
                    "(committee_size must equal n)"
                )
            overlay.validate(n)

        #: Execution layer (hyperdrive_tpu/exec): pass
        #: ``execution=ExecutionConfig`` to give every committed height
        #: a deterministic transaction block. Each replica runs its own
        #: executor (host reference or device kernel per
        #: ``config.device``) over exactly the heights it commits, and
        #: every commit value stored in ``self.commits`` is extended
        #: with the 32-byte chained state root (raw 32-byte values
        #: still flow to votes, certificates, and epoch anchors — the
        #: extension is the EXTERNAL commit record, which is where the
        #: commit digest reads). With epochs, boundary elections read
        #: the committed ledger's stake column instead of the static
        #: table. ``sign_txs`` blocks submit their signature triples
        #: through the devsched queue (ExecApplyLauncher — the
        #: ``exec.apply`` command kind) when one is wired, coalescing
        #: with vote verifies in the same drain.
        self.executors: list = []
        self._execution = None
        self._exec_source = None
        self._exec_masks: dict = {}
        self._exec_futs: dict = {}
        self._exec_launcher = None
        #: Unique executor objects (dedup_exec aliases one across all
        #: replicas) — the speculate/resolve fan-out target.
        self._exec_unique: list = []
        self._exec_spec_heights: set = set()
        self._exec_speculate = False
        self._exec_fused = False
        if execution is not None:
            if payload_bytes:
                raise ValueError(
                    "execution blocks and MPC payload bundles both "
                    "define the proposed value's content; run one "
                    "content layer at a time"
                )
            if load is not None:
                raise ValueError(
                    "open-loop load re-injects recorded votes with no "
                    "block content; execution-driven traffic is the "
                    "named ROADMAP follow-up — run them separately"
                )
            import dataclasses as _dc

            from hyperdrive_tpu.exec.ledger import BlockSource

            cfg = execution
            if cfg.stake_accounts == 0 and cfg.stake_every > 0:
                if cfg.accounts < n:
                    raise ValueError(
                        f"execution.accounts={cfg.accounts} cannot host "
                        f"{n} validator stake accounts (accounts 0..n-1)"
                    )
                cfg = _dc.replace(cfg, stake_accounts=n)
            self._execution = cfg
            self._exec_source = BlockSource(cfg)
            genesis_stakes = (
                self.epoch_schedule.stakes
                if self.epoch_schedule is not None
                else ()
            )
            if cfg.device:
                from hyperdrive_tpu.exec.device import DeviceLedgerExecutor

                exec_cls = DeviceLedgerExecutor
            else:
                from hyperdrive_tpu.exec.ledger import HostLedgerExecutor

                exec_cls = HostLedgerExecutor
            #: Executor dedup (pipelined default): executors are pure
            #: functions of the committed height sequence, so in a run
            #: where every replica commits every height the n per-
            #: replica ledgers are n identical recomputations — alias
            #: ONE executor across all replicas and a height's block is
            #: applied once per NETWORK instead of once per replica.
            #: Digest-neutral by the same purity (advance_to re-reads
            #: cached roots); off by default outside pipelined runs so
            #: the chaos monitor's cross-replica root agreement check
            #: still compares independently-computed chains.
            if dedup_exec is None:
                dedup_exec = self._pipeline_heights
            self._dedup_exec = bool(dedup_exec)
            count = 1 if self._dedup_exec else n
            for i in range(count):
                self._exec_unique.append(
                    exec_cls(
                        cfg,
                        genesis_stakes,
                        source=self._exec_source,
                        masks=self._exec_masks,
                        obs=self.obs.scoped(i) if observe else _OBS_NULL,
                    )
                )
            self.executors = (
                self._exec_unique * n
                if self._dedup_exec else list(self._exec_unique)
            )
            #: Speculative execution (PR 16 tentpole): apply height h's
            #: block at PROPOSE time under the well-formedness guess
            #: while the fused verify launch is in flight; the exec
            #: future's resolution confirms or rolls back
            #: (exec/ledger.py speculation API), and commit finalize
            #: reads the already-settled root. Default: on exactly when
            #: the run pipelines heights; the lock-step chaos seam opts
            #: in explicitly (injected devsched).
            if exec_speculate is None:
                exec_speculate = self._pipeline_heights
            elif exec_speculate and self._sched is None:
                raise ValueError(
                    "exec_speculate resolves speculation at queue "
                    "drains — wire a devsched (pipeline_heights=True "
                    "or devsched=)"
                )
            self._exec_speculate = bool(exec_speculate)
            if cfg.sign_txs and self._sched is not None:
                #: Fused drain (PR 16 tentpole): submit the block's tx-
                #: signature triples through the SAME memoized launcher
                #: that carries the vote verifies, so one drain cycle
                #: issues ONE coalesced launch for votes + exec rows —
                #: a height costs one launch bill, not two. The two-
                #: kind path (ExecApplyLauncher, its own launch per
                #: drain) remains for lock-step runs and as the
                #: comparison baseline.
                if fused_exec_drain is None:
                    fused_exec_drain = self._pipeline_heights
                self._exec_fused = bool(fused_exec_drain)
                if self._exec_fused:
                    bv = getattr(self, "batch_verifier", None)
                    if bv is None:
                        raise ValueError(
                            "fused_exec_drain coalesces exec rows into "
                            "the vote verify launch — requires a "
                            "batch_verifier (burst mode)"
                        )
                    self._exec_launcher = self._sched.verify_launcher(bv)
                else:
                    from hyperdrive_tpu.exec.ledger import ExecApplyLauncher
                    from hyperdrive_tpu.verifier import HostVerifier

                    self._exec_launcher = ExecApplyLauncher(
                        getattr(self, "batch_verifier", None)
                        or HostVerifier()
                    )
            elif fused_exec_drain:
                raise ValueError(
                    "fused_exec_drain requires sign_txs execution and "
                    "a devsched queue"
                )
            if self.epoch_schedule is not None:
                from hyperdrive_tpu.exec.ledger import HostLedgerExecutor

                if cfg.accounts < n:
                    raise ValueError(
                        f"execution.accounts={cfg.accounts} < n={n}: "
                        "epoch elections read stake accounts 0..n-1"
                    )
                # Stake oracle: one extra host executor bound to the
                # schedule's stake_source hook, so the FIRST path to
                # mint a boundary transition — EpochCertifier
                # .observe_commit fires inside the replica commit,
                # before this sim's commit seam — already elects from
                # committed ledger state. Host class on purpose:
                # root-parity with the device executors is enforced, so
                # the oracle is digest-neutral and jax-free.
                oracle = HostLedgerExecutor(
                    cfg, genesis_stakes,
                    source=self._exec_source, masks=self._exec_masks,
                )
                self._exec_oracle = oracle

                def _stake_source(height, _o=oracle, _n=n):
                    _o.advance_to(height)
                    return _o.election_stakes(_n)

                self.epoch_schedule.stake_source = _stake_source
            self.record.execution = cfg.as_ints()
        elif exec_speculate or fused_exec_drain or dedup_exec:
            raise ValueError(
                "exec_speculate/fused_exec_drain/dedup_exec require "
                "execution="
            )

        byz_prop = byzantine_proposer or {}
        byz_val = byzantine_validator or {}

        self.replicas: list[Replica] = []
        for i in range(n):
            self.replicas.append(
                self._build_replica(
                    i,
                    timeout,
                    timeout_scaling,
                    max_capacity,
                    byz_prop.get(i),
                    byz_val.get(i),
                    verifier_for(i) if verifier_for else None,
                )
            )
        if self.epoch_schedule is not None:
            # One shared retired-identity map: the vote admission check
            # (replica._buffer_vote) is a statement about the NETWORK's
            # key history — "identity X is invalid from height H" — not
            # about the receiving replica's own epoch progress, so every
            # replica reads the same dict by reference and a laggard
            # still finishing the boundary height keeps accepting the
            # old key's votes at heights below H.
            for r in self.replicas:
                r.retired = self._retired
        if overlay is not None:
            from hyperdrive_tpu.overlay import OverlayRuntime

            verifier = None
            ov_sched = None
            ov_bls_keyring = None
            if getattr(overlay, "bls_partials", False):
                ov_bls_keyring = self._bls_committee_keyring()
                # Partial-aggregate merges batch through the device
                # queue when one is wired (devsched=) or the run is
                # signed; otherwise the host fold stands in so the
                # jax-free chaos soak still arms the merge-level check.
                ov_sched = self._sched
            if sign:
                from hyperdrive_tpu.verifier import HostVerifier

                verifier = HostVerifier()
                ov_sched = self._sched
                if ov_sched is None:
                    from hyperdrive_tpu.devsched.queue import DeviceWorkQueue

                    ov_sched = self._sched = DeviceWorkQueue()
            if self.epoch_schedule is not None:
                anchor = self.epoch_schedule.anchor(0)
            else:
                from hyperdrive_tpu.epochs import genesis_anchor

                anchor = genesis_anchor(seed)
            self._overlay_coalesce = overlay.coalesce_ingest
            self._overlay = OverlayRuntime(
                overlay,
                n=n,
                seed=seed,
                anchor=anchor,
                identities=list(self._identity),
                quorum=2 * self.f + 1,
                delivery_cost=delivery_cost,
                enqueue=lambda to, fr: self.queue.append((to, fr)),
                schedule=self.clock.schedule,
                now=lambda: self.clock.now,
                deliver=self._overlay_deliver,
                alive=self.alive,
                order_pos=self._order_pos,
                retired=self._retired,
                verifier=verifier,
                sched=ov_sched,
                obs=self.obs if observe else None,
                registry=self.registry,
                bls_keyring=ov_bls_keyring,
            )
        if self._load is not None and self._load.profile.admission:
            # The backpressure spine rides the loaded run: one shared
            # controller pinned at the profile's floor (pin=False also
            # couples the device-queue depth/drain signals, the bench's
            # escalation mode), one AdmissionGate per replica so dedup
            # memory stays a local property of each ingress.
            from hyperdrive_tpu.load.backpressure import (
                AdmissionGate,
                BackpressureController,
            )

            p = self._load.profile
            ctrl = BackpressureController(
                registry=self.registry,
                obs=self._obs_sim,
                time_fn=lambda: self.clock.now,
            )
            ctrl.floor = p.floor
            if not p.pin and self._sched is not None:
                ctrl.watch(self._sched)
            ctrl.poll()
            self.load_controller = ctrl
            for i, r in enumerate(self.replicas):
                r.admission = AdmissionGate(
                    ctrl,
                    height_fn=r.current_height,
                    registry=self.registry,
                    obs=self.obs.scoped(i),
                )
        if device_tally:
            # The grid answers the hot quorum queries; the host keeps the
            # logs (checkpoints, evidence) but skips the derived per-value
            # tally dicts — declined queries fall back to State.count_*'s
            # log scan.
            for r in self.replicas:
                r.proc.host_counts = False
            # Whitelist identity snapshot: a replica whose procs_allowed
            # was replaced (signatory rotation) can no longer ride the
            # shared scatter (its accept filter diverged from the grid's
            # validator axis), so the fused path checks identity.
            self._allowed_objs = [r.procs_allowed for r in self.replicas]

    # ------------------------------------------------------------- wiring

    def _default_value(self, height: Height, round_: int) -> Value:
        return hashlib.sha256(
            b"value-%d-%d-%d" % (self.seed, height, round_)
        ).digest()

    # ---------------------------------------------------------- execution

    def _exec_value(self, height: Height, round_: int) -> Value:
        """Proposal value in execution mode: commits to the height's
        deterministic tx block. First proposal of a sign_txs height
        also submits the block's signature triples through the device
        queue — fused into the SAME launcher the vote verifies ride
        (one coalesced launch per drain) or as a separate
        ``exec.apply`` command on the two-kind path — resolving the
        admission mask into the shared ``_exec_masks`` dict.

        With ``exec_speculate`` the height is also APPLIED here, under
        the well-formedness guess, while that launch is in flight: the
        future's resolution confirms the guess or rolls the executor
        back and re-applies under the true mask, so by the time the
        covering drain finalizes the gated commit the root is already
        settled (exec/ledger.py speculation API — a rolled-back root
        can never reach a commit record)."""
        if (
            (self._exec_launcher is not None or self._exec_speculate)
            and height not in self._exec_futs
        ):
            self._exec_futs[height] = None
            items = guess = None
            if self._execution.sign_txs:
                blk = self._exec_source.block(height)
                items = self._exec_source.sig_items(blk)
                guess = [
                    s is not None and len(s) == 64 and len(p) == 32
                    for (p, _, s) in items
                ]
            # Heights past the target are proposed (the pipeline runs
            # ahead) but never finalized — don't burn an apply on them.
            if self._exec_speculate and height <= self.target_height:
                self._exec_spec_heights.add(height)
                for ex in self._exec_unique:
                    ex.speculate(height, guess)
            if items is not None and self._exec_launcher is not None:
                if self._exec_fused:
                    # Fused rows count toward the row-aware slot close
                    # (_settle_speculative's would_spill check): exec
                    # rows share the vote launch's verify bucket.
                    self._spec_rows += len(items)
                from hyperdrive_tpu.obs.devtel import EXEC_ORIGIN

                fut = self._sched.submit(
                    self._exec_launcher, items,
                    origin=EXEC_ORIGIN, rows=len(items),
                )
                self._exec_futs[height] = fut

                def _resolve(f, h=height):
                    verdicts = f.result()  # host list, settled future
                    mask = [bool(b) for b in verdicts]
                    self._exec_masks.setdefault(h, mask)
                    if h in self._exec_spec_heights:
                        for ex in self._exec_unique:
                            ex.resolve(h, mask)

                fut.add_done_callback(_resolve)
        return self._exec_source.value(height)

    def _exec_valid(self, height: Height, round_: int, value: Value) -> bool:
        return value == self._exec_source.value(height)

    def _exec_extend(self, i: int, height: Height, value: Value) -> Value:
        """The external commit record in execution mode: the agreed
        value extended with replica ``i``'s chained state root after
        applying every block up to ``height`` (resync gaps included).
        Votes, certificates, and epoch anchors keep the raw 32-byte
        value; the extension is what ``commits``/``commit_digest``
        cover, so two runs agree end-to-end only if their ledgers do."""
        return value + self.executors[i].advance_to(height)

    # -------------------------------------------------- BLS certificates

    def _bls_committee_keyring(self) -> dict:
        """The shared committee keyring (identity -> BlsKeyPair), derived
        deterministically from signatory identities and built once — all
        certifiers alias one dict, exactly like the Ed25519 KeyRing."""
        if self._bls_keyring is None:
            from hyperdrive_tpu.crypto import bls

            ids = (
                self.epoch_schedule.signatories(0)
                if self.epoch_schedule is not None
                else self.signatories
            )
            self._bls_keyring = {
                s: bls.bls_keypair_from_identity(s) for s in ids
            }
        return self._bls_keyring

    def _bls_device_aggregate(self, partials):
        """Certifier aggregation backend on the device bitmask-tree
        kernel. Launch width is the committee size rounded up to a power
        of two, so every commit — whatever its quorum count — reuses the
        same compiled kernel."""
        from hyperdrive_tpu.ops import g1 as g1k

        width = 1
        while width < max(len(self.signatories), 1):
            width *= 2
        return g1k.aggregate_points(partials, width=width)

    # ---------------------------------------------------- payload (config 5)

    def _payload_for_value(self, value: Value) -> bytes:
        """The deterministic payload a value commits to: a SHA-256 stream
        keyed by (seed, value), expanded to ``payload_bytes``."""
        out = bytearray()
        counter = 0
        while len(out) < self.payload_bytes:
            out += hashlib.sha256(
                b"payload-%d-" % self.seed + value + counter.to_bytes(4, "little")
            ).digest()
            counter += 1
        return bytes(out[: self.payload_bytes])

    def _bundle_for_value(self, value: Value) -> bytes:
        """The encoded (2f+1)-of-n share bundle for a value's payload.
        Deterministic (tagged by the value), so every replica — proposer,
        validator, re-proposer — derives the identical bundle; cached
        because splitting is the expensive host-side step."""
        bundle = self._bundle_cache.get(value)
        if bundle is None:
            from hyperdrive_tpu.crypto import shamir as host_shamir

            blocks = host_shamir.split_payload(
                self._payload_for_value(value), self.k, self.n, tag=value
            )
            bundle = host_shamir.encode_share_bundle(blocks)
            # Bounded FIFO: entries are dead once every replica passes the
            # value's height; 64 in-flight values covers any realistic
            # pipeline depth while keeping long soak runs memory-flat
            # (bundles are ~n*blocks*32 bytes each).
            while len(self._bundle_cache) >= 64:
                self._bundle_cache.pop(next(iter(self._bundle_cache)))
            self._bundle_cache[value] = bundle
        return bundle

    def _reconstruct_commit(self, i: int, height: Height, value: Value) -> None:
        """Committer half of the payload path: pull the committed round's
        bundle from replica i's propose log, reconstruct from k shares on
        device, check the payload against the value's commitment."""
        payload = (
            self._recon_cache.get(value) if self.dedup_reconstruct else None
        )
        if payload is None:
            import time as _time

            from hyperdrive_tpu.crypto import shamir as host_shamir

            state = self.replicas[i].proc.state
            # Only a propose that passed validation can be the committed
            # one — an earlier-round tampered propose for the same value
            # sits in the logs marked invalid and must not be picked.
            propose = next(
                (
                    p
                    for rnd, p in state.propose_logs.items()
                    if p.value == value
                    and p.payload
                    and state.propose_is_valid.get(rnd)
                ),
                None,
            )
            if propose is None:  # committed without a payload-carrying propose
                return
            blocks = host_shamir.decode_share_bundle(propose.payload)
            # Any k of the n shares reconstruct; rotate the contributor set
            # by height so different subsets (hence different Lagrange
            # weight sets) are exercised across the run.
            start = height % self.n
            picked = [
                (start + j) % self.n for j in range(self.k)
            ]
            subset = [[shares[x] for x in picked] for shares in blocks]
            # Wall-clock timing: the sim tracer's virtual clock does not
            # advance inside host/device calls, so a span would read 0.
            t0 = _time.perf_counter()
            payload = self.reconstructor.reconstruct_payload_shares(subset)
            self.tracer.observe(
                "sim.reconstruct.latency", _time.perf_counter() - t0
            )
            if payload != self._payload_for_value(value):
                raise AssertionError(
                    f"reconstructed payload mismatch at height {height}"
                )
            if self.dedup_reconstruct:
                while len(self._recon_cache) >= 64:
                    self._recon_cache.pop(next(iter(self._recon_cache)))
                self._recon_cache[value] = payload
        self.reconstructed[i][height] = payload

    def _build_replica(
        self, i, timeout, scaling, capacity, byz_proposer, byz_validator, verifier
    ) -> Replica:
        keypair = self.ring[i] if self.ring is not None else None

        recipients = range(self.n)

        if self._shared_mode:
            def bcast(msg):
                # Shared-superstep mode: ONE queue entry per broadcast
                # (to=-1 means "all live replicas"); the burst loop
                # expands it for accounting/recording and appends the
                # message once to the shared lane.
                if keypair is not None:
                    msg = keypair.sign_message(msg)
                self.queue.append((-1, msg))
        else:
            def bcast(msg):
                # Broadcast to all, including self (reference: 174-208). In
                # signed mode the sender attaches its detached signature here —
                # the outbound edge of the replica, like a real wire stack.
                # zip+repeat builds the n delivery tuples in C.
                if keypair is not None:
                    msg = keypair.sign_message(msg)
                ov = self._overlay
                if ov is not None:
                    # Overlay dissemination: votes enter the aggregation
                    # tree instead of fanning out n-wide. The sender's
                    # own copy still rides the queue (recorded like any
                    # delivery); proposals keep all-to-all fan-out —
                    # there is exactly one per round, no aggregation to
                    # win — verified once network-wide.
                    if type(msg) is not Propose:
                        self.queue.append((i, msg))
                        ov.on_broadcast(i, msg)
                        return
                    if not ov.verify_propose(msg):
                        return
                self.queue.extend(zip(recipients, repeat(msg, self.n)))

        # The owned clock tags each scheduled timeout with its owner index so
        # the delivery queue can route the fired event back to that replica.
        timer = VirtualTimer(
            _OwnedClock(self.clock, i),
            handler=None,
            timeout=timeout,
            timeout_scaling=scaling,
        )

        proposer = MockProposer(fn=byz_proposer or self._default_value)
        validator = (
            MockValidator(fn=byz_validator)
            if byz_validator
            else MockValidator(ok=True)
        )
        if self.payload_bytes:
            proposer = _PayloadProposer(self, byz_proposer or self._default_value)
            if not byz_validator:
                validator = _PayloadValidator(self)
        if self._execution is not None:
            # Execution mode: the proposed value commits to the
            # height's deterministic tx block (round-independent —
            # retries re-propose the same block), and honest validators
            # accept ONLY that value, so a Byzantine proposer cannot
            # commit a valueless block. Proposing also submits the
            # block's signature triples to the device queue (sign_txs),
            # so the admission mask rides the drain its settles share.
            proposer = MockProposer(fn=byz_proposer or self._exec_value)
            if not byz_validator:
                validator = MockValidator(fn=self._exec_valid)

        certifier = None
        if self.certificates_on:
            # Bind the settle layer's batch verifier lazily: its
            # last_transcript is the launch that verified this
            # commit's quorum (b"" on unsigned/ladder paths).
            transcript_source = lambda: getattr(  # noqa: E731
                self.batch_verifier, "last_transcript", b""
            )
            bls_keyring = None
            bls_agg_fn = None
            if self.bls_certificates:
                bls_keyring = self._bls_committee_keyring()
                if str(self.bls_certificates) == "device":
                    bls_agg_fn = self._bls_device_aggregate
            if self.epoch_schedule is not None:
                from hyperdrive_tpu.epochs import EpochCertifier

                certifier = EpochCertifier(
                    self.epoch_schedule,
                    transcript_source=transcript_source,
                    obs=self.obs.scoped(i),
                    bls_keyring=bls_keyring,
                    bls_aggregate_fn=bls_agg_fn,
                )
            else:
                from hyperdrive_tpu.certificates import Certifier

                certifier = Certifier(
                    list(self.signatories),
                    self.f,
                    transcript_source=transcript_source,
                    obs=self.obs.scoped(i),
                    bls_keyring=bls_keyring,
                    bls_aggregate_fn=bls_agg_fn,
                )
            self.certifiers.append(certifier)

        # Epoch mode: consensus runs under epoch 0's elected committee
        # (quorum f = k // 3, round-robin over committee order, committee
        # whitelist), while the replica keeps its own pool identity — a
        # non-member is a follower: it tracks commits but its votes are
        # filtered by everyone's whitelist.
        committee = (
            list(self.epoch_schedule.signatories(0))
            if self.epoch_schedule is not None
            else list(self.signatories)
        )

        return Replica(
            ReplicaOptions(
                max_capacity=capacity,
                tracer=self.tracer,
                external_flush=self.burst,
                batch_ingest=self.batch_ingest,
                obs=self.obs.scoped(i),
            ),
            self.signatories[i],
            committee,
            timer,
            proposer,
            validator,
            CommitterCallback(on_commit=lambda h, v, i=i: self._on_commit(i, h, v)),
            CatcherCallbacks(
                on_double_propose=lambda a, b, i=i: self.caught.append(("double_propose", i)),
                on_double_prevote=lambda a, b, i=i: self.caught.append(("double_prevote", i)),
                on_double_precommit=lambda a, b, i=i: self.caught.append(("double_precommit", i)),
                on_out_of_turn_propose=lambda p, i=i: self.caught.append(("out_of_turn", i)),
            ),
            BroadcasterCallbacks(
                on_propose=bcast, on_prevote=bcast, on_precommit=bcast
            ),
            verifier=verifier,
            flusher=(
                self._flusher_for(i, committee)
                if self._flusher_for is not None
                else None
            ),
            certifier=certifier,
        )

    # -------------------------------------------------------------- running

    def _on_commit(self, i: int, height: Height, value: Value):
        if self._spec_inflight:
            # Pipelined finalize ordering: the commit rests on windows
            # whose verification is still in flight — buffer it (in
            # commit order) until the covering drain confirms the
            # speculation. The replica itself proceeds into the next
            # height (that is the pipeline); only the EXTERNAL commit
            # effects — the recorded commit, completion accounting —
            # wait. Rollback-free: a speculation mismatch raises out of
            # the drain before any gated commit is finalized.
            self._gated_commits.append(
                (i, height, value, self._spec_last_fut)
            )
            if self._obs_sim is not _OBS_NULL:
                self._obs_sim.emit("sched.gated", height, -1, i)
            return (0, None)
        self.commits[i][height] = (
            self._exec_extend(i, height, value) if self.executors else value
        )
        if self.payload_bytes:
            self._reconstruct_commit(i, height, value)
        if self._overlay is not None:
            # Slots below height-1 can no longer change any replica —
            # catch-up resyncs laggards (no-retransmission doctrine).
            self._overlay.note_commit(height)
        if height >= self.target_height:
            self._pending_replicas.discard(i)
        if (
            self.epoch_schedule is not None
            and self.epoch_schedule.is_boundary(height)
        ):
            return self._epoch_advance(i, height, value)
        return (0, None)

    # ------------------------------------------------------------- epochs

    def _epoch_advance(self, i: int, height: Height, value: Value):
        """Replica ``i`` committed an epoch boundary: compute (or fetch)
        the deterministic transition, install the network-level effects
        once (first committer wins — every later committer of the same
        boundary value fetches the identical cached transition; a
        different value trips the schedule's fork check), and hand the
        Process its next-height committee: the returned ``(f,
        scheduler)`` pair flows through the commit seam into
        ``start_round(0)`` of ``height + 1``."""
        sched = self.epoch_schedule
        stakes = None
        if self.executors:
            # Stake-driven election (ROADMAP item 4 tail): the ledger's
            # stake column at the boundary height — this replica's
            # executor already applied the boundary block in
            # _exec_extend — floored so candidacy never collapses
            # (ROBUSTNESS.md "State-root doctrine"). Deterministic
            # across replicas: same committed heights, same blocks,
            # same stakes; the root-equality invariant enforces it.
            stakes = self.executors[i].election_stakes(self.n)
            if (
                self._obs_sim is not _OBS_NULL
                and sched.epoch_of(height) + 1 > sched.latest_epoch
            ):
                self._obs_sim.emit(
                    "exec.stake", height, -1,
                    "e%d min=%d max=%d" % (
                        sched.epoch_of(height) + 1,
                        min(stakes), max(stakes),
                    ),
                )
        tr = sched.transition_at(height, value, stakes=stakes)
        if tr.epoch > self.epoch:
            self._epoch_install(tr, height)
        r = self.replicas[i]
        sigs = list(tr.signatories)
        r.procs_allowed = set(sigs)
        for s in sigs:
            r.mq.order_of(s)
        # The replica's own identity may have rotated in this transition
        # (or an earlier one it is only now catching up to).
        r.proc.whoami = self._identity[i]
        if self._replica_epoch[i] != tr.epoch:
            self._replica_epoch[i] = tr.epoch
            if r.obs is not _OBS_NULL:
                r.obs.emit("epoch.switch", height, -1, tr.epoch)
        if self.certifiers and self.certifiers[i].epoch != tr.epoch:
            # Normally EpochCertifier.observe_commit already rotated
            # itself at this boundary; this catches certifier-less
            # paths through the seam (restored replicas whose certifier
            # missed the boundary rotate in _apply_epoch_state).
            self.certifiers[i].rotate_to(tr.epoch)
        return len(sigs) // 3, RoundRobin(sigs)

    def _epoch_install(self, tr, height: Height) -> None:
        """One-time network-level effects of a transition: rotated
        identities become current (the pool member signs with the new
        key from ``height + 1`` on), retired identities enter the shared
        stale-vote map, and the sim-track obs events mark the switch."""
        new_by_index = {v.index: v.signatory for v in tr.committee}
        for idx, old in zip(tr.rekeyed, tr.retired):
            fresh = new_by_index[idx]
            self._identity[idx] = fresh
            # Partition routing (_chaos_deliver) keys on sender; the
            # rotated identity maps to the same replica slot.
            self._order_pos[fresh] = idx
            self._retired[old] = height + 1
        self.epoch = tr.epoch
        if self._overlay is not None:
            # Churn re-keys tree positions: the next epoch's tree hangs
            # off the boundary-chained anchor and the rotated identity
            # set, so interior-node assignments are unpredictable before
            # the boundary commits.
            self._overlay.rekey(
                self.epoch_schedule.anchor(tr.epoch),
                list(self._identity),
                tr.epoch,
            )
        if self._obs_sim is not _OBS_NULL:
            self._obs_sim.emit(
                "epoch.elect", height, -1,
                "e%d j%d l%d r%d" % (
                    tr.epoch, len(tr.joined), len(tr.left),
                    len(tr.rekeyed),
                ),
            )
            self._obs_sim.emit("epoch.begin", height + 1, -1, tr.epoch)

    def _resync_sigs(self, target: Height) -> tuple:
        """The signatory set a ResetHeight to ``target`` must carry:
        the committee of ``target``'s epoch (clamped to the latest
        elected — the schedule cannot see past the last committed
        boundary), or the static whitelist outside epoch mode."""
        sched = self.epoch_schedule
        if sched is None:
            return tuple(self.signatories)
        e = min(sched.latest_epoch, sched.epoch_of(target))
        return sched.signatories(e)

    def _apply_epoch_state(self, i: int, target: Height) -> None:
        """Epoch effects of a resync/restore jump to ``target`` that the
        ResetHeight itself cannot carry: the replica's own (possibly
        rotated) identity and its certifier's committee rotation. Must
        run BEFORE the ResetHeight is handled — start_round(0) at the
        target may make this replica the proposer, and it must propose
        under its current key."""
        sched = self.epoch_schedule
        if sched is None:
            return
        e = min(sched.latest_epoch, sched.epoch_of(target))
        r = self.replicas[i]
        r.proc.whoami = self._identity[i]
        if self.certifiers and self.certifiers[i].epoch != e:
            self.certifiers[i].rotate_to(e)
        if self._replica_epoch[i] != e:
            self._replica_epoch[i] = e
            if r.obs is not _OBS_NULL:
                r.obs.emit("epoch.switch", target, -1, e)

    def _on_sched_drain(self, resolved: int) -> None:
        """Queue drain hook: every in-flight speculative settle just
        resolved (mismatches raise out of the drain itself), so gated
        commits are confirmed — finalize them in commit order."""
        self._spec_inflight = 0
        self._spec_rows = 0
        self._spec_last_fut = None
        if self._exec_speculate and self._exec_source is not None:
            # The drain just resolved every exec speculation it
            # covered: confirm any still-open exact windows so the
            # gated finalizes below read settled roots, then close the
            # speculation epoch — the block cache may evict the
            # window's columns from here on (rollbacks can no longer
            # replay them).
            for ex in self._exec_unique:
                ex.confirm_to(ex.height)
            self._exec_source.spec_epoch += 1
        if not self._gated_commits:
            return
        gated = self._gated_commits
        self._gated_commits = []
        for i, height, value, fut in gated:
            # Execution rides the finalize edge: the covering drain
            # just resolved the height's exec.apply mask (submitted at
            # proposal time), so the executor can apply the block and
            # extend the commit record with its root.
            if self.executors:
                value = self._exec_extend(i, height, value)
            self.commits[i][height] = value
            if (
                self._obs_sim is not _OBS_NULL
                and fut is not None
                and fut.launch_id is not None
            ):
                # Close the cross-layer loop on the replica's own
                # track: this commit finalized because THAT coalesced
                # launch confirmed its speculation (the Perfetto
                # exporter draws the drain -> commit flow arrow from
                # this event).
                self.obs.emit(
                    "sched.launch.commit", i, height, -1, fut.launch_id
                )
            if height >= self.target_height:
                self._pending_replicas.discard(i)

    def _completed(self) -> bool:
        return not self._pending_replicas

    def metrics_snapshot(self) -> dict:
        """The run's metrics-registry snapshot (obs/metrics.py), with
        the tracer's counters/histograms folded in — the one uniform
        view the obs CLI exports and bench artifacts embed. On the
        virtual clock everything in it is deterministic, so two
        fixed-seed runs snapshot to identical bytes
        (``self.registry.digest()``)."""
        self.registry.absorb_tracer(self.tracer)
        if self._obs_sim is not _OBS_NULL:
            # Flight-recorder health rides the same snapshot: a
            # journal that silently overwrote its oldest events would
            # otherwise present a truncated anatomy as a complete one.
            self.registry.set_gauge("obs.recorder.dropped",
                                    self.obs.dropped)
            self.registry.set_gauge("obs.recorder.capacity",
                                    self.obs.capacity)
            self.registry.set_gauge("obs.recorder.total",
                                    self.obs.total)
        gates = [r.admission for r in self.replicas
                 if getattr(r, "admission", None) is not None]
        if gates:
            # Admission-gate health gauges: how many distinct peers the
            # gates have charged sheds to, and how many signers stand
            # reputation-demoted right now — the metrics plane alerts
            # on the latter (per-peer detail rides the labeled
            # ``admission.shed_by_peer`` / ``admission.verify_failed``
            # counters the gates feed live).
            demoted: set = set()
            peers_shed = 0
            for g in gates:
                peers_shed += len(g.shed_by_peer)
                if g.reputation is not None:
                    demoted |= g.reputation.demoted
            self.registry.set_gauge("admission.shed_peers", peers_shed)
            self.registry.set_gauge(
                "admission.reputation.demoted", len(demoted)
            )
        snap = self.registry.snapshot()
        if self._obs_sim is not _OBS_NULL:
            self._obs_sim.emit(
                "metrics.snapshot", -1, -1,
                len(snap["counters"]) + len(snap["histograms"]),
            )
        return snap

    def run(self, max_steps: int = 2_000_000, start: bool = True) -> SimulationResult:
        """Drive the network to the target height. ``start=False`` resumes
        a network whose replicas are already mid-protocol (the crash-
        restore-rejoin scenario: phase two continues after a revived
        replica was restored from its checkpoint) — replicas are NOT
        (re)started, so nobody re-proposes or re-arms round timers."""
        if start:
            for i, r in enumerate(self.replicas):
                if self.alive[i]:
                    r.start()
        obs = self._obs_sim
        if obs is _OBS_NULL:
            return self._finish(self._run_delivery(max_steps))
        # Observed run: tap every device_fetch for the journal. The
        # observer is a module global (annotations.py), so install/remove
        # brackets the run — nested observed sims are not a thing.
        from hyperdrive_tpu.analysis.annotations import set_fetch_observer

        set_fetch_observer(
            lambda why: obs.emit("fetch.sync", -1, -1, why or None)
        )
        try:
            return self._finish(self._run_delivery(max_steps))
        finally:
            set_fetch_observer(None)

    def _finish(self, result: SimulationResult) -> SimulationResult:
        """Post-run stamping: certificate chain digests (certificates=
        True runs) ride the result for equality checks."""
        if self.certifiers:
            result.cert_digests = [
                c.chain_digest() for c in self.certifiers
            ]
        return result

    def overload_snapshot(self) -> dict:
        """Aggregated overload accounting for a loaded run: injected
        duplicates, network-wide offered/admitted/shed-by-class gate
        counters, and the controller's level/transition count. The soak
        CLI and the overload bench assert against this — notably that
        no shed class outside the admission vocabulary ever appears
        (certificates/proposals never shed)."""
        lr = self._load
        out: dict = {
            "injected": lr.offered if lr is not None else 0,
            #: Vote duplicates injected at un-advanced heights — the
            #: storm fraction the gate MUST shed (a bursty storm landing
            #: only on proposals legitimately sheds nothing).
            "injected_sheddable": lr.sheddable if lr is not None else 0,
            "offered": 0,
            "admitted": 0,
            "shed": {},
            "level": 0,
            "transitions": 0,
        }
        for r in self.replicas:
            gate = r.admission
            if gate is None:
                continue
            snap = gate.snapshot()
            out["offered"] += snap["offered"]
            out["admitted"] += snap["admitted"]
            for cls, v in snap["shed"].items():
                out["shed"][cls] = out["shed"].get(cls, 0) + v
        if self.load_controller is not None:
            out["level"] = self.load_controller.level
            out["transitions"] = self.load_controller.transitions
        return out

    def overlay_snapshot(self) -> dict:
        """The overlay runtime's accounting (frames by kind, verify rows,
        scores/demotions, topology digest) — the overlay bench, the soak
        CLI, and ``obs report --overlay`` all read this shape."""
        if self._overlay is None:
            raise ValueError("overlay_snapshot() on a run without overlay=")
        return self._overlay.snapshot()

    def _overlay_blocked(self, frame, to: int) -> bool:
        """Chaos faults for overlay frames: partitions block on the
        (contributor, receiver) pair exactly as _chaos_deliver blocks
        vote senders; link faults apply their drop rate (duplication and
        delay stay vote-only — frame bookkeeping is idempotent but the
        clock cost of a ghost frame is not)."""
        src = frame.src
        for p in self._chaos_parts:
            if p.engaged and p.blocks(src, to):
                return True
        lf = self._chaos_links.get((src, to))
        if lf is not None and lf.drop and self._chaos_rng.random() < lf.drop:
            return True
        return False

    def _overlay_deliver(self, to: int, votes) -> None:
        """Constituent votes reaching replica ``to`` from one overlay
        frame. Delivered per message and recorded as plain (to, vote)
        tuples — replay is record-driven and never rebuilds the overlay
        — or batched through handle_coalesced for unrecorded
        mega-committee benches (OverlayConfig.coalesce_ingest)."""
        if not self.alive[to] or not votes:
            return
        rec = self.record.messages if self._record_on else _DISCARD
        r = self.replicas[to]
        for v in votes:
            rec.append((to, v))
        if self._overlay_coalesce and len(votes) > 1:
            r.handle_coalesced(votes)
        else:
            for v in votes:
                r.handle(v)
        if to in self._ckpt_capture:
            self._ckpt_store.save(to, r.proc)

    def _run_delivery(self, max_steps: int) -> SimulationResult:
        """The delivery loop behind :meth:`run` (burst or lock-step)."""
        if self.burst:
            return self._run_burst(max_steps)

        steps = 0
        record_messages = self.record.messages if self._record_on else _DISCARD
        sched = self._sched
        while steps < max_steps and not self._completed():
            if self._qhead >= len(self.queue):
                # Resolve in-flight device work (queue-backed flushers)
                # before advancing virtual time: a timeout must not
                # fire over a settle that is still in flight — the
                # drain's cascade may broadcast, refilling the queue.
                if sched is not None and sched.depth and sched.drain():
                    continue
                # Network drained: advance virtual time to the next timeout.
                if self.clock.pending() == 0:
                    if self._chaos_rescue(steps):
                        continue
                    break  # genuine stall — nothing can ever happen again
                if self.clock.pending() > 65536:
                    self._prune_clock()
                    if self.clock.pending() == 0:
                        if self._chaos_rescue(steps):
                            continue
                        break
                event, owner = self.clock.fire_next()
                self.queue.append((owner, event))
                continue

            if self.reorder:
                # Swap a random remaining entry to the head — O(1) and the
                # chosen delivery order is recorded, so replay is exact.
                idx = self.rng.randrange(self._qhead, len(self.queue))
                self.queue[self._qhead], self.queue[idx] = (
                    self.queue[idx],
                    self.queue[self._qhead],
                )
            to, msg = self.queue[self._qhead]
            self._qhead += 1
            if self._qhead > 8192 and self._qhead * 2 > len(self.queue):
                del self.queue[: self._qhead]
                self._qhead = 0
            steps += 1

            ov = self._overlay
            if ov is not None:
                t = type(msg)
                if t is OverlayFrame:
                    if self._chaos is not None:
                        self._chaos_tick(steps)
                        if self._overlay_blocked(msg, to):
                            continue
                    else:
                        self._laggard_sweep(steps)
                    if not self.alive[to]:
                        continue
                    # One delivery_cost per frame regardless of how many
                    # constituent votes its mask carries — THE pricing
                    # that makes commit latency count frames (O(n log n))
                    # instead of votes (O(n^2)).
                    self.clock.now += self.delivery_cost
                    ov.on_frame(to, msg)
                    continue
                if t is OverlayTick:
                    if self._chaos is not None:
                        self._chaos_tick(steps)
                    else:
                        self._laggard_sweep(steps)
                    # Ticks are local timers, not network messages: no
                    # delivery cost, no liveness gate here (the runtime
                    # disarms dead owners itself).
                    ov.on_tick(to, msg)
                    continue

            if self._chaos is not None:
                self._chaos_tick(steps)
                msg = self._chaos_deliver(to, msg)
                if msg is None:
                    continue
            if self.drop_rate and not isinstance(msg, Timeout):
                if self.rng.random() < self.drop_rate:
                    continue
            if self.kill_at_step:
                for victim, at in list(self.kill_at_step.items()):
                    if steps >= at:
                        if self.alive[victim]:
                            self.alive[victim] = False
                            self._pending_replicas.discard(victim)
                        del self.kill_at_step[victim]  # fired — stop rescanning
            if not self.alive[to]:
                continue

            if self.delivery_cost:
                self.clock.now += self.delivery_cost
            record_messages.append((to, msg))
            self.replicas[to].handle(msg)
            if to in self._ckpt_capture:
                # The reference's durability contract, taken literally:
                # "State should be saved after every method call"
                # (process/state.go:18-20). Scheduled crash victims
                # snapshot their Process through the self-validating
                # checkpoint envelope after every handled delivery, so
                # the restore image is the exact mid-protocol state at
                # the last message the process survived.
                self._ckpt_store.save(to, self.replicas[to].proc)

            lr = self._load
            if lr is not None and (
                type(msg) is Prevote
                or type(msg) is Precommit
                or type(msg) is Propose
            ):
                # Open-loop injection point: every schedule arrival due
                # at this virtual instant re-delivers the CURRENT vote
                # to the same replica as a gossip duplicate — inline,
                # after the real delivery, with no step count, no clock
                # advance, and no rng draw, so the unloaded trajectory
                # is untouched. Duplicates are recorded (replay is
                # exact) and checkpointed like any handled delivery.
                k = lr.due(self.clock.now)
                if k:
                    self.registry.count("load.offered", k)
                    obs = self._obs_sim
                    if obs is not _OBS_NULL:
                        obs.emit("load.offered", -1, -1, k)
                        if k >= lr.profile.amp_cap:
                            obs.emit("load.burst", -1, -1, k)
                    r = self.replicas[to]
                    # Vote duplicates at an un-advanced height are the
                    # gate's guaranteed prey (the original just passed
                    # through it, so the dedup key is warm); proposal
                    # duplicates and votes behind the commit edge are
                    # admitted/height-filtered by doctrine.
                    if (
                        type(msg) is not Propose
                        and msg.height >= r.current_height()
                    ):
                        lr.sheddable += k
                    capture = to in self._ckpt_capture
                    for _ in range(k):
                        record_messages.append((to, msg))
                        r.handle(msg)
                        if capture:
                            self._ckpt_store.save(to, r.proc)

        if sched is not None:
            sched.drain()
        return SimulationResult(
            completed=self._completed(),
            steps=steps,
            virtual_time=self.clock.now,
            heights=[r.current_height() for r in self.replicas],
            commits=self.commits,
            record=self.record if self._record_on else None,
            alive=self.alive,
        )

    # ---------------------------------------------------------- burst mode

    def _run_burst(self, max_steps: int) -> SimulationResult:
        """Superstep delivery: buffer every pending message into its
        destination, then settle the whole network through aggregated
        verification. Delivery order within a superstep is recorded, so
        replay is exact; faults/drops/reorder apply per message exactly as
        in lock-step mode."""
        steps = 0
        sched = self._sched
        while steps < max_steps and not self._completed():
            if self.clock.pending() > 65536:
                self._prune_clock()
            if self._qhead >= len(self.queue):
                # Nothing left to deliver: resolve in-flight device
                # work FIRST — a drain can finalize gated commits (and
                # so complete the run) without burning a timeout, and
                # virtual time must never jump over a pipeline slot
                # that still owes its verdict.
                if sched is not None and sched.depth and sched.drain():
                    continue
                if self.clock.pending() == 0:
                    break  # genuine stall
                event, owner = self.clock.fire_next()
                self.queue.append((owner, event))

            # Take the whole pending slice; broadcasts emitted while
            # delivering (timeout dispatches, settle-phase votes) append to
            # the fresh queue and form the NEXT superstep.
            batch = self.queue[self._qhead :]
            self.queue = []
            self._qhead = 0
            if self.reorder:
                self.rng.shuffle(batch)

            # Kills apply at superstep boundaries (not mid-burst): a replica
            # alive for any part of a superstep settles the whole superstep,
            # so every recorded delivery was also dispatched — replay (where
            # kills don't exist) then reproduces the run exactly.
            if self.kill_at_step:
                for victim, at in list(self.kill_at_step.items()):
                    if steps >= at:
                        if self.alive[victim]:
                            self.alive[victim] = False
                            self._pending_replicas.discard(victim)
                        del self.kill_at_step[victim]

            # Group VOTE deliveries per replica (global record order
            # unchanged; within-replica order preserved — vote buffering
            # is state-invisible until settle) so each replica buffers its
            # slice in one handle_burst pass instead of per-message calls.
            # Timeouts and resets are NOT state-invisible — a timeout
            # handler reads the virtual clock (follow-up timers schedule at
            # clock.now) and can broadcast — so they process inline at
            # their delivery clock point, after flushing that replica's
            # accumulated votes to keep its per-message order.
            delivered = 0
            record_messages = (
                self.record.messages if self._record_on else _DISCARD
            )
            if self._shared_mode:
                # Shared-superstep path: a (-1, msg) entry is one broadcast
                # to every live replica. Accounting (steps, clock, record,
                # burst sizes) expands per delivery exactly as the
                # per-delivery loop would — broadcast-major, ascending
                # replica order — but the message itself is appended ONCE
                # to the shared lane; _settle filters/inserts it per
                # replica (the per-sender fast-lane capacity is applied
                # there, height-aware, matching delivery-time accounting).
                alive = self.alive
                live = [i for i in range(self.n) if alive[i]]
                nlive = len(live)
                shared = self._shared
                cost = self.delivery_cost
                tracer = self.tracer
                for to, msg in batch:
                    if to < 0:
                        steps += self.n
                        if not nlive:
                            continue
                        if cost:
                            self.clock.now += cost * nlive
                        record_messages.append_broadcast(msg, live)
                        delivered += nlive
                        t = type(msg)
                        tracer.count(
                            "replica.msg.prevote" if t is Prevote
                            else "replica.msg.precommit" if t is Precommit
                            else "replica.msg.propose",
                            nlive,
                        )
                        shared.append(msg)
                        continue
                    steps += 1
                    if not alive[to]:
                        continue
                    if cost:
                        self.clock.now += cost
                    record_messages.append((to, msg))
                    self.replicas[to].handle(msg)
                    delivered += 1
                    # A targeted event (timeout/reset) may kill nobody but
                    # never changes aliveness; live stays valid.
                if __debug__:
                    # Enforce the invariant the loop above leans on: if a
                    # future scenario hook toggled aliveness from a handler,
                    # broadcasts already expanded against the stale ``live``
                    # list would silently diverge from _settle's windows.
                    # Fail loudly instead.
                    assert live == [
                        i for i in range(self.n) if alive[i]
                    ], "aliveness changed mid-superstep (targeted handler?)"
                if self._record_on:
                    self.record.bursts.append(delivered)
                shared_batch = self._shared
                self._shared = []
                self._settle(shared_batch)
                continue
            per_replica: dict[int, list] = {}
            for to, msg in batch:
                steps += 1
                if self.drop_rate and not isinstance(msg, Timeout):
                    if self.rng.random() < self.drop_rate:
                        continue
                if not self.alive[to]:
                    continue
                if self.delivery_cost:
                    self.clock.now += self.delivery_cost
                record_messages.append((to, msg))
                t = type(msg)
                if t is Propose or t is Prevote or t is Precommit:
                    lst = per_replica.get(to)
                    if lst is None:
                        lst = per_replica[to] = []
                    lst.append(msg)
                else:
                    lst = per_replica.pop(to, None)
                    if lst:
                        self.replicas[to].handle_burst(lst)
                    self.replicas[to].handle(msg)
                delivered += 1
            for to, msgs in per_replica.items():
                self.replicas[to].handle_burst(msgs)
            if self._record_on:
                self.record.bursts.append(delivered)
            self._settle()

        if sched is not None:
            # Shutdown contract: no command may be dropped — the final
            # drain resolves every outstanding speculation (raising on
            # mismatch) and finalizes the gated commits the result
            # below reports.
            sched.drain()
        return SimulationResult(
            completed=self._completed(),
            steps=steps,
            virtual_time=self.clock.now,
            heights=[r.current_height() for r in self.replicas],
            commits=self.commits,
            record=self.record if self._record_on else None,
            alive=self.alive,
        )

    def _prune_clock(self) -> None:
        """Drop timeouts for heights every live replica has already left —
        they would fire as guaranteed no-ops (the Process height-guards
        every on_timeout_*), and keeping them makes deep runs accumulate
        memory linearly in committed heights."""
        alive_heights = [
            r.proc.current_height
            for i, r in enumerate(self.replicas)
            if self.alive[i]
        ]
        if not alive_heights:
            return
        min_h = min(alive_heights)
        self.clock.prune(
            lambda ev: (
                ev.height >= min_h
                if isinstance(ev, (Timeout, OverlayTick))
                else True
            )
        )

    # ------------------------------------------------------------ chaos

    def _chaos_tick(self, steps: int) -> None:
        """Advance the FaultPlan's schedule: engage/heal partitions by
        virtual time, crash and restore replicas by delivery step."""
        now = self.clock.now
        for p in self._chaos_parts:
            if not p.engaged and not p.healed and now >= p.spec.at:
                p.engaged = True
                if self._obs_sim is not _OBS_NULL:
                    self._obs_sim.emit(
                        "chaos.partition", -1, -1, len(p.gid)
                    )
            if p.engaged and now >= p.spec.heal:
                p.engaged = False
                p.healed = True
                self._chaos_heal(p)
        for victim, c in list(self._chaos_crashes.items()):
            if steps >= c.crash_at_step:
                del self._chaos_crashes[victim]
                if not self.alive[victim]:
                    continue
                self.alive[victim] = False
                # Unlike kill_at_step's permanent kills, the victim
                # STAYS in _pending_replicas: a restart is scheduled,
                # so the run must not declare completion while it is
                # down — the 2f+1 survivors keep consensus (and the
                # delivery queue) busy until the restore step arrives.
                self._chaos_restores[victim] = (
                    steps + c.restart_after_steps
                )
                self._note_lifecycle(ScenarioRecord.OP_CRASH, victim, 0)
                if self._obs_sim is not _OBS_NULL:
                    self._obs_sim.emit("chaos.crash", -1, -1, victim)
                m = self._chaos_monitor
                if m is not None:
                    m.note_crash(victim, now)
        for victim, due in list(self._chaos_restores.items()):
            if steps >= due:
                del self._chaos_restores[victim]
                target = self._net_height()
                self._note_lifecycle(
                    ScenarioRecord.OP_RESTORE, victim, target
                )
                self._apply_restore(victim, target)
                if self._obs_sim is not _OBS_NULL:
                    self._obs_sim.emit("chaos.restore", -1, -1, victim)
                m = self._chaos_monitor
                if m is not None:
                    m.note_restore(victim, target)
        self._laggard_sweep(steps)

    def _laggard_sweep(self, steps: int) -> None:
        """Laggard catch-up: a replica that loses a commit quorum falls
        off the network's height wavefront and — no retransmission —
        can never climb back by itself; the heal-time resync only
        rescues the partition case. Sweep periodically for any alive
        replica far enough behind the working height that its stream is
        unrecoverable, and jump it forward — the reference's
        application-driven catch-up (replica/replica.go:222-235) on a
        timer. Swept resyncs are recorded as RESYNC lifecycle ops like
        any other, so replay reproduces them without knowing the
        cadence. Runs from _chaos_tick on chaos runs AND from the
        overlay delivery path on chaos-free overlay runs: the overlay
        prunes slots at the commit floor (its own no-retransmission
        doctrine), so a replica that misses a quorum while the rest of
        the network churns forward is stranded exactly like the
        dropped-vote case — and in lock-step delivery its round timeout
        can never fire while the busy majority keeps the queue full."""
        if steps % self._catchup_every == 0:
            net = self._net_height()
            if net > self._catchup_lag + 1:
                self._chaos_resync(net, lag=self._catchup_lag)

    def _chaos_deliver(self, to: int, msg):
        """Apply the fault plan to one pending delivery. Returns the
        message to deliver, or None when a fault swallowed it (dropped,
        blocked by an active partition, or deferred on the clock).
        Timeouts are local events — never faulted. Delayed/duplicated
        copies ride a :class:`_ChaosEnvelope` so they are never
        re-faulted, though partitions still apply at their eventual
        delivery time."""
        if isinstance(msg, Timeout):
            return msg
        immune = type(msg) is _ChaosEnvelope
        if immune:
            msg = msg.msg
        src = self._order_pos.get(getattr(msg, "sender", None))
        for p in self._chaos_parts:
            if p.engaged and src is not None and p.blocks(src, to):
                return None
        if immune or src is None:
            return msg
        lf = self._chaos_links.get((src, to))
        if lf is None:
            return msg
        rng = self._chaos_rng
        if lf.drop and rng.random() < lf.drop:
            return None
        if lf.duplicate and rng.random() < lf.duplicate:
            self.queue.append((to, _ChaosEnvelope(msg)))
        if lf.delay and rng.random() < lf.delay:
            self.clock.schedule(
                rng.uniform(lf.delay_min, lf.delay_max),
                _ChaosEnvelope(msg),
                to,
            )
            return None
        return msg

    def _chaos_heal(self, p: "_PartitionRT") -> None:
        if self._obs_sim is not _OBS_NULL:
            self._obs_sim.emit("chaos.heal", -1, -1, len(p.gid))
        m = self._chaos_monitor
        if m is not None:
            m.note_heal(self.clock.now)
        if not p.spec.resync_on_heal:
            return
        # The protocol has no retransmission: whatever a replica missed
        # while cut off — committed heights, or just enough dropped
        # votes to lose a quorum — is gone for good, so a laggard can
        # never finish a height the rest of the network abandoned. Jump
        # every alive laggard to the network's current working height
        # (the reference's catch-up path, replica/replica.go:222-235).
        # The reset carries the signatory set, so the ResetHeight
        # handler actively starts round 0 there — arming the propose
        # timeout, or proposing — where a bare reset would leave the
        # replica passive, which deadlocks when the height's proposer
        # is itself a rejoiner. The active join is equivocation-free: a
        # replica below the target height never voted at it.
        self._chaos_resync(self._net_height())

    def _chaos_resync(self, target: Height, lag: int = 0) -> int:
        """Jump every alive replica more than ``lag`` heights below
        ``target`` to an active join of it (see :meth:`_chaos_heal` for
        why active, and why the in-flight height rather than a future
        one: the join keeps the height at full strength, and rejoiners
        catch up through the next round's fresh propose). ``lag > 0``
        (the periodic sweep) tolerates the normal commit wavefront —
        only a replica the network has demonstrably left behind is
        rescued."""
        sigs = self._resync_sigs(target)
        resynced = 0
        for i in range(self.n):
            r = self.replicas[i]
            if self.alive[i] and target - r.proc.current_height > lag:
                self._note_lifecycle(ScenarioRecord.OP_RESYNC, i, target)
                self._apply_epoch_state(i, target)
                r.handle(ResetHeight(height=target, signatories=sigs))
                resynced += 1
        return resynced

    def _chaos_rescue(self, steps: int) -> bool:
        """The delivery queue AND the virtual clock drained mid-run.

        Both chaos timelines are delivery-driven — virtual time advances
        on delivery cost and timeout firings, the step counter on
        deliveries — so a deadlocked network (say the majority group
        one crashed member short of a precommit quorum, every survivor
        parked mid-step with no timeout armed) freezes the FaultPlan's
        remaining schedule forever: the heal or restore that would end
        the deadlock can never come due. Real time does not stop for a
        stalled process. Jump to the next scheduled event — the nearest
        partition boundary in virtual time first, then any frozen
        step-scheduled crash/restore pulled to the present — and
        re-tick; with no schedule left, resync stranded laggards as a
        last resort. Returns True when anything was applied, so the
        delivery loop keeps going instead of declaring a genuine stall.

        Termination: partitions engage and heal monotonically, crashes
        and restores are consumed when applied, and a laggard resync
        lifts a replica to the working height (it cannot re-fire for
        that replica until the network commits further) — every rescue
        strictly consumes schedule or raises a height, so a run that
        cannot make progress still reaches ``False`` and stops.
        Lifecycle ops recorded here carry the current delivered-message
        position like any other, so replay reproduces rescue-applied
        events with no knowledge of the stall."""
        if self._chaos is None:
            return False
        boundary = None
        for p in self._chaos_parts:
            if p.healed:
                continue
            b = p.spec.heal if p.engaged else p.spec.at
            if boundary is None or b < boundary:
                boundary = b
        if boundary is not None:
            if boundary > self.clock.now:
                self.clock.now = boundary
            self._chaos_tick(steps)
            return True
        if self._chaos_crashes:
            victim = min(
                self._chaos_crashes,
                key=lambda v: self._chaos_crashes[v].crash_at_step,
            )
            c = self._chaos_crashes[victim]
            if c.crash_at_step > steps:
                self._chaos_crashes[victim] = replace(
                    c, crash_at_step=steps
                )
            self._chaos_tick(steps)
            return True
        if self._chaos_restores:
            victim = min(
                self._chaos_restores, key=self._chaos_restores.get
            )
            if self._chaos_restores[victim] > steps:
                self._chaos_restores[victim] = steps
            self._chaos_tick(steps)
            return True
        return self._chaos_resync(self._net_height()) > 0

    def _net_height(self) -> Height:
        """The network's next height: one past the best commit any
        replica has recorded — the resync target for rejoiners."""
        best = 0
        for c in self.commits:
            if c:
                m = max(c)
                if m > best:
                    best = m
        return best + 1

    def _apply_restore(self, victim: int, net_height: Height) -> None:
        """The revive path, shared by the live chaos engine and replay:
        restore the Process from the victim's latest checkpoint (None =
        crashed before its first commit -> genesis state), then rejoin.

        Two cases, keyed on whether the network committed past the
        checkpoint while the victim was down (``net_height`` is the
        network's current working height at restore time):

        - It did not (the victim's height is still live — possibly the
          network is even stalled waiting for its vote): resume in
          place. :meth:`Process.resume` re-arms the current step's
          timeout and broadcasts nothing, so the checkpoint's restored
          locked/valid values steer the victim's next votes and it
          cannot equivocate against its pre-crash self.
        - It did: the victim's finished heights will never be re-sent,
          so it actively joins the network's in-flight height instead
          (a signatory-carrying ResetHeight, exactly the heal-resync
          path — see :meth:`_chaos_resync`). Safe for the same reason:
          a victim restored below ``net_height`` never voted there.

        Both branches are pure functions of the restored Process state
        and ``net_height``, which replay reproduces exactly (identical
        delivery stream -> identical checkpoints and commits), so the
        recorded RESTORE op only needs to carry ``net_height``."""
        r = self.replicas[victim]
        r.restore(self._ckpt_store.latest(victim))
        self.alive[victim] = True
        if net_height > r.proc.current_height:
            self._apply_epoch_state(victim, net_height)
            r.handle(
                ResetHeight(
                    height=net_height,
                    signatories=self._resync_sigs(net_height),
                )
            )
        else:
            # The checkpoint's whoami may predate a rotation that
            # happened while the victim was down (epoch mode).
            self._apply_epoch_state(victim, r.proc.current_height)
            r.proc.resume()
        if not any(
            h >= self.target_height for h in self.commits[victim]
        ):
            self._pending_replicas.add(victim)

    def _note_lifecycle(self, kind: int, replica: int, aux: int) -> None:
        if self._record_on:
            self.record.lifecycle.append(
                (kind, len(self.record.messages), replica, aux)
            )

    def _replay_lifecycle(self, op: tuple[int, int, int, int]) -> None:
        kind, _, replica, aux = op
        if kind == ScenarioRecord.OP_CRASH:
            self.alive[replica] = False
        elif kind == ScenarioRecord.OP_RESTORE:
            self._apply_restore(replica, aux)
        else:  # OP_RESYNC
            self._apply_epoch_state(replica, aux)
            self.replicas[replica].handle(
                ResetHeight(
                    height=aux, signatories=self._resync_sigs(aux)
                )
            )

    def _settle(self, shared: "list | None" = None) -> None:
        """Drain every live replica's window, verify ALL windows in one
        aggregated ``batch_verifier`` launch, dispatch the survivors; repeat
        until the network is quiescent — the flush-until-quiescent contract
        (reference: replica/replica.go:251-264) lifted to the superstep.

        ``shared`` is the shared-superstep broadcast lane (one entry per
        broadcast; every live replica receives the same sequence). The
        first pass sorts it once, verifies it once, and hands every
        lockstep replica the SAME window list; later passes fall back to
        per-replica drains for whatever the cascade made newly eligible.
        """
        while True:
            shared_window = None
            if shared:
                shared_window, windows = self._shared_windows(shared)
                shared = None
            else:
                shared = None
                windows = []
                for i, r in enumerate(self.replicas):
                    if not self.alive[i]:
                        continue
                    w = r.drain_pending()
                    if w:
                        windows.append((i, w))
            if not windows:
                return
            obs = self._obs_sim
            if obs is not _OBS_NULL:
                obs.emit("settle.pass", -1, -1, len(windows))
            if self._pipeline_heights:
                self._settle_speculative(windows, shared_window)
                continue
            if (
                shared_window is not None
                and self.device_tally
                and self._fused_ok
                and len(shared_window)
                <= self.batch_verifier.host.buckets[-1]
                and all(w is shared_window for _, w in windows)
                and all(
                    self.replicas[i].procs_allowed is self._allowed_objs[i]
                    for i, _ in windows
                )
            ):
                if len(shared_window) < self._fused_min_window:
                    # Sub-crossover settle: the host finishes verify +
                    # cascade before one device round trip would return.
                    # Handle it fully on host and poison the grid for the
                    # affected heights (its counts would be missing these
                    # votes).
                    self._route_settle_to_host(windows, shared_window)
                    continue
                self._reengage_grid()
                if self._dispatch_fused(shared_window, windows):
                    self._note_route(False)
                    continue
                # Vote-free window (the propose settle): verification is
                # still needed, but there is nothing to scatter or tally —
                # skip the grid entirely (reset defers to the height's
                # first vote-bearing settle) and cascade on host fallback,
                # whose logs are near-empty this early in the height.
                keeps = self._verify_windows(windows, shared_window)
                self._dispatch_windows(windows, keeps, shared_window)
                continue
            if self.device_tally and self._fused_min_window and not (
                # A single window never holds the same object twice, so
                # any window at/above the floor proves uniq >= floor
                # without building the id-set — the common (big-settle)
                # case stays O(#windows).
                max(len(w) for _, w in windows) >= self._fused_min_window
            ):
                # UNIQUE broadcasts, not per-receiver deliveries: the
                # crossover floor is calibrated in unique signatures (the
                # host verify cost under dedup), and the shared-lane
                # branch compares the same unit (len(shared_window)).
                # Duplicate-counted totals would stop the route engaging
                # once n receivers alone exceeded the floor — the exact
                # pathology this branch removes, one doubling up.
                uniq = len({id(m) for _, w in windows for m in w})
                if uniq < self._fused_min_window:
                    # Sub-crossover settle on the per-delivery / straggler
                    # path (adversarial reorder collapses windows to 1-2
                    # messages — BENCH.md config 8): the host finishes
                    # verify + cascade before one device round trip would
                    # return, so verification is forced to host too (the
                    # shared-lane router's rule) and the grid slots these
                    # windows' votes would have filled are poisoned.
                    # Without this, every tiny settle paid an
                    # update_and_tally launch the fused-path router could
                    # never see (measured 8.8x the host leg's wall in the
                    # adversarial regime). Under hysteresis disengagement
                    # the poison upkeep itself is skipped — the grid is
                    # already marked down for rebuild, and the per-window
                    # touched-slot scans were the remaining device-path
                    # tax on a host-shaped workload.
                    if self._grid_engaged:
                        for i, w in windows:
                            touched = self._touched_slots(w)
                            if touched:
                                self._poison_grid(i, touched)
                    else:
                        self.tracer.count("sim.settle.grid_upkeep_skipped")
                    self._note_route(True)
                    self.tracer.observe("sim.settle.host_routed", uniq)
                    keeps = self._verify_windows(
                        windows, shared_window, force_host=True
                    )
                    self._dispatch_windows(windows, keeps, shared_window)
                    continue
            if (
                self._pipeline_verify
                and not self.device_tally
                and not self.dedup_verify
                and self.batch_verifier is not None
                and len(windows) > 1
            ):
                self._settle_pipelined(windows, shared_window)
                continue
            keeps = self._verify_windows(windows, shared_window)
            if self.device_tally:
                self._reengage_grid()
                self._dispatch_tallied(windows, keeps, shared_window)
                self._note_route(False)
            else:
                self._dispatch_windows(windows, keeps, shared_window)

    def _order_key(self, sender) -> int:
        """The sim-level sender tie-break index: whitelist order for
        signatories (identical to every replica's pre-registered mq order),
        first-seen registration after that."""
        o = self._order_pos.get(sender)
        if o is None:
            o = self._order_pos[sender] = len(self._order_pos)
        return o

    def _shared_windows(self, shared: list):
        """Turn the superstep's shared broadcast lane into per-replica
        windows. One global sort by the drain contract's key — ascending
        (height, round), senders tie-broken by registration order, arrival
        FIFO within ties (sort stability). Lockstep replicas (backlog-free,
        window entirely at their height — the overwhelmingly common case)
        share the sorted list itself; stragglers get a per-replica split:
        current-height rows merge with their drained backlog, future rows
        buffer into their mq exactly as delivery-time filtering would."""
        okey = self._order_key
        # Per-sender fast-lane capacity, height-aware, in ARRIVAL order —
        # exactly the per-delivery path's lane accounting (only messages at
        # a replica's current height consume its budget; future-height
        # messages ride to the mq, whose own capacity applies there). A
        # sender can only exceed the cap when the superstep holds more than
        # ``cap`` broadcasts total, so the common case pays one length
        # check. Capped-out rows are resolved lazily per distinct replica
        # height (lockstep replicas share one computation).
        cap = self._max_capacity
        dropped_for: dict = {}
        if len(shared) > cap:
            arrival = list(shared)

            def dropped_at(cur) -> set:
                d = dropped_for.get(cur)
                if d is None:
                    d = dropped_for[cur] = set()
                    counts: dict = {}
                    for m in arrival:
                        if m.height == cur:
                            c = counts.get(m.sender, 0)
                            if c >= cap:
                                d.add(id(m))
                            else:
                                counts[m.sender] = c + 1
                return d
        else:
            def dropped_at(cur) -> set:
                return ()

        shared.sort(key=lambda m: (m.height, m.round, okey(m.sender)))
        hmin = shared[0].height
        hmax = shared[-1].height
        windows: list[tuple[int, list]] = []
        shared_capped: dict = {}  # cur -> capped shared list (lockstep case)
        for i, r in enumerate(self.replicas):
            if not self.alive[i]:
                continue
            cur = r.proc.current_height
            plain = not r._lane and not r.mq.has_eligible(cur)
            if plain and hmin == hmax == cur:
                if len(shared) <= cap:
                    windows.append((i, shared))
                    continue
                w = shared_capped.get(cur)
                if w is None:
                    d = dropped_at(cur)
                    # When the per-sender cap drops nothing (n senders,
                    # few messages each — every network above
                    # max_capacity validators), the capped list IS the
                    # shared list: reuse it, preserving the identity the
                    # fused settle's eligibility check reads. A copy here
                    # silently demoted every >1000-validator lockstep
                    # settle to the two-launch path.
                    w = shared_capped[cur] = (
                        shared if not d
                        else [m for m in shared if id(m) not in d]
                    )
                windows.append((i, w))
                continue
            d = dropped_at(cur)
            cur_rows: list = []
            for m in shared:
                h = m.height
                if h == cur:
                    if id(m) not in d:
                        cur_rows.append(m)
                elif h > cur:
                    t = type(m)
                    if t is Prevote:
                        r.mq.insert_prevote(m)
                    elif t is Precommit:
                        r.mq.insert_precommit(m)
                    else:
                        r.mq.insert_propose(m)
            w = merge_drain(r.drain_pending(), cur_rows, okey)
            if w:
                windows.append((i, w))
        return shared, windows

    def _route_settle_to_host(self, windows, shared_window) -> None:
        """Handle one sub-crossover settle fully on host: aggregated host
        verification, plain window dispatch (host-counter cascade), and
        grid poisoning — the device grid is now missing this settle's
        votes for the affected heights, so exactly the (plane, round)
        slots this window's votes would have occupied are marked dirty
        until the height advances (TallyView declines dirty rounds and
        the cascade falls back to its host counters, which are always
        complete; untouched rounds stay live on the grid). A vote-free
        window poisons nothing — there is nothing the grid could miss
        (mirroring _dispatch_fused's vote-free skip). While hysteresis
        has the grid disengaged the poison upkeep is skipped wholesale:
        the rebuild on re-engage claims every slot dirty anyway."""
        if self._grid_engaged:
            touched = self._touched_slots(shared_window)
            if touched:
                for i, _ in windows:
                    self._poison_grid(i, touched)
        else:
            self.tracer.count("sim.settle.grid_upkeep_skipped")
        self._note_route(True)
        self.tracer.observe("sim.settle.host_routed", len(shared_window))
        keeps = self._verify_windows(windows, shared_window, force_host=True)
        self._dispatch_windows(windows, keeps, shared_window)

    def _note_route(self, host_routed: bool) -> None:
        """Feed the router hysteresis: one observation per routed settle.
        A full window of >= 95% host routes disengages grid upkeep; the
        history only governs disengagement (re-engagement is size-driven,
        see :meth:`_reengage_grid`), so a disengaged router records
        nothing."""
        n = self._route_hyst_n
        if not n or not self._fused_min_window or not self._grid_engaged:
            return
        hist = self._route_hist
        hist.append(host_routed)
        if len(hist) > n:
            del hist[0]
        elif len(hist) < n:
            return
        if sum(hist) >= self._route_hyst_thresh:
            self._grid_engaged = False
            hist.clear()
            self.tracer.count("sim.settle.grid_disengaged")

    def _reengage_grid(self) -> None:
        """Rebuild the grid bookkeeping before a device-routed settle
        touches a disengaged grid. The rebuild claims each live replica's
        CURRENT height with every slot dirty: votes host-routed while
        disengaged never scattered, so no device count for this height
        can be trusted (TallyView declines dirty slots and the cascade
        reads its host fallback); the next height's reset starts the grid
        clean, and upkeep resumes immediately."""
        if self._grid_engaged:
            return
        all_slots = self.vote_grid.all_slots()
        for i, r in enumerate(self.replicas):
            if not self.alive[i]:
                continue
            self._grid_height[i] = r.proc.current_height
            self._grid_dirty[i] = set(all_slots)
        self._grid_engaged = True
        self._route_hist.clear()
        self.tracer.count("sim.settle.grid_reengaged")

    def _dispatch_windows(self, windows, keeps, shared_window) -> None:
        """Plain (host-cascade) dispatch of a settle pass's windows,
        riding the columnar fast path for every window that IS the shared
        lockstep list — one WindowColumns extraction serves all of them.
        Stragglers (per-replica merged windows) keep the object path."""
        cols = None
        for (i, w), keep in zip(windows, keeps):
            if self.columnar_ingest and w is shared_window:
                if cols is None:
                    cols = WindowColumns.from_messages(shared_window)
                self.replicas[i].dispatch_window_cols(cols, keep)
            else:
                self.replicas[i].dispatch_window(w, keep)

    def _settle_pipelined(self, windows, shared_window) -> None:
        """Double-buffered redundant settle: verify+dispatch with the
        windows chunked into replica groups, group g+1's pack+verify
        launches enqueued BEFORE group g's mask is fetched. The device
        round trip (the ~100 ms tunnel sync floor of BENCH.md config 8)
        then runs underneath group g's host insert+cascade instead of
        serializing ahead of it.

        Shared lockstep windows pack once for the whole pass:
        ``verify_signatures_begin(items, repeats=len(group))`` re-launches
        the packed device arrays per receiver copy (every copy is real
        device verification; no lane is re-packed or re-shipped — the
        wire layer's pack reuse across buffered windows). Verifiers
        without an async entry point degrade to per-group synchronous
        verification — same verdicts, no overlap.

        Only the redundant (non-dedup) path chunks: dedup'd verification
        is one launch of unique lanes by construction, and the fused
        device-tally settle is a single kernel either way.
        """
        begin = getattr(self.batch_verifier, "verify_signatures_begin",
                        None)
        from hyperdrive_tpu.ops.bucketing import launch_target

        buckets = getattr(
            getattr(self.batch_verifier, "host", None), "buckets", None
        )
        # Group so one launch carries about one verify bucket of lanes:
        # finer groups pay launch overhead, coarser ones leave nothing
        # in flight to hide behind the cascade.
        target = launch_target(buckets)
        per_win = max(len(w) for _, w in windows)
        gsize = max(1, target // max(per_win, 1))
        groups = [
            windows[a : a + gsize] for a in range(0, len(windows), gsize)
        ]
        shared_items = None
        cols = None
        total_items = 0

        def launch(group):
            nonlocal shared_items, total_items
            if shared_window is not None and all(
                w is shared_window for _, w in group
            ):
                if shared_items is None:
                    shared_items = [
                        (m.sender, m.digest(), m.signature)
                        for m in shared_window
                    ]
                total_items += len(shared_items) * len(group)
                if begin is not None:
                    return begin(shared_items, repeats=len(group)), None
                return self._verify_items(shared_items * len(group)), None
            items = []
            bounds = []
            for _, w in group:
                start = len(items)
                items.extend(
                    (m.sender, m.digest(), m.signature) for m in w
                )
                bounds.append((start, len(items)))
            total_items += len(items)
            if begin is not None:
                return begin(items), bounds
            return self._verify_items(items), bounds

        inflight = launch(groups[0])
        for gi, group in enumerate(groups):
            nxt = launch(groups[gi + 1]) if gi + 1 < len(groups) else None
            handle, bounds = inflight
            mask = handle.mask() if hasattr(handle, "mask") else handle
            mask = (
                mask.tolist() if hasattr(mask, "tolist") else list(mask)
            )
            if bounds is None:
                m = len(mask) // len(group)
                keeps = [
                    mask[j * m : (j + 1) * m] for j in range(len(group))
                ]
            else:
                keeps = [mask[a:b] for a, b in bounds]
            for (i, w), keep in zip(group, keeps):
                if self.columnar_ingest and w is shared_window:
                    if cols is None:
                        cols = WindowColumns.from_messages(shared_window)
                    self.replicas[i].dispatch_window_cols(cols, keep)
                else:
                    self.replicas[i].dispatch_window(w, keep)
            inflight = nxt
        self.tracer.count("sim.settle.pipelined")
        self.tracer.observe("sim.verify.launch", total_items)
        if self._obs_sim is not _OBS_NULL:
            self._obs_sim.emit("verify.launch", -1, -1, total_items)

    def _settle_speculative(self, windows, shared_window) -> None:
        """Chained height pipelining (ROADMAP item 5): dispatch this
        settle pass NOW on a speculative verdict and push the actual
        verification onto the async device-work queue
        (:mod:`hyperdrive_tpu.devsched`) — replicas enter the next
        height's propose/prevote while this height's launch is still in
        flight, and the queue coalesces up to ``pipeline_depth``
        settles into ONE launch, so the device sync floor is paid once
        per pipeline slot instead of once per settle.

        The speculation rule accepts exactly the parseable-and-signed
        rows (32-byte sender, 64-byte signature) — for every honest
        signature the device's verdict is identical, so honest
        trajectories are superstep-identical to the sequential run:
        commit-digest parity holds by construction (asserted by
        tests/test_devsched.py and the CI parity smoke). A forged-but-
        well-formed row that speculation admitted raises
        :class:`SpeculationMismatch` at drain, BEFORE any commit gated
        on it finalizes (_on_commit buffers while futures are in
        flight) — loud failure here, because a vote verdict has no
        snapshot to unwind to. The EXECUTION pipeline's speculative
        apply (``exec_speculate`` -> exec/ledger.py) is the contrast:
        ledger state DOES snapshot, so its mismatches roll back
        bit-identically and re-apply under the true mask instead of
        aborting.

        Dispatch runs on the host counters (the crossover router's
        sub-floor path), so under ``device_tally`` the grid gets the
        same poison upkeep as a host-routed settle.
        """
        from hyperdrive_tpu.devsched import SpeculationMismatch

        if self.device_tally:
            if self._grid_engaged:
                shared_touched = None
                for i, w in windows:
                    if w is shared_window:
                        if shared_touched is None:
                            shared_touched = self._touched_slots(w)
                        touched = shared_touched
                    else:
                        touched = self._touched_slots(w)
                    if touched:
                        self._poison_grid(i, touched)
            else:
                self.tracer.count("sim.settle.grid_upkeep_skipped")
            self._note_route(True)

        # Speculative verdicts for the unique-broadcast batch (identity
        # dedup — the same keying as _verify_windows' dedup path).
        index: dict[int, int] = {}
        items: list = []
        expect: list = []

        def spec(m) -> bool:
            sig = m.signature
            return (
                sig is not None and len(sig) == 64 and len(m.sender) == 32
            )

        keeps: list = []
        shared_keep = None
        if shared_window is not None:
            for m in shared_window:
                index[id(m)] = len(items)
                items.append((m.sender, m.digest(), m.signature))
                expect.append(spec(m))
            shared_keep = list(expect)
        for _, w in windows:
            if w is shared_window:
                keeps.append(shared_keep)
                continue
            row = []
            for m in w:
                j = index.get(id(m))
                if j is None:
                    j = index[id(m)] = len(items)
                    items.append((m.sender, m.digest(), m.signature))
                    expect.append(spec(m))
                row.append(expect[j])
            keeps.append(row)

        if items:
            if self._obs_sim is not _OBS_NULL:
                self._obs_sim.emit(
                    "settle.speculative", -1, -1, len(items)
                )
            self.tracer.observe("sim.verify.speculated", len(items))
            sched = self._sched
            # Row-aware slot close: if adding this settle would push the
            # coalesced batch into a LARGER verify bucket, drain first —
            # padded launches cost by bucket, not by fill, so crossing
            # the boundary quadruples the launch for the same rows.
            # Verifiers without a bucket ladder (HostVerifier) fall back
            # to the queue's command-count depth bound.
            buckets = getattr(
                getattr(self.batch_verifier, "host", None), "buckets", None
            )
            from hyperdrive_tpu.ops.bucketing import would_spill

            if would_spill(self._spec_rows, len(items), buckets):
                sched.drain()
            # Account BEFORE submit: submit may auto-drain at max_depth
            # (resolving this very command and zeroing the counters via
            # _on_sched_drain) — incrementing afterwards would record a
            # phantom in-flight settle that gates commits forever.
            self._spec_rows += len(items)
            self._spec_inflight += 1
            fut = sched.submit(
                sched.verify_launcher(self.batch_verifier), items,
                origin=-1, rows=len(items),
            )
            self._spec_last_fut = fut
            expected = expect

            def confirm(f, expected=expected, items=items):
                # hdlint: disable=HD001 resolved futures hold a host list; the one device fetch happened inside the coalesced launch
                actual = [bool(b) for b in f.result()]
                if actual != expected:
                    bad = next(
                        j
                        for j in range(len(actual))
                        if actual[j] != expected[j]
                    )
                    raise SpeculationMismatch(
                        "pipelined settle diverged from the device "
                        f"verdict at lane {bad}/{len(actual)} "
                        f"(sender {items[bad][0].hex()[:16]}…, "
                        f"speculated {expected[bad]}, actual "
                        f"{actual[bad]}): a forged-but-well-formed "
                        "signature was speculatively dispatched; rerun "
                        "with pipeline_heights=False"
                    )

            fut.add_done_callback(confirm)

        # Dispatch immediately — THIS is the pipeline: the network
        # progresses on the speculative verdicts while the launch is in
        # flight. Commits raised by the cascade gate in _on_commit.
        self._dispatch_windows(windows, keeps, shared_window)

        # A gated commit that would complete the run must not wait for
        # the depth trigger — drain now so run() terminates promptly
        # instead of speculating extra heights past the target.
        if self._gated_commits and any(
            h >= self.target_height and i in self._pending_replicas
            for i, h, _, _ in self._gated_commits
        ):
            self._sched.drain()

    def _touched_slots(self, msgs) -> set:
        """The (plane, round) grid slots a window's votes would fill —
        what a host-routed settle must poison. Out-of-window rounds never
        scatter and TallyView never serves them, so they need no poison."""
        grid_r = self.vote_grid.R
        touched = set()
        for m in msgs:
            t = type(m)
            if t is Prevote or t is Precommit:
                rnd = m.round
                if 0 <= rnd < grid_r:
                    touched.add((1 if t is Precommit else 0, rnd))
        return touched

    def _poison_grid(self, i, touched) -> None:
        """Mark replica ``i``'s grid slots missing after a host-routed
        settle (``touched``: non-empty set of (plane, round) pairs its
        window's votes would have filled)."""
        h = self.replicas[i].current_height()
        if self._grid_height[i] != h:
            # The grid was never reset for this height: its rows are
            # stale for EVERY round, and claiming the height here (so
            # the next fused settle does not reset-and-clear the poison)
            # means no zeroing will happen — poison the whole height.
            self._grid_height[i] = h
            self._grid_dirty[i] = set(self.vote_grid.all_slots())
        else:
            # Grid live at this height: only the slots this window's
            # votes would have filled are now missing; untouched rounds'
            # counts remain complete and servable.
            self._grid_dirty[i].update(touched)

    def _verify_windows(self, windows, shared_window=None,
                        force_host: bool = False) -> list:
        """One aggregated verification launch for a settle pass's windows;
        returns the per-window keep masks (None entries = no verifier)."""
        keeps: list = [None] * len(windows)
        if self.batch_verifier is None:
            return keeps
        if self.dedup_verify:
            # One lane per distinct broadcast. The same message OBJECT
            # fans out to all receivers, so identity keying suffices —
            # no 128-byte tuple keys, no per-delivery digest calls.
            # (Two equal-content distinct objects would just occupy two
            # lanes; verification is deterministic so verdicts agree.
            # The window lists keep every object alive, so ids are
            # stable for the duration of the pass.) Windows that ARE the
            # shared list skip the keying entirely: their keep mask is the
            # mask's shared prefix, one list reused by every replica.
            index: dict[int, int] = {}
            items: list = []
            shared_len = 0
            if shared_window is not None:
                items = [
                    (m.sender, m.digest(), m.signature) for m in shared_window
                ]
                shared_len = len(items)
                for j, m in enumerate(shared_window):
                    index[id(m)] = j
            slots: list = []
            for _, w in windows:
                if w is shared_window:
                    slots.append(None)
                    continue
                row = []
                for m in w:
                    j = index.get(id(m))
                    if j is None:
                        j = index[id(m)] = len(items)
                        items.append((m.sender, m.digest(), m.signature))
                    row.append(j)
                slots.append(row)
            self.tracer.observe("sim.verify.launch", len(items))
            if self._obs_sim is not _OBS_NULL:
                self._obs_sim.emit("verify.launch", -1, -1, len(items))
            mask = self._verify_items(items, force_host)
            shared_keep = (
                mask if shared_len == len(mask) else mask[:shared_len]
            )
            for wi, row in enumerate(slots):
                keeps[wi] = shared_keep if row is None else [mask[j] for j in row]
        else:
            items = []
            bounds = []
            for _, w in windows:
                start = len(items)
                items.extend((m.sender, m.digest(), m.signature) for m in w)
                bounds.append((start, len(items)))
            self.tracer.observe("sim.verify.launch", len(items))
            if self._obs_sim is not _OBS_NULL:
                self._obs_sim.emit("verify.launch", -1, -1, len(items))
            mask = self._verify_items(items, force_host)
            keeps = [mask[a:b] for a, b in bounds]
        return keeps

    def _verify_items(self, items, force_host: bool = False) -> list:
        """One aggregated signature verification, routed: sub-64-item
        windows go to the bit-identical host verifier (a device sync for
        two signatures costs three orders of magnitude more than
        computing them), everything else to the installed backend.
        ``force_host``: a settle the crossover router already decided to
        keep on host (fused_min_window) verifies there too — unless the
        small_window_host knob disabled the host verifier, in which case
        the device backend still answers (correctly, just slower)."""
        if self._small_win_host is not None and (
            force_host or len(items) < 64
        ):
            mask = self._small_win_host.verify_signatures(items)
        else:
            mask = self.batch_verifier.verify_signatures(items)
        return mask.tolist() if hasattr(mask, "tolist") else list(mask)

    def _dispatch_tallied(self, windows, keeps, shared_window=None) -> None:
        """Device-tally dispatch: insert every window, scatter the accepted
        votes into the persistent device vote grid, run ONE fused tally
        launch for the whole network, then run each replica's rule cascade
        against its :class:`TallyView` slice.

        This is the north-star data path: quorum counts come from masked
        reductions over device-resident vote tensors (fused behind the
        verification mask — only verified survivors are scattered), and the
        Process consumes the resulting counts instead of rescanning its
        logs. The counts are *exactly equal* to the host counters whenever
        the view answers (enforced by CheckedTallyView in tests), so runs,
        records, and replays are bit-identical to host-tally mode.
        """
        from hyperdrive_tpu.batch import MessageBlock
        from hyperdrive_tpu.ops.tally import pack_value
        from hyperdrive_tpu.ops.votegrid import TallyView

        grid = self.vote_grid
        R = grid.R
        n = self.n

        # Reset planes for replicas whose height moved since their grid
        # rows were last valid. Inserts never change heights, so computing
        # resets before the insert phase is safe — and necessary, so the
        # insert hooks' dirty marks for the NEW height survive.
        reset = np.zeros(n, dtype=bool)
        for i, _ in windows:
            h = self.replicas[i].current_height()
            if self._grid_height[i] != h:
                reset[i] = True
                self._grid_height[i] = h
                self._grid_dirty[i] = set()

        accepted: list = []  # (replica, plane, msg) in scatter order

        def make_hook(i, dirty):
            def on_accepted(msg, is_precommit):
                rnd = msg.round
                plane = 1 if is_precommit else 0
                if rnd < 0 or rnd >= R:
                    # Outside the slot window: TallyView declines these
                    # rounds, so not scattering them is safe. The lower
                    # bound matters — vote inserts (unlike propose) accept
                    # negative rounds, and a slot of -1 would alias into a
                    # neighboring lane's slot R-1 as a phantom vote.
                    return
                v = self._sender_pos.get(msg.sender)
                if v is None:
                    # Whitelisted sender outside the grid's validator axis
                    # (post-rotation): this round's device count would
                    # undercount, so poison it for the height.
                    dirty.add((plane, rnd))
                    return
                accepted.append((i, plane, msg))
            return on_accepted

        plans = []
        cols = None
        for (i, w), keep in zip(windows, keeps):
            hook = make_hook(i, self._grid_dirty[i])
            if self.columnar_ingest and w is shared_window:
                if cols is None:
                    cols = WindowColumns.from_messages(shared_window)
                plans.append((
                    i,
                    self.replicas[i].ingest_insert_window_cols(
                        cols, keep, hook
                    ),
                ))
            else:
                plans.append(
                    (i, self.replicas[i].ingest_insert_window(w, keep, hook))
                )

        # Launch inputs. Matching targets are each replica's proposal value
        # per round slot (post-insert, so this window's proposals count);
        # the L28 lane carries the cross-round (valid_round, current
        # proposal value) query.
        targets = np.zeros((n, R, 8), dtype=np.int32)
        tvalid = np.zeros((n, R), dtype=bool)
        l28_slot = np.full(n, -1, dtype=np.int32)
        l28_target = np.zeros((n, 8), dtype=np.int32)
        fs = np.zeros(n, dtype=np.int32)
        tmaps: dict[int, dict] = {}
        l28_vals: dict[int, bytes] = {}
        for i, _ in windows:
            proc = self.replicas[i].proc
            st = proc.state
            fs[i] = proc.f
            tmap: dict = {}
            for rnd, p in st.propose_logs.items():
                if 0 <= rnd < R:
                    targets[i, rnd] = pack_value(p.value)
                    tvalid[i, rnd] = True
                    tmap[rnd] = p.value
            tmaps[i] = tmap
            cur = st.propose_logs.get(st.current_round)
            if cur is not None and 0 <= cur.valid_round < R:
                l28_slot[i] = cur.valid_round
                l28_target[i] = pack_value(cur.value)
                l28_vals[i] = cur.value

        if accepted:
            block = MessageBlock.from_messages([m for _, _, m in accepted])
            words = np.ascontiguousarray(block.rows["value"]).view("<i4")
            idx = np.array(
                [
                    (i, plane, m.round, self._sender_pos[m.sender])
                    for i, plane, m in accepted
                ],
                dtype=np.int32,
            )
        else:
            words = np.zeros((0, 8), dtype=np.int32)
            idx = np.zeros((0, 4), dtype=np.int32)
        counts = grid.update_and_tally(
            idx, words, reset, targets, tvalid, l28_slot, l28_target, fs
        )
        self.tracer.observe("sim.tally.launch", len(idx))
        if self._obs_sim is not _OBS_NULL:
            self._obs_sim.emit("tally.launch", -1, -1, len(idx))

        for i, plan in plans:
            view = TallyView(
                i,
                self._grid_height[i],
                counts,
                R,
                tmaps[i],
                int(l28_slot[i]),
                l28_vals.get(i, b""),
                dirty=self._grid_dirty[i],
            )
            if self._tally_check is not None:
                view = self._tally_check(view, self.replicas[i].proc)
            self.replicas[i].ingest_cascade_window(plan, view)

    def _dispatch_fused(self, shared, windows) -> None:
        """Device-tally settle in ONE launch: Ed25519-verify the shared
        window, scatter the verified votes into every lockstep replica's
        grid (presence-guarded, shared rows), tally — then the host inserts
        with the mask and cascades against the counts. The settle pays a
        single blocking sync (the mask), exactly what the verify-only
        baseline pays; the packed counts ride the same async copy and are
        ready by cascade time.

        Eligibility (checked by the caller): shared-superstep lockstep
        (every window IS the shared list), dedup verification, single-chip
        grid, un-rotated whitelists, window within one verify bucket.
        """
        from hyperdrive_tpu.ops.tally import pack_value
        from hyperdrive_tpu.ops.votegrid import TallyView

        grid = self.vote_grid
        R = grid.R
        n = self.n
        h = shared[0].height

        if not any(
            type(m) is Prevote or type(m) is Precommit for m in shared
        ):
            # No votes anywhere in the window: nothing can scatter and no
            # count can have changed — tell the caller to run the
            # verify-only settle. Grid heights stay stale on purpose; the
            # next vote-bearing settle's reset brings them forward.
            return False

        items = [(m.sender, m.digest(), m.signature) for m in shared]
        self.tracer.observe("sim.verify.launch", len(items))
        if self._obs_sim is not _OBS_NULL:
            self._obs_sim.emit("verify.launch", h, -1, len(items))
        arrays, prevalid, nitems = self.batch_verifier.host.pack(items)

        # The dense one-superstep update image: one lane per (plane,
        # round, validator), first parseable claimant wins (the host's
        # first-wins insert rule); conflicting claims poison the round for
        # this height (host counters stay authoritative there). Proposes
        # aren't scattered — they feed the target prediction below.
        upd_lane = np.full((2, R, grid.V), -1, dtype=np.int32)
        upd_vals = np.zeros((2, R, grid.V, 8), dtype=np.int32)
        k = 0
        hazard: set = set()
        win_props: dict = {}
        sender_pos = self._sender_pos
        for j, m in enumerate(shared):
            t = type(m)
            if t is Prevote:
                plane = 0
            elif t is Precommit:
                plane = 1
            else:
                rnd = m.round
                if 0 <= rnd < R:
                    win_props[rnd] = None if rnd in win_props else m
                continue
            rnd = m.round
            if rnd < 0 or rnd >= R:
                continue
            v = sender_pos.get(m.sender)
            if v is None:
                # Whitelisted sender outside the grid's validator axis
                # (post-rotation): the device count would diverge.
                hazard.add((plane, rnd))
                continue
            if upd_lane[plane, rnd, v] >= 0:
                hazard.add((plane, rnd))
                continue
            if not prevalid[j]:
                # Unparseable signature: the host rejects it
                # deterministically; the lane stays unclaimed for a later
                # well-formed row.
                continue
            upd_lane[plane, rnd, v] = j
            upd_vals[plane, rnd, v] = np.frombuffer(m.value, dtype="<i4")
            k += 1
        self.tracer.observe("sim.tally.launch", k)
        if self._obs_sim is not _OBS_NULL:
            self._obs_sim.emit("tally.launch", h, -1, k)

        # Per-replica launch metadata. Targets come from PRE-insert propose
        # logs plus this window's (schedule-checked) proposes — identical
        # to the post-insert logs except when a window propose fails
        # verification, in which case the host log stays empty at that
        # round and the cascade never queries it.
        reset = np.zeros(n, dtype=bool)
        participate = np.zeros(n, dtype=bool)
        targets = np.zeros((n, R, 8), dtype=np.int32)
        tvalid = np.zeros((n, R), dtype=bool)
        l28_slot = np.full(n, -1, dtype=np.int32)
        l28_target = np.zeros((n, 8), dtype=np.int32)
        fs = np.zeros(n, dtype=np.int32)
        tmaps: dict[int, dict] = {}
        l28_vals: dict[int, bytes] = {}
        # Lockstep replicas almost always share identical propose logs
        # (the very same broadcast objects), so the target row is computed
        # once and fanned out; any replica that diverges (or any window
        # with in-flight proposes, whose schedule check is per-replica
        # scheduler state) gets the full per-replica build.
        ref = None  # (logs, round, trow, tvalid_row, tmap, l28s, l28t, l28v)
        for i, _ in windows:
            participate[i] = True
            if self._grid_height[i] != h:
                reset[i] = True
                self._grid_height[i] = h
                self._grid_dirty[i] = set()
            dirty = self._grid_dirty[i]
            dirty.update(hazard)
            proc = self.replicas[i].proc
            st = proc.state
            fs[i] = proc.f
            if (
                ref is not None
                and not win_props
                and st.propose_logs == ref[0]
                and st.current_round == ref[1]
            ):
                targets[i] = ref[2]
                tvalid[i] = ref[3]
                tmaps[i] = ref[4]
                l28_slot[i] = ref[5]
                l28_target[i] = ref[6]
                if ref[7] is not None:
                    l28_vals[i] = ref[7]
                continue
            tmap: dict = {}
            for rnd, p in st.propose_logs.items():
                if 0 <= rnd < R:
                    targets[i, rnd] = pack_value(p.value)
                    tvalid[i, rnd] = True
                    tmap[rnd] = p.value
            scheduler = proc.scheduler
            for rnd, wp in win_props.items():
                if rnd in tmap:
                    continue  # logged propose wins; window dup is rejected
                if wp is None:
                    # Conflicting window proposes: the accepted one depends
                    # on per-row verdicts; don't predict.
                    dirty.add((0, rnd))
                    dirty.add((1, rnd))
                    continue
                if scheduler is not None and scheduler.schedule(
                    h, rnd
                ) != wp.sender:
                    continue  # out-of-turn: host rejects it
                targets[i, rnd] = pack_value(wp.value)
                tvalid[i, rnd] = True
                tmap[rnd] = wp.value
            tmaps[i] = tmap
            cur = st.propose_logs.get(st.current_round)
            if cur is not None and 0 <= cur.valid_round < R:
                l28_slot[i] = cur.valid_round
                l28_target[i] = pack_value(cur.value)
                l28_vals[i] = cur.value
            if ref is None and not win_props:
                ref = (
                    st.propose_logs, st.current_round, targets[i].copy(),
                    tvalid[i].copy(), tmap, int(l28_slot[i]),
                    l28_target[i].copy(), l28_vals.get(i),
                )

        fused_out = grid.fused_update_and_tally(
            arrays, upd_lane, upd_vals, reset, participate,
            targets, tvalid, l28_slot, l28_target, fs,
        )
        # The settle's ONE blocking sync: mask and packed counts arrive in
        # the same transfer. Wall-clock it (histogram value in seconds):
        # the insert + cascade below are data-dependent on this mask and
        # these counts, so this sync is the settle's un-hideable device
        # cost — the telemetry BENCH.md's settle-pipeline analysis reads.
        t_sync = time.perf_counter()
        keep = (fused_out.mask() & prevalid)[:nitems].tolist()
        counts = fused_out.counts()
        self.tracer.observe(
            "sim.fused.sync.latency", time.perf_counter() - t_sync
        )

        t_host = time.perf_counter()
        plans = []
        # Every window IS the shared list (fused eligibility), so one
        # columnar extraction serves all n lockstep inserts.
        cols = (
            WindowColumns.from_messages(shared)
            if self.columnar_ingest else None
        )
        for i, w in windows:
            if cols is not None and w is shared:
                plans.append((
                    i,
                    self.replicas[i].ingest_insert_window_cols(cols, keep),
                ))
            else:
                plans.append(
                    (i, self.replicas[i].ingest_insert_window(w, keep))
                )
        for i, plan in plans:
            view = TallyView(
                i,
                h,
                counts,
                R,
                tmaps[i],
                int(l28_slot[i]),
                l28_vals.get(i, b""),
                dirty=self._grid_dirty[i],
            )
            if self._tally_check is not None:
                view = self._tally_check(view, self.replicas[i].proc)
            self.replicas[i].ingest_cascade_window(plan, view)
        # Host insert+cascade wall time, the companion to
        # sim.fused.sync.latency: when the cascade leg is shorter than the
        # sync leg, even a perfectly overlapped pipeline cannot hide the
        # sync behind host work — the settle is RTT-bound.
        self.tracer.observe(
            "sim.fused.cascade.latency", time.perf_counter() - t_host
        )
        return True

    # -------------------------------------------------------------- replay

    @classmethod
    def replay(cls, record: ScenarioRecord, **kwargs) -> SimulationResult:
        """Re-deliver a recorded interleaving message-for-message
        (reference: replay(), replica_test.go:325-370).

        The replayed network uses the recorded signatories and delivers only
        the recorded messages — no clock, no adversary — so a dumped failure
        reproduces exactly. Burst-mode records (non-empty ``bursts``) replay
        superstep-for-superstep: each recorded burst is buffered then
        settled, reproducing the original window boundaries (pass
        ``batch_verifier=`` to re-verify during replay).
        """
        if record.epochs is not None and "epochs" not in kwargs:
            from hyperdrive_tpu.epochs import EpochConfig

            epoch_length, committee, rekey, eseed, stakes = record.epochs
            kwargs["epochs"] = EpochConfig(
                epoch_length=epoch_length,
                committee_size=committee,
                rekey_per_epoch=rekey,
                seed=eseed,
                stakes=stakes,
            )
        if record.execution is not None and "execution" not in kwargs:
            from hyperdrive_tpu.exec import ExecutionConfig

            # The replayed ledger trajectory is a pure function of the
            # config ints plus the committed heights the message stream
            # reproduces — device and host executors are root-identical
            # (the parity smoke), so the recorded backend choice only
            # affects replay speed, never its commits.
            kwargs["execution"] = ExecutionConfig.from_ints(
                record.execution
            )
        sim = cls(
            n=record.n,
            target_height=record.target_height,
            seed=record.seed,
            signatories=list(record.signatories),
            burst=bool(record.bursts),
            batch_ingest=record.batch_ingest if record.bursts else None,
            **kwargs,
        )
        for i, r in enumerate(sim.replicas):
            if sim.alive[i]:
                r.start()
        sim.queue.clear()
        sim._qhead = 0
        steps = 0
        # Chaos records carry a lifecycle trailer (crash/restore/resync
        # ops pinned to delivery positions). Replay re-derives each
        # victim's checkpoint at its recorded commits — an identical
        # delivery stream produces identical Process bytes — so the
        # restore image never needs to be stored in the dump.
        ops = record.lifecycle
        optr = 0
        sim._ckpt_capture = {
            rep
            for kind, _, rep, _ in ops
            if kind == ScenarioRecord.OP_RESTORE
        }
        if record.bursts:
            idx = 0
            for b in record.bursts:
                for to, msg in record.messages[idx : idx + b]:
                    if sim.alive[to]:
                        sim.replicas[to].handle(msg)
                        steps += 1
                idx += b
                sim.queue.clear()  # replay ignores re-broadcasts
                sim._qhead = 0
                sim._settle()
        else:
            for j, (to, msg) in enumerate(record.messages):
                while optr < len(ops) and ops[optr][1] <= j:
                    sim._replay_lifecycle(ops[optr])
                    optr += 1
                if not sim.alive[to]:
                    continue
                sim.replicas[to].handle(msg)
                if to in sim._ckpt_capture:
                    sim._ckpt_store.save(to, sim.replicas[to].proc)
                steps += 1
            while optr < len(ops):
                sim._replay_lifecycle(ops[optr])
                optr += 1
        return SimulationResult(
            completed=sim._completed(),
            steps=steps,
            virtual_time=sim.clock.now,
            heights=[r.current_height() for r in sim.replicas],
            commits=sim.commits,
            record=record,
            alive=sim.alive,
            sim=sim,
        )


class _PayloadProposer:
    """Proposer for the MPC payload path: values as usual, with the
    value-keyed share bundle attached via the Process's duck-typed
    ``payload_for_value`` hook (so re-proposed ValidValues re-derive their
    original bundle)."""

    __slots__ = ("_sim", "_fn")

    def __init__(self, sim: "Simulation", fn):
        self._sim = sim
        self._fn = fn

    def propose(self, height, round_):
        return self._fn(height, round_)

    def payload_for_value(self, value):
        return self._sim._bundle_for_value(value)


class _PayloadValidator:
    """Accepts a proposal iff its payload is exactly the share bundle its
    value commits to (the Process's duck-typed ``valid_propose`` hook)."""

    __slots__ = ("_sim",)

    def __init__(self, sim: "Simulation"):
        self._sim = sim

    def valid(self, height, round_, value):
        return True

    def valid_propose(self, propose):
        return propose.payload == self._sim._bundle_for_value(propose.value)


#: Laggard catch-up sweep cadence (delivery steps) and tolerated height
#: lag. A height takes a few dozen deliveries, so 256 steps bounds how
#: long a dropped-off replica free-falls; lag 2 tolerates the normal
#: commit wavefront (replicas briefly straddle adjacent heights) while
#: anything further behind has provably missed messages it will never
#: see again.
_CATCHUP_EVERY = 256
_CATCHUP_LAG = 2


class _ChaosEnvelope:
    """Marks a delayed or duplicated delivery that already passed the
    link-fault stage, so re-delivery applies partitions only (a delayed
    frame must not be re-delayed or re-duplicated forever). Not a
    Timeout, so a pending delayed delivery survives ``_prune_clock``."""

    __slots__ = ("msg",)

    def __init__(self, msg):
        self.msg = msg


class _PartitionRT:
    """Runtime state for one scheduled :class:`~hyperdrive_tpu.chaos.plan.
    Partition`: group membership resolved to a dict, plus the
    engaged/healed latch (each partition fires exactly once)."""

    __slots__ = ("spec", "engaged", "healed", "gid")

    def __init__(self, spec):
        self.spec = spec
        self.engaged = False
        self.healed = False
        self.gid: dict[int, int] = {}
        for g, members in enumerate(spec.groups):
            for m in members:
                self.gid[m] = g

    def blocks(self, a: int, b: int) -> bool:
        # Replicas absent from every listed group share the implicit
        # remainder group (-1).
        return self.gid.get(a, -1) != self.gid.get(b, -1)


class _OwnedClock:
    """Wraps the shared clock so fired timeouts carry their owner index."""

    __slots__ = ("_clock", "_owner")

    def __init__(self, clock: VirtualClock, owner: int):
        self._clock = clock
        self._owner = owner

    def schedule(self, delay: float, event, handler) -> None:
        self._clock.schedule(delay, event, self._owner)
