"""The Tendermint-BFT consensus automaton (host-side).

Capability parity with the reference's core state machine
(``process/process.go``): a deterministic finite automaton that consumes
Propose/Prevote/Precommit messages and fires the paper's rules L11-L65
("The latest gossip on BFT consensus", arXiv:1807.04938), with the same
seven dependency-injection seams (Timer, Scheduler, Proposer, Validator,
Broadcaster, Committer, Catcher), the same once-flag discipline, the same
deferred retry cascade, and the same equivocation catching.

Design stance (SURVEY.md §7.1): this control flow is branchy, per-message,
and operates on tiny state — it runs on the host. The TPU handles the
batchable work in front of it: signature verification and quorum tallies
over vote tensors (:mod:`hyperdrive_tpu.ops`). A Process assumes messages
reaching it are already authenticated (reference: process/process.go:95-98);
authentication is performed by the Verifier in the replica's drain loop.

A Process is **not** safe for concurrent use: all methods must be called
from a single thread (reference: process/process.go:100-101).

Rule map (paper label -> method):

- L10/L11  start / start_round
- L22      _try_prevote_upon_propose
- L28      _try_prevote_upon_sufficient_prevotes
- L34      _try_timeout_prevote_upon_sufficient_prevotes
- L36      _try_precommit_upon_sufficient_prevotes
- L44      _try_precommit_nil_upon_sufficient_prevotes
- L47      _try_timeout_precommit_upon_sufficient_precommits
- L49      _try_commit_upon_sufficient_precommits
- L55      _try_skip_to_future_round
- L57/61/65  on_timeout_propose / on_timeout_prevote / on_timeout_precommit
"""

from __future__ import annotations

from typing import Optional, Protocol, runtime_checkable

from hyperdrive_tpu.analysis.annotations import wire_codec
from hyperdrive_tpu.codec import Reader, Writer
from hyperdrive_tpu.messages import Precommit, Prevote, Propose
from hyperdrive_tpu.obs.recorder import NULL_BOUND
from hyperdrive_tpu.state import OnceFlag, State
from hyperdrive_tpu.types import (
    INVALID_ROUND,
    NIL_VALUE,
    Height,
    Round,
    Signatory,
    Step,
    Value,
)

__all__ = [
    "Timer",
    "Scheduler",
    "Proposer",
    "Validator",
    "Broadcaster",
    "Committer",
    "Catcher",
    "Process",
]


# --------------------------------------------------------------------- seams
# The seven DI interfaces (reference: process/process.go:17-88). All are
# structural protocols; any object with the right methods satisfies them.


@runtime_checkable
class Timer(Protocol):
    """Schedules timeout events proportional to the round."""

    def timeout_propose(self, height: Height, round: Round) -> None: ...
    def timeout_prevote(self, height: Height, round: Round) -> None: ...
    def timeout_precommit(self, height: Height, round: Round) -> None: ...


@runtime_checkable
class Scheduler(Protocol):
    """Elects the proposer for a (height, round); must be deterministic and
    derived only from values on which consensus has already been reached."""

    def schedule(self, height: Height, round: Round) -> Signatory: ...


@runtime_checkable
class Proposer(Protocol):
    """Produces new values to propose; must never return two different
    values for the same (height, round)."""

    def propose(self, height: Height, round: Round) -> Value: ...


@runtime_checkable
class Validator(Protocol):
    """Application-defined validity predicate; correct processes are not
    required to agree on validity."""

    def valid(self, height: Height, round: Round, value: Value) -> bool: ...


@runtime_checkable
class Broadcaster(Protocol):
    """Fans a message out to all processes, including the sender. Eventual
    delivery is assumed; ordering is not."""

    def broadcast_propose(self, propose: Propose) -> None: ...
    def broadcast_prevote(self, prevote: Prevote) -> None: ...
    def broadcast_precommit(self, precommit: Precommit) -> None: ...


@runtime_checkable
class Committer(Protocol):
    """Receives committed values; may rotate the validator set by returning
    a non-zero f and/or a new Scheduler (epoch change)."""

    def commit(
        self, height: Height, value: Value
    ) -> tuple[int, Optional[Scheduler]]: ...


@runtime_checkable
class Catcher(Protocol):
    """Receives evidence of Byzantine behaviour (equivocation, out-of-turn
    proposing). Catching is best-effort: messages dropped by height filters
    are never inspected."""

    def catch_double_propose(self, new: Propose, existing: Propose) -> None: ...
    def catch_double_prevote(self, new: Prevote, existing: Prevote) -> None: ...
    def catch_double_precommit(self, new: Precommit, existing: Precommit) -> None: ...
    def catch_out_of_turn_propose(self, propose: Propose) -> None: ...


# -------------------------------------------------------------------- process


@wire_codec(tag="process.checkpoint", max_bytes=1 << 28)
class Process:
    """The consensus automaton for one replica identity.

    All injected collaborators except ``committer`` are optional (nil-safe),
    matching the reference's null-check discipline
    (process/process.go:324-348); the committer is demanded at commit time
    exactly as the reference demands it (process/process.go:703).
    """

    __slots__ = (
        "whoami",
        "f",
        "timer",
        "scheduler",
        "proposer",
        "validator",
        "broadcaster",
        "committer",
        "certifier",
        "catcher",
        "state",
        "_tally_source",
        "host_counts",
        "obs",
    )

    def __init__(
        self,
        whoami: Signatory,
        f: int,
        timer: Optional[Timer] = None,
        scheduler: Optional[Scheduler] = None,
        proposer: Optional[Proposer] = None,
        validator: Optional[Validator] = None,
        broadcaster: Optional[Broadcaster] = None,
        committer: Optional[Committer] = None,
        certifier=None,
        catcher: Optional[Catcher] = None,
        height: Height | None = None,
        state: State | None = None,
        obs=None,
    ):
        self.whoami = whoami
        self.f = int(f)
        self.timer = timer
        self.scheduler = scheduler
        self.proposer = proposer
        self.validator = validator
        self.broadcaster = broadcaster
        self.committer = committer
        #: Optional certificates.Certifier: when set, every L49 commit
        #: also mints a constant-size QuorumCertificate from the 2f+1
        #: precommit signers (the O(1) commit proof downstream consumers
        #: carry instead of the vote set).
        self.certifier = certifier
        self.catcher = catcher
        if state is not None:
            self.state = state
        elif height is not None:
            self.state = State.default_with_height(height)
        else:
            self.state = State()
        #: Device tally counts installed for the duration of one
        #: ingest_cascade call (see the _prevotes_for family); None means
        #: every threshold check reads the host counters.
        self._tally_source = None
        #: When False (device-tally deployments), batched ingestion skips
        #: maintaining the derived per-value tally dicts — the vote grid
        #: answers the hot quorum queries, and declined queries fall back
        #: to State.count_*'s O(V) log scan. The logs themselves (the
        #: checkpoint/evidence source of truth) are always maintained.
        self.host_counts = True
        #: Flight-recorder handle (obs/recorder.py); the shared no-op
        #: singleton when observability is off, so every emit site can
        #: gate on one identity check. Named ``obs`` because ``recorder``
        #: already means the transport-replay FlightRecorder elsewhere.
        self.obs = obs if obs is not None else NULL_BOUND

    # ---------------------------------------------------------------- inputs

    def propose(self, propose: Propose) -> None:
        """Receive a Propose (including our own broadcasts); try every rule
        its receipt could open (reference: process/process.go:229-239)."""
        if not self._insert_propose(propose):
            return
        self._try_skip_to_future_round(propose.round)
        self._try_commit_upon_sufficient_precommits(propose.round)
        self._try_precommit_upon_sufficient_prevotes()
        self._try_prevote_upon_propose()
        self._try_prevote_upon_sufficient_prevotes()

    def prevote(self, prevote: Prevote) -> None:
        """Receive a Prevote (reference: process/process.go:245-255)."""
        if not self._insert_prevote(prevote):
            return
        self._try_skip_to_future_round(prevote.round)
        self._try_precommit_upon_sufficient_prevotes()
        self._try_precommit_nil_upon_sufficient_prevotes()
        self._try_prevote_upon_sufficient_prevotes()
        self._try_timeout_prevote_upon_sufficient_prevotes()

    def precommit(self, precommit: Precommit) -> None:
        """Receive a Precommit (reference: process/process.go:261-269)."""
        if not self._insert_precommit(precommit):
            return
        self._try_skip_to_future_round(precommit.round)
        self._try_commit_upon_sufficient_precommits(precommit.round)
        self._try_timeout_precommit_upon_sufficient_precommits()

    def ingest(self, msgs) -> None:
        """Receive a whole verified window: insert every message, then run
        the rule cascade ONCE (per touched round for the round-
        parameterized rules) instead of once per message.

        This is the batched driving mode (SURVEY.md §7.1(4)): the try*
        rules are monotone threshold checks over the logs with once-flag
        idempotence, so evaluating them after the window sees exactly the
        final log state every per-message schedule would eventually reach —
        the outcome corresponds to a legal delivery order of the same
        messages (order-insensitivity is property-tested). Observable
        differences vs strict per-message delivery are confined to (a)
        equivocation evidence for messages a mid-window commit would have
        dropped — strictly more evidence — and (b) timeout schedulings
        whose guards (step checks at fire time) make them no-ops anyway.

        All messages must be for the current height (the mq drain
        guarantees this); inserts therefore happen before any rule can
        advance the height, and a commit fired from the cascade wipes the
        very logs later-round rule evaluations would have read — those
        evaluations then no-op on empty logs, exactly as if the messages
        had arrived after the commit and been height-filtered.
        """
        self.ingest_cascade(self.ingest_insert(msgs))

    def ingest_insert(self, msgs, on_accepted=None):
        """Insert phase of the batched driving mode: log every message,
        fire no rules. Returns the opaque plan for :meth:`ingest_cascade`.

        ``on_accepted(msg, is_precommit)`` is invoked for each *accepted*
        prevote/precommit — the hook the device vote grid uses to scatter
        exactly the votes the host logs accepted (duplicates, equivocation,
        and wrong-height messages never reach it), keeping grid and logs
        byte-equivalent.
        """
        commit_rounds = set()
        vote_rounds = set()
        # The vote inserts are inlined from State.add_prevote/add_precommit
        # (same semantics, property-tested equivalent): at 256 validators a
        # settle window is ~512 votes x 256 replicas, and the per-message
        # call chain (_insert_* -> State.add_*) costs more than the dict
        # operations themselves. Windows arrive (height, round)-sorted, so
        # the per-round dict views are cached across consecutive messages.
        st = self.state
        cur_h = st.current_height
        catcher = self.catcher
        traces = st.trace_logs
        hc = self.host_counts
        last_rnd = None
        last_is_pc = None
        votes = counts = trace = None
        for msg in msgs:
            t = type(msg)
            if t is Prevote:
                if msg.height != cur_h:
                    continue
                rnd = msg.round
                if rnd != last_rnd or last_is_pc is not False:
                    last_rnd, last_is_pc = rnd, False
                    votes = st.prevote_logs.get(rnd)
                    if votes is None:
                        votes = st.prevote_logs[rnd] = {}
                    if hc:
                        counts = st.prevote_counts.get(rnd)
                        if counts is None:
                            counts = st.prevote_counts[rnd] = {}
                    else:
                        # A stale tally (e.g. rebuilt by a checkpoint
                        # restore) must not shadow the scan fallback.
                        st.prevote_counts.pop(rnd, None)
                    trace = traces.get(rnd)
                    if trace is None:
                        trace = traces[rnd] = set()
                sender = msg.sender
                existing = votes.get(sender)
                if existing is not None:
                    if msg != existing and catcher is not None:
                        catcher.catch_double_prevote(msg, existing)
                    continue
                votes[sender] = msg
                if hc:
                    v = msg.value
                    counts[v] = counts.get(v, 0) + 1
                trace.add(sender)
                vote_rounds.add(rnd)
                if on_accepted is not None:
                    on_accepted(msg, False)
            elif t is Precommit:
                if msg.height != cur_h:
                    continue
                rnd = msg.round
                if rnd != last_rnd or last_is_pc is not True:
                    last_rnd, last_is_pc = rnd, True
                    votes = st.precommit_logs.get(rnd)
                    if votes is None:
                        votes = st.precommit_logs[rnd] = {}
                    if hc:
                        counts = st.precommit_counts.get(rnd)
                        if counts is None:
                            counts = st.precommit_counts[rnd] = {}
                    else:
                        st.precommit_counts.pop(rnd, None)
                    trace = traces.get(rnd)
                    if trace is None:
                        trace = traces[rnd] = set()
                sender = msg.sender
                existing = votes.get(sender)
                if existing is not None:
                    if msg != existing and catcher is not None:
                        catcher.catch_double_precommit(msg, existing)
                    continue
                votes[sender] = msg
                if hc:
                    v = msg.value
                    counts[v] = counts.get(v, 0) + 1
                trace.add(sender)
                vote_rounds.add(rnd)
                commit_rounds.add(rnd)
                if on_accepted is not None:
                    on_accepted(msg, True)
            else:
                if self._insert_propose(msg):
                    vote_rounds.add(msg.round)
                    commit_rounds.add(msg.round)
                # The propose insert may have touched the cached round's
                # trace set; invalidate so the next vote re-fetches.
                last_rnd = None
        return (commit_rounds, vote_rounds)

    def ingest_insert_cols(self, cols, keep=None, allowed=None,
                           on_accepted=None):
        """Columnar insert phase: the settle fast path over a
        :class:`~hyperdrive_tpu.batch.WindowColumns` view.

        Semantically identical to :meth:`ingest_insert` over the filtered
        window ``[cols.msg(i) for i surviving keep/allowed]`` (property-
        tested: equal logs, once-flags, locks, and catcher calls) — but the
        per-message attribute extraction and type dispatch were paid once
        when ``cols`` was built, the keep-mask and whitelist filters fuse
        into the loop (no intermediate window copy per replica), and the
        round-log views are fetched once per (kind, height, round) run.
        Message objects are touched only for rows the automaton keeps
        (log insertion) or reports (equivocation evidence); on a wire-built
        view (``WindowColumns.from_block``) every other row skips object
        materialization entirely.

        Returns ``(plan, ingested)`` where ``plan`` feeds
        :meth:`ingest_cascade` and ``ingested`` counts the rows that
        survived the keep/allowed filters (the replica's accept
        accounting).
        """
        commit_rounds = set()
        vote_rounds = set()
        vr_add = vote_rounds.add
        cr_add = commit_rounds.add
        st = self.state
        cur_h = st.current_height
        catcher = self.catcher
        traces = st.trace_logs
        hc = self.host_counts
        senders = cols.senders
        values = cols.values
        msg_at = cols.msg
        # Accepted/equivocating rows read the message LIST directly — on
        # the from_messages path every slot is populated, so the common
        # case is a plain index instead of a bound-method call; only
        # wire-built views (None slots) fall back to lazy materialization.
        mlist = cols.msgs
        KP = cols.KIND_PROPOSE
        ingested = 0
        for kind, h, rnd, start, end in cols.runs:
            if kind == KP:
                for i in range(start, end):
                    if keep is not None and not keep[i]:
                        continue
                    if allowed is not None and senders[i] not in allowed:
                        continue
                    ingested += 1
                    m = msg_at(i)
                    if self._insert_propose(m):
                        vote_rounds.add(rnd)
                        commit_rounds.add(rnd)
                continue
            is_pc = kind == cols.KIND_PRECOMMIT
            if h != cur_h:
                # Wrong-height rows still count as delivered (they passed
                # the keep/allowed filters — the object path counts them
                # in its filtered window before the height check drops
                # them), but never touch state or materialize objects.
                if keep is None and allowed is None:
                    ingested += end - start
                else:
                    for i in range(start, end):
                        if (keep is None or keep[i]) and (
                            allowed is None or senders[i] in allowed
                        ):
                            ingested += 1
                continue
            # Round-log views fetch lazily on the first surviving row:
            # a fully filtered-out run must not create empty log dicts
            # the object path would never have created (checkpoint bytes
            # and state-parity both see the difference).
            votes = vget = cget = tadd = counts = trace = None
            n0 = 0
            for i in range(start, end):
                if keep is not None and not keep[i]:
                    continue
                sender = senders[i]
                if allowed is not None and sender not in allowed:
                    continue
                ingested += 1
                if votes is None:
                    if is_pc:
                        votes = st.precommit_logs.get(rnd)
                        if votes is None:
                            votes = st.precommit_logs[rnd] = {}
                        if hc:
                            counts = st.precommit_counts.get(rnd)
                            if counts is None:
                                counts = st.precommit_counts[rnd] = {}
                        else:
                            st.precommit_counts.pop(rnd, None)
                    else:
                        votes = st.prevote_logs.get(rnd)
                        if votes is None:
                            votes = st.prevote_logs[rnd] = {}
                        if hc:
                            counts = st.prevote_counts.get(rnd)
                            if counts is None:
                                counts = st.prevote_counts[rnd] = {}
                        else:
                            st.prevote_counts.pop(rnd, None)
                    trace = traces.get(rnd)
                    if trace is None:
                        trace = traces[rnd] = set()
                    # Bind the per-run view methods once: the row loop
                    # below is the engine's hottest host code, and a
                    # LOAD_METHOD per row costs as much as the dict op.
                    vget = votes.get
                    tadd = trace.add
                    if hc:
                        cget = counts.get
                    n0 = len(votes)
                existing = vget(sender)
                if existing is not None:
                    m = mlist[i]
                    if m is None:
                        m = msg_at(i)
                    if m != existing and catcher is not None:
                        if is_pc:
                            catcher.catch_double_precommit(m, existing)
                        else:
                            catcher.catch_double_prevote(m, existing)
                    continue
                m = mlist[i]
                if m is None:
                    m = msg_at(i)
                votes[sender] = m
                if hc:
                    v = values[i]
                    counts[v] = cget(v, 0) + 1
                tadd(sender)
                if on_accepted is not None:
                    on_accepted(m, is_pc)
            # The round sets are run-constant: one membership add when any
            # row of the run was accepted (every accepted row grows the
            # votes dict, so the length delta is the acceptance signal)
            # instead of a set.add per row.
            if votes is not None and len(votes) != n0:
                vr_add(rnd)
                if is_pc:
                    cr_add(rnd)
        return (commit_rounds, vote_rounds), ingested

    def ingest_cascade(self, plan, tallies=None) -> None:
        """Rule phase of the batched driving mode. With ``tallies`` (a
        TallyView over the device vote grids), the quorum threshold checks
        read the device counts; the host counters remain the fallback for
        anything the grid doesn't cover (rounds beyond its slot window,
        post-commit heights, value mismatches)."""
        commit_rounds, vote_rounds = plan
        if not vote_rounds and not commit_rounds:
            return
        self._tally_source = tallies
        try:
            # Commits first (progress beats round-skipping when both are
            # enabled — each is a legal next transition); then the
            # future-round skip; then the current-round cascade. The skip
            # walks rounds highest-first and stops at the first that fires:
            # the final round is the maximal qualifying one either way, and
            # stopping there avoids scheduling timeouts for intermediate
            # rounds the automaton would immediately leave.
            for r in sorted(commit_rounds):
                self._try_commit_upon_sufficient_precommits(r)
            for r in sorted(vote_rounds, reverse=True):
                before = self.state.current_round
                self._try_skip_to_future_round(r)
                if self.state.current_round != before:
                    break
            self._try_precommit_upon_sufficient_prevotes()
            self._try_precommit_nil_upon_sufficient_prevotes()
            self._try_prevote_upon_propose()
            self._try_prevote_upon_sufficient_prevotes()
            self._try_timeout_precommit_upon_sufficient_precommits()
            self._try_timeout_prevote_upon_sufficient_prevotes()
        finally:
            self._tally_source = None

    # ------------------------------------------------------- tally sources

    def _prevotes_for(self, round: Round, value: Value) -> int:
        """Prevotes at ``round`` for ``value`` — from the device tally
        source when one is installed and covers the query, else the O(1)
        host counter. The source declines (returns None) whenever its
        snapshot might not match the logs: different height (a commit
        advanced us mid-cascade), uncovered round slot, or a target value
        other than the one it tallied against."""
        src = self._tally_source
        if src is not None and src.height == self.state.current_height:
            c = src.prevotes_for(round, value)
            if c is not None:
                return c
        return self.state.count_prevotes_for(round, value)

    def _precommits_for(self, round: Round, value: Value) -> int:
        src = self._tally_source
        if src is not None and src.height == self.state.current_height:
            c = src.precommits_for(round, value)
            if c is not None:
                return c
        return self.state.count_precommits_for(round, value)

    def _prevote_total(self, round: Round) -> int:
        src = self._tally_source
        if src is not None and src.height == self.state.current_height:
            c = src.prevote_total(round)
            if c is not None:
                return c
        return len(self.state.prevote_logs.get(round, {}))

    def _precommit_total(self, round: Round) -> int:
        src = self._tally_source
        if src is not None and src.height == self.state.current_height:
            c = src.precommit_total(round)
            if c is not None:
                return c
        return len(self.state.precommit_logs.get(round, {}))

    # --------------------------------------------------------------- control

    def start(self) -> None:
        """L10: upon start do StartRound(0)."""
        self.start_round(0)

    def start_with_new_signatories(self, f: int, scheduler: Scheduler) -> None:
        """Restart at round 0 under a rotated validator set
        (reference: process/process.go:281-285)."""
        self.f = int(f)
        self.scheduler = scheduler
        self.start_round(0)

    def resume(self) -> None:
        """Re-arm the current step's timeout after a crash-restore.

        A restored Process re-enters consensus mid-round with whatever
        its checkpoint held — locked/valid values, vote logs, once-flags
        — but the timer it had armed died with the old process, and
        without a deadline the replica could wait forever on a quorum
        that already moved on. Re-arming is safe where re-running
        ``start_round`` would not be: no message is broadcast (a re-sent
        propose or vote after restore is exactly the double-send the
        catcher flags as equivocation), and a duplicate timeout is
        harmless — every on_timeout_* height/round/step-guards itself.
        """
        if self.timer is None:
            return
        h = self.state.current_height
        r = self.state.current_round
        step = self.state.current_step
        obs = self.obs
        if step == Step.PROPOSING:
            self.timer.timeout_propose(h, r)
            if obs is not NULL_BOUND:
                obs.emit("timeout.propose.scheduled", h, r)
        elif step == Step.PREVOTING:
            self.timer.timeout_prevote(h, r)
            if obs is not NULL_BOUND:
                obs.emit("timeout.prevote.scheduled", h, r)
        else:
            self.timer.timeout_precommit(h, r)
            if obs is not NULL_BOUND:
                obs.emit("timeout.precommit.scheduled", h, r)

    def start_round(self, round: Round) -> None:
        """L11: begin a new round at the current height.

        After the round/step reset — whatever path is taken — every condition
        that depends on the current round or step is retried (the reference
        does this with a deferred closure, process/process.go:305-312).
        """
        try:
            self.state.current_round = round
            self.state.current_step = Step.PROPOSING
            obs = self.obs
            if obs is not NULL_BOUND:
                obs.emit("round.start", self.state.current_height, round)

            # Without a scheduler we can never know the proposer; do nothing
            # (matching reference behaviour when the seam is nil).
            if self.scheduler is None:
                return
            proposer = self.scheduler.schedule(
                self.state.current_height, self.state.current_round
            )
            if proposer != self.whoami:
                if self.timer is not None:
                    self.timer.timeout_propose(
                        self.state.current_height, self.state.current_round
                    )
                    if obs is not NULL_BOUND:
                        obs.emit(
                            "timeout.propose.scheduled",
                            self.state.current_height,
                            round,
                        )
                return

            # We are the proposer: re-propose our ValidValue if we have one,
            # otherwise ask the application for a fresh value.
            propose_value = self.state.valid_value
            if propose_value == NIL_VALUE and self.proposer is not None:
                propose_value = self.proposer.propose(
                    self.state.current_height, self.state.current_round
                )
            # MPC extension: a proposer that derives payloads from values
            # (duck-typed `payload_for_value`) attaches the share bundle.
            # Keying on the VALUE — not (height, round) — means a
            # re-proposed ValidValue from an earlier round carries its
            # original payload.
            payload = b""
            if propose_value != NIL_VALUE and self.proposer is not None:
                payload_fn = getattr(self.proposer, "payload_for_value", None)
                if payload_fn is not None:
                    payload = payload_fn(propose_value)
            if self.broadcaster is not None:
                self.broadcaster.broadcast_propose(
                    Propose(
                        height=self.state.current_height,
                        round=self.state.current_round,
                        valid_round=self.state.valid_round,
                        value=propose_value,
                        sender=self.whoami,
                        payload=payload,
                    )
                )
        finally:
            self._try_precommit_upon_sufficient_prevotes()
            self._try_precommit_nil_upon_sufficient_prevotes()
            self._try_prevote_upon_propose()
            self._try_prevote_upon_sufficient_prevotes()
            self._try_timeout_precommit_upon_sufficient_precommits()
            self._try_timeout_prevote_upon_sufficient_prevotes()

    # -------------------------------------------------------------- timeouts

    def on_timeout_propose(self, height: Height, round: Round) -> None:
        """L57: a propose timeout fired — prevote nil if still proposing
        (reference: process/process.go:361-373)."""
        if (
            height == self.state.current_height
            and round == self.state.current_round
            and self.state.current_step == Step.PROPOSING
        ):
            if self.obs is not NULL_BOUND:
                self.obs.emit("timeout.propose.fired", height, round)
            if self.broadcaster is not None:
                self.broadcaster.broadcast_prevote(
                    Prevote(
                        height=self.state.current_height,
                        round=self.state.current_round,
                        value=NIL_VALUE,
                        sender=self.whoami,
                    )
                )
            self._step_to_prevoting()

    def on_timeout_prevote(self, height: Height, round: Round) -> None:
        """L61: a prevote timeout fired — precommit nil if still prevoting
        (reference: process/process.go:384-396)."""
        if (
            height == self.state.current_height
            and round == self.state.current_round
            and self.state.current_step == Step.PREVOTING
        ):
            if self.obs is not NULL_BOUND:
                self.obs.emit("timeout.prevote.fired", height, round)
            if self.broadcaster is not None:
                self.broadcaster.broadcast_precommit(
                    Precommit(
                        height=self.state.current_height,
                        round=self.state.current_round,
                        value=NIL_VALUE,
                        sender=self.whoami,
                    )
                )
            self._step_to_precommitting()

    def on_timeout_precommit(self, height: Height, round: Round) -> None:
        """L65: a precommit timeout fired — move to the next round
        (reference: process/process.go:406-410)."""
        if height == self.state.current_height and round == self.state.current_round:
            if self.obs is not NULL_BOUND:
                self.obs.emit("timeout.precommit.fired", height, round)
            self.start_round(round + 1)

    # ------------------------------------------------------------- rules L22+

    def _try_prevote_upon_propose(self) -> None:
        """L22: fresh proposal (valid_round == -1) at the current round while
        proposing -> prevote it (or nil) (reference: process/process.go:424-457)."""
        if self.state.current_step != Step.PROPOSING:
            return
        propose = self.state.propose_logs.get(self.state.current_round)
        if propose is None or propose.valid_round != INVALID_ROUND:
            return
        propose_is_valid = self.state.propose_is_valid.get(
            self.state.current_round, False
        )

        if self.broadcaster is not None:
            lockable = (
                self.state.locked_round == INVALID_ROUND
                or self.state.locked_value == propose.value
            )
            self.broadcaster.broadcast_prevote(
                Prevote(
                    height=self.state.current_height,
                    round=self.state.current_round,
                    value=propose.value if (lockable and propose_is_valid) else NIL_VALUE,
                    sender=self.whoami,
                )
            )
        self._step_to_prevoting()

    def _try_prevote_upon_sufficient_prevotes(self) -> None:
        """L28: re-proposal carrying valid_round vr plus 2f+1 prevotes for
        its value at vr -> prevote it (or nil)
        (reference: process/process.go:472-515)."""
        if self.state.current_step != Step.PROPOSING:
            return
        propose = self.state.propose_logs.get(self.state.current_round)
        if propose is None:
            return
        vr = propose.valid_round
        if vr <= INVALID_ROUND or vr >= self.state.current_round:
            return
        propose_is_valid = self.state.propose_is_valid.get(
            self.state.current_round, False
        )

        # Device-or-host tally (the reference scans the round's votes here,
        # process/process.go:486-491). Cross-round query: the vote grid
        # answers it via its L28 lane (prevotes at vr vs the CURRENT
        # round's proposal value).
        if self._prevotes_for(vr, propose.value) < 2 * self.f + 1:
            return

        if self.broadcaster is not None:
            lockable = (
                self.state.locked_round <= vr
                or self.state.locked_value == propose.value
            )
            self.broadcaster.broadcast_prevote(
                Prevote(
                    height=self.state.current_height,
                    round=self.state.current_round,
                    value=propose.value if (lockable and propose_is_valid) else NIL_VALUE,
                    sender=self.whoami,
                )
            )
        self._step_to_prevoting()

    def _try_timeout_prevote_upon_sufficient_prevotes(self) -> None:
        """L34: first time 2f+1 prevotes (any value) arrive while prevoting
        -> schedule the prevote timeout (reference: process/process.go:527-540)."""
        if self._check_once_flag(
            self.state.current_round, OnceFlag.TIMEOUT_PREVOTE_UPON_SUFFICIENT_PREVOTES
        ):
            return
        if self.state.current_step != Step.PREVOTING:
            return
        if self._prevote_total(self.state.current_round) >= 2 * self.f + 1:
            if self.timer is not None:
                self.timer.timeout_prevote(
                    self.state.current_height, self.state.current_round
                )
                if self.obs is not NULL_BOUND:
                    self.obs.emit(
                        "timeout.prevote.scheduled",
                        self.state.current_height,
                        self.state.current_round,
                    )
                self._set_once_flag(
                    self.state.current_round,
                    OnceFlag.TIMEOUT_PREVOTE_UPON_SUFFICIENT_PREVOTES,
                )

    def _try_precommit_upon_sufficient_prevotes(self) -> None:
        """L36: valid proposal plus 2f+1 prevotes for its value, first time,
        at step >= prevote -> lock it, precommit it, and record it as valid
        (reference: process/process.go:558-611).

        The reference sets the once-flag *before* its deferred
        step-change/retries run (Go defers are LIFO); the equivalent ordering
        here is: lock+broadcast, record valid value/round, set the flag, and
        only then run the retries and the step change.
        """
        if self._check_once_flag(
            self.state.current_round, OnceFlag.PRECOMMIT_UPON_SUFFICIENT_PREVOTES
        ):
            return
        if self.state.current_step < Step.PREVOTING:
            return
        propose = self.state.propose_logs.get(self.state.current_round)
        if propose is None:
            return
        if not self.state.propose_is_valid.get(self.state.current_round, False):
            return
        # Device-or-host tally (reference scan: process/process.go:574-579).
        if (
            self._prevotes_for(self.state.current_round, propose.value)
            < 2 * self.f + 1
        ):
            return

        was_prevoting = self.state.current_step == Step.PREVOTING
        if was_prevoting:
            self.state.locked_value = propose.value
            self.state.locked_round = self.state.current_round
            if self.broadcaster is not None:
                self.broadcaster.broadcast_precommit(
                    Precommit(
                        height=self.state.current_height,
                        round=self.state.current_round,
                        value=propose.value,
                        sender=self.whoami,
                    )
                )
        self.state.valid_value = propose.value
        self.state.valid_round = self.state.current_round
        self._set_once_flag(
            self.state.current_round, OnceFlag.PRECOMMIT_UPON_SUFFICIENT_PREVOTES
        )
        if was_prevoting:
            # Locked value/round changed: retry the prevote rules (no-ops
            # unless a later rule moved us back to Proposing), then step.
            self._try_prevote_upon_propose()
            self._try_prevote_upon_sufficient_prevotes()
            self._step_to_precommitting()

    def _try_precommit_nil_upon_sufficient_prevotes(self) -> None:
        """L44: 2f+1 nil prevotes while prevoting -> precommit nil
        (reference: process/process.go:622-643)."""
        if self.state.current_step != Step.PREVOTING:
            return
        # Device-or-host tally (reference scan: process/process.go:626-631).
        if (
            self._prevotes_for(self.state.current_round, NIL_VALUE)
            >= 2 * self.f + 1
        ):
            if self.broadcaster is not None:
                self.broadcaster.broadcast_precommit(
                    Precommit(
                        height=self.state.current_height,
                        round=self.state.current_round,
                        value=NIL_VALUE,
                        sender=self.whoami,
                    )
                )
            self._step_to_precommitting()

    def _try_timeout_precommit_upon_sufficient_precommits(self) -> None:
        """L47: first time 2f+1 precommits (any value) arrive at the
        current round -> schedule the precommit timeout
        (reference: process/process.go:654-664). The reference checks
        ``== 2f+1`` — safe there because per-message inserts grow the log
        by exactly one, so the first sufficient state is always exactly
        2f+1. Under batched ingestion (:meth:`ingest`) a window can jump
        the count past 2f+1 in one pass, so the check must be ``>=``; the
        once-flag keeps it single-fire, making ``>=`` and ``==``
        observationally identical on the per-message path."""
        if self._check_once_flag(
            self.state.current_round,
            OnceFlag.TIMEOUT_PRECOMMIT_UPON_SUFFICIENT_PRECOMMITS,
        ):
            return
        if self._precommit_total(self.state.current_round) >= 2 * self.f + 1:
            if self.timer is not None:
                self.timer.timeout_precommit(
                    self.state.current_height, self.state.current_round
                )
                if self.obs is not NULL_BOUND:
                    self.obs.emit(
                        "timeout.precommit.scheduled",
                        self.state.current_height,
                        self.state.current_round,
                    )
                self._set_once_flag(
                    self.state.current_round,
                    OnceFlag.TIMEOUT_PRECOMMIT_UPON_SUFFICIENT_PRECOMMITS,
                )

    def _try_commit_upon_sufficient_precommits(self, round: Round) -> None:
        """L49: valid proposal at ``round`` plus 2f+1 precommits for its
        value -> commit, advance the height, and restart at round 0
        (reference: process/process.go:686-730). The committer may rotate
        the validator set by returning a non-zero f / non-None scheduler."""
        propose = self.state.propose_logs.get(round)
        if propose is None:
            return
        if not self.state.propose_is_valid.get(round, False):
            return
        # Device-or-host tally (reference scan: process/process.go:696-701).
        if self._precommits_for(round, propose.value) < 2 * self.f + 1:
            return

        if self.obs is not NULL_BOUND:
            # Emit before the height advance so the event's (height, round)
            # keys name the committed height, not its successor.
            self.obs.emit(
                "commit",
                self.state.current_height,
                round,
                propose.value.hex()[:16],
            )
        if self.certifier is not None:
            # Mint the O(1) commit proof from the quorum that just fired.
            # The log scan is once-per-commit (cold path); the hot tally
            # checks above never touch it.
            signers = [
                sender
                for sender, pc in self.state.precommit_logs.get(
                    round, {}
                ).items()
                if pc.value == propose.value
            ]
            self.certifier.observe_commit(
                self.state.current_height, round, propose.value, signers
            )
        new_f, new_scheduler = self.committer.commit(
            self.state.current_height, propose.value
        )
        if new_f != 0:
            self.f = int(new_f)
        if new_scheduler is not None:
            self.scheduler = new_scheduler
        self.state.current_height += 1
        self.state.reset_for_new_height()
        self.start_round(0)

    def _try_skip_to_future_round(self, round: Round) -> None:
        """L55: messages from f+1 unique signatories at a future round ->
        jump to that round (reference: process/process.go:744-754)."""
        if round <= self.state.current_round:
            return
        if len(self.state.trace_logs.get(round, ())) >= self.f + 1:
            if self.obs is not NULL_BOUND:
                self.obs.emit(
                    "round.skip",
                    self.state.current_height,
                    round,
                    self.state.current_round,
                )
            self.start_round(round)

    # --------------------------------------------------------------- inserts

    def _insert_propose(self, propose: Propose) -> bool:
        """Validate and log a Propose (reference: process/process.go:758-819).

        Returns True iff the message was inserted (valid or not); an invalid
        or nil-valued proposal is logged as invalid so duplicates are still
        detected, but its sender earns no trace-log credit.
        """
        if propose.height != self.state.current_height:
            return False
        if propose.round <= INVALID_ROUND:
            return False

        # Schedule check precedes duplicate detection: duplicates only matter
        # from the scheduled proposer.
        if self.scheduler is not None:
            expected = self.scheduler.schedule(propose.height, propose.round)
            if expected != propose.sender:
                if self.catcher is not None:
                    self.catcher.catch_out_of_turn_propose(propose)
                return False

        existing = self.state.propose_logs.get(propose.round)
        if existing is not None:
            if propose != existing and self.catcher is not None:
                self.catcher.catch_double_propose(propose, existing)
            return False

        # NIL proposals short-circuit before the validator runs (validators
        # never see NIL values — the pre-existing contract). Otherwise a
        # validator that checks whole proposals (duck-typed `valid_propose`,
        # e.g. "does the payload bundle match the value commitment?") takes
        # precedence over the value-only check — the MPC extension hook.
        if propose.value == NIL_VALUE:
            is_valid = False
        elif self.validator is None:
            is_valid = True
        else:
            valid_propose = getattr(self.validator, "valid_propose", None)
            if valid_propose is not None:
                is_valid = valid_propose(propose)
            else:
                is_valid = self.validator.valid(
                    propose.height, propose.round, propose.value
                )
        if not is_valid:
            self.state.propose_logs[propose.round] = propose
            self.state.propose_is_valid[propose.round] = False
            return True

        self.state.propose_logs[propose.round] = propose
        self.state.propose_is_valid[propose.round] = True
        self.state.trace_logs.setdefault(propose.round, set()).add(propose.sender)
        return True

    def _insert_prevote(self, prevote: Prevote) -> bool:
        """Validate and log a Prevote (reference: process/process.go:823-855)."""
        if prevote.height != self.state.current_height:
            return False
        existing = self.state.add_prevote(prevote)
        if existing is not None:
            if prevote != existing and self.catcher is not None:
                self.catcher.catch_double_prevote(prevote, existing)
            return False
        return True

    def _insert_precommit(self, precommit: Precommit) -> bool:
        """Validate and log a Precommit (reference: process/process.go:860-892)."""
        if precommit.height != self.state.current_height:
            return False
        existing = self.state.add_precommit(precommit)
        if existing is not None:
            if precommit != existing and self.catcher is not None:
                self.catcher.catch_double_precommit(precommit, existing)
            return False
        return True

    # ------------------------------------------------------------ step moves

    def _step_to_prevoting(self) -> None:
        """Enter Prevoting and retry the rules the step change could open
        (reference: process/process.go:896-905)."""
        self.state.current_step = Step.PREVOTING
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "step.prevoting",
                self.state.current_height,
                self.state.current_round,
            )
        self._try_precommit_upon_sufficient_prevotes()
        self._try_precommit_nil_upon_sufficient_prevotes()
        self._try_timeout_prevote_upon_sufficient_prevotes()

    def _step_to_precommitting(self) -> None:
        """Enter Precommitting and retry the rules the step change could open
        (reference: process/process.go:909-916)."""
        self.state.current_step = Step.PRECOMMITTING
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "step.precommitting",
                self.state.current_height,
                self.state.current_round,
            )
        self._try_precommit_upon_sufficient_prevotes()

    # ------------------------------------------------------------ once flags

    def _check_once_flag(self, round: Round, flag: int) -> bool:
        return (self.state.once_flags.get(round, 0) & flag) == flag

    def _set_once_flag(self, round: Round, flag: int) -> None:
        self.state.once_flags[round] = self.state.once_flags.get(round, 0) | flag

    # ----------------------------------------------------------------- serde

    def marshal(self, w: Writer) -> None:
        """Checkpoint identity, f, and the full State
        (reference: process/process.go:183-206)."""
        w.bytes32(self.whoami)
        w.u64(self.f)
        self.state.marshal(w)

    def unmarshal_into(self, r: Reader) -> None:
        """Restore identity, f, and State from a checkpoint
        (reference: process/process.go:209-223).

        All fields are parsed into locals first and assigned only once the
        whole payload has deserialized, so a malformed checkpoint (even one
        that passes the envelope CRC) raises without leaving the Process
        torn between old and new state.
        """
        whoami = r.bytes32()
        f = r.u64()
        state = State.unmarshal(r)
        self.whoami = whoami
        self.f = f
        self.state = state

    # ------------------------------------------------------------ properties

    @property
    def current_height(self) -> Height:
        return self.state.current_height

    @property
    def current_round(self) -> Round:
        return self.state.current_round

    @property
    def current_step(self) -> Step:
        return self.state.current_step
