"""Dynamic validator sets: epoch schedule, proportional election, and
light-client-checkable transition proofs.

The validator set is frozen at construction everywhere else in the
engine — no real deployment survives that (ROADMAP item 4). This module
adds the missing lifecycle:

- **Election** (:func:`elect_committee`): stake-weighted proportional
  sampling without replacement, deterministic from a seed digest
  (PAPERS.md: "A verifiably secure and proportional committee election
  rule", arXiv:2004.12990 — the committee is a verifiable random
  function of public randomness and the stake table, so every observer
  recomputes the same set).
- **Schedule** (:class:`EpochSchedule`): heights partition into
  fixed-length epochs; committing an epoch's last height ("the
  boundary") elects the next committee. The election seed chains
  ``anchor(e+1) = H(seed ‖ e+1 ‖ anchor(e) ‖ H(boundary value))`` — a
  pure function of *agreed* consensus state, so replicas that committed
  the same boundary value compute the same committee. (Seeding from the
  per-replica :class:`~hyperdrive_tpu.certificates.QuorumCertificate`
  digest instead would fork elections: a certificate's round and signer
  bitmap legitimately differ per replica under partitions.) Re-keying
  rides the same anchor: each transition deterministically picks
  ``rekey_per_epoch`` members of the new committee and bumps their key
  generation, retiring the old identity.
- **Proofs** (:class:`EpochProof`, :func:`verify_epoch_chain`): a
  constant-size :class:`~hyperdrive_tpu.certificates.QuorumCertificate`
  over the *transition digest* (epoch ‖ next-set digest ‖ prev-set
  digest), signed — via the boundary commit's 2f+1 precommit quorum —
  under the OLD committee. A light client holding epoch N's validator
  set walks to N+1 with a constant number of checks per hop: two set
  digests, one transition digest, one bitmap popcount, one binding
  recompute. No history is ever re-verified.
- **Emission** (:class:`EpochCertifier`): a
  :class:`~hyperdrive_tpu.certificates.Certifier` that mints the epoch
  proof at each boundary commit and hot-swaps itself to the next
  committee (``Certifier.rotate``), keeping one continuous certificate
  chain across the transition.

The chaos engine is the proving ground — see ROBUSTNESS.md for the
churn/rotation scenario families and the invariants the monitor holds
over them.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from hyperdrive_tpu.certificates import (
    Certifier,
    QuorumCertificate,
    _binding,
    marshal_certificate,
    unmarshal_certificate,
)
from hyperdrive_tpu.analysis.annotations import wire_codec
from hyperdrive_tpu.codec import Reader, SerdeError, Writer
from hyperdrive_tpu.obs.recorder import NULL_BOUND

__all__ = [
    "ValidatorInfo",
    "EpochConfig",
    "EpochTransition",
    "EpochSchedule",
    "EpochProof",
    "EpochChainError",
    "elect_committee",
    "set_digest",
    "transition_digest",
    "default_signatory",
    "genesis_anchor",
    "verify_epoch_chain",
    "marshal_epoch_proof",
    "unmarshal_epoch_proof",
    "EpochCertifier",
]

#: Domain separator for every epoch-layer hash (versioned: a format
#: change must not collide with old anchors/digests).
_EPOCH_TAG = b"hd-epoch-v1"


# ------------------------------------------------------------------ election


def _draw(material: bytes, ctr: int, bound: int) -> tuple[int, int]:
    """One uniform draw in ``[0, bound)`` from the sha256 counter stream
    keyed by ``material``; returns ``(value, next_ctr)``. Rejection
    sampling over the top of the 64-bit range keeps the draw exactly
    uniform (no modulo bias), and the counter advance makes the stream
    position part of the deterministic contract."""
    if bound <= 0:
        raise ValueError(f"draw bound must be positive, got {bound}")
    limit = (1 << 64) - ((1 << 64) % bound)
    while True:
        h = hashlib.sha256()
        h.update(_EPOCH_TAG)
        h.update(b"draw")
        h.update(material)
        h.update(ctr.to_bytes(8, "little"))
        ctr += 1
        v = int.from_bytes(h.digest()[:8], "little")
        if v < limit:
            return v % bound, ctr


def elect_committee(stakes, k: int, seed_material: bytes) -> tuple:
    """Stake-weighted proportional election: sample ``k`` distinct pool
    indices without replacement, each draw proportional to remaining
    stake (arXiv:2004.12990's proportionality, instantiated over a hash
    counter stream so every observer of ``seed_material`` recomputes the
    identical committee). Zero-stake candidates are never elected.
    Returns the winners in election order — the committee's canonical
    whitelist order."""
    pool = [(i, int(s)) for i, s in enumerate(stakes) if int(s) > 0]
    if k > len(pool):
        raise ValueError(
            f"committee size {k} exceeds {len(pool)} staked candidates"
        )
    ctr = 0
    chosen: list = []
    for _ in range(k):
        total = sum(s for _, s in pool)
        r, ctr = _draw(seed_material, ctr, total)
        acc = 0
        for j, (idx, s) in enumerate(pool):
            acc += s
            if r < acc:
                chosen.append(idx)
                pool.pop(j)
                break
    return tuple(chosen)


# ------------------------------------------------------------------- digests


def set_digest(signatories) -> bytes:
    """Canonical digest of a validator set *in whitelist order* — the
    order certificate signer bitmaps index, so the digest commits to the
    bitmap semantics, not just the membership."""
    h = hashlib.sha256()
    h.update(_EPOCH_TAG)
    h.update(b"set")
    sigs = list(signatories)
    h.update(len(sigs).to_bytes(4, "little"))
    for s in sigs:
        h.update(len(s).to_bytes(2, "little"))
        h.update(s)
    return h.digest()


def transition_digest(epoch: int, next_set_digest: bytes,
                      prev_set_digest: bytes) -> bytes:
    """The value an epoch proof's certificate commits to: "epoch
    ``epoch`` runs under the set whose digest is ``next_set_digest``,
    succeeding ``prev_set_digest``"."""
    h = hashlib.sha256()
    h.update(_EPOCH_TAG)
    h.update(b"transition")
    h.update(int(epoch).to_bytes(8, "little"))
    h.update(next_set_digest)
    h.update(prev_set_digest)
    return h.digest()


def default_signatory(index: int, generation: int,
                      namespace: bytes = b"epoch") -> bytes:
    """The unsigned-harness identity function: a 32-byte digest per
    (pool index, key generation). Signed deployments pass a
    ``signatory_fn`` that derives real pubkeys instead."""
    h = hashlib.sha256()
    h.update(_EPOCH_TAG)
    h.update(b"sig")
    h.update(namespace)
    h.update(int(index).to_bytes(4, "little"))
    h.update(int(generation).to_bytes(4, "little"))
    return h.digest()


def genesis_anchor(seed: int) -> bytes:
    """The epoch-0 anchor — a pure function of the schedule seed.

    Exposed at module level because consumers that key material off the
    anchor chain (the aggregation overlay's topology, FaultPlan.overlay
    computing tree-slicing partitions *before* a sim exists) need the
    epoch-0 value without constructing a schedule. Must stay
    byte-identical to the value ``EpochSchedule.__init__`` installs."""
    return hashlib.sha256(
        _EPOCH_TAG + b"anchor" + int(seed).to_bytes(8, "little")
        + b"genesis"
    ).digest()


# ----------------------------------------------------------------- schedule


@dataclass(frozen=True)
class ValidatorInfo:
    """One committee seat: pool index, current-generation identity,
    stake, and key generation."""

    index: int
    signatory: bytes
    stake: int
    generation: int


@dataclass(frozen=True)
class EpochConfig:
    """Harness-facing epoch knobs (``Simulation(epochs=EpochConfig())``).

    ``committee_size`` of 0 means "the whole pool". ``stakes`` of ()
    means uniform stake 1 per pool member. ``rekey_per_epoch`` members
    of each NEW committee rotate to a fresh key generation at the
    boundary, retiring their old identity."""

    epoch_length: int = 4
    committee_size: int = 0
    rekey_per_epoch: int = 1
    seed: int = 0
    stakes: tuple = ()


@dataclass(frozen=True)
class EpochTransition:
    """The computed outcome of one boundary commit."""

    epoch: int                      #: the NEW epoch index
    committee: tuple                #: tuple[ValidatorInfo] in whitelist order
    signatories: tuple              #: committee identities, same order
    set_digest: bytes               #: digest of ``signatories``
    prev_set_digest: bytes          #: digest of the outgoing committee
    joined: tuple = ()              #: pool indices newly seated
    left: tuple = ()                #: pool indices unseated
    rekeyed: tuple = ()             #: pool indices with a bumped generation
    retired: tuple = ()             #: the old identities those retired
    anchoring_digest: bytes = b""   #: sha256 of the boundary value


class EpochSchedule:
    """The deterministic epoch state machine.

    Advances strictly in epoch order as boundary commits arrive
    (:meth:`transition_at`); every query before the corresponding
    boundary commit raises, because the committee genuinely does not
    exist yet — it is a function of a value the network has not agreed
    on. Idempotent per epoch: replicas committing the same boundary
    value share one cached transition, and a replica committing a
    *different* value at the same boundary trips the fork check here
    before it can elect a divergent committee.
    """

    def __init__(self, stakes, committee_size: int, epoch_length: int,
                 seed: int, *, rekey_per_epoch: int = 1,
                 signatory_fn=default_signatory):
        self.stakes = tuple(int(s) for s in stakes)
        if committee_size < 3:
            raise ValueError(
                f"committee_size must be >= 3 (got {committee_size}): "
                "f = k // 3 must stay positive for 2f+1 quorums"
            )
        staked = sum(1 for s in self.stakes if s > 0)
        if committee_size > staked:
            raise ValueError(
                f"committee_size {committee_size} exceeds {staked} "
                "staked pool members"
            )
        if epoch_length < 1:
            raise ValueError(f"epoch_length must be >= 1, got {epoch_length}")
        self.committee_size = int(committee_size)
        self.epoch_length = int(epoch_length)
        self.seed = int(seed)
        self.rekey_per_epoch = int(rekey_per_epoch)
        self.signatory_fn = signatory_fn
        #: Optional ``height -> stake vector`` hook consulted when
        #: :meth:`transition_at` gets no explicit ``stakes`` override:
        #: the execution layer binds the committed ledger's stake
        #: column here, so EVERY transition-creating path — the sim's
        #: commit seam and EpochCertifier.observe_commit alike — elects
        #: from replicated state. Must be deterministic in ``height``.
        self.stake_source = None
        self._gens = [0] * len(self.stakes)
        anchor0 = genesis_anchor(self.seed)
        self._anchors: dict = {0: anchor0}
        members = elect_committee(
            self.stakes, self.committee_size, anchor0 + b"elect"
        )
        committee = tuple(
            ValidatorInfo(i, signatory_fn(i, 0), self.stakes[i], 0)
            for i in members
        )
        sigs = tuple(v.signatory for v in committee)
        self._transitions: dict = {
            0: EpochTransition(
                epoch=0,
                committee=committee,
                signatories=sigs,
                set_digest=set_digest(sigs),
                prev_set_digest=bytes(32),
                joined=members,
            )
        }

    # ------------------------------------------------------------- geometry

    def epoch_of(self, height: int) -> int:
        """The epoch height ``height`` belongs to (heights start at 1)."""
        return (int(height) - 1) // self.epoch_length

    def anchor(self, epoch: int) -> bytes:
        """The chained anchor digest for ``epoch``.

        Only anchors already derived exist — epoch e's anchor is minted
        by :meth:`advance` from the committed boundary value of epoch
        e-1, so asking for a future epoch is a programming error, not a
        lookup miss. The overlay keys its per-epoch tree off this value."""
        a = self._anchors.get(int(epoch))
        if a is None:
            raise KeyError(
                f"anchor for epoch {epoch} not derived yet "
                f"(have epochs {sorted(self._anchors)})"
            )
        return a

    def is_boundary(self, height: int) -> bool:
        """True when committing ``height`` triggers the next election."""
        return int(height) % self.epoch_length == 0

    def boundary_height(self, epoch: int) -> int:
        """The last height of ``epoch`` — its commit elects ``epoch+1``."""
        return (int(epoch) + 1) * self.epoch_length

    # -------------------------------------------------------------- queries

    @property
    def latest_epoch(self) -> int:
        return max(self._transitions)

    def transition(self, epoch: int) -> EpochTransition:
        got = self._transitions.get(int(epoch))
        if got is None:
            raise KeyError(
                f"epoch {epoch} not elected yet (latest: "
                f"{self.latest_epoch}) — its boundary has not committed"
            )
        return got

    def committee(self, epoch: int) -> tuple:
        return self.transition(epoch).committee

    def signatories(self, epoch: int) -> tuple:
        return self.transition(epoch).signatories

    def f(self, epoch: int) -> int:
        return len(self.committee(epoch)) // 3

    def generation_of(self, index: int) -> int:
        return self._gens[index]

    # ----------------------------------------------------------- transition

    def transition_at(
        self, height: int, value: bytes, stakes=None
    ) -> EpochTransition:
        """Compute (or fetch) the transition triggered by committing
        ``value`` at boundary ``height``. Raises on a non-boundary
        height, and raises ``ValueError`` when a cached transition was
        anchored on a *different* committed value — that is a fork at
        the boundary, and electing from it would split the network into
        two futures.

        ``stakes`` overrides the static construction-time table for
        THIS election (and the committee's ValidatorInfo stakes): the
        execution layer passes the committed ledger's stake column at
        the boundary, so elections read replicated state instead of a
        fixed table (ROADMAP item 4). Callers must be deterministic —
        every replica reaching this boundary passes the same vector
        (the chained state root enforces it); the cached first-
        committer transition is returned as-is, same as value-anchored
        determinism."""
        if not self.is_boundary(height):
            raise ValueError(f"height {height} is not an epoch boundary")
        new_epoch = self.epoch_of(height) + 1
        vdigest = hashlib.sha256(value).digest()
        got = self._transitions.get(new_epoch)
        if got is not None:
            if got.anchoring_digest != vdigest:
                raise ValueError(
                    f"epoch {new_epoch} fork: boundary {height} already "
                    f"anchored on {got.anchoring_digest.hex()[:16]}, "
                    f"got {vdigest.hex()[:16]}"
                )
            return got
        if new_epoch != self.latest_epoch + 1:
            raise ValueError(
                f"transition to epoch {new_epoch} out of order "
                f"(latest: {self.latest_epoch})"
            )
        prev = self._transitions[new_epoch - 1]
        anchor = hashlib.sha256(
            _EPOCH_TAG + b"anchor" + self.seed.to_bytes(8, "little")
            + new_epoch.to_bytes(8, "little")
            + self._anchors[new_epoch - 1] + vdigest
        ).digest()
        self._anchors[new_epoch] = anchor
        if stakes is None and self.stake_source is not None:
            stakes = self.stake_source(height)
        elect_stakes = (
            self.stakes if stakes is None else tuple(int(s) for s in stakes)
        )
        if len(elect_stakes) != len(self.stakes):
            raise ValueError(
                f"stake override has {len(elect_stakes)} entries for a "
                f"{len(self.stakes)}-member pool"
            )
        members = elect_committee(
            elect_stakes, self.committee_size, anchor + b"elect"
        )
        # Deterministic re-key: rekey_per_epoch distinct members of the
        # NEW committee bump their key generation, drawn from the same
        # anchor so every replica retires the same identities.
        rekeyed: list = []
        retired: list = []
        if self.rekey_per_epoch > 0 and members:
            ctr = 0
            picks = min(self.rekey_per_epoch, len(members))
            remaining = list(members)
            for _ in range(picks):
                j, ctr = _draw(anchor + b"rekey", ctr, len(remaining))
                idx = remaining.pop(j)
                retired.append(
                    self.signatory_fn(idx, self._gens[idx])
                )
                self._gens[idx] += 1
                rekeyed.append(idx)
        committee = tuple(
            ValidatorInfo(
                i, self.signatory_fn(i, self._gens[i]),
                elect_stakes[i], self._gens[i],
            )
            for i in members
        )
        sigs = tuple(v.signatory for v in committee)
        old_members = {v.index for v in prev.committee}
        tr = EpochTransition(
            epoch=new_epoch,
            committee=committee,
            signatories=sigs,
            set_digest=set_digest(sigs),
            prev_set_digest=prev.set_digest,
            joined=tuple(i for i in members if i not in old_members),
            left=tuple(sorted(old_members - set(members))),
            rekeyed=tuple(rekeyed),
            retired=tuple(retired),
            anchoring_digest=vdigest,
        )
        self._transitions[new_epoch] = tr
        return tr


# ------------------------------------------------------------------- proofs


class EpochChainError(ValueError):
    """An epoch-proof chain failed verification; the message names the
    hop and the check that broke."""


@dataclass(frozen=True)
class EpochProof:
    """The light-client hop from epoch ``epoch - 1`` to ``epoch``.

    ``cert`` is a constant-size quorum certificate whose value digest is
    :func:`transition_digest` — minted from the boundary commit's 2f+1
    precommit quorum, so its signer bitmap indexes the OLD committee's
    whitelist order. ``next_signatories`` rides along (committed to by
    ``next_set_digest``) so the verifier can keep walking."""

    epoch: int
    prev_set_digest: bytes
    next_set_digest: bytes
    next_signatories: tuple
    cert: QuorumCertificate


@wire_codec(tag="epoch.proof", max_bytes=4 << 20)
def marshal_epoch_proof(proof: EpochProof, w: Writer) -> None:
    w.u64(proof.epoch)
    w.bytes32(proof.prev_set_digest)
    w.bytes32(proof.next_set_digest)
    w.u32(len(proof.next_signatories))
    for s in proof.next_signatories:
        w.raw(s)
    marshal_certificate(proof.cert, w)


@wire_codec(tag="epoch.proof", max_bytes=4 << 20)
def unmarshal_epoch_proof(r: Reader) -> EpochProof:
    epoch = r.u64()
    prev_digest = r.bytes32()
    next_digest = r.bytes32()
    n = r.u32()
    if n > 65536:
        raise SerdeError(f"epoch proof signatory count too large: {n}")
    sigs = tuple(r.raw() for _ in range(n))
    cert = unmarshal_certificate(r)
    return EpochProof(
        epoch=epoch,
        prev_set_digest=prev_digest,
        next_set_digest=next_digest,
        next_signatories=sigs,
        cert=cert,
    )


def verify_epoch_chain(genesis_signatories, proofs) -> int:
    """Walk epoch N → N+1 → … with a constant number of checks per hop.

    ``genesis_signatories``: the trusted starting committee (whitelist
    order). ``proofs``: consecutive :class:`EpochProof` hops. Per hop:
    the prev-set digest must match the set we trust, the next-set digest
    must match the carried signatories, the certificate must commit to
    exactly this transition, and its signer bitmap must hold a 2f+1
    quorum of the OLD committee with an intact binding — no signature
    set, no history, nothing proportional to chain length. Returns the
    number of hops verified; raises :class:`EpochChainError` on any
    break."""
    cur = tuple(genesis_signatories)
    hops = 0
    prev_epoch = None
    for proof in proofs:
        tag = f"hop to epoch {proof.epoch}"
        if prev_epoch is not None and proof.epoch != prev_epoch + 1:
            raise EpochChainError(
                f"{tag}: not consecutive after epoch {prev_epoch}"
            )
        if set_digest(cur) != proof.prev_set_digest:
            raise EpochChainError(f"{tag}: prev-set digest mismatch")
        if set_digest(proof.next_signatories) != proof.next_set_digest:
            raise EpochChainError(
                f"{tag}: carried signatories do not match next-set digest"
            )
        want = transition_digest(
            proof.epoch, proof.next_set_digest, proof.prev_set_digest
        )
        cert = proof.cert
        if cert.value_digest != want:
            raise EpochChainError(
                f"{tag}: certificate commits to a different transition"
            )
        n = len(cur)
        if len(cert.signers) != -(-n // 8):
            raise EpochChainError(
                f"{tag}: signer bitmap width {len(cert.signers)} for "
                f"committee of {n}"
            )
        if cert.signer_count() < 2 * (n // 3) + 1:
            raise EpochChainError(
                f"{tag}: {cert.signer_count()} signers < 2f+1 quorum"
            )
        if cert.binding != _binding(
            cert.height, cert.round, cert.value_digest, cert.signers,
            cert.transcript,
        ):
            raise EpochChainError(f"{tag}: certificate binding broken")
        cur = proof.next_signatories
        prev_epoch = proof.epoch
        hops += 1
    return hops


# ----------------------------------------------------------------- emission


class EpochCertifier(Certifier):
    """A :class:`~hyperdrive_tpu.certificates.Certifier` that follows
    the epoch schedule: per boundary commit it mints the epoch proof
    (under the OLD committee's whitelist order — the quorum that
    committed the boundary) and rotates itself to the new committee, so
    one certifier instance carries a continuous certificate chain plus
    the proof chain across every transition it lived through."""

    def __init__(self, schedule: EpochSchedule, epoch: int = 0,
                 transcript_source=None, obs=None, bls_keyring=None,
                 bls_aggregate_fn=None):
        super().__init__(
            schedule.signatories(epoch), schedule.f(epoch),
            transcript_source, obs,
            bls_keyring=bls_keyring, bls_aggregate_fn=bls_aggregate_fn,
        )
        self.schedule = schedule
        self.epoch = int(epoch)
        #: new-epoch index -> EpochProof, in emission order.
        self.proofs: dict = {}

    def observe_commit(self, height, round, value, signers):
        cert = super().observe_commit(height, round, value, signers)
        if not self.schedule.is_boundary(height):
            return cert
        tr = self.schedule.transition_at(height, value)
        td = transition_digest(tr.epoch, tr.set_digest, tr.prev_set_digest)
        pcert = QuorumCertificate(
            height=cert.height,
            round=cert.round,
            value_digest=td,
            signers=cert.signers,
            transcript=cert.transcript,
            binding=_binding(
                cert.height, cert.round, td, cert.signers, cert.transcript
            ),
        )
        self.proofs[tr.epoch] = EpochProof(
            epoch=tr.epoch,
            prev_set_digest=tr.prev_set_digest,
            next_set_digest=tr.set_digest,
            next_signatories=tr.signatories,
            cert=pcert,
        )
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "epoch.proof", int(height), int(round), td.hex()[:16]
            )
        self.rotate_to(tr.epoch)
        return cert

    def rotate_to(self, epoch: int) -> None:
        """Hot-swap to ``epoch``'s committee (boundary commit, or a
        resync that jumped the replica over one or more boundaries)."""
        self.rotate(
            self.schedule.signatories(epoch), self.schedule.f(epoch)
        )
        self.epoch = int(epoch)

    def proof_chain(self) -> list:
        """The held proofs in epoch order — feed to
        :func:`verify_epoch_chain` with the first hop's predecessor
        committee."""
        return [self.proofs[e] for e in sorted(self.proofs)]

    def reset(self) -> None:
        """Crash-restart hook: certificates AND proofs re-emit from the
        restored state; the committee rotation itself is re-derived by
        the restore path (``rotate_to``)."""
        super().reset()
        self.proofs.clear()
