"""The Replica driver: an event loop around one consensus Process.

Capability parity with the reference's ``replica/replica.go``: a Replica
owns a :class:`~hyperdrive_tpu.process.Process` and a
:class:`~hyperdrive_tpu.mq.MessageQueue`, computes ``f = n // 3`` from the
signatory set, filters messages below the current height, whitelists
senders, serializes all handling through a single inbox, flushes the queue
into the Process until quiescent after every handled message, supports
``reset_height`` for chain resync (including signatory-set rotation), and
invokes a ``did_handle_message`` callback after each handled message (the
harness uses it for lock-step backpressure).

Two driving modes:

- **Synchronous** (:meth:`Replica.handle`): the caller delivers one message
  at a time on its own thread. This is what the deterministic harness and
  the benchmarks use — it is the moral equivalent of the reference's
  single-goroutine ``Run`` loop fed by a channel, with the channel hop
  removed.
- **Threaded** (:meth:`Replica.run`): a background thread drains a
  ``queue.Queue`` inbox until a stop event fires, for production-style
  integration (the analogue of ``Replica.Run`` + ``mch``,
  replica/replica.go:88-151).

TPU extension: when a ``verifier`` is supplied (see
:mod:`hyperdrive_tpu.verifier`), queued votes are drained in wide windows
and signature-checked in one batched device launch before the survivors are
fed to the Process — the reference instead assumes the application
authenticated everything upstream (process/process.go:95-98).
"""

from __future__ import annotations

import queue as _queue
import threading
from collections import deque
from dataclasses import dataclass, replace
from typing import Callable, Optional

from hyperdrive_tpu.analysis.annotations import hot_path
from hyperdrive_tpu.analysis.sanitizer import maybe_install as _maybe_sanitize
from hyperdrive_tpu.messages import Precommit, Prevote, Propose, Timeout
from hyperdrive_tpu.obs.recorder import NULL_BOUND
from hyperdrive_tpu.utils.log import get_logger, kv as _kv
from hyperdrive_tpu.utils.trace import NULL_TRACER
from hyperdrive_tpu.mq import DEFAULT_MAX_CAPACITY, MessageQueue
from hyperdrive_tpu.process import (
    Broadcaster,
    Catcher,
    Committer,
    Process,
    Proposer,
    Timer,
    Validator,
)
from hyperdrive_tpu.scheduler import RoundRobin
from hyperdrive_tpu.state import State
from hyperdrive_tpu.types import DEFAULT_HEIGHT, Height, MessageType, Round, Signatory, Step

__all__ = ["Replica", "ReplicaOptions", "ResetHeight", "merge_drain"]


def merge_drain(backlog: list, fresh: list, order_of) -> list:
    """Merge two message lists under the drain ordering contract: global
    ascending (height, round), senders tie-broken by ``order_of``
    registration order, ``backlog`` entries preceding ``fresh`` on full
    ties (backlog predates by construction), FIFO within each list.

    Shared by :meth:`Replica.drain_pending` (queue backlog + fast lane)
    and the harness's shared-superstep window builder (queue backlog +
    shared broadcast lane) — the run-for-run equivalence of the two burst
    paths depends on them merging identically, so there is exactly one
    implementation of the contract.
    """
    if not fresh:
        return backlog
    if not backlog:
        fresh = [
            (m.height, m.round, order_of(m.sender), j, m)
            for j, m in enumerate(fresh)
        ]
        fresh.sort()
        return [t[4] for t in fresh]
    keyed = [
        (m.height, m.round, order_of(m.sender), 0, j, m)
        for j, m in enumerate(backlog)
    ]
    keyed += [
        (m.height, m.round, order_of(m.sender), 1, j, m)
        for j, m in enumerate(fresh)
    ]
    keyed.sort()
    return [t[5] for t in keyed]

#: Precomputed metric names — the dispatch path must not pay string
#: formatting per message.
_MSG_METRIC = {
    Propose: "replica.msg.propose",
    Prevote: "replica.msg.prevote",
    Precommit: "replica.msg.precommit",
    Timeout: "replica.msg.timeout",
}

#: Same discipline for evidence counters (and the HD005 lint contract:
#: metric names are literals or table lookups, never built per call).
_CAUGHT_METRIC = {
    "double_propose": "replica.caught.double_propose",
    "double_prevote": "replica.caught.double_prevote",
    "double_precommit": "replica.caught.double_precommit",
    "out_of_turn_propose": "replica.caught.out_of_turn_propose",
}


@dataclass(frozen=True)
class ReplicaOptions:
    """Immutable functional options (reference: replica/opt.go:11-46).

    ``verify_window`` sizes the batched drain handed to the Verifier; it is
    a TPU-path tunable with no reference analogue. ``tracer`` and
    ``logger`` fill the reference's injectable-logger seam — except this
    framework actually emits (the reference configures zap and never logs a
    line; SURVEY.md §5).
    """

    starting_height: Height = DEFAULT_HEIGHT
    max_capacity: int = DEFAULT_MAX_CAPACITY
    verify_window: int = 1024
    #: When True, :meth:`Replica.handle` buffers into the mq but never
    #: flushes — an external driver runs the two-phase
    #: :meth:`Replica.drain_pending` / :meth:`Replica.dispatch_window`
    #: protocol so many replicas' windows can be signature-verified in one
    #: aggregated device launch (the harness burst mode).
    external_flush: bool = False
    #: When True, :meth:`Replica.dispatch_window` feeds survivors through
    #: :meth:`Process.ingest` — one rule-cascade pass per window instead of
    #: per message (the batched driving mode; see Process.ingest for the
    #: equivalence argument).
    batch_ingest: bool = False
    tracer: object = None
    logger: object = None
    #: Flight-recorder handle (a BoundRecorder from obs/recorder.py, or
    #: None for the shared no-op). The seam is called ``obs`` — not
    #: ``recorder`` — because Replica already takes a ``recorder``
    #: constructor argument for the transport consumption log.
    obs: object = None

    def with_starting_height(self, height: Height) -> "ReplicaOptions":
        return replace(self, starting_height=height)

    def with_max_capacity(self, capacity: int) -> "ReplicaOptions":
        return replace(self, max_capacity=capacity)

    def with_verify_window(self, window: int) -> "ReplicaOptions":
        return replace(self, verify_window=window)

    def with_tracer(self, tracer) -> "ReplicaOptions":
        return replace(self, tracer=tracer)

    def with_logger(self, logger) -> "ReplicaOptions":
        return replace(self, logger=logger)

    def with_obs(self, obs) -> "ReplicaOptions":
        return replace(self, obs=obs)


@dataclass(frozen=True)
class ResetHeight:
    """Resync instruction: jump to ``height``, optionally rotating the
    signatory set (reference: replica/replica.go:266-270)."""

    height: Height
    signatories: tuple[Signatory, ...] = ()


class Replica:
    """A replicated-state-machine participant."""

    def __init__(
        self,
        opts: ReplicaOptions,
        whoami: Signatory,
        signatories: list[Signatory],
        timer: Optional[Timer],
        proposer: Optional[Proposer],
        validator: Optional[Validator],
        committer: Optional[Committer],
        catcher: Optional[Catcher],
        broadcaster: Optional[Broadcaster],
        did_handle_message: Optional[Callable[[], None]] = None,
        verifier=None,
        flusher=None,
        recorder=None,
        certifier=None,
    ):
        f = len(signatories) // 3
        self.opts = opts
        self.tracer = opts.tracer if opts.tracer is not None else NULL_TRACER
        self.logger = opts.logger if opts.logger is not None else get_logger()
        self.obs = opts.obs if opts.obs is not None else NULL_BOUND
        self.proc = Process(
            whoami=whoami,
            f=f,
            timer=timer,
            scheduler=RoundRobin(signatories),
            proposer=proposer,
            validator=validator,
            broadcaster=broadcaster,
            committer=self._instrument_committer(committer),
            certifier=certifier,
            catcher=self._instrument_catcher(catcher),
            height=opts.starting_height,
            obs=self.obs,
        )
        # Consensus sanitizer (ANALYSIS.md, HDS001-HDS003): interposes on
        # the committer/broadcaster seams when HD_SANITIZE is on. No-op
        # otherwise — perf runs export HD_SANITIZE=0 (BENCH.md).
        _maybe_sanitize(self.proc)
        self.procs_allowed: set[Signatory] = set(signatories)
        self.mq = MessageQueue(max_capacity=opts.max_capacity)
        self.mq.obs = self.obs
        # Pre-register the whitelist in the queue's tie-break order map:
        # "senders tie-broken by registration order" then means whitelist
        # order — identical across replicas and across driving modes — so a
        # burst run, its replay, and the lock-step differential all merge
        # equal-(height, round) messages identically. (Unknown senders still
        # register on first use, after the whitelist block.)
        for s in signatories:
            self.mq.order_of(s)
        self.did_handle_message = did_handle_message
        self.verifier = verifier
        #: Optional flush delegate (``flush(replica) -> None`` drains the
        #: queue to quiescence): the seam a deployment uses to put a
        #: device vote grid behind this replica's own event loop — see
        #: :class:`hyperdrive_tpu.tallyflush.DeviceTallyFlusher`. The
        #: sim's settle layer aggregates MANY lockstep replicas into one
        #: launch instead (harness/sim.py), so it does not use this.
        self.flusher = flusher
        #: Optional consumption log (``record(msg)``): every input this
        #: replica consumes — votes, timeouts, resets — in the exact
        #: order consumed. This is the deployment path's record/replay
        #: seam (:class:`hyperdrive_tpu.transport.FlightRecorder`): the
        #: replica IS the serialization point (one event loop), so its
        #: consumption order is the whole behavior — the sim's
        #: failure.dump workflow (reference:
        #: replica/replica_test.go:850-928) extended to socket runs.
        self.recorder = recorder
        self._inbox: _queue.Queue = _queue.Queue(maxsize=opts.max_capacity)
        # Synchronous-mode reentrancy guard: a broadcaster wired straight
        # back into handle() (loopback) must enqueue, not recurse — the
        # moral equivalent of the reference's inbox channel hop.
        self._handling = False
        self._pending: deque = deque()
        self._last_commit_time: Optional[float] = None
        # Burst fast lane (external_flush only): votes for the CURRENT
        # height skip the sorted queue entirely — the next settle drains
        # everything anyway, so sorted insertion + head-heap maintenance
        # is pure overhead for them. drain_pending merges lane and queue
        # under the same (height, round, sender, arrival) ordering the
        # queue drain guarantees. Per-sender capacity mirrors the queue's
        # bound so a current-height flood cannot bypass DoS limits.
        self._lane: list = []
        self._lane_counts: dict = {}
        #: Retired identities (epochs.py key rotation): signatory ->
        #: first height at which votes under it are stale. The bound is
        #: a height, not a blanket ban, because the retiring boundary's
        #: own height legitimately carries old-key votes — a laggard
        #: still finishing it must keep accepting them. The harness
        #: shares one dict by reference across all replicas; deployments
        #: populate it from their epoch schedule. Empty = no admission
        #: cost beyond one truthiness check.
        self.retired: dict = {}
        #: Stale-generation votes rejected (epoch.stale_vote events).
        self.stale_votes = 0
        #: Optional AdmissionGate (load/backpressure.py): consulted after
        #: the height/retired filters in :meth:`_buffer_vote` and the
        #: inlined :meth:`handle_burst` rule. Under pressure, classified
        #: traffic (duplicates, over-share prevotes) sheds here before it
        #: can buffer. None = admit everything.
        self.admission = None

    # --------------------------------------------------------- observability

    def _instrument_committer(self, committer):
        """Wrap the app's committer with metrics + logging: commit counter,
        per-height latency histogram, rounds-to-commit histogram."""
        if committer is None:
            return None
        replica = self

        class _TracingCommitter:
            def commit(self, height, value):
                t = replica.tracer
                now = t.now()
                t.count("replica.commits")
                t.observe("replica.commit.rounds", replica.proc.current_round + 1)
                if replica._last_commit_time is not None:
                    t.observe("replica.height.latency", now - replica._last_commit_time)
                replica._last_commit_time = now
                if replica.logger.isEnabledFor(20):  # INFO — kv() is eager
                    replica.logger.info(
                        "commit %s",
                        _kv(height=height, round=replica.proc.current_round,
                            value=value),
                    )
                return committer.commit(height, value)

        return _TracingCommitter()

    def _instrument_catcher(self, catcher):
        """Wrap the app's catcher: count + log every piece of evidence."""
        if catcher is None:
            return None
        replica = self

        class _TracingCatcher:
            def _note(self, kind, sender):
                replica.tracer.count(_CAUGHT_METRIC[kind])
                if replica.obs is not NULL_BOUND:
                    replica.obs.emit(
                        "equivocation",
                        replica.proc.current_height,
                        replica.proc.current_round,
                        kind,
                    )
                replica.logger.warning(
                    "byzantine evidence %s", _kv(kind=kind, sender=sender)
                )

            def catch_double_propose(self, new, existing):
                self._note("double_propose", new.sender)
                catcher.catch_double_propose(new, existing)

            def catch_double_prevote(self, new, existing):
                self._note("double_prevote", new.sender)
                catcher.catch_double_prevote(new, existing)

            def catch_double_precommit(self, new, existing):
                self._note("double_precommit", new.sender)
                catcher.catch_double_precommit(new, existing)

            def catch_out_of_turn_propose(self, propose):
                self._note("out_of_turn_propose", propose.sender)
                catcher.catch_out_of_turn_propose(propose)

        return _TracingCatcher()

    # ------------------------------------------------------------ sync driving

    def start(self) -> None:
        """Start the underlying Process (round 0 of the starting height)."""
        self.proc.start()

    def restore(self, checkpoint: "bytes | None" = None) -> None:
        """Crash-restart revive path: restore the Process from a
        checkpoint envelope (utils/checkpoint.py) and reset every
        volatile buffer — the sorted queue, the burst fast lane, and any
        reentrant backlog died with the old process; only the checkpoint
        survives a crash. The queue's per-sender tie-break order map is
        kept (it derives from the signatory whitelist, not from traffic,
        and must match the network's for deterministic drains).

        ``checkpoint=None`` models a replica that crashed before its
        first checkpoint: the Process restarts from the default state at
        ``opts.starting_height`` (genesis recovery). Callers then rejoin
        via ResetHeight (network moved on) or ``proc.resume()`` (same
        height — re-arm the current step's timeout, broadcast nothing).
        """
        if checkpoint is not None:
            from hyperdrive_tpu.utils.checkpoint import restore_bytes

            restore_bytes(self.proc, checkpoint)
        else:
            self.proc.state = State.default_with_height(
                self.opts.starting_height
            )
        self.mq.clear()
        self._lane.clear()
        self._lane_counts.clear()
        self._pending.clear()
        self._last_commit_time = None
        if self.flusher is not None and hasattr(self.flusher, "reset"):
            # Queue-backed flushers hold in-flight settle futures; the
            # revived replica must not apply its dead predecessor's
            # windows on top of the checkpoint (devsched cancel path).
            self.flusher.reset(self)
        self.logger.info(
            "restored %s",
            _kv(
                height=self.proc.current_height,
                round=self.proc.current_round,
                from_checkpoint=checkpoint is not None,
            ),
        )

    def handle(self, msg) -> None:
        """Synchronously handle one input message, then flush the queue.

        Mirrors one iteration of the reference's Run loop
        (replica/replica.go:104-148): timeouts dispatch straight into the
        Process; votes are height-filtered and buffered; ResetHeight resets
        state and optionally rotates the signatory set.

        Reentrant calls (e.g. a loopback broadcaster invoked from inside the
        Process) are buffered and drained by the outermost call, preserving
        the reference's serialized-event-loop semantics.
        """
        self._pending.append(msg)
        if self._handling:
            return
        self._handling = True
        try:
            while self._pending:
                self._handle_one(self._pending.popleft())
        except BaseException:
            # A failing callback aborts the cascade; the undelivered tail
            # would otherwise leak into the next unrelated handle() call.
            self._pending.clear()
            raise
        finally:
            self._handling = False

    @hot_path
    def handle_burst(self, msgs) -> None:
        """Buffer one superstep's deliveries in a single pass.

        Semantically identical to calling :meth:`handle` per message in
        ``external_flush`` mode (votes buffer to the fast lane or queue;
        timeouts and resets take the full path), with the per-message
        wrapper costs — reentrancy deque, per-message tracer calls —
        amortized over the batch. Only valid with ``external_flush=True``:
        without an external settle driver nothing drains the fast lane,
        so misuse would silently strand messages.
        """
        if not self.opts.external_flush:
            raise RuntimeError(
                "handle_burst requires external_flush=True (burst driving); "
                "use handle() in self-flushing modes"
            )
        lane = self._lane
        counts = self._lane_counts
        cap = self.opts.max_capacity
        cur = self.proc.current_height
        dh = self.did_handle_message
        retired = self.retired
        adm = self.admission
        n_pv = n_pc = n_pp = 0
        for msg in msgs:
            t = type(msg)
            if t is Prevote or t is Precommit or t is Propose:
                if t is Prevote:
                    n_pv += 1
                elif t is Precommit:
                    n_pc += 1
                else:
                    n_pp += 1
                h = msg.height
                if retired:
                    bad_from = retired.get(msg.sender)
                    if bad_from is not None and h >= bad_from:
                        self._note_stale(msg)
                        if dh is not None:
                            dh()
                        continue
                if h >= cur:
                    if adm is not None and not adm.admit(msg):
                        if dh is not None:
                            dh()
                        continue
                    if h == cur:
                        c = counts.get(msg.sender, 0)
                        if c < cap:
                            counts[msg.sender] = c + 1
                            lane.append(msg)
                    elif t is Prevote:
                        self.mq.insert_prevote(msg)
                    elif t is Precommit:
                        self.mq.insert_precommit(msg)
                    else:
                        self.mq.insert_propose(msg)
                if dh is not None:
                    dh()
            else:
                # Timeouts / ResetHeight: the full path (may move the
                # height); counted there, did_handle_message called there.
                self.handle(msg)
                cur = self.proc.current_height
                counts = self._lane_counts
                lane = self._lane
        if self.tracer is not NULL_TRACER:
            if n_pv:
                self.tracer.count("replica.msg.prevote", n_pv)
            if n_pc:
                self.tracer.count("replica.msg.precommit", n_pc)
            if n_pp:
                self.tracer.count("replica.msg.propose", n_pp)

    def _handle_one(self, msg) -> None:
        if self.recorder is not None:
            self.recorder.record(msg)
        if self.tracer is not NULL_TRACER:
            self.tracer.count(
                _MSG_METRIC.get(type(msg), "replica.msg.other")
            )
        try:
            if isinstance(msg, Timeout):
                if msg.message_type == MessageType.PROPOSE:
                    self.proc.on_timeout_propose(msg.height, msg.round)
                elif msg.message_type == MessageType.PREVOTE:
                    self.proc.on_timeout_prevote(msg.height, msg.round)
                elif msg.message_type == MessageType.PRECOMMIT:
                    self.proc.on_timeout_precommit(msg.height, msg.round)
                else:
                    return
            elif isinstance(msg, (Propose, Prevote, Precommit)):
                self._buffer_vote(msg)
            elif isinstance(msg, ResetHeight):
                self.logger.info(
                    "reset height %s",
                    _kv(
                        from_height=self.proc.current_height,
                        to_height=msg.height,
                        rotating=bool(msg.signatories),
                    ),
                )
                if self.obs is not NULL_BOUND:
                    self.obs.emit(
                        "height.resync",
                        self.proc.current_height,
                        self.proc.current_round,
                        msg.height,
                    )
                self.proc.state = State.default_with_height(msg.height)
                self.mq.drop_messages_below_height(msg.height)
                # Lane messages were for the pre-reset current height,
                # which is below the resync target by contract.
                self._lane.clear()
                self._lane_counts.clear()
                if msg.signatories:
                    sigs = list(msg.signatories)
                    self.proc.start_with_new_signatories(
                        len(sigs) // 3, RoundRobin(sigs)
                    )
                    self.procs_allowed = set(sigs)
            else:
                return
            if not self.opts.external_flush:
                self._flush()
        finally:
            if self.did_handle_message is not None:
                self.did_handle_message()

    def _buffer_vote(self, msg) -> None:
        """Height-filter + buffer one vote: below-height drops, the
        current-height fast lane in ``external_flush`` mode, the sorted
        queue otherwise. The ONE copy of the vote admission rule shared
        by the per-message (:meth:`_handle_one`) and coalesced
        (:meth:`handle_coalesced`) paths — :meth:`handle_burst` inlines
        the same rule with hoisted locals for the sim's hot loop; change
        both together."""
        h = msg.height
        cur = self.proc.current_height
        if h < cur:
            return
        if self.retired:
            bad_from = self.retired.get(msg.sender)
            if bad_from is not None and h >= bad_from:
                self._note_stale(msg)
                return
        if self.admission is not None and not self.admission.admit(msg):
            return
        if h == cur and self.opts.external_flush:
            c = self._lane_counts.get(msg.sender, 0)
            if c < self.opts.max_capacity:
                self._lane_counts[msg.sender] = c + 1
                self._lane.append(msg)
            return
        if isinstance(msg, Propose):
            self.mq.insert_propose(msg)
        elif isinstance(msg, Prevote):
            self.mq.insert_prevote(msg)
        else:
            self.mq.insert_precommit(msg)

    def _note_stale(self, msg) -> None:
        """A vote signed under a retired key generation at a height
        where the rotation is already binding: drop it before it can
        buffer. First rejection logs at WARNING (the
        ``transport.peer.dropped`` convention — one loud line per
        replica, then counters); every rejection emits
        ``epoch.stale_vote`` so round-anatomy reports see the churn."""
        self.stale_votes += 1
        if self.stale_votes == 1:
            self.logger.warning(
                "stale-generation vote %s",
                _kv(
                    sender=msg.sender,
                    height=msg.height,
                    stale_from=self.retired.get(msg.sender),
                ),
            )
        if self.tracer is not NULL_TRACER:
            self.tracer.count("replica.msg.stale_vote")
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "epoch.stale_vote",
                msg.height,
                getattr(msg, "round", -1),
                self.stale_votes,
            )

    def _flush(self) -> None:
        """Drain the queue into the Process until quiescent
        (reference: replica/replica.go:251-264).

        With a Verifier installed, votes are drained in wide windows and
        batch-verified before dispatch; without one, this is the reference's
        synchronous consume loop.
        """
        if self.flusher is not None:
            self.flusher.flush(self)
            return
        if self.verifier is None:
            while True:
                n = self.mq.consume(
                    self.proc.current_height,
                    self.proc.propose,
                    self.proc.prevote,
                    self.proc.precommit,
                    self.procs_allowed,
                )
                if n == 0:
                    return
        else:
            while True:
                window = self.mq.drain_window(
                    self.proc.current_height, self.opts.verify_window
                )
                if not window:
                    return
                self.tracer.observe("replica.verify.window", len(window))
                with self.tracer.span("replica.verify.latency"):
                    keep = self.verifier.verify_batch(window)
                self.dispatch_window(window, keep)

    # ------------------------------------------------- external (burst) flush
    #
    # The two-phase protocol behind ``external_flush=True``: a driver that
    # owns many replicas pulls each one's eligible window (phase 1), verifies
    # every window in one aggregated batch — one device launch for the whole
    # network instead of one per replica — then hands each replica its
    # verdict slice to dispatch (phase 2). Repeating until every window is
    # empty reproduces the flush-until-quiescent contract
    # (reference: replica/replica.go:251-264) at the network level.

    @hot_path
    def drain_pending(self) -> list:
        """Phase 1: pop this replica's eligible window without dispatching.

        Uncapped: a settle pass wants the whole backlog in one aggregated
        launch (the verifier and vote grid chunk/bucket internally), and
        the uncapped drain skips the k-way merge's per-message heap work.
        ``verify_window`` still caps the incremental per-message flush path
        (:meth:`_flush`), where windows must stay small for latency.

        The window merges the queue backlog (messages buffered while their
        height was in the future) with the current-height fast lane, under
        the queue drain's exact ordering contract: global ascending
        (height, round), FIFO within a sender (backlog entries predate lane
        entries by construction), senders tie-broken by registration order.
        """
        cur = self.proc.current_height
        backlog = self.mq.drain_all(cur)
        lane = self._lane
        if not lane:
            return backlog
        self._lane = []
        self._lane_counts = {}
        return merge_drain(backlog, lane, self.mq.order_of)

    @hot_path
    def dispatch_window(self, window, keep=None) -> None:
        """Phase 2: feed the verified survivors of ``window`` to the Process.

        ``keep`` is the external verifier's accept mask (None = all
        accepted). Whitelisting stays here — it is replica state
        (reference: replica/replica.go:69-72), not a property of the
        signature. A mid-window commit advances the height; stale survivors
        are rejected by the Process's own height check, matching what the
        per-message consume loop would have dropped.
        """
        if self.opts.batch_ingest:
            # Single copy of the filter/accounting contract: the batched
            # path is exactly insert + cascade with no tallies installed.
            self.proc.ingest_cascade(self.ingest_insert_window(window, keep))
            return
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "ingest.window",
                self.proc.current_height,
                self.proc.current_round,
                len(window),
            )
        verified = keep is not None
        allowed = self.procs_allowed
        n_ok = 0
        for j, msg in enumerate(window):
            if verified and not keep[j]:
                continue
            if msg.sender not in allowed:
                continue
            n_ok += 1
            if isinstance(msg, Propose):
                self.proc.propose(msg)
            elif isinstance(msg, Prevote):
                self.proc.prevote(msg)
            else:
                self.proc.precommit(msg)
        if verified and self.tracer is not NULL_TRACER:
            self.tracer.count("replica.verify.accepted", n_ok)
            self.tracer.count("replica.verify.rejected", len(window) - n_ok)

    def ingest_insert_window(self, window, keep=None, on_accepted=None):
        """Phase 2a (device-tally mode): filter + insert only, no rules.

        Same filtering as :meth:`dispatch_window`; accepted votes flow to
        ``on_accepted`` so the driver can scatter them into the device vote
        grid before the rule phase. Returns the plan for
        :meth:`ingest_cascade_window`.
        """
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "ingest.window",
                self.proc.current_height,
                self.proc.current_round,
                len(window),
            )
        verified = keep is not None
        allowed = self.procs_allowed
        batch = [
            msg
            for j, msg in enumerate(window)
            if (not verified or keep[j]) and msg.sender in allowed
        ]
        if verified and self.tracer is not NULL_TRACER:
            self.tracer.count("replica.verify.accepted", len(batch))
            self.tracer.count("replica.verify.rejected",
                              len(window) - len(batch))
        return self.proc.ingest_insert(batch, on_accepted)

    def ingest_insert_window_cols(self, cols, keep=None, on_accepted=None):
        """Columnar phase 2a: insert a :class:`~hyperdrive_tpu.batch.
        WindowColumns` view with the keep-mask and whitelist filters fused
        into the loop — no per-replica window copy, no per-replica
        attribute extraction (it was paid once when ``cols`` was built).
        Accounting matches :meth:`ingest_insert_window` row for row;
        ``replica.ingest.fastpath_rows`` counts the rows that rode the
        columnar path."""
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "ingest.window",
                self.proc.current_height,
                self.proc.current_round,
                cols.n,
            )
        plan, n_ok = self.proc.ingest_insert_cols(
            cols, keep, self.procs_allowed, on_accepted
        )
        if self.tracer is not NULL_TRACER:
            self.tracer.count("replica.ingest.fastpath_rows", cols.n)
            if keep is not None:
                self.tracer.count("replica.verify.accepted", n_ok)
                self.tracer.count("replica.verify.rejected", cols.n - n_ok)
        return plan

    @hot_path
    def dispatch_window_cols(self, cols, keep=None) -> None:
        """Columnar phase 2: insert + cascade over a WindowColumns view
        (the batched-ingest analogue of :meth:`dispatch_window`; callers
        must only use it when ``opts.batch_ingest`` is set — the
        per-message path has no columnar equivalent)."""
        self.proc.ingest_cascade(self.ingest_insert_window_cols(cols, keep))

    def ingest_cascade_window(self, plan, tallies=None) -> None:
        """Phase 2b (device-tally mode): run the rule cascade with the
        device tally counts installed."""
        self.proc.ingest_cascade(plan, tallies)

    def _filter_height(self, height: Height) -> bool:
        """Only current-or-future heights are kept
        (reference: replica/replica.go:247-249)."""
        return height >= self.proc.current_height

    # -------------------------------------------------------- threaded driving

    def run(self, stop: threading.Event, coalesce: bool = False) -> None:
        """Drain the inbox until ``stop`` fires (the reference's Run loop,
        replica/replica.go:88-151). Call from a dedicated thread.

        ``coalesce=True`` drains every message already waiting in the
        inbox before flushing once, instead of flushing after each — the
        threaded analogue of the harness burst mode, and what makes a
        device-verified deployment replica pay one launch per burst
        rather than one per vote. Under per-message flushing the two
        schedules are equivalent (the batched cascade's outcome
        corresponds to a legal delivery order — see Process.ingest);
        backpressure still fires ``did_handle_message`` per message.
        """
        self.proc.start()
        cap = max(self.opts.verify_window, 1)
        while not stop.is_set():
            try:
                msg = self._inbox.get(timeout=0.05)
            except _queue.Empty:
                continue
            if not coalesce:
                self.handle(msg)
                continue
            batch = [msg]
            while len(batch) < cap:
                try:
                    batch.append(self._inbox.get_nowait())
                except _queue.Empty:
                    break
            self.handle_coalesced(batch)
        # Match the reference: the callback also fires when the context is
        # cancelled (replica/replica.go:16-18).
        if self.did_handle_message is not None:
            self.did_handle_message()

    def handle_coalesced(self, msgs) -> None:
        """Buffer a burst of inbox messages, then flush ONCE.

        Votes height-filter and insert into the queue without the
        per-message flush-until-quiescent pass; timeouts and resets take
        the full :meth:`handle` path (they can move the height). The
        single flush at the end restores the quiescence contract for the
        whole burst. Not meaningful with ``external_flush`` (an external
        driver owns settling there) — :meth:`handle_burst` is that mode's
        batch entry."""
        if self.opts.external_flush:
            raise RuntimeError(
                "handle_coalesced is the self-flushing batch entry; "
                "external_flush drivers use handle_burst"
            )
        dh = self.did_handle_message
        for msg in msgs:
            t = type(msg)
            if t is Propose or t is Prevote or t is Precommit:
                if self.recorder is not None:
                    self.recorder.record(msg)
                if self.tracer is not NULL_TRACER:
                    self.tracer.count(_MSG_METRIC[t])
                self._buffer_vote(msg)
                if dh is not None:
                    dh()
            else:
                self.handle(msg)
        self._flush()

    def _enqueue(self, msg, stop: Optional[threading.Event] = None) -> None:
        while True:
            try:
                self._inbox.put(msg, timeout=0.05)
                return
            except _queue.Full:
                if stop is not None and stop.is_set():
                    return

    def propose(self, propose: Propose, stop=None) -> None:
        """Async insert (reference: replica/replica.go:156-161)."""
        self._enqueue(propose, stop)

    def prevote(self, prevote: Prevote, stop=None) -> None:
        self._enqueue(prevote, stop)

    def precommit(self, precommit: Precommit, stop=None) -> None:
        self._enqueue(precommit, stop)

    def timeout(self, timeout: Timeout, stop=None) -> None:
        self._enqueue(timeout, stop)

    def reset_height(
        self, new_height: Height, signatories: list[Signatory] = (), stop=None
    ) -> None:
        """Jump a lagging replica to ``new_height`` (> current), dropping
        stale queued messages (reference: replica/replica.go:222-235)."""
        if new_height <= self.proc.current_height:
            return
        self._enqueue(ResetHeight(new_height, tuple(signatories)), stop)

    # ------------------------------------------------------------- inspection

    def current_state(self) -> tuple[Height, Round, Step]:
        return (
            self.proc.current_height,
            self.proc.current_round,
            self.proc.current_step,
        )

    def current_height(self) -> Height:
        return self.proc.current_height
