"""The Replica driver: an event loop around one consensus Process.

Capability parity with the reference's ``replica/replica.go``: a Replica
owns a :class:`~hyperdrive_tpu.process.Process` and a
:class:`~hyperdrive_tpu.mq.MessageQueue`, computes ``f = n // 3`` from the
signatory set, filters messages below the current height, whitelists
senders, serializes all handling through a single inbox, flushes the queue
into the Process until quiescent after every handled message, supports
``reset_height`` for chain resync (including signatory-set rotation), and
invokes a ``did_handle_message`` callback after each handled message (the
harness uses it for lock-step backpressure).

Two driving modes:

- **Synchronous** (:meth:`Replica.handle`): the caller delivers one message
  at a time on its own thread. This is what the deterministic harness and
  the benchmarks use — it is the moral equivalent of the reference's
  single-goroutine ``Run`` loop fed by a channel, with the channel hop
  removed.
- **Threaded** (:meth:`Replica.run`): a background thread drains a
  ``queue.Queue`` inbox until a stop event fires, for production-style
  integration (the analogue of ``Replica.Run`` + ``mch``,
  replica/replica.go:88-151).

TPU extension: when a ``verifier`` is supplied (see
:mod:`hyperdrive_tpu.verifier`), queued votes are drained in wide windows
and signature-checked in one batched device launch before the survivors are
fed to the Process — the reference instead assumes the application
authenticated everything upstream (process/process.go:95-98).
"""

from __future__ import annotations

import queue as _queue
import threading
from collections import deque
from dataclasses import dataclass, replace
from typing import Callable, Optional

from hyperdrive_tpu.messages import Precommit, Prevote, Propose, Timeout
from hyperdrive_tpu.mq import DEFAULT_MAX_CAPACITY, MessageQueue
from hyperdrive_tpu.process import (
    Broadcaster,
    Catcher,
    Committer,
    Process,
    Proposer,
    Timer,
    Validator,
)
from hyperdrive_tpu.scheduler import RoundRobin
from hyperdrive_tpu.state import State
from hyperdrive_tpu.types import DEFAULT_HEIGHT, Height, MessageType, Round, Signatory, Step

__all__ = ["Replica", "ReplicaOptions", "ResetHeight"]


@dataclass(frozen=True)
class ReplicaOptions:
    """Immutable functional options (reference: replica/opt.go:11-46).

    ``verify_window`` sizes the batched drain handed to the Verifier; it is
    a TPU-path tunable with no reference analogue.
    """

    starting_height: Height = DEFAULT_HEIGHT
    max_capacity: int = DEFAULT_MAX_CAPACITY
    verify_window: int = 1024

    def with_starting_height(self, height: Height) -> "ReplicaOptions":
        return replace(self, starting_height=height)

    def with_max_capacity(self, capacity: int) -> "ReplicaOptions":
        return replace(self, max_capacity=capacity)

    def with_verify_window(self, window: int) -> "ReplicaOptions":
        return replace(self, verify_window=window)


@dataclass(frozen=True)
class ResetHeight:
    """Resync instruction: jump to ``height``, optionally rotating the
    signatory set (reference: replica/replica.go:266-270)."""

    height: Height
    signatories: tuple[Signatory, ...] = ()


class Replica:
    """A replicated-state-machine participant."""

    def __init__(
        self,
        opts: ReplicaOptions,
        whoami: Signatory,
        signatories: list[Signatory],
        timer: Optional[Timer],
        proposer: Optional[Proposer],
        validator: Optional[Validator],
        committer: Optional[Committer],
        catcher: Optional[Catcher],
        broadcaster: Optional[Broadcaster],
        did_handle_message: Optional[Callable[[], None]] = None,
        verifier=None,
    ):
        f = len(signatories) // 3
        self.opts = opts
        self.proc = Process(
            whoami=whoami,
            f=f,
            timer=timer,
            scheduler=RoundRobin(signatories),
            proposer=proposer,
            validator=validator,
            broadcaster=broadcaster,
            committer=committer,
            catcher=catcher,
            height=opts.starting_height,
        )
        self.procs_allowed: set[Signatory] = set(signatories)
        self.mq = MessageQueue(max_capacity=opts.max_capacity)
        self.did_handle_message = did_handle_message
        self.verifier = verifier
        self._inbox: _queue.Queue = _queue.Queue(maxsize=opts.max_capacity)
        # Synchronous-mode reentrancy guard: a broadcaster wired straight
        # back into handle() (loopback) must enqueue, not recurse — the
        # moral equivalent of the reference's inbox channel hop.
        self._handling = False
        self._pending: deque = deque()

    # ------------------------------------------------------------ sync driving

    def start(self) -> None:
        """Start the underlying Process (round 0 of the starting height)."""
        self.proc.start()

    def handle(self, msg) -> None:
        """Synchronously handle one input message, then flush the queue.

        Mirrors one iteration of the reference's Run loop
        (replica/replica.go:104-148): timeouts dispatch straight into the
        Process; votes are height-filtered and buffered; ResetHeight resets
        state and optionally rotates the signatory set.

        Reentrant calls (e.g. a loopback broadcaster invoked from inside the
        Process) are buffered and drained by the outermost call, preserving
        the reference's serialized-event-loop semantics.
        """
        self._pending.append(msg)
        if self._handling:
            return
        self._handling = True
        try:
            while self._pending:
                self._handle_one(self._pending.popleft())
        except BaseException:
            # A failing callback aborts the cascade; the undelivered tail
            # would otherwise leak into the next unrelated handle() call.
            self._pending.clear()
            raise
        finally:
            self._handling = False

    def _handle_one(self, msg) -> None:
        try:
            if isinstance(msg, Timeout):
                if msg.message_type == MessageType.PROPOSE:
                    self.proc.on_timeout_propose(msg.height, msg.round)
                elif msg.message_type == MessageType.PREVOTE:
                    self.proc.on_timeout_prevote(msg.height, msg.round)
                elif msg.message_type == MessageType.PRECOMMIT:
                    self.proc.on_timeout_precommit(msg.height, msg.round)
                else:
                    return
            elif isinstance(msg, Propose):
                if not self._filter_height(msg.height):
                    return
                self.mq.insert_propose(msg)
            elif isinstance(msg, Prevote):
                if not self._filter_height(msg.height):
                    return
                self.mq.insert_prevote(msg)
            elif isinstance(msg, Precommit):
                if not self._filter_height(msg.height):
                    return
                self.mq.insert_precommit(msg)
            elif isinstance(msg, ResetHeight):
                self.proc.state = State.default_with_height(msg.height)
                self.mq.drop_messages_below_height(msg.height)
                if msg.signatories:
                    sigs = list(msg.signatories)
                    self.proc.start_with_new_signatories(
                        len(sigs) // 3, RoundRobin(sigs)
                    )
                    self.procs_allowed = set(sigs)
            else:
                return
            self._flush()
        finally:
            if self.did_handle_message is not None:
                self.did_handle_message()

    def _flush(self) -> None:
        """Drain the queue into the Process until quiescent
        (reference: replica/replica.go:251-264).

        With a Verifier installed, votes are drained in wide windows and
        batch-verified before dispatch; without one, this is the reference's
        synchronous consume loop.
        """
        if self.verifier is None:
            while True:
                n = self.mq.consume(
                    self.proc.current_height,
                    self.proc.propose,
                    self.proc.prevote,
                    self.proc.precommit,
                    self.procs_allowed,
                )
                if n == 0:
                    return
        else:
            while True:
                window = self.mq.drain_window(
                    self.proc.current_height, self.opts.verify_window
                )
                if not window:
                    return
                keep = self.verifier.verify_batch(window)
                for msg, ok in zip(window, keep):
                    if not ok or msg.sender not in self.procs_allowed:
                        continue
                    if isinstance(msg, Propose):
                        self.proc.propose(msg)
                    elif isinstance(msg, Prevote):
                        self.proc.prevote(msg)
                    else:
                        self.proc.precommit(msg)

    def _filter_height(self, height: Height) -> bool:
        """Only current-or-future heights are kept
        (reference: replica/replica.go:247-249)."""
        return height >= self.proc.current_height

    # -------------------------------------------------------- threaded driving

    def run(self, stop: threading.Event) -> None:
        """Drain the inbox until ``stop`` fires (the reference's Run loop,
        replica/replica.go:88-151). Call from a dedicated thread."""
        self.proc.start()
        while not stop.is_set():
            try:
                msg = self._inbox.get(timeout=0.05)
            except _queue.Empty:
                continue
            self.handle(msg)
        # Match the reference: the callback also fires when the context is
        # cancelled (replica/replica.go:16-18).
        if self.did_handle_message is not None:
            self.did_handle_message()

    def _enqueue(self, msg, stop: Optional[threading.Event] = None) -> None:
        while True:
            try:
                self._inbox.put(msg, timeout=0.05)
                return
            except _queue.Full:
                if stop is not None and stop.is_set():
                    return

    def propose(self, propose: Propose, stop=None) -> None:
        """Async insert (reference: replica/replica.go:156-161)."""
        self._enqueue(propose, stop)

    def prevote(self, prevote: Prevote, stop=None) -> None:
        self._enqueue(prevote, stop)

    def precommit(self, precommit: Precommit, stop=None) -> None:
        self._enqueue(precommit, stop)

    def timeout(self, timeout: Timeout, stop=None) -> None:
        self._enqueue(timeout, stop)

    def reset_height(
        self, new_height: Height, signatories: list[Signatory] = (), stop=None
    ) -> None:
        """Jump a lagging replica to ``new_height`` (> current), dropping
        stale queued messages (reference: replica/replica.go:222-235)."""
        if new_height <= self.proc.current_height:
            return
        self._enqueue(ResetHeight(new_height, tuple(signatories)), stop)

    # ------------------------------------------------------------- inspection

    def current_state(self) -> tuple[Height, Round, Step]:
        return (
            self.proc.current_height,
            self.proc.current_round,
            self.proc.current_step,
        )

    def current_height(self) -> Height:
        return self.proc.current_height
