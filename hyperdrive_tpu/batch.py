"""Messages as NumPy struct-array rows — the dense message layer.

SURVEY.md §7.2 step 1: "messages double as NumPy struct-array rows". A
:class:`MessageBlock` is a window of consensus messages in columnar form —
exactly the layout the device data path consumes:

- :meth:`MessageBlock.verify_items` / :meth:`MessageBlock.pack_arrays`
  feed the Ed25519 batch verifier (one contiguous array per field, no
  per-message marshalling);
- :meth:`MessageBlock.tally_inputs` builds the ``[rounds, validators, 8]``
  vote tensor + presence mask that :mod:`hyperdrive_tpu.ops.tally` fuses
  behind the verification mask;
- :meth:`MessageBlock.digests` computes signing digests with vectorized
  preimage assembly (one hashlib call per row over a prebuilt byte
  matrix — the serialization work is columnar).

Row layout mirrors the wire envelope (`messages.marshal_message`); Propose
payloads are variable-length and rare, so they ride in a sparse side table
rather than widening every row.
"""

from __future__ import annotations

import hashlib

import numpy as np

from hyperdrive_tpu.messages import Precommit, Prevote, Propose, Timeout
from hyperdrive_tpu.types import INVALID_ROUND, MessageType

__all__ = ["MESSAGE_DTYPE", "MessageBlock"]

#: One consensus message as a fixed-width structured row.
MESSAGE_DTYPE = np.dtype(
    [
        ("type", "<i1"),
        ("height", "<i8"),
        ("round", "<i8"),
        ("valid_round", "<i8"),
        ("value", "u1", 32),
        ("sender", "u1", 32),
        ("signature", "u1", 64),
        ("has_sig", "?"),
    ]
)

_TYPE_TAG = {
    Propose: int(MessageType.PROPOSE),
    Prevote: int(MessageType.PREVOTE),
    Precommit: int(MessageType.PRECOMMIT),
}

def _bytes_col(parts: list[bytes], width: int) -> np.ndarray:
    return np.frombuffer(b"".join(parts), dtype=np.uint8).reshape(
        len(parts), width
    )


class MessageBlock:
    """A window of Propose/Prevote/Precommit messages in columnar form."""

    __slots__ = ("rows", "payloads")

    def __init__(self, rows: np.ndarray, payloads: dict[int, bytes]):
        self.rows = rows
        #: Sparse row index -> Propose payload bytes (empty payloads and
        #: non-propose rows are absent).
        self.payloads = payloads

    def __len__(self) -> int:
        return len(self.rows)

    # ------------------------------------------------------------ construct

    @classmethod
    def from_messages(cls, msgs) -> "MessageBlock":
        """Columnarize a window. Timeouts are control events, not votes —
        they have no row representation and are rejected."""
        n = len(msgs)
        rows = np.zeros(n, dtype=MESSAGE_DTYPE)
        if n == 0:
            return cls(rows, {})
        values, senders, sigs = [], [], []
        payloads: dict[int, bytes] = {}
        heights = np.empty(n, dtype=np.int64)
        rounds = np.empty(n, dtype=np.int64)
        vrounds = np.full(n, INVALID_ROUND, dtype=np.int64)
        types = np.empty(n, dtype=np.int8)
        has_sig = np.zeros(n, dtype=bool)
        for i, m in enumerate(msgs):
            tag = _TYPE_TAG.get(type(m))
            if tag is None:
                raise TypeError(f"not a batchable message: {type(m)!r}")
            types[i] = tag
            heights[i] = m.height
            rounds[i] = m.round
            values.append(m.value)
            senders.append(m.sender)
            if isinstance(m, Propose):
                vrounds[i] = m.valid_round
                if m.payload:
                    payloads[i] = m.payload
            sig = m.signature
            if sig and len(sig) == 64:
                sigs.append(sig)
                has_sig[i] = True
            else:
                # Missing/wrong-length signatures cannot ride in a fixed
                # 64-byte row; the row is zero-filled ONLY as padding and
                # has_sig=False gates it — every consumer must route such
                # rows to deterministic rejection (verify_items emits b"",
                # which the packers length-check to invalid), never hand
                # the zero bytes to the verifier as if they were the
                # signature (a zero sig can verify under an adversarial
                # small-order pubkey).
                sigs.append(b"\x00" * 64)
        rows["type"] = types
        rows["height"] = heights
        rows["round"] = rounds
        rows["valid_round"] = vrounds
        rows["value"] = _bytes_col(values, 32)
        rows["sender"] = _bytes_col(senders, 32)
        rows["signature"] = _bytes_col(sigs, 64)
        rows["has_sig"] = has_sig
        return cls(rows, payloads)

    def to_messages(self) -> list:
        """Materialize the rows back into message objects (exact inverse of
        :meth:`from_messages` for well-formed inputs)."""
        out = []
        for i, row in enumerate(self.rows):
            ty = int(row["type"])
            common = dict(
                height=int(row["height"]),
                round=int(row["round"]),
                value=row["value"].tobytes(),
                sender=row["sender"].tobytes(),
            )
            if ty == int(MessageType.PROPOSE):
                msg = Propose(
                    valid_round=int(row["valid_round"]),
                    payload=self.payloads.get(i, b""),
                    **common,
                )
            elif ty == int(MessageType.PREVOTE):
                msg = Prevote(**common)
            else:
                msg = Precommit(**common)
            if row["has_sig"]:
                msg = msg.with_signature(row["signature"].tobytes())
            out.append(msg)
        return out

    # -------------------------------------------------------------- digests

    def digests(self) -> list[bytes]:
        """Per-row signing digests, preimages assembled columnar.

        Vote digests are sha256(tag || i64 h || i64 r || value); proposes
        additionally splice valid_round (and the payload hash when one
        rides along), handled per-row since proposes are ~1/(2n) of
        traffic.
        """
        n = len(self.rows)
        pre = np.zeros((n, 1 + 8 + 8 + 32), dtype=np.uint8)
        pre[:, 0:1] = self.rows["type"].astype(np.uint8).reshape(n, 1)
        pre[:, 1:9] = self.rows["height"].astype("<i8").view(np.uint8).reshape(n, 8)
        pre[:, 9:17] = self.rows["round"].astype("<i8").view(np.uint8).reshape(n, 8)
        pre[:, 17:49] = self.rows["value"]
        flat = pre.tobytes()
        w = pre.shape[1]
        out: list[bytes] = []
        is_propose = self.rows["type"] == int(MessageType.PROPOSE)
        for i in range(n):
            if is_propose[i]:
                row = self.rows[i]
                buf = (
                    b"\x01"
                    + row["height"].astype("<i8").tobytes()
                    + row["round"].astype("<i8").tobytes()
                    + row["valid_round"].astype("<i8").tobytes()
                    + row["value"].tobytes()
                )
                payload = self.payloads.get(i, b"")
                if payload:
                    buf += hashlib.sha256(payload).digest()
                out.append(hashlib.sha256(buf).digest())
            else:
                out.append(hashlib.sha256(flat[i * w : (i + 1) * w]).digest())
        return out

    # -------------------------------------------------------- verifier feed

    def verify_items(self) -> list[tuple[bytes, bytes, bytes]]:
        """(pub, digest, sig) triples for the Verifier protocol. Rows with
        ``has_sig=False`` (absent or wrong-length signature) emit ``b""``
        so the packer's length check rejects them deterministically — the
        same verdict the object path gives them — instead of forwarding
        the zero padding as a signature."""
        digests = self.digests()
        senders = self.rows["sender"]
        sigs = self.rows["signature"]
        has_sig = self.rows["has_sig"]
        return [
            (
                senders[i].tobytes(),
                digests[i],
                sigs[i].tobytes() if has_sig[i] else b"",
            )
            for i in range(len(self.rows))
        ]

    def pack_arrays(self):
        """Contiguous (pubs[n,32], digests[n,32], sigs[n,64], has_sig[n])
        uint8/bool arrays — the zero-copy feed for the native packer ABI.
        Callers MUST mask verdicts with ``has_sig``: a False lane's
        signature bytes are padding, not a signature."""
        digests = _bytes_col(self.digests(), 32)
        return (
            np.ascontiguousarray(self.rows["sender"]),
            digests,
            np.ascontiguousarray(self.rows["signature"]),
            np.ascontiguousarray(self.rows["has_sig"]),
        )

    # ----------------------------------------------------------- tally feed

    def tally_inputs(self, signatories: list[bytes], vote_type: MessageType,
                     height: int):
        """Build the device tally tensors for one vote type at one height.

        Returns (rounds, vote_vals [R, V, 8] int32, present [R, V] bool)
        where R spans the distinct rounds this block holds for that
        (type, height) and V indexes ``signatories``. Unknown senders and
        duplicate votes (first wins, the log rule) are excluded. Feed
        ``present & verify_mask`` to :func:`hyperdrive_tpu.ops.tally.
        tally_counts` to fuse quorum counting behind signature
        verification.
        """
        sel = (self.rows["type"] == int(vote_type)) & (
            self.rows["height"] == height
        )
        idx = np.nonzero(sel)[0]
        rounds = sorted({int(self.rows["round"][i]) for i in idx})
        round_pos = {r: j for j, r in enumerate(rounds)}
        sender_pos = {s: v for v, s in enumerate(signatories)}
        R, V = max(len(rounds), 1), len(signatories)
        vote_vals = np.zeros((R, V, 8), dtype=np.int32)
        present = np.zeros((R, V), dtype=bool)
        for i in idx:
            v = sender_pos.get(self.rows["sender"][i].tobytes())
            if v is None:
                continue
            rj = round_pos[int(self.rows["round"][i])]
            if present[rj, v]:
                continue  # duplicate: first vote wins (the log rule)
            present[rj, v] = True
            vote_vals[rj, v] = (
                self.rows["value"][i].view("<i4").astype(np.int32)
            )
        return rounds, vote_vals, present
