"""Messages as NumPy struct-array rows — the dense message layer.

SURVEY.md §7.2 step 1: "messages double as NumPy struct-array rows". A
:class:`MessageBlock` is a window of consensus messages in columnar form —
exactly the layout the device data path consumes:

- :meth:`MessageBlock.verify_items` / :meth:`MessageBlock.pack_arrays`
  feed the Ed25519 batch verifier (one contiguous array per field, no
  per-message marshalling);
- :meth:`MessageBlock.tally_inputs` builds the ``[rounds, validators, 8]``
  vote tensor + presence mask that :mod:`hyperdrive_tpu.ops.tally` fuses
  behind the verification mask;
- :meth:`MessageBlock.digests` computes signing digests with vectorized
  preimage assembly (one hashlib call per row over a prebuilt byte
  matrix — the serialization work is columnar).

Row layout mirrors the wire envelope (`messages.marshal_message`); Propose
payloads are variable-length and rare, so they ride in a sparse side table
rather than widening every row.
"""

from __future__ import annotations

import hashlib

import numpy as np

from hyperdrive_tpu.messages import Precommit, Prevote, Propose, Timeout
from hyperdrive_tpu.types import INVALID_ROUND, MessageType

__all__ = ["MESSAGE_DTYPE", "MessageBlock", "WindowColumns"]

#: One consensus message as a fixed-width structured row.
MESSAGE_DTYPE = np.dtype(
    [
        ("type", "<i1"),
        ("height", "<i8"),
        ("round", "<i8"),
        ("valid_round", "<i8"),
        ("value", "u1", 32),
        ("sender", "u1", 32),
        ("signature", "u1", 64),
        ("has_sig", "?"),
    ]
)

_TYPE_TAG = {
    Propose: int(MessageType.PROPOSE),
    Prevote: int(MessageType.PREVOTE),
    Precommit: int(MessageType.PRECOMMIT),
}

def _bytes_col(parts: list[bytes], width: int) -> np.ndarray:
    return np.frombuffer(b"".join(parts), dtype=np.uint8).reshape(
        len(parts), width
    )


class MessageBlock:
    """A window of Propose/Prevote/Precommit messages in columnar form."""

    __slots__ = ("rows", "payloads")

    def __init__(self, rows: np.ndarray, payloads: dict[int, bytes]):
        self.rows = rows
        #: Sparse row index -> Propose payload bytes (empty payloads and
        #: non-propose rows are absent).
        self.payloads = payloads

    def __len__(self) -> int:
        return len(self.rows)

    # ------------------------------------------------------------ construct

    @classmethod
    def from_messages(cls, msgs) -> "MessageBlock":
        """Columnarize a window. Timeouts are control events, not votes —
        they have no row representation and are rejected."""
        n = len(msgs)
        rows = np.zeros(n, dtype=MESSAGE_DTYPE)
        if n == 0:
            return cls(rows, {})
        values, senders, sigs = [], [], []
        payloads: dict[int, bytes] = {}
        heights = np.empty(n, dtype=np.int64)
        rounds = np.empty(n, dtype=np.int64)
        vrounds = np.full(n, INVALID_ROUND, dtype=np.int64)
        types = np.empty(n, dtype=np.int8)
        has_sig = np.zeros(n, dtype=bool)
        for i, m in enumerate(msgs):
            tag = _TYPE_TAG.get(type(m))
            if tag is None:
                raise TypeError(f"not a batchable message: {type(m)!r}")
            types[i] = tag
            heights[i] = m.height
            rounds[i] = m.round
            values.append(m.value)
            senders.append(m.sender)
            if isinstance(m, Propose):
                vrounds[i] = m.valid_round
                if m.payload:
                    payloads[i] = m.payload
            sig = m.signature
            if sig and len(sig) == 64:
                sigs.append(sig)
                has_sig[i] = True
            else:
                # Missing/wrong-length signatures cannot ride in a fixed
                # 64-byte row; the row is zero-filled ONLY as padding and
                # has_sig=False gates it — every consumer must route such
                # rows to deterministic rejection (verify_items emits b"",
                # which the packers length-check to invalid), never hand
                # the zero bytes to the verifier as if they were the
                # signature (a zero sig can verify under an adversarial
                # small-order pubkey).
                sigs.append(b"\x00" * 64)
        rows["type"] = types
        rows["height"] = heights
        rows["round"] = rounds
        rows["valid_round"] = vrounds
        rows["value"] = _bytes_col(values, 32)
        rows["sender"] = _bytes_col(senders, 32)
        rows["signature"] = _bytes_col(sigs, 64)
        rows["has_sig"] = has_sig
        return cls(rows, payloads)

    def message_at(self, i: int):
        """Materialize one row into its message object (the lazy unit of
        :meth:`to_messages`; the columnar settle fast path calls it only
        for rows the automaton keeps or reports)."""
        row = self.rows[i]
        ty = int(row["type"])
        common = dict(
            height=int(row["height"]),
            round=int(row["round"]),
            value=row["value"].tobytes(),
            sender=row["sender"].tobytes(),
        )
        if ty == int(MessageType.PROPOSE):
            msg = Propose(
                valid_round=int(row["valid_round"]),
                payload=self.payloads.get(i, b""),
                **common,
            )
        elif ty == int(MessageType.PREVOTE):
            msg = Prevote(**common)
        else:
            msg = Precommit(**common)
        if row["has_sig"]:
            msg = msg.with_signature(row["signature"].tobytes())
        return msg

    def to_messages(self) -> list:
        """Materialize the rows back into message objects (exact inverse of
        :meth:`from_messages` for well-formed inputs)."""
        return [self.message_at(i) for i in range(len(self.rows))]

    def columns(self) -> "WindowColumns":
        """A :class:`WindowColumns` view over this block: the columnar
        ingest entry point for wire-delivered windows — rows flow into the
        automaton without up-front object materialization."""
        return WindowColumns.from_block(self)

    # -------------------------------------------------------------- digests

    def digests(self) -> list[bytes]:
        """Per-row signing digests, preimages assembled columnar.

        Vote digests are sha256(tag || i64 h || i64 r || value); proposes
        additionally splice valid_round (and the payload hash when one
        rides along), handled per-row since proposes are ~1/(2n) of
        traffic.
        """
        n = len(self.rows)
        pre = np.zeros((n, 1 + 8 + 8 + 32), dtype=np.uint8)
        pre[:, 0:1] = self.rows["type"].astype(np.uint8).reshape(n, 1)
        pre[:, 1:9] = self.rows["height"].astype("<i8").view(np.uint8).reshape(n, 8)
        pre[:, 9:17] = self.rows["round"].astype("<i8").view(np.uint8).reshape(n, 8)
        pre[:, 17:49] = self.rows["value"]
        flat = pre.tobytes()
        w = pre.shape[1]
        out: list[bytes] = []
        is_propose = self.rows["type"] == int(MessageType.PROPOSE)
        for i in range(n):
            if is_propose[i]:
                row = self.rows[i]
                buf = (
                    b"\x01"
                    + row["height"].astype("<i8").tobytes()
                    + row["round"].astype("<i8").tobytes()
                    + row["valid_round"].astype("<i8").tobytes()
                    + row["value"].tobytes()
                )
                payload = self.payloads.get(i, b"")
                if payload:
                    buf += hashlib.sha256(payload).digest()
                out.append(hashlib.sha256(buf).digest())
            else:
                out.append(hashlib.sha256(flat[i * w : (i + 1) * w]).digest())
        return out

    # -------------------------------------------------------- verifier feed

    def verify_items(self) -> list[tuple[bytes, bytes, bytes]]:
        """(pub, digest, sig) triples for the Verifier protocol. Rows with
        ``has_sig=False`` (absent or wrong-length signature) emit ``b""``
        so the packer's length check rejects them deterministically — the
        same verdict the object path gives them — instead of forwarding
        the zero padding as a signature."""
        digests = self.digests()
        senders = self.rows["sender"]
        sigs = self.rows["signature"]
        has_sig = self.rows["has_sig"]
        return [
            (
                senders[i].tobytes(),
                digests[i],
                sigs[i].tobytes() if has_sig[i] else b"",
            )
            for i in range(len(self.rows))
        ]

    def pack_arrays(self):
        """Contiguous (pubs[n,32], digests[n,32], sigs[n,64], has_sig[n])
        uint8/bool arrays — the zero-copy feed for the native packer ABI.
        Callers MUST mask verdicts with ``has_sig``: a False lane's
        signature bytes are padding, not a signature."""
        digests = _bytes_col(self.digests(), 32)
        return (
            np.ascontiguousarray(self.rows["sender"]),
            digests,
            np.ascontiguousarray(self.rows["signature"]),
            np.ascontiguousarray(self.rows["has_sig"]),
        )

    # ----------------------------------------------------------- tally feed

    def tally_inputs(self, signatories: list[bytes], vote_type: MessageType,
                     height: int):
        """Build the device tally tensors for one vote type at one height.

        Returns (rounds, vote_vals [R, V, 8] int32, present [R, V] bool)
        where R spans the distinct rounds this block holds for that
        (type, height) and V indexes ``signatories``. Unknown senders and
        duplicate votes (first wins, the log rule) are excluded. Feed
        ``present & verify_mask`` to :func:`hyperdrive_tpu.ops.tally.
        tally_counts` to fuse quorum counting behind signature
        verification.
        """
        sel = (self.rows["type"] == int(vote_type)) & (
            self.rows["height"] == height
        )
        idx = np.nonzero(sel)[0]
        rounds = sorted({int(self.rows["round"][i]) for i in idx})
        round_pos = {r: j for j, r in enumerate(rounds)}
        sender_pos = {s: v for v, s in enumerate(signatories)}
        R, V = max(len(rounds), 1), len(signatories)
        vote_vals = np.zeros((R, V, 8), dtype=np.int32)
        present = np.zeros((R, V), dtype=bool)
        for i in idx:
            v = sender_pos.get(self.rows["sender"][i].tobytes())
            if v is None:
                continue
            rj = round_pos[int(self.rows["round"][i])]
            if present[rj, v]:
                continue  # duplicate: first vote wins (the log rule)
            present[rj, v] = True
            vote_vals[rj, v] = (
                self.rows["value"][i].view("<i4").astype(np.int32)
            )
        return rounds, vote_vals, present


class WindowColumns:
    """A settle window decomposed into per-row columns plus run segments —
    the feed of the columnar ingest fast path (``Process.
    ingest_insert_cols``).

    The object-path hot loop pays per-message attribute access and type
    dispatch once per (message, replica); a lockstep settle re-pays it for
    every one of the n replicas sharing the same window. This view hoists
    that extraction to ONE pass per window: plain Python lists for the
    fields the insert loop reads (kind tag, height, round, sender, value)
    and maximal consecutive ``runs`` sharing (kind, height, round), so the
    per-replica loop fetches its round-log views once per run instead of
    re-checking per row.

    Message objects stay the log/checkpoint/evidence source of truth, so
    the fast path still stores them — but via :meth:`msg`, which is a list
    index when the window already holds objects (:meth:`from_messages`)
    and lazy row materialization when it came off the wire
    (:meth:`from_block`): rows the automaton filters out (wrong height,
    duplicate, unverified) never become objects at all.
    """

    __slots__ = ("n", "kinds", "heights", "rounds", "senders", "values",
                 "runs", "msgs", "_block")

    #: Row kind tags — the MessageType wire tags, matching
    #: ``MESSAGE_DTYPE``'s ``type`` column.
    KIND_PROPOSE = int(MessageType.PROPOSE)
    KIND_PREVOTE = int(MessageType.PREVOTE)
    KIND_PRECOMMIT = int(MessageType.PRECOMMIT)

    def __init__(self, kinds, heights, rounds, senders, values, msgs,
                 block=None):
        self.n = len(kinds)
        self.kinds = kinds
        self.heights = heights
        self.rounds = rounds
        self.senders = senders
        self.values = values
        #: Per-row message objects; ``None`` entries materialize lazily
        #: from ``_block`` on first :meth:`msg` access.
        self.msgs = msgs
        self._block = block
        self.runs = self._segment()

    def _segment(self):
        """Maximal consecutive (kind, height, round) runs as
        (kind, height, round, start, end) tuples. Windows arrive (height,
        round)-sorted so runs are long; adversarial interleavings just
        degrade to shorter runs with identical semantics (row order inside
        and across runs is preserved)."""
        kinds, heights, rounds = self.kinds, self.heights, self.rounds
        runs = []
        n = self.n
        i = 0
        while i < n:
            k, h, r = kinds[i], heights[i], rounds[i]
            j = i + 1
            while j < n and kinds[j] == k and heights[j] == h \
                    and rounds[j] == r:
                j += 1
            runs.append((k, h, r, i, j))
            i = j
        return runs

    @classmethod
    def from_messages(cls, msgs) -> "WindowColumns":
        """Columnarize a window of live message objects (the simulator's
        shared-superstep lane): one extraction pass serves every replica
        that ingests the window."""
        kinds = []
        heights = []
        rounds = []
        senders = []
        values = []
        for m in msgs:
            tag = _TYPE_TAG.get(type(m))
            if tag is None:
                raise TypeError(f"not a batchable message: {type(m)!r}")
            kinds.append(tag)
            heights.append(m.height)
            rounds.append(m.round)
            senders.append(m.sender)
            values.append(m.value)
        return cls(kinds, heights, rounds, senders, values,
                   msgs if isinstance(msgs, list) else list(msgs))

    @classmethod
    def from_block(cls, block: MessageBlock) -> "WindowColumns":
        """Columnar view over wire rows; message objects materialize only
        on demand (accepted/equivocating/propose rows)."""
        rows = block.rows
        n = len(rows)
        senders = [s.tobytes() for s in rows["sender"]]
        values = [v.tobytes() for v in rows["value"]]
        return cls(
            rows["type"].tolist(), rows["height"].tolist(),
            rows["round"].tolist(), senders, values,
            [None] * n, block=block,
        )

    def msg(self, i: int):
        """Row ``i`` as a message object (cached)."""
        m = self.msgs[i]
        if m is None:
            m = self.msgs[i] = self._block.message_at(i)
        return m
