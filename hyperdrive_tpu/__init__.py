"""hyperdrive_tpu — a TPU-native Byzantine fault tolerant consensus framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of the reference
Tendermint-BFT library ("The latest gossip on BFT consensus",
arXiv:1807.04938; reference layout surveyed in SURVEY.md):

- ``hyperdrive_tpu.process``   — the consensus state automaton (host-side).
- ``hyperdrive_tpu.mq``        — per-sender (height, round)-sorted bounded queues.
- ``hyperdrive_tpu.scheduler`` — deterministic proposer election.
- ``hyperdrive_tpu.timer``     — linearly scaled timeout scheduling.
- ``hyperdrive_tpu.replica``   — the replica driver / event loop.
- ``hyperdrive_tpu.crypto``    — Ed25519 identity, signing, Shamir sharing (host).
- ``hyperdrive_tpu.ops``       — TPU kernels: GF(2^255-19) limb arithmetic,
  batched Ed25519 verification, quorum tallies, Shamir reconstruction.
- ``hyperdrive_tpu.parallel``  — SPMD sharding of verification + tallies over
  a ``jax.sharding.Mesh`` (ICI/DCN collectives).
- ``hyperdrive_tpu.harness``   — deterministic in-process network simulator
  with seeded record/replay and fault/Byzantine injection.
- ``hyperdrive_tpu.transport`` — loopback-TCP binding of the Broadcaster
  seam (full-mesh, length-framed signed envelopes).
- ``hyperdrive_tpu.tallyflush``— per-replica device-tally flushing: the
  deployment (n = 1) shape of the vote grid behind a threaded replica.
- ``hyperdrive_tpu.native``    — C++ host runtime (batch signature packing:
  point decompression, SHA-512 challenges, limb packing) via ctypes.
- ``hyperdrive_tpu.utils``     — tracing/metrics, structured logging, and
  crash-restart checkpointing.

The consensus control flow (branchy, per-message, tiny state) runs on the
host; the TPU executes the batchable numeric work: vote signature
verification, 2f+1 tallies, and Shamir share reconstruction, vectorized over
validators x in-flight (height, round) pairs.
"""

from hyperdrive_tpu.types import (
    DEFAULT_HEIGHT,
    DEFAULT_ROUND,
    INVALID_ROUND,
    NIL_VALUE,
    Step,
)

__version__ = "0.1.0"

__all__ = [
    "DEFAULT_HEIGHT",
    "DEFAULT_ROUND",
    "INVALID_ROUND",
    "NIL_VALUE",
    "Step",
    "__version__",
]
