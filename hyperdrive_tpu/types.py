"""Core scalar types and sentinels for the consensus automaton.

Capability parity with the reference's type layer
(``process/state.go:283-338`` in the reference tree): heights and rounds are
signed 64-bit integers, steps are a tiny enum, values and signatories are
32-byte identifiers. The new framework makes two deliberate design changes:

- A ``Signatory`` is the raw 32-byte Ed25519 public key (the reference uses a
  Keccak hash of a secp256k1 public key, ``renproject/id``). Using the key
  itself as the identity removes one indirection and is exactly the array
  layout the TPU verification kernel wants.
- All types are plain Python ``int`` / ``bytes`` rather than wrapper classes,
  so messages can be packed densely into NumPy structured arrays for the
  batched device path.
"""

from __future__ import annotations

import enum

# Heights and rounds are int64 on the wire. Python ints are unbounded; the
# codec enforces the 64-bit range at serialization boundaries.
Height = int
Round = int

# 32-byte hash of a proposed value (a block, in blockchain terms).
Value = bytes

# 32-byte replica identity (Ed25519 public key).
Signatory = bytes

#: The genesis block is assumed to exist at height 0, so consensus starts at 1
#: (reference: process/state.go:12-14).
DEFAULT_HEIGHT: Height = 1
DEFAULT_ROUND: Round = 0

#: Reserved round meaning "no such round" — used for LockedRound/ValidRound
#: before any lock exists (reference: process/state.go:304).
INVALID_ROUND: Round = -1

#: Reserved all-zero value meaning "vote for nothing / advance the round"
#: (reference: process/state.go:337).
NIL_VALUE: Value = b"\x00" * 32

#: Reserved all-zero signatory (never a valid Ed25519 key in practice).
NIL_SIGNATORY: Signatory = b"\x00" * 32

INT64_MIN = -(1 << 63)
INT64_MAX = (1 << 63) - 1


class Step(enum.IntEnum):
    """The three steps of a consensus round (reference: process/state.go:288-295)."""

    PROPOSING = 0
    PREVOTING = 1
    PRECOMMITTING = 2


class MessageType(enum.IntEnum):
    """Wire tags for consensus messages (reference: process/message.go:11-22)."""

    PROPOSE = 1
    PREVOTE = 2
    PRECOMMIT = 3
    TIMEOUT = 4


def check_value(value: bytes, what: str = "value") -> bytes:
    """Validate that ``value`` is exactly 32 bytes."""
    if not isinstance(value, (bytes, bytearray)) or len(value) != 32:
        raise ValueError(f"{what} must be 32 bytes, got {value!r}")
    return bytes(value)


def check_int64(v: int, what: str = "int") -> int:
    """Validate that ``v`` fits a signed 64-bit integer."""
    if not INT64_MIN <= v <= INT64_MAX:
        raise ValueError(f"{what} out of int64 range: {v}")
    return v
