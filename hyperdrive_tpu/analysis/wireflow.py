"""Interprocedural wire-taint dataflow: rules HD007–HD010.

The first six hdlint rules are per-file and syntactic. The wire rules
cannot be: untrusted bytes enter in ``transport.py`` and flow through
decoders defined two modules away, and codec-pair completeness is a
property of the PACKAGE, not of any single file. This module builds a
package index over every :class:`~hyperdrive_tpu.analysis.engine.
FileContext` the engine parsed — every function, every call edge
resolved by leaf name, every ``@wire_codec`` registration and
``declare_wire_budget`` call, every ``TAG_*``/``KIND_*`` constant
group — then seeds a taint lattice at the wire entry points and
propagates assignment flow across call edges to a fixpoint. Nothing is
ever imported: like the rest of hdlint, the analysis reads the same
decorators the runtime registry executes, purely from the AST.

The lattice is deliberately byte-centric:

* **wire bytes** — values a Byzantine peer authored: results of socket
  receives (``_recv_exact``/``recv``), parameters of ``@wire_entry``
  functions, parameters of registered decode-role codecs (a decoder's
  input is untrusted BY CONTRACT — that is what the registration
  asserts), file reads inside ``@wire_entry`` replay loaders, and
  anything sliced/concatenated from the above.
* **wire ints** — integers derived from wire bytes: reader primitives
  (``r.u32()`` …), ``int.from_bytes`` over tainted bytes,
  ``struct.unpack`` of tainted buffers, subscripts of tainted bytes.
* **laundering** — passing wire bytes to ``Reader``/
  ``maybe_wire_reader`` or a *registered* decoder produces clean
  values: the codec layer's byte budget plus the decoder's own caps
  are the validation boundary (and HD008 audits the decoders
  themselves, so the boundary is not taken on faith).

HD007 flags raw wire bytes reaching digest/commit/state scope without
crossing that boundary. HD008 flags allocation-shaped uses of wire
ints (``range``/``bytearray``/sequence-repeat) with no bounds check,
and ``int.from_bytes`` over unbounded tainted buffers; a loop that
consumes its own reader per iteration is exempt (the byte budget
bounds it). HD009 proves codec-registry closure and pair
completeness. HD010 proves frame-tag dispatch exhaustiveness in every
codec-bearing module. The runtime complement is HDS005
(analysis/sanitizer.py): the same registered budgets, enforced on live
frames under ``HD_SANITIZE``.
"""

from __future__ import annotations

import ast
import os

from hyperdrive_tpu.analysis.engine import Finding

__all__ = [
    "WireTaintRule",
    "WireBoundsRule",
    "CodecPairRule",
    "TagDispatchRule",
    "PackageIndex",
    "wire_report",
]

#: Calls whose result is attacker-authored bytes wherever they appear.
_SOURCE_CALLS = frozenset({"_recv_exact", "recv", "recvfrom", "recv_into"})
#: Inside a @wire_entry function, file reads are replay input — the
#: chaos/flight loaders feed recorded (possibly mutated) frames back in.
_ENTRY_SOURCE_CALLS = _SOURCE_CALLS | frozenset({"read"})
#: Reader primitives yielding wire ints / validated byte fields.
_READER_INT_METHODS = frozenset({"u8", "u16", "u32", "u64", "i8", "i64"})
_READER_METHODS = _READER_INT_METHODS | frozenset(
    {"raw", "bytes32", "f64", "bool", "done", "remaining_bytes"}
)
#: Constructors that launder wire bytes into budget-accounted reads.
_LAUNDER_CALLS = frozenset({"Reader", "maybe_wire_reader"})
#: Digest/commit sinks: hash constructors, incremental hash feeding,
#: and the committer seam. Raw wire bytes must never reach these.
_SINK_CALLS = frozenset(
    {"sha256", "sha512", "sha3_256", "blake2b", "blake2s", "md5",
     "update", "commit"}
)
#: Allocation shapes a wire int must not size unguarded.
_ALLOC_CALLS = frozenset({"range", "bytearray", "bytes"})

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_TAINT_ROUNDS = 8


# --------------------------------------------------------------- helpers


def _leaf(node):
    """Rightmost identifier of a Name/Attribute/Call target, or None."""
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _const_int(node, env=None):
    """Evaluate a compile-time int expression (Constant, module
    constant by Name, +,-,*,<<,// of the same). None when dynamic."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name) and env is not None:
        return env.get(node.id)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_int(node.operand, env)
        return None if v is None else -v
    if isinstance(node, ast.BinOp):
        lhs = _const_int(node.left, env)
        rhs = _const_int(node.right, env)
        if lhs is None or rhs is None:
            return None
        if isinstance(node.op, ast.Add):
            return lhs + rhs
        if isinstance(node.op, ast.Sub):
            return lhs - rhs
        if isinstance(node.op, ast.Mult):
            return lhs * rhs
        if isinstance(node.op, ast.LShift):
            return lhs << rhs
        if isinstance(node.op, ast.FloorDiv) and rhs:
            return lhs // rhs
    return None


def _decorators(node):
    """{leaf name: decorator node} for a function/class definition."""
    out = {}
    for dec in node.decorator_list:
        name = _leaf(dec)
        if name is not None:
            out[name] = dec
    return out


def _slice_width(sub, env):
    """Constant byte width of ``x[a:b]``, or None. Recognizes const
    bounds and the ``x[off : off + K]`` cursor idiom."""
    sl = sub.slice
    if not isinstance(sl, ast.Slice) or sl.step is not None:
        return None
    lo, hi = sl.lower, sl.upper
    lo_c = 0 if lo is None else _const_int(lo, env)
    hi_c = None if hi is None else _const_int(hi, env)
    if lo_c is not None and hi_c is not None:
        return max(0, hi_c - lo_c)
    if lo is not None and isinstance(hi, ast.BinOp) \
            and isinstance(hi.op, ast.Add):
        k = _const_int(hi.right, env)
        if k is not None and ast.dump(hi.left) == ast.dump(lo):
            return k
        k = _const_int(hi.left, env)
        if k is not None and ast.dump(hi.right) == ast.dump(lo):
            return k
    return None


# --------------------------------------------------------- package index


class _Func:
    __slots__ = ("node", "ctx", "qual", "leaf", "params", "is_method",
                 "decorators")

    def __init__(self, node, ctx, qual, is_method):
        self.node = node
        self.ctx = ctx
        self.qual = qual
        self.leaf = node.name
        args = node.args
        self.params = [a.arg for a in (
            args.posonlyargs + args.args
        )]
        self.is_method = is_method
        self.decorators = _decorators(node)


class _Codec:
    __slots__ = ("tag", "max_bytes", "version", "role", "name", "path",
                 "line")

    def __init__(self, tag, max_bytes, version, role, name, path, line):
        self.tag = tag
        self.max_bytes = max_bytes
        self.version = version
        self.role = role
        self.name = name
        self.path = path
        self.line = line


def _codec_role(node):
    if isinstance(node, ast.ClassDef):
        return "both"
    leaf = node.name.lstrip("_")
    if leaf.startswith(("decode", "unmarshal")):
        return "decode"
    if leaf.startswith(("encode", "marshal")):
        return "encode"
    return "both"


class PackageIndex:
    """Everything the wire rules need, built once from the parsed
    FileContexts and shared by all four ``check_package`` calls."""

    def __init__(self, ctxs):
        self.ctxs = list(ctxs)
        #: leaf name -> [_Func]: the call-resolution table.
        self.by_leaf: dict = {}
        #: all functions, definition order.
        self.funcs: list = []
        #: every @wire_codec registration found in the AST.
        self.codecs: list = []
        #: every declare_wire_budget(tag, n) module-level call.
        self.budgets: list = []
        #: path -> {const name: int} module constant environment.
        self.const_env: dict = {}
        #: leafs that launder taint (registered decoders + Reader).
        self.launder_leafs: set = set(_LAUNDER_CALLS)
        #: (dec node, def node, ctx) registrations, evaluated after every
        #: module's constants are known (max_bytes may name a constant
        #: IMPORTED from another module, e.g. transport._MAX_FRAME).
        self._pending_codecs: list = []
        for ctx in self.ctxs:
            self._index_file(ctx)
        #: name -> int across every module: the cross-module fallback for
        #: max_bytes expressions naming an imported constant. Ambiguous
        #: names (same name, different values) are dropped — a budget
        #: must resolve uniquely or not at all.
        self.global_consts: dict = {}
        dropped: set = set()
        for env in self.const_env.values():
            for name, value in env.items():
                if name in dropped:
                    continue
                if name in self.global_consts \
                        and self.global_consts[name] != value:
                    del self.global_consts[name]
                    dropped.add(name)
                else:
                    self.global_consts[name] = value
        for dec, node, ctx in self._pending_codecs:
            self._collect_codec(dec, node, ctx)
        for codec in self.codecs:
            if codec.role in ("decode", "both"):
                self.launder_leafs.add(codec.name)

    # -- construction

    def _index_file(self, ctx) -> None:
        env: dict = {}
        self.const_env[ctx.path] = env
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                v = _const_int(node.value, env)
                if v is not None:
                    env[node.targets[0].id] = v
            elif isinstance(node, ast.Expr) \
                    and isinstance(node.value, ast.Call) \
                    and _leaf(node.value) == "declare_wire_budget":
                self._collect_budget(node.value, ctx, env)
        self._index_scope(ctx.tree.body, ctx, qual_prefix="",
                          is_method=False, env=env)

    def _index_scope(self, body, ctx, qual_prefix, is_method, env) -> None:
        for node in body:
            if isinstance(node, _FUNC_NODES):
                fn = _Func(node, ctx, qual_prefix + node.name, is_method)
                self.funcs.append(fn)
                self.by_leaf.setdefault(fn.leaf, []).append(fn)
                for dec in node.decorator_list:
                    if _leaf(dec) == "wire_codec":
                        self._pending_codecs.append((dec, node, ctx))
                # nested defs resolve like module functions (closures
                # over inbox pumps etc.) — index one level down.
                self._index_scope(node.body, ctx,
                                  qual_prefix + node.name + ".",
                                  is_method=False, env=env)
            elif isinstance(node, ast.ClassDef):
                for dec in node.decorator_list:
                    if _leaf(dec) == "wire_codec":
                        self._pending_codecs.append((dec, node, ctx))
                self._index_scope(node.body, ctx, node.name + ".",
                                  is_method=True, env=env)

    def _collect_codec(self, dec, node, ctx) -> None:
        env = dict(self.global_consts)
        env.update(self.const_env.get(ctx.path, {}))
        tag = max_bytes = None
        version = 1
        role = None
        if isinstance(dec, ast.Call):
            for kw in dec.keywords:
                if kw.arg == "tag" and isinstance(kw.value, ast.Constant):
                    tag = kw.value.value
                elif kw.arg == "max_bytes":
                    max_bytes = _const_int(kw.value, env)
                elif kw.arg == "version":
                    version = _const_int(kw.value, env) or 1
                elif kw.arg == "role" \
                        and isinstance(kw.value, ast.Constant):
                    role = kw.value.value
        self.codecs.append(_Codec(
            tag=tag, max_bytes=max_bytes, version=version,
            role=role if role is not None else _codec_role(node),
            name=node.name, path=ctx.path, line=node.lineno,
        ))

    def _collect_budget(self, call, ctx, env) -> None:
        if len(call.args) >= 2 and isinstance(call.args[0], ast.Constant):
            self.budgets.append(_Codec(
                tag=call.args[0].value,
                max_bytes=_const_int(call.args[1], env),
                version=1, role="budget", name="declare_wire_budget",
                path=ctx.path, line=call.lineno,
            ))

    # -- call resolution

    def resolve(self, call) -> list:
        """Candidate package functions for a call, by leaf name. A call
        through an attribute only matches methods; a bare name only
        matches module-level functions."""
        leaf = _leaf(call)
        if leaf is None:
            return []
        via_attr = isinstance(call.func, ast.Attribute)
        return [
            f for f in self.by_leaf.get(leaf, ())
            if f.is_method == via_attr
        ]


_INDEX_CACHE: list = [None, None]  # [key, PackageIndex]


def index_for(ctxs) -> PackageIndex:
    """One shared index per lint run: the four wire rules receive the
    same ctx list object sequence, so a single-slot memo suffices."""
    key = tuple(id(c) for c in ctxs)
    if _INDEX_CACHE[0] != key:
        _INDEX_CACHE[0] = key
        _INDEX_CACHE[1] = PackageIndex(ctxs)
    return _INDEX_CACHE[1]


# ---------------------------------------------------------- taint engine


class _FuncTaint:
    """One function's lattice state after intraprocedural propagation."""

    __slots__ = ("func", "tainted", "wire_ints", "readers", "guarded",
                 "prop")

    def __init__(self, func):
        self.func = func
        self.tainted: set = set()      # wire-bytes names
        self.wire_ints: dict = {}      # name -> producing reader or None
        self.readers: set = set()      # names used as codec Readers
        self.guarded: set = set()      # names bounds-checked somewhere
        self.prop: list = []           # (call, tainted arg positions)


def _find_readers(node) -> set:
    """Names that behave as codec Readers in this function: assigned
    from a laundering constructor, or having reader primitives called
    on them."""
    out: set = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and isinstance(n.func.value, ast.Name) \
                and n.func.attr in _READER_METHODS:
            out.add(n.func.value.id)
        elif isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and _leaf(n.value) in _LAUNDER_CALLS:
            for t in n.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _find_guards(node) -> set:
    """Names that appear in any comparison or min()/max() clamp — the
    coarse 'a bounds check exists' evidence HD008 accepts."""
    out: set = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Compare):
            for sub in ast.walk(n):
                leaf = _leaf(sub) if isinstance(
                    sub, (ast.Name, ast.Attribute)
                ) else None
                if leaf is not None:
                    out.add(leaf)
        elif isinstance(n, ast.Call) and _leaf(n) in ("min", "max"):
            for arg in n.args:
                if isinstance(arg, ast.Name):
                    out.add(arg.id)
    return out


def _is_entry(func) -> bool:
    return "wire_entry" in func.decorators


def _analyze(func, index, seed_params) -> _FuncTaint:
    """Intraprocedural pass: seed taint, iterate assignments to a local
    fixpoint, record interprocedural propagation edges."""
    st = _FuncTaint(func)
    st.readers = _find_readers(func.node)
    st.guarded = _find_guards(func.node)
    st.tainted |= seed_params
    entry = _is_entry(func)
    sources = _ENTRY_SOURCE_CALLS if entry else _SOURCE_CALLS

    def bytes_tainted(e) -> bool:
        if isinstance(e, ast.Name):
            return e.id in st.tainted
        if isinstance(e, ast.Subscript):
            if isinstance(e.slice, ast.Slice):
                return bytes_tainted(e.value)
            return False  # x[i] is an int, handled by wire_int
        if isinstance(e, ast.Call):
            leaf = _leaf(e)
            if leaf in sources:
                return True
            return False
        if isinstance(e, ast.BinOp) and isinstance(e.op, ast.Add):
            return bytes_tainted(e.left) or bytes_tainted(e.right)
        if isinstance(e, (ast.IfExp,)):
            return bytes_tainted(e.body) or bytes_tainted(e.orelse)
        return False

    def int_reader(e):
        """(is_wire_int, producing_reader_name) for an expression."""
        if isinstance(e, ast.Name):
            if e.id in st.wire_ints:
                return True, st.wire_ints[e.id]
            return False, None
        if isinstance(e, ast.Call):
            f = e.func
            if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                if f.attr in _READER_INT_METHODS \
                        and f.value.id in st.readers:
                    return True, f.value.id
                if f.attr in ("from_bytes",) and e.args \
                        and bytes_tainted(e.args[0]):
                    return True, None
                if f.attr in ("unpack", "unpack_from") and any(
                    bytes_tainted(a) for a in e.args
                ):
                    return True, None
            return False, None
        if isinstance(e, ast.Subscript) \
                and not isinstance(e.slice, ast.Slice) \
                and bytes_tainted(e.value):
            return True, None
        if isinstance(e, ast.BinOp):
            li, lr = int_reader(e.left)
            ri, rr = int_reader(e.right)
            if li or ri:
                return True, lr if li else rr
        return False, None

    for _ in range(_TAINT_ROUNDS):
        before = (len(st.tainted), len(st.wire_ints))
        for n in ast.walk(func.node):
            if not isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                continue
            value = n.value
            if value is None:
                continue
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            names: list = []
            for t in targets:
                if isinstance(t, ast.Name):
                    names.append(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    names.extend(
                        e.id for e in t.elts if isinstance(e, ast.Name)
                    )
            if not names:
                continue
            if bytes_tainted(value):
                st.tainted.update(names)
            is_int, reader = int_reader(value)
            if is_int:
                for name in names:
                    st.wire_ints.setdefault(name, reader)
        if (len(st.tainted), len(st.wire_ints)) == before:
            break

    # Interprocedural edges: tainted bytes handed to package functions
    # (laundering callees stop the flow — that boundary is audited by
    # HD008 on the decoder side).
    for n in ast.walk(func.node):
        if not isinstance(n, ast.Call):
            continue
        leaf = _leaf(n)
        if leaf is None or leaf in index.launder_leafs:
            continue
        positions = [
            i for i, a in enumerate(n.args) if bytes_tainted(a)
        ]
        if positions:
            st.prop.append((n, positions))
    return st


def _taint_fixpoint(index) -> dict:
    """Propagate parameter taint across call edges until stable.
    Returns {func -> _FuncTaint} with final lattices."""
    seeds: dict = {}
    for f in index.funcs:
        seed: set = set()
        if _is_entry(f):
            seed |= {p for p in f.params if p not in ("self", "cls")}
        if "wire_codec" in f.decorators \
                and _codec_role(f.node) == "decode":
            seed |= {p for p in f.params if p not in ("self", "cls")}
        seeds[f] = seed
    states: dict = {}
    for _ in range(_TAINT_ROUNDS):
        changed = False
        for f in index.funcs:
            states[f] = _analyze(f, index, seeds[f])
        for f in index.funcs:
            for call, positions in states[f].prop:
                for callee in index.resolve(call):
                    offset = 1 if callee.is_method and callee.params[:1] in (
                        ["self"], ["cls"]
                    ) else 0
                    for i in positions:
                        if i + offset < len(callee.params):
                            p = callee.params[i + offset]
                            if p not in seeds[callee]:
                                seeds[callee].add(p)
                                changed = True
        if not changed:
            break
    return states


_STATES_CACHE: list = [None, None]


def _states_for(index) -> dict:
    if _STATES_CACHE[0] is not index:
        _STATES_CACHE[0] = index
        _STATES_CACHE[1] = _taint_fixpoint(index)
    return _STATES_CACHE[1]


# ----------------------------------------------------------------- rules


class WireTaintRule:
    """HD007: untrusted wire bytes reaching digest/commit/state scope
    without passing a registered validator/decoder.

    Wire bytes (socket receives, ``@wire_entry`` parameters, registered
    decoders' inputs and everything derived from them by slicing or
    concatenation) may flow into exactly one kind of consumer: a
    laundering boundary — ``Reader``/``maybe_wire_reader`` or a
    registered ``@wire_codec`` decoder, whose own body HD008 audits.
    Feeding them RAW to a hash constructor, an ``.update(...)``, a
    ``commit(...)`` call, or storing them on ``self`` in digest scope
    means attacker-authored bytes shape a digest or survive into state
    with zero validation between — the exact bug class surge exists to
    kill. Route the bytes through the registered decoder for their
    frame family first (or register one).
    """

    code = "HD007"
    name = "wire-taint-to-digest"
    summary = "raw wire bytes reach digest/commit/state without a decoder"

    def check_package(self, ctxs):
        index = index_for(ctxs)
        states = _states_for(index)
        findings: list = []
        for f, st in states.items():
            for n in ast.walk(f.node):
                if isinstance(n, ast.Call):
                    leaf = _leaf(n)
                    if leaf not in _SINK_CALLS:
                        continue
                    dirty = [
                        a for a in list(n.args)
                        + [kw.value for kw in n.keywords]
                        if isinstance(a, ast.Name) and a.id in st.tainted
                    ]
                    if dirty:
                        findings.append(Finding(
                            self.code, f.ctx.path, n.lineno,
                            f"wire-tainted bytes {dirty[0].id!r} reach "
                            f"{leaf}() without passing a registered "
                            "@wire_codec decoder: decode (and "
                            "budget-check) peer bytes before they touch "
                            "digest/commit scope",
                        ))
                elif isinstance(n, ast.Assign) and "digest" in f.ctx.scopes:
                    if not (isinstance(n.value, ast.Name)
                            and n.value.id in st.tainted):
                        continue
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            findings.append(Finding(
                                self.code, f.ctx.path, n.lineno,
                                f"wire-tainted bytes {n.value.id!r} "
                                "stored into digest-scope state "
                                f"(self.{t.attr}) without passing a "
                                "registered decoder",
                            ))
        return findings


class WireBoundsRule:
    """HD008: allocation sized by a wire int with no bounds check
    against a declared budget.

    A length a peer wrote — a reader primitive's result, an
    ``int.from_bytes`` over tainted bytes, a ``struct.unpack`` of a
    received buffer — must not size a ``range``/``bytearray``/sequence
    repeat until the code has compared it against SOMETHING (a cap
    constant, ``min()``). Two idioms are recognized as already safe:
    a loop that consumes bytes from the SAME reader every iteration
    (the codec byte budget bounds it — each iteration costs at least
    one byte), and constant-width slices (Python clamps slice bounds).
    ``int.from_bytes`` over a whole tainted buffer or a dynamic-width
    slice is flagged too: a bigint parse is an allocation.
    """

    code = "HD008"
    name = "wire-bounds"
    summary = "wire-derived length sizes an allocation with no bounds check"

    def _loop_consumes_reader(self, call, parents, reader) -> bool:
        """True when the range() is the iterable of a loop whose body
        consumes the producing reader (budget-bounded by construction)."""
        if reader is None:
            return False
        parent = parents.get(id(call))
        body: list = []
        if isinstance(parent, (ast.For,)) and parent.iter is call:
            body = parent.body
        elif isinstance(parent, ast.comprehension) and parent.iter is call:
            comp = parents.get(id(parent))
            if comp is not None:
                body = [getattr(comp, "elt", None) or comp]
        for stmt in body:
            if stmt is None:
                continue
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and isinstance(n.func.value, ast.Name) \
                        and n.func.value.id == reader \
                        and n.func.attr in _READER_METHODS:
                    return True
        return False

    def check_package(self, ctxs):
        index = index_for(ctxs)
        states = _states_for(index)
        findings: list = []
        for f, st in states.items():
            env = index.const_env.get(f.ctx.path, {})
            parents: dict = {}
            for n in ast.walk(f.node):
                for child in ast.iter_child_nodes(n):
                    parents[id(child)] = n

            def wire_len(e):
                """(is_wire_int, producer, display name) for an
                allocation-size argument."""
                if isinstance(e, ast.Name) and e.id in st.wire_ints:
                    return True, st.wire_ints[e.id], e.id
                if isinstance(e, ast.Call) \
                        and isinstance(e.func, ast.Attribute) \
                        and isinstance(e.func.value, ast.Name) \
                        and e.func.attr in _READER_INT_METHODS \
                        and e.func.value.id in st.readers:
                    return True, e.func.value.id, \
                        f"{e.func.value.id}.{e.func.attr}()"
                return False, None, None

            for n in ast.walk(f.node):
                if isinstance(n, ast.Call):
                    leaf = _leaf(n)
                    if leaf in _ALLOC_CALLS and n.args:
                        # range(stop) / range(start, stop[, step])
                        args = n.args if leaf != "range" or len(n.args) == 1 \
                            else n.args[1:2]
                        for a in args:
                            hit, reader, shown = wire_len(a)
                            if not hit or (
                                isinstance(a, ast.Name)
                                and a.id in st.guarded
                            ):
                                continue
                            if leaf == "range" and self._loop_consumes_reader(
                                n, parents, reader
                            ):
                                continue
                            findings.append(Finding(
                                self.code, f.ctx.path, n.lineno,
                                f"{leaf}({shown}) sized by a wire-"
                                "derived length with no bounds check: "
                                "compare it against a declared cap "
                                "(or consume the reader inside the "
                                "loop so the byte budget bounds it)",
                            ))
                    elif leaf == "from_bytes" and n.args:
                        a = n.args[0]
                        if isinstance(a, ast.Name) and a.id in st.tainted \
                                and a.id not in st.guarded:
                            findings.append(Finding(
                                self.code, f.ctx.path, n.lineno,
                                f"int.from_bytes({a.id}) over a whole "
                                "wire-tainted buffer: a peer-sized "
                                "bigint parse is an unbounded "
                                "allocation — slice a constant width "
                                "or length-check first",
                            ))
                        elif isinstance(a, ast.Subscript) \
                                and isinstance(a.value, ast.Name) \
                                and a.value.id in st.tainted \
                                and isinstance(a.slice, ast.Slice) \
                                and _slice_width(a, env) is None:
                            findings.append(Finding(
                                self.code, f.ctx.path, n.lineno,
                                "int.from_bytes over a dynamic-width "
                                "slice of wire-tainted bytes: make the "
                                "width a compile-time constant or "
                                "bounds-check it first",
                            ))
                elif isinstance(n, ast.BinOp) \
                        and isinstance(n.op, ast.Mult):
                    for side, other in ((n.left, n.right),
                                        (n.right, n.left)):
                        if isinstance(side, ast.Name) \
                                and side.id in st.wire_ints \
                                and side.id not in st.guarded \
                                and isinstance(
                                    other, (ast.Constant, ast.List,
                                            ast.Tuple)
                                ):
                            findings.append(Finding(
                                self.code, f.ctx.path, n.lineno,
                                f"sequence repeat sized by wire int "
                                f"{side.id!r} with no bounds check",
                            ))
                            break
        return findings


class CodecPairRule:
    """HD009: codec registry closure and pair completeness.

    Every module-level ``encode_*``/``marshal_*``/``decode_*``/
    ``unmarshal_*`` function, and every class carrying a
    ``marshal``/``unmarshal`` method pair, must be registered with
    ``@wire_codec(tag=..., max_bytes=...)`` — an unregistered codec is
    a frame family with no declared budget, invisible to HDS005 and to
    the fuzz corpus (tests/test_wire_audit.py parametrizes over the
    registry, so registration IS test coverage). And every tag must
    have both directions: an encoder whose tag has no decoder is a
    frame nobody can reject; a decoder with no encoder is dead attack
    surface. Registrations must carry a literal tag and a resolvable
    constant ``max_bytes``.
    """

    code = "HD009"
    name = "wire-codec-registry"
    summary = "codec missing @wire_codec registration or its pair"

    _PREFIXES = ("encode_", "decode_", "marshal_", "unmarshal_")
    _METHODS = frozenset({"marshal", "unmarshal", "unmarshal_into"})

    def check_package(self, ctxs):
        index = index_for(ctxs)
        findings: list = []
        registered_lines = {(c.path, c.line) for c in index.codecs}
        # -- closure: every syntactic codec carries the decorator
        for ctx in ctxs:
            for node in ctx.tree.body:
                if isinstance(node, _FUNC_NODES) \
                        and node.name.startswith(self._PREFIXES) \
                        and (ctx.path, node.lineno) not in registered_lines:
                    findings.append(Finding(
                        self.code, ctx.path, node.lineno,
                        f"wire codec {node.name}() is not registered: "
                        "decorate it with @wire_codec(tag=..., "
                        "max_bytes=...) so its budget is declared and "
                        "the fuzz corpus covers it",
                    ))
                elif isinstance(node, ast.ClassDef):
                    methods = {
                        m.name for m in node.body
                        if isinstance(m, _FUNC_NODES)
                    }
                    if methods & self._METHODS \
                            and (ctx.path, node.lineno) \
                            not in registered_lines:
                        findings.append(Finding(
                            self.code, ctx.path, node.lineno,
                            f"class {node.name} carries a marshal/"
                            "unmarshal pair but is not registered: "
                            "decorate the class with @wire_codec(tag="
                            "..., max_bytes=...)",
                        ))
        # -- registration hygiene + pair completeness
        by_tag: dict = {}
        for c in index.codecs:
            if c.tag is None or c.max_bytes is None:
                findings.append(Finding(
                    self.code, c.path, c.line,
                    f"@wire_codec on {c.name} needs a literal tag and "
                    "a compile-time-constant max_bytes (the linter and "
                    "the sanitizer must both resolve them)",
                ))
                continue
            by_tag.setdefault(c.tag, []).append(c)
        for tag, specs in sorted(by_tag.items()):
            roles = {c.role for c in specs}
            first = specs[0]
            if "both" in roles:
                continue
            if "decode" not in roles:
                findings.append(Finding(
                    self.code, first.path, first.line,
                    f"codec tag {tag!r} has encoder(s) but no "
                    "registered decoder: a frame family nobody can "
                    "parse-and-reject is unaudited attack surface",
                ))
            if "encode" not in roles:
                findings.append(Finding(
                    self.code, first.path, first.line,
                    f"codec tag {tag!r} has decoder(s) but no "
                    "registered encoder: roundtrip fuzzing needs both "
                    "directions",
                ))
        return findings


class TagDispatchRule:
    """HD010: frame-tag dispatch exhaustiveness.

    In every codec-bearing module (one that registers at least one
    ``@wire_codec``), the module's ``TAG_*``/``KIND_*`` integer
    constants form its frame-tag namespace. Two properties must hold:
    every tag in the namespace is COMPARED somewhere (a tag nobody
    dispatches on is either dead or silently accepted), and at least
    one comparing function explicitly raises — the unknown-tag
    fallthrough must be a typed rejection, never an implicit pass.
    Fail-closed dispatch is the wire doctrine's second half: budget
    accounting bounds what a frame may cost, tag exhaustiveness bounds
    what a frame may MEAN.
    """

    code = "HD010"
    name = "tag-dispatch-exhaustive"
    summary = "frame-tag constant not dispatched, or no unknown-tag reject"

    def check_package(self, ctxs):
        index = index_for(ctxs)
        codec_paths = {c.path for c in index.codecs}
        findings: list = []
        for ctx in ctxs:
            if ctx.path not in codec_paths:
                continue
            groups: dict = {}  # namespace -> {name: lineno}
            for node in ctx.tree.body:
                self._collect(node, "", groups)
                if isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        self._collect(sub, node.name + ".", groups)
            compared: set = set()
            raising_compare: set = set()
            for fn in ast.walk(ctx.tree):
                if not isinstance(fn, _FUNC_NODES):
                    continue
                names = self._compared_names(fn)
                compared |= names
                if names and any(
                    isinstance(n, ast.Raise) for n in ast.walk(fn)
                ):
                    raising_compare |= names
            for ns, members in sorted(groups.items()):
                if len(members) < 2:
                    continue
                missing = sorted(
                    name for name in members if name not in compared
                )
                for name in missing:
                    findings.append(Finding(
                        self.code, ctx.path, members[name],
                        f"frame tag {name} is never compared in any "
                        "dispatch: a registered tag every decoder "
                        "ignores is either dead or silently accepted",
                    ))
                handled = set(members) - set(missing)
                if handled and not (handled & raising_compare):
                    first = min(members.values())
                    findings.append(Finding(
                        self.code, ctx.path, first,
                        f"tag namespace {ns or 'module'} has dispatch "
                        "but no function that rejects unknown tags "
                        "with a raise: unknown frames must fail "
                        "closed",
                    ))
        return findings

    @staticmethod
    def _collect(node, prefix, groups) -> None:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if name.startswith(("TAG_", "KIND_")) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, int):
                groups.setdefault(prefix, {})[name] = node.lineno

    @staticmethod
    def _compared_names(fn) -> set:
        out: set = set()
        for n in ast.walk(fn):
            if not isinstance(n, ast.Compare):
                continue
            for sub in ast.walk(n):
                leaf = None
                if isinstance(sub, ast.Name):
                    leaf = sub.id
                elif isinstance(sub, ast.Attribute):
                    leaf = sub.attr
                if leaf is not None and leaf.startswith(("TAG_", "KIND_")):
                    out.add(leaf)
        return out


# ------------------------------------------------------------ wire report


def wire_report(paths) -> str:
    """The ``--wire-report`` inventory: every registered codec and
    budget-only declaration, with its roundtrip-test locations in
    tests/test_wire_audit.py (found by walking up from the scanned
    tree). Pure AST — importing nothing, same as the rules."""
    from hyperdrive_tpu.analysis.engine import FileContext, \
        iter_python_files

    ctxs = []
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                ctxs.append(FileContext(path, fh.read()))
        except (OSError, SyntaxError):
            continue
    index = PackageIndex(ctxs)
    # locate the roundtrip corpus relative to the scanned tree
    test_lines: dict = {}
    test_path = None
    probe = os.path.abspath(paths[0] if paths else ".")
    for _ in range(6):
        cand = os.path.join(probe, "tests", "test_wire_audit.py")
        if os.path.isfile(cand):
            test_path = cand
            break
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    if test_path is not None:
        rel = os.path.relpath(test_path)
        with open(test_path, encoding="utf-8") as fh:
            for lineno, line in enumerate(fh, 1):
                for tag in {c.tag for c in index.codecs if c.tag} | {
                    b.tag for b in index.budgets
                }:
                    if f'"{tag}"' in line and tag not in test_lines:
                        test_lines[tag] = f"{rel}:{lineno}"
    rows = []
    by_tag: dict = {}
    for c in index.codecs:
        if c.tag is not None:
            by_tag.setdefault(c.tag, []).append(c)
    for tag, specs in sorted(by_tag.items()):
        enc = [c.name for c in specs if c.role in ("encode", "both")]
        dec = [c.name for c in specs if c.role in ("decode", "both")]
        rows.append((
            tag,
            str(max(c.version for c in specs)),
            str(min(c.max_bytes for c in specs if c.max_bytes is not None)),
            "/".join(enc) or "—",
            "/".join(dec) or "—",
            test_lines.get(tag, "—"),
        ))
    for b in sorted(index.budgets, key=lambda b: b.tag):
        rows.append((b.tag, "-", str(b.max_bytes), "(budget only)",
                     "(charged at seam)", test_lines.get(b.tag, "—")))
    header = ("TAG", "VER", "MAX_BYTES", "ENCODER", "DECODER",
              "ROUNDTRIP TEST")
    widths = [
        max(len(header[i]), *(len(r[i]) for r in rows)) if rows
        else len(header[i])
        for i in range(len(header))
    ]
    lines = [
        "  ".join(h.ljust(widths[i]) for i, h in enumerate(header)),
        "  ".join("-" * w for w in widths),
    ]
    for r in rows:
        lines.append("  ".join(r[i].ljust(widths[i]) for i in range(len(r))))
    lines.append("")
    lines.append(f"{len(by_tag)} codec tag(s), {len(index.budgets)} "
                 "budget-only declaration(s)")
    return "\n".join(lines)
