"""Runtime consensus sanitizer: HDS001–HDS004 invariant checks.

The static rules keep hazards out of the source; this half watches the
running automaton. It interposes on the Process DI seams — the
committer and broadcaster slots are plain assignable attributes, so no
monkeypatching of slotted methods is needed — and on the
DeviceTallyFlusher's per-launch tally view:

* **HDS001** quorum recount: every commit is re-derived from the HOST
  message logs (a valid proposal round whose value holds ≥ 2f+1
  precommits). A device tally that lies its way past L49 dies here.
* **HDS002** lock sanity: ``locked_round ≤ current_round`` at every
  broadcast and commit (the automaton only ever locks the round it is
  in — paper L36).
* **HDS003** height monotonicity: committed heights strictly increase
  and always equal the automaton's ``current_height`` at commit time.
* **HDS004** settle-path parity: the flusher's device counts must be
  bit-equal to the host counters for every answered query
  (:class:`~hyperdrive_tpu.ops.votegrid.CheckedTallyView` differential,
  re-raised under the rule name).
* **HDS005** wire decode budget: every frame decoded at a wire seam
  (TcpNode ingress, ServicePort/RemoteServiceClient frames, flight and
  scenario replay loaders, overlay partial frames) is charged against
  the ``max_bytes`` its ``@wire_codec`` registration declared — the
  surge-style accounting from codec.py, but with the PER-FAMILY budget
  the format author wrote down instead of the one global MAX_BYTES.
  A decode that reads past its family budget, or a frame family with
  no registration at all, raises here.

Toggled by ``HD_SANITIZE`` (tests default it ON via conftest; perf runs
export ``HD_SANITIZE=0`` — see BENCH.md). Violations raise
:class:`SanitizerError`, an ``AssertionError`` whose message leads with
the rule name so harnesses can match on it.
"""

from __future__ import annotations

import os

__all__ = ["SanitizerError", "enabled", "install", "maybe_install",
           "maybe_tally_check", "WireBudget", "maybe_wire_reader",
           "wire_charge"]


class SanitizerError(AssertionError):
    """An HDS invariant violation. ``rule`` is the HDSnnn code; the
    message always starts with it."""

    def __init__(self, rule: str, message: str):
        super().__init__(f"{rule}: {message}")
        self.rule = rule


def enabled() -> bool:
    return os.environ.get("HD_SANITIZE", "0").strip().lower() in (
        "1", "true", "on", "yes"
    )


class WireBudget:
    """HDS005 accounting for ONE frame family: resolves the registered
    ``max_bytes`` for ``tag`` and charges decode reads against it.

    ``reader(payload)`` returns a budget-capped
    :class:`~hyperdrive_tpu.codec.Reader` whose exhaustion re-raises as
    HDS005 (instead of the generic SerdeError budget message), so a
    decoder that reads past its family's declared budget dies loudly
    under HD_SANITIZE while plain malformed input keeps its typed
    SerdeError. ``charge(nbytes)`` is the object-frame variant for
    seams with no byte decode (overlay partial frames): the handler
    estimates the frame's wire size and charges it up front.
    """

    __slots__ = ("tag", "max_bytes", "_obs")

    def __init__(self, tag: str, obs=None):
        from hyperdrive_tpu.analysis.annotations import wire_budget_for

        max_bytes = wire_budget_for(tag)
        if max_bytes is None:
            raise SanitizerError(
                "HDS005",
                f"decode of unregistered wire frame family {tag!r}: every "
                "decode seam must name a @wire_codec tag (or a "
                "declare_wire_budget entry) so its byte budget is "
                "accounted",
            )
        self.tag = tag
        self.max_bytes = max_bytes
        self._obs = obs

    def _exceeded(self, needed: int) -> SanitizerError:
        if self._obs is not None:
            self._obs.emit("wire.budget.exceeded", -1, -1, -1,
                           f"{self.tag}:{needed}")
        return SanitizerError(
            "HDS005",
            f"decode of a {self.tag!r} frame read past its registered "
            f"budget: needs {needed} bytes, max_bytes={self.max_bytes} "
            "(raise the registration or fix the decoder's caps)",
        )

    def charge(self, nbytes: int) -> int:
        if nbytes > self.max_bytes:
            raise self._exceeded(nbytes)
        return nbytes

    def reader(self, payload: bytes):
        # Charge the frame itself first: a payload already wider than
        # the family budget is a violation before the first read.
        if len(payload) > self.max_bytes:
            raise self._exceeded(len(payload))
        r = _budget_reader_cls()(payload, rem=self.max_bytes)
        r._budget = self
        return r


#: Built once on first use — this sits on every frame decode under
#: HD_SANITIZE, so per-call class creation would tax the whole suite.
_BUDGET_READER_CLS = None


def _budget_reader_cls():
    global _BUDGET_READER_CLS
    if _BUDGET_READER_CLS is None:
        from hyperdrive_tpu.codec import Reader, SerdeError

        class _BudgetReader(Reader):
            __slots__ = ("_budget",)

            def _take(self, n):
                try:
                    return Reader._take(self, n)
                except SerdeError:
                    b = self._budget
                    if self.rem < n:  # budget breach, not mere underflow
                        raise b._exceeded(
                            b.max_bytes - self.rem + n
                        ) from None
                    raise

        _BUDGET_READER_CLS = _BudgetReader
    return _BUDGET_READER_CLS


def maybe_wire_reader(tag: str, payload: bytes, obs=None, rem=None):
    """The decode-seam helper: an HDS005 budget reader for ``tag`` when
    the sanitizer is on, a plain Reader otherwise. Wire seams call this
    instead of ``Reader(payload)`` so the per-family accounting
    interposes with zero code at the call site. ``rem`` preserves a
    seam's historical sanitizer-off byte budget when it differs from
    the codec default (the giant scenario/checkpoint loaders)."""
    if enabled():
        return WireBudget(tag, obs=obs).reader(payload)
    from hyperdrive_tpu.codec import Reader

    return Reader(payload) if rem is None else Reader(payload, rem=rem)


def wire_charge(tag: str, nbytes: int, obs=None) -> int:
    """Object-frame seams (no byte decode): charge an estimated wire
    size against ``tag``'s budget under HD_SANITIZE; no-op otherwise.
    Returns ``nbytes`` so the charge can wrap an expression."""
    if enabled():
        WireBudget(tag, obs=obs).charge(nbytes)
    return nbytes


def _check_lock(proc) -> None:
    st = proc.state
    if st.locked_round > st.current_round:
        raise SanitizerError(
            "HDS002",
            f"locked_round {st.locked_round} > current_round "
            f"{st.current_round} at height {st.current_height} "
            f"(replica {proc.whoami!r}): the automaton only locks the "
            "round it is in (L36)",
        )


class _SanitizedCommitter:
    """Wraps the committer seam: HDS001 + HDS002 + HDS003 on the way
    into every commit. Delegates everything else to the wrapped
    committer (which may itself be the replica's tracing wrapper)."""

    def __init__(self, inner, proc):
        self._inner = inner
        self._proc = proc
        self._last_height = None

    def commit(self, height, value):
        proc = self._proc
        st = proc.state
        if height != st.current_height:
            raise SanitizerError(
                "HDS003",
                f"commit at height {height} while the automaton is at "
                f"{st.current_height} (replica {proc.whoami!r})",
            )
        if self._last_height is not None and height <= self._last_height:
            raise SanitizerError(
                "HDS003",
                f"commit height {height} does not advance past "
                f"{self._last_height} (replica {proc.whoami!r}): heights "
                "must be strictly increasing",
            )
        _check_lock(proc)
        need = 2 * proc.f + 1
        quorum = any(
            p.value == value
            and st.propose_is_valid.get(rnd, False)
            and st.count_precommits_for(rnd, value) >= need
            for rnd, p in st.propose_logs.items()
        )
        if not quorum:
            raise SanitizerError(
                "HDS001",
                f"commit of {value!r} at height {height} has no host-log "
                f"quorum: no valid proposal round carries >= {need} "
                f"(2f+1) precommits for it (replica {proc.whoami!r}); a "
                "device tally overruled the message logs",
            )
        self._last_height = height
        return self._inner.commit(height, value)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _SanitizedBroadcaster:
    """Wraps the broadcaster seam: HDS002 before every outbound step —
    the automaton's externally visible actions never leave a state
    where it locked a round it has not reached."""

    def __init__(self, inner, proc):
        self._inner = inner
        self._proc = proc

    def broadcast_propose(self, msg):
        _check_lock(self._proc)
        return self._inner.broadcast_propose(msg)

    def broadcast_prevote(self, msg):
        _check_lock(self._proc)
        return self._inner.broadcast_prevote(msg)

    def broadcast_precommit(self, msg):
        _check_lock(self._proc)
        return self._inner.broadcast_precommit(msg)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def install(proc):
    """Interpose HDS checks on ``proc``'s committer/broadcaster seams.
    Idempotent; tolerates absent seams (a Process built without a
    committer has no commit effect to guard)."""
    if proc.committer is not None and not isinstance(
        proc.committer, _SanitizedCommitter
    ):
        proc.committer = _SanitizedCommitter(proc.committer, proc)
    if proc.broadcaster is not None and not isinstance(
        proc.broadcaster, _SanitizedBroadcaster
    ):
        proc.broadcaster = _SanitizedBroadcaster(proc.broadcaster, proc)
    return proc


def maybe_install(proc):
    """:func:`install` iff ``HD_SANITIZE`` is on (the Replica
    constructor's hook)."""
    if enabled():
        install(proc)
    return proc


def maybe_tally_check():
    """HDS004 factory for the DeviceTallyFlusher's ``tally_check`` seam:
    a ``(view, proc) -> view`` wrapper cross-checking device counts
    against the host counters, or None when the sanitizer is off.

    Imported lazily so merely loading this module never drags in jax.
    """
    if not enabled():
        return None

    from hyperdrive_tpu.ops.votegrid import CheckedTallyView

    class _HDS004View(CheckedTallyView):
        __slots__ = ()

        def _check(self, device, host, what):
            try:
                return super()._check(device, host, what)
            except SanitizerError:
                raise
            except AssertionError as e:
                raise SanitizerError(
                    "HDS004",
                    f"device/host tally divergence: {e} — the redundant "
                    "settle paths no longer agree",
                ) from e

    return _HDS004View
