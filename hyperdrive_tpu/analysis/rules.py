"""hdlint rules HD001–HD006.

Every rule is a heuristic tuned against THIS repo's idioms (see
ANALYSIS.md for the catalog with examples). False positives are waived
in place with ``# hdlint: disable=HDnnn <reason>`` — the reason is part
of the syntax, so the waiver ledger stays reviewable.
"""

from __future__ import annotations

import ast
import re

from hyperdrive_tpu.analysis.engine import Finding

__all__ = ["ALL_RULES", "default_rules", "HostSyncRule", "RetraceRule",
           "NondetIterRule", "DtypeWidthRule", "MetricNameRule",
           "AsyncFetchRule", "WireTaintRule", "WireBoundsRule",
           "CodecPairRule", "TagDispatchRule"]

_CASTS = frozenset({"int", "float", "bool"})
_NP_CONVERTERS = frozenset(
    {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
)
_STATIC_ATTRS = frozenset(
    {"shape", "ndim", "dtype", "size", "itemsize", "weak_type", "aval"}
)
_STATIC_FUNCS = frozenset(
    {"len", "isinstance", "hasattr", "getattr", "type", "id"}
)
_SET_METHODS = frozenset(
    {"union", "intersection", "difference", "symmetric_difference"}
)
_SET_BINOPS = (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)


# ------------------------------------------------------------------ helpers

def _dotted(node):
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _contains_name(node, name) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id == name for n in ast.walk(node)
    )


def _contains_jnp(node) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id == "jnp":
            return True
        if isinstance(n, ast.Attribute):
            d = _dotted(n)
            if d and d.startswith("jax.numpy"):
                return True
    return False


def _is_device_fetch(call) -> bool:
    if not isinstance(call, ast.Call):
        return False
    d = _dotted(call.func)
    return bool(d) and d.split(".")[-1] == "device_fetch"


def _walk_skipping_fetch(node):
    """ast.walk, but a ``device_fetch(...)`` call hides its whole
    subtree: whatever syncs inside it is the annotated, accounted-for
    sync."""
    stack = [node]
    while stack:
        n = stack.pop()
        if _is_device_fetch(n):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _has_self_call(node) -> bool:
    """Any call whose callee dereferences ``self`` (``self.fn(...)``,
    ``self.a.fn(...)``) — the classic shape of a method returning a
    device value that is then cast on the host."""
    for n in _walk_skipping_fetch(node):
        if isinstance(n, ast.Call) and _contains_name(n.func, "self"):
            return True
    return False


def _attr_call_outside_fetch(node) -> bool:
    for n in _walk_skipping_fetch(node):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            return True
    return False


def _decorator_targets(fn):
    for d in fn.decorator_list:
        yield d.func if isinstance(d, ast.Call) else d, d


def _has_decorator(fn, leaf_names) -> bool:
    for target, _ in _decorator_targets(fn):
        d = _dotted(target)
        if d and d.split(".")[-1] in leaf_names:
            return True
    return False


def _jit_decorator(fn):
    """The jit (or partial(jit, ...)) decorator Call/expr, or None."""
    for target, full in _decorator_targets(fn):
        d = _dotted(target)
        if not d:
            continue
        leaf = d.split(".")[-1]
        if leaf == "jit":
            return full
        if leaf == "partial" and isinstance(full, ast.Call) and full.args:
            inner = _dotted(full.args[0])
            if inner and inner.split(".")[-1] == "jit":
                return full
    return None


def _parent_map(tree):
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _enclosing_function(node, parents):
    n = parents.get(node)
    while n is not None:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return n
        n = parents.get(n)
    return None


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


# ------------------------------------------------------------------- HD001

class HostSyncRule:
    """HD001: implicit host↔device sync on a hot path.

    In hot scope (``ops/``, ``tallyflush.py``, ``batch.py``,
    ``harness/sim.py``, or any ``@hot_path`` function) flags:

    * ``x.item()`` / ``x.block_until_ready()``
    * ``np.asarray(x)`` / ``np.array(x)`` where ``x`` references ``jnp``
      or ``self`` (device-resident state); list/tuple/comprehension
      payloads are host-side construction and pass
    * ``int()/float()/bool()`` over a ``jnp`` expression or a
      ``self.…(...)`` method result
    * per-element cast loops (``[bool(b) for b in x.mask()]``) whose
      iterable calls a method — a device mask materialized one scalar at
      a time instead of one ``device_fetch``

    Anything inside ``device_fetch(...)`` is exempt by design.
    """

    code = "HD001"
    name = "implicit-host-sync"
    summary = "implicit host<->device sync on a hot path"

    def check(self, ctx):
        findings: list = []
        if "hot" in ctx.scopes:
            roots = [ctx.tree]
        else:
            roots = [
                n for n in ast.walk(ctx.tree)
                if isinstance(n, _FUNC_NODES)
                and _has_decorator(n, {"hot_path"})
            ]
        for root in roots:
            self._scan(root, ctx.path, findings)
        return findings

    def _scan(self, root, path, findings):
        def flag(node, msg):
            findings.append(Finding(self.code, path, node.lineno, msg))

        for n in _walk_skipping_fetch(root):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
                if n.func.attr == "item" and not n.args:
                    flag(n, "'.item()' forces a device sync; fetch the "
                            "batch once via device_fetch(...)")
                    continue
                if n.func.attr == "block_until_ready":
                    flag(n, "'.block_until_ready()' is a device sync; if "
                            "deliberate, route it through device_fetch(...)")
                    continue
            if isinstance(n, ast.Call):
                d = _dotted(n.func)
                if d in _NP_CONVERTERS and n.args:
                    x = n.args[0]
                    host_side = isinstance(
                        x, (ast.List, ast.Tuple, ast.Dict, ast.ListComp,
                            ast.GeneratorExp, ast.Constant)
                    )
                    if not host_side and (
                        _contains_jnp(x) or _contains_name(x, "self")
                    ):
                        flag(n, f"'{d}(...)' over a device-resident value "
                                "is an implicit sync; use device_fetch(...)")
                        continue
                if (
                    isinstance(n.func, ast.Name)
                    and n.func.id in _CASTS
                    and len(n.args) == 1
                    and (_contains_jnp(n.args[0])
                         or _has_self_call(n.args[0]))
                ):
                    flag(n, f"'{n.func.id}(...)' over a device-derived "
                            "value syncs per call; fetch once via "
                            "device_fetch(...)")
                    continue
            if isinstance(n, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
                elt_casts = any(
                    isinstance(c, ast.Call)
                    and isinstance(c.func, ast.Name)
                    and c.func.id in _CASTS
                    for c in ast.walk(n.elt)
                )
                if elt_casts and any(
                    _attr_call_outside_fetch(g.iter) for g in n.generators
                ):
                    flag(n, "per-element cast over a method-call iterable "
                            "materializes a device result one scalar at a "
                            "time; fetch the array once via "
                            "device_fetch(...) and cast on host")


# ------------------------------------------------------------------- HD002

class RetraceRule:
    """HD002: ``jax.jit`` retrace / recompile hazards.

    * a jit call inside a function with no compile cache (no
      ``lru_cache``/``cache`` decorator, result not stored into a
      cache-dict subscript) recompiles on every call
    * a jitted function that references ``self`` closes over mutable
      attributes — traced values silently refresh per instance, or
      retrace per mutation when marked static
    * ``static_argnums``/``static_argnames`` naming a parameter with a
      mutable (unhashable) default fails at call time
    * a Python ``if``/``while`` on a traced parameter retraces per value
      (or raises TracerBoolConversionError); branch on ``.shape`` /
      ``.ndim`` / ``len()`` or move the branch to ``jnp.where``
    """

    code = "HD002"
    name = "jit-retrace-hazard"
    summary = "jax.jit retrace / recompile hazard"

    def check(self, ctx):
        findings: list = []
        parents = _parent_map(ctx.tree)
        path = ctx.path

        def flag(node, msg):
            findings.append(Finding(self.code, path, node.lineno, msg))

        # (a) uncached jit construction inside a function
        for n in ast.walk(ctx.tree):
            if not (isinstance(n, ast.Call) and self._is_jit_name(n.func)):
                continue
            fn = _enclosing_function(n, parents)
            if fn is None:
                continue  # module-level jit: compiled once per import
            if _has_decorator(fn, {"lru_cache", "cache"}):
                continue
            if self._stored_in_cache(n, parents):
                continue
            flag(n, "jax.jit(...) built inside a function with no compile "
                    "cache recompiles per call; hoist to module level, "
                    "decorate the factory with functools.lru_cache, or "
                    "store the result in an explicit cache dict")

        # (b)(c)(d) jitted function bodies
        for fn in self._jitted_functions(ctx.tree, parents):
            dec = _jit_decorator(fn)
            static = self._static_params(fn, dec)
            if _contains_name(fn, "self"):
                flag(fn, f"jitted function '{fn.name}' references 'self': "
                         "closing over mutable attributes retraces per "
                         "mutation (or silently stales); pass arrays as "
                         "arguments")
            for name, default in self._mutable_static_defaults(fn, static):
                flag(default, f"static arg '{name}' has a mutable default "
                              "(unhashable under jit); use a tuple/None")
            self._scan_traced_branches(fn, static, flag)
        return findings

    # -------------------------------------------------------------- helpers

    @staticmethod
    def _is_jit_name(func) -> bool:
        d = _dotted(func)
        return bool(d) and (d == "jit" or d.endswith(".jit"))

    @staticmethod
    def _stored_in_cache(call, parents) -> bool:
        """Constructions that amortize the compile: ``fn = CACHE[k] =
        jax.jit(...)`` (explicit cache dict), ``self._fn = jax.jit(...)``
        (per-instance cache), ``return jax.jit(...)`` (factory — the
        caller owns the lifetime)."""
        n, p = call, parents.get(call)
        while p is not None and not isinstance(p, ast.stmt):
            n, p = p, parents.get(p)
        # The exemptions only hold when the jit call itself is what gets
        # returned/stored; jax.jit(...)(x) nested in a larger expression
        # still compiles per invocation.
        if isinstance(p, ast.Return) and p.value is call:
            return True
        if isinstance(p, ast.Assign) and p.value is call:
            return any(
                isinstance(t, ast.Subscript)
                or (isinstance(t, ast.Attribute)
                    and _contains_name(t.value, "self"))
                for t in p.targets
            )
        return False

    def _jitted_functions(self, tree, parents):
        """Defs decorated with jit/partial(jit, ...), plus local defs
        passed positionally to a jit call in the same scope."""
        out = []
        local_jitted: set = set()
        for n in ast.walk(tree):
            if isinstance(n, ast.Call) and self._is_jit_name(n.func):
                for a in n.args:
                    if isinstance(a, ast.Name):
                        local_jitted.add(a.id)
            if isinstance(n, _FUNC_NODES) and _jit_decorator(n) is not None:
                out.append(n)
        for n in ast.walk(tree):
            if (
                isinstance(n, _FUNC_NODES)
                and n.name in local_jitted
                and n not in out
            ):
                out.append(n)
        return out

    @staticmethod
    def _static_params(fn, dec):
        """Names of parameters marked static on the jit decorator."""
        static: set = set()
        if not isinstance(dec, ast.Call):
            return static
        posnames = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        for kw in dec.keywords:
            if kw.arg not in ("static_argnums", "static_argnames"):
                continue
            vals = (
                kw.value.elts
                if isinstance(kw.value, (ast.Tuple, ast.List))
                else [kw.value]
            )
            for v in vals:
                if not isinstance(v, ast.Constant):
                    continue
                if isinstance(v.value, int) and 0 <= v.value < len(posnames):
                    static.add(posnames[v.value])
                elif isinstance(v.value, str):
                    static.add(v.value)
        return static

    @staticmethod
    def _mutable_static_defaults(fn, static):
        args = fn.args.posonlyargs + fn.args.args
        defaults = fn.args.defaults
        for a, d in zip(args[len(args) - len(defaults):], defaults):
            mutable = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(d, ast.Call)
                and isinstance(d.func, ast.Name)
                and d.func.id in ("list", "dict", "set")
            )
            if a.arg in static and mutable:
                yield a.arg, d
        for a, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            if d is None:
                continue
            mutable = isinstance(d, (ast.List, ast.Dict, ast.Set))
            if a.arg in static and mutable:
                yield a.arg, d

    def _scan_traced_branches(self, fn, static, flag):
        tainted = {
            a.arg
            for a in (fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs)
            if a.arg not in static and a.arg != "self"
        }
        if fn.args.vararg:
            tainted.add(fn.args.vararg.arg)

        def is_static(e) -> bool:
            if isinstance(e, ast.Constant):
                return True
            if isinstance(e, ast.Name):
                return e.id not in tainted
            if isinstance(e, ast.Attribute):
                if e.attr in _STATIC_ATTRS:
                    return True
                return is_static(e.value)
            if isinstance(e, ast.Call):
                d = _dotted(e.func)
                if d and d.split(".")[-1] in _STATIC_FUNCS:
                    return True
                args = list(e.args) + [k.value for k in e.keywords]
                return is_static(e.func) and all(is_static(a) for a in args)
            if isinstance(e, ast.Compare):
                if (
                    all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops)
                    and all(
                        isinstance(c, ast.Constant) for c in e.comparators
                    )
                ):
                    return True  # `x is None` probes arg presence, not value
                return is_static(e.left) and all(
                    is_static(c) for c in e.comparators
                )
            if isinstance(e, (ast.BoolOp, ast.Tuple, ast.List)):
                vals = e.values if isinstance(e, ast.BoolOp) else e.elts
                return all(is_static(v) for v in vals)
            if isinstance(e, ast.BinOp):
                return is_static(e.left) and is_static(e.right)
            if isinstance(e, ast.UnaryOp):
                return is_static(e.operand)
            if isinstance(e, ast.Subscript):
                return is_static(e.value) and is_static(e.slice)
            if isinstance(e, ast.IfExp):
                return all(is_static(x) for x in (e.test, e.body, e.orelse))
            if isinstance(e, (ast.JoinedStr, ast.Lambda)):
                return True
            return all(is_static(c) for c in ast.iter_child_nodes(e))

        def visit(stmts):
            for s in stmts:
                if isinstance(s, _FUNC_NODES + (ast.Lambda, ast.ClassDef)):
                    continue  # separate scope
                if isinstance(s, ast.Assign):
                    val_static = is_static(s.value)
                    for t in s.targets:
                        for nm in ast.walk(t):
                            if isinstance(nm, ast.Name):
                                if val_static:
                                    tainted.discard(nm.id)
                                else:
                                    tainted.add(nm.id)
                elif isinstance(s, ast.AugAssign):
                    if isinstance(s.target, ast.Name) and not is_static(
                        s.value
                    ):
                        tainted.add(s.target.id)
                elif isinstance(s, (ast.If, ast.While)):
                    if not is_static(s.test):
                        flag(s, f"python branch on a traced value in "
                                f"jitted '{fn.name}' retraces per value "
                                "(or raises on bool()); branch on "
                                ".shape/.ndim/len() or use jnp.where/"
                                "lax.cond")
                elif isinstance(s, ast.For):
                    if not is_static(s.iter):
                        for nm in ast.walk(s.target):
                            if isinstance(nm, ast.Name):
                                tainted.add(nm.id)
                body_lists = [
                    getattr(s, f)
                    for f in ("body", "orelse", "finalbody")
                    if getattr(s, f, None)
                ]
                for bl in body_lists:
                    if isinstance(bl, list):
                        visit([x for x in bl if isinstance(x, ast.stmt)])
                for h in getattr(s, "handlers", []) or []:
                    visit(h.body)

        visit(fn.body)


# ------------------------------------------------------------------- HD003

class NondetIterRule:
    """HD003: nondeterministic iteration feeding digests / wire bytes.

    In digest scope (``codec.py``, ``process.py``, ``harness/sim.py``)
    flags ``for``-loops and comprehensions whose iterable is set-typed:
    set/frozenset literals and calls, ``.union()``-family chains rooted
    at a set, set-operator expressions (``a | b`` of sets), and locals
    assigned from any of those. Iterating a set hashes pointers —
    PYTHONHASHSEED decides the order, and any digest or wire encoding
    folded over it forks across runs. ``sorted(...)`` at the iteration
    point is the fix and the exemption.
    """

    code = "HD003"
    name = "nondeterministic-iteration"
    summary = "set iteration feeding digests or wire bytes"

    def check(self, ctx):
        if "digest" not in ctx.scopes:
            return []
        findings: list = []
        local_sets = self._set_named_locals(ctx.tree)

        def setish(e) -> bool:
            if isinstance(e, (ast.Set, ast.SetComp)):
                return True
            if isinstance(e, ast.Name):
                return e.id in local_sets
            if isinstance(e, ast.Call):
                if isinstance(e.func, ast.Name) and e.func.id in (
                    "set", "frozenset"
                ):
                    return True
                if (
                    isinstance(e.func, ast.Attribute)
                    and e.func.attr in _SET_METHODS
                ):
                    return setish(e.func.value)
                return False
            if isinstance(e, ast.BinOp) and isinstance(e.op, _SET_BINOPS):
                return setish(e.left) or setish(e.right)
            if isinstance(e, ast.IfExp):
                return setish(e.body) or setish(e.orelse)
            return False

        def flag(node):
            findings.append(Finding(
                self.code, ctx.path, node.lineno,
                "iteration over a set is hash-order nondeterministic and "
                "this file feeds commit digests / wire bytes; iterate "
                "sorted(...) instead",
            ))

        for n in ast.walk(ctx.tree):
            iters = []
            if isinstance(n, ast.For):
                iters.append(n.iter)
            elif isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp,
                                ast.GeneratorExp)):
                iters.extend(g.iter for g in n.generators)
            for it in iters:
                if setish(it):
                    flag(it)
        return findings

    @staticmethod
    def _set_named_locals(tree) -> set:
        names: set = set()
        for n in ast.walk(tree):
            if isinstance(n, ast.Assign):
                v = n.value
                is_set = isinstance(v, (ast.Set, ast.SetComp)) or (
                    isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Name)
                    and v.func.id in ("set", "frozenset")
                )
                if is_set:
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
            elif isinstance(n, ast.AnnAssign) and isinstance(
                n.target, ast.Name
            ):
                ann = _dotted(n.annotation) or ""
                if ann.split(".")[-1].lower() in ("set", "frozenset"):
                    names.add(n.target.id)
        return names


# ------------------------------------------------------------------- HD004

class DtypeWidthRule:
    """HD004: dtype-width drift in ops kernels.

    In ``ops/``, a bare Python int literal ≥ 2³¹ inside a function that
    touches ``jnp`` will not fit int32 — whether it overflows, promotes
    to int64, or raises depends on ``jax_enable_x64`` and the op it
    meets. Flagged unless some enclosing call pins ``dtype=`` (the
    constant-table idiom: ``jnp.asarray([...], dtype=jnp.uint32)``).
    """

    code = "HD004"
    name = "dtype-width-drift"
    summary = "int literal >= 2**31 in a jnp kernel without dtype pin"

    _LIMIT = 2 ** 31

    def check(self, ctx):
        if "ops" not in ctx.scopes:
            return []
        findings: list = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, _FUNC_NODES):
                continue
            if not _contains_jnp(fn):
                continue
            self._scan(fn, ctx.path, findings, protected=False)
        return findings

    def _scan(self, node, path, findings, protected):
        if isinstance(node, ast.Call) and any(
            kw.arg == "dtype" for kw in node.keywords
        ):
            protected = True
        if (
            not protected
            and isinstance(node, ast.Constant)
            and isinstance(node.value, int)
            and not isinstance(node.value, bool)
            and abs(node.value) >= self._LIMIT
        ):
            findings.append(Finding(
                self.code, path, node.lineno,
                f"int literal {node.value:#x} does not fit int32; pin a "
                "dtype= on the enclosing constructor or build it from "
                "narrow parts",
            ))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES):
                continue  # nested defs scanned on their own
            self._scan(child, path, findings, protected)


# ------------------------------------------------------------------- HD005

class MetricNameRule:
    """HD005: metric / event names must be static lowercase dotted names.

    Tracer metrics (``tracer.count/observe/span``) and flight-recorder
    events (``obs.emit``) form a queryable taxonomy: dashboards, bench
    diffs and the obs CLI all key on exact strings. A name built per
    call — an f-string, ``+`` concatenation, ``str.format`` — forks the
    taxonomy silently (``replica.caught.double_propose`` vs a typo'd
    interpolation) and defeats grep. It can also allocate a fresh
    counter per distinct value, unbounding the registry.

    Applies in every file (the receiver leaf — ``tracer``, ``obs``,
    ``recorder`` — is the scope). The name argument must be one of:

    * a string literal matching ``segment(.segment)*`` of
      ``[a-z0-9_]`` — the documented ``<subsystem>.<stage>.<event>``
      shape;
    * a name / attribute / subscript — a table lookup
      (``_MSG_METRIC[t]``), where the table's literals are checked at
      their definition site by the same grep-ability argument;
    * a ``<table>.get(...)`` call — the dict-with-default lookup idiom;
    * a conditional expression whose arms are themselves allowed.

    Everything else — f-strings, concatenation, ``.format()``/arbitrary
    call results, non-conforming literals — is flagged.

    Additionally, ``.emit`` literals under the *closed* event families
    (``sched.launch.*``, ``verify.occupancy.*``, ``metrics.*``,
    ``bls.*``, ``exec.*``) must be
    members of the recorder's EVENT_KINDS taxonomy: these families are
    machine-consumed (Perfetto device track, tenant report, registry
    snapshot), so a well-formed-but-unknown name there is a silent
    taxonomy fork the journal digest test cannot catch in files the
    test's grep does not cover.
    """

    code = "HD005"
    name = "dynamic-metric-name"
    summary = "tracer/recorder metric name built per call or malformed"

    _METHODS = frozenset({"count", "observe", "span", "emit"})
    _RECEIVERS = frozenset({"tracer", "obs", "recorder"})
    _NAME_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)*$")
    #: Event-name prefixes whose membership is closed: an ``.emit``
    #: literal under one of these must appear in EVENT_KINDS verbatim.
    _CLOSED_PREFIXES = ("sched.launch.", "verify.occupancy.", "metrics.",
                        "load.", "admission.", "bls.", "tenant.drain.",
                        "service.", "exec.", "merkle.", "proof.",
                        "trace.", "slo.", "campaign.")

    def check(self, ctx):
        findings: list = []
        for n in ast.walk(ctx.tree):
            if not (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in self._METHODS
                and n.args
            ):
                continue
            recv = _dotted(n.func.value)
            if recv is None or recv.split(".")[-1] not in self._RECEIVERS:
                continue
            problem = self._problem(n.args[0], n.func.attr)
            if problem:
                findings.append(Finding(
                    self.code, ctx.path, n.lineno,
                    f"metric name for .{n.func.attr}() {problem}; use a "
                    "lowercase dotted literal or a lookup into a literal "
                    "table",
                ))
        return findings

    def _problem(self, arg, method="count"):
        """None if ``arg`` is an acceptable name form, else a description."""
        if isinstance(arg, ast.Constant):
            if isinstance(arg.value, str) and self._NAME_RE.match(arg.value):
                if method == "emit" and arg.value.startswith(
                    self._CLOSED_PREFIXES
                ):
                    # Imported lazily so the lint core stays importable
                    # even if the obs package is being refactored.
                    from hyperdrive_tpu.obs.recorder import EVENT_KINDS

                    if arg.value not in EVENT_KINDS:
                        return (
                            f"literal {arg.value!r} is under a closed "
                            "event family but is not in EVENT_KINDS"
                        )
                return None
            return f"literal {arg.value!r} is not lowercase dotted form"
        if isinstance(arg, (ast.Name, ast.Attribute, ast.Subscript)):
            return None  # table lookup; literals audited where defined
        if isinstance(arg, ast.IfExp):
            return (
                self._problem(arg.body, method)
                or self._problem(arg.orelse, method)
            )
        if isinstance(arg, ast.JoinedStr):
            return "is an f-string built per call"
        if isinstance(arg, ast.BinOp):
            return "is concatenated per call"
        if isinstance(arg, ast.Call):
            if (
                isinstance(arg.func, ast.Attribute)
                and arg.func.attr == "get"
            ):
                return None  # dict .get(key, default) lookup
            return "is a call result, not a static name"
        return "is not a static name"


# ------------------------------------------------------------------- HD006

class AsyncFetchRule:
    """HD006: blocking device fetch inside a devsched async scope.

    In async scope (``devsched/``, any ``@async_scope`` function, or a
    ``# hdlint: scope=async`` pragma) the device is reached through
    :class:`~hyperdrive_tpu.devsched.DeviceWorkQueue` futures — that is
    the scope's whole contract. A raw ``device_fetch(...)`` there
    re-serializes the pipeline the scope exists to overlap: it blocks
    THIS submitter on a sync the queue would have amortized across
    every pending command at the next drain. Flagged unless the
    enclosing function is a declared ``@drain_point`` — blocking is the
    point of a drain, exactly as ``device_fetch`` is the point of a
    sync under HD001 (the two rules compose: HD001 funnels hot-path
    syncs into ``device_fetch``; HD006 funnels async-scope fetches into
    drain points).
    """

    code = "HD006"
    name = "blocking-fetch-in-async-scope"
    summary = "raw device_fetch inside a devsched async scope"

    def check(self, ctx):
        findings: list = []
        parents = _parent_map(ctx.tree)
        if "async" in ctx.scopes:
            roots = [ctx.tree]
        else:
            roots = [
                n for n in ast.walk(ctx.tree)
                if isinstance(n, _FUNC_NODES)
                and _has_decorator(n, {"async_scope"})
            ]
        seen: set = set()
        for root in roots:
            for n in ast.walk(root):
                if not _is_device_fetch(n) or id(n) in seen:
                    continue
                seen.add(id(n))
                fn = _enclosing_function(n, parents)
                if fn is not None and _has_decorator(fn, {"drain_point"}):
                    continue
                findings.append(Finding(
                    self.code, ctx.path, n.lineno,
                    "blocking device_fetch inside a devsched async scope "
                    "re-serializes the pipeline; submit to the work queue "
                    "and read the mask in the future's callback, or mark "
                    "the enclosing function @drain_point",
                ))
        return findings


from hyperdrive_tpu.analysis.wireflow import (  # noqa: E402
    CodecPairRule,
    TagDispatchRule,
    WireBoundsRule,
    WireTaintRule,
)

ALL_RULES = {
    r.code: r
    for r in (HostSyncRule, RetraceRule, NondetIterRule, DtypeWidthRule,
              MetricNameRule, AsyncFetchRule, WireTaintRule, WireBoundsRule,
              CodecPairRule, TagDispatchRule)
}


def default_rules():
    return [cls() for cls in ALL_RULES.values()]
