"""CLI: ``python -m hyperdrive_tpu.analysis [paths...] [--strict]``.

Exit codes: 0 clean, 1 findings, 2 usage/parse errors. With no paths,
lints the installed ``hyperdrive_tpu`` package tree (what CI gates on).
"""

from __future__ import annotations

import argparse
import os
import sys

from hyperdrive_tpu.analysis.engine import lint_paths
from hyperdrive_tpu.analysis.rules import ALL_RULES, default_rules


def _default_target() -> str:
    import hyperdrive_tpu

    return os.path.dirname(os.path.abspath(hyperdrive_tpu.__file__))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m hyperdrive_tpu.analysis",
        description="hdlint: JAX-aware static analysis for hyperdrive_tpu "
                    "(rule catalog: ANALYSIS.md)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to lint (default: the hyperdrive_tpu "
             "package)",
    )
    parser.add_argument(
        "--strict", action="store_true",
        help="also fail on suppressions that omit a reason (HD000)",
    )
    parser.add_argument(
        "--rules", default=None, metavar="HD001,HD003",
        help="comma-separated rule codes to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "--wire-report", action="store_true",
        help="print the wire-codec inventory (tag, version, max_bytes, "
             "roundtrip-test locations) instead of linting",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code, cls in sorted(ALL_RULES.items()):
            print(f"{code}  {cls.name:28s} {cls.summary}")
        return 0

    if args.wire_report:
        from hyperdrive_tpu.analysis.wireflow import wire_report

        print(wire_report(args.paths or [_default_target()]))
        return 0

    if args.rules:
        codes = [c.strip().upper() for c in args.rules.split(",") if c.strip()]
        unknown = [c for c in codes if c not in ALL_RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(ALL_RULES))})", file=sys.stderr)
            return 2
        rules = [ALL_RULES[c]() for c in codes]
    else:
        rules = default_rules()

    paths = args.paths or [_default_target()]
    findings, errors = lint_paths(paths, rules, strict=args.strict)

    for err in errors:
        print(f"error: {err}", file=sys.stderr)
    for f in findings:
        print(f.format())
    if findings:
        print(f"hdlint: {len(findings)} finding(s)", file=sys.stderr)
    if errors:
        return 2
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
