"""Source annotations the lint rules key on.

``@hot_path`` marks a function outside the path-scoped hot set
(``ops/``, ``tallyflush.py``, ``batch.py``, ``harness/sim.py``) as a
throughput-critical leg: HD001 then audits its body for implicit
host↔device syncs exactly as it audits the scoped files.

``device_fetch`` is the ONE blessed device→host materialization point.
A sync that is genuinely required (a verify mask the host automaton
must branch on, a warmup result that forces compilation) goes through
it; HD001 treats anything inside a ``device_fetch(...)`` call as
accounted-for. Keeping every deliberate sync behind one name makes the
cost grep-able: ``grep -rn device_fetch hyperdrive_tpu`` IS the sync
budget.

``@wire_codec`` is the same doctrine applied to the wire surface:
every encoder/decoder pair that touches bytes a Byzantine peer can
author registers itself under a frame-family ``tag`` with a declared
``max_bytes`` decode budget. The registry is read three ways — the
wire rules (HD007–HD010, analysis/wireflow.py) check it syntactically
without importing anything, the HDS005 WireBudget sanitizer charges
decodes against it at runtime, and ``--wire-report`` prints it as the
one-glance codec inventory. ``grep -rn wire_codec hyperdrive_tpu`` IS
the attack surface.
"""

from __future__ import annotations

__all__ = [
    "hot_path",
    "async_scope",
    "drain_point",
    "device_fetch",
    "set_fetch_observer",
    "set_fetch_probe",
    "wire_codec",
    "wire_entry",
    "declare_wire_budget",
    "wire_budget_for",
    "WIRE_CODECS",
    "WIRE_BUDGETS",
    "WireCodecSpec",
]

#: Optional callback invoked with the ``why`` string on every
#: device_fetch — the flight recorder's tap (obs/recorder.py). Module
#: global, not thread-local: the sim installs it for the duration of an
#: observed run and removes it in a finally; the default None keeps the
#: fetch path at one global load.
_fetch_observer = None


def set_fetch_observer(cb) -> None:
    """Install (or, with None, remove) the device_fetch observer."""
    global _fetch_observer
    _fetch_observer = cb


#: Optional timing probe bracketing the materialization itself — the
#: device-telemetry tap (obs/devtel.py) installs it for the duration of
#: one coalesced launch so the launch's wall time decomposes into a
#: sync share. Same discipline as the observer: module global, default
#: None, one load + None check on the untapped path.
_fetch_probe = None


def set_fetch_probe(probe) -> None:
    """Install (or, with None, remove) the device_fetch timing probe —
    an object with ``fetch_begin(why)`` / ``fetch_end(why)`` hooks."""
    global _fetch_probe
    _fetch_probe = probe


def hot_path(fn=None):
    """Mark ``fn`` as a throughput-critical leg for HD001.

    Usable bare (``@hot_path``) or called (``@hot_path()``). Pure
    marker: returns ``fn`` unchanged apart from a ``__hd_hot_path__``
    attribute, so it composes with jit/caching decorators and costs
    nothing at call time.
    """
    if fn is None:
        return hot_path
    try:
        fn.__hd_hot_path__ = True
    except (AttributeError, TypeError):  # builtins / slotted callables
        pass
    return fn


def async_scope(fn=None):
    """Mark ``fn`` as devsched-managed async code for HD006.

    Inside an async scope (this marker, or the path-scoped
    ``devsched/`` package, or a ``# hdlint: scope=async`` pragma),
    futures are the only allowed device-access idiom: a raw blocking
    :func:`device_fetch` would re-serialize the pipeline the scope
    exists to overlap, so HD006 flags it unless the enclosing function
    is a declared :func:`drain_point`. Pure marker like
    :func:`hot_path`: usable bare or called, zero call-time cost.
    """
    if fn is None:
        return async_scope
    try:
        fn.__hd_async_scope__ = True
    except (AttributeError, TypeError):  # builtins / slotted callables
        pass
    return fn


def drain_point(fn=None):
    """Mark ``fn`` as a devsched drain point: the ONE place an async
    scope is allowed to block (resolve futures, materialize masks).
    HD006 exempts the marked function's body — blocking is the point
    of a drain, exactly as ``device_fetch`` is the point of a sync.
    """
    if fn is None:
        return drain_point
    try:
        fn.__hd_drain_point__ = True
    except (AttributeError, TypeError):  # builtins / slotted callables
        pass
    return fn


class WireCodecSpec:
    """One registered codec endpoint: ``tag`` names the frame family,
    ``max_bytes`` is its per-frame decode byte budget, ``role`` is
    ``encode`` / ``decode`` / ``both`` (classes carrying a
    marshal/unmarshal pair register once as ``both``)."""

    __slots__ = ("tag", "max_bytes", "version", "role", "name", "module")

    def __init__(self, tag, max_bytes, version, role, name, module):
        self.tag = tag
        self.max_bytes = max_bytes
        self.version = version
        self.role = role
        self.name = name
        self.module = module

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"WireCodecSpec(tag={self.tag!r}, role={self.role!r}, "
                f"max_bytes={self.max_bytes}, v{self.version}, "
                f"{self.module}.{self.name})")


#: tag -> list[WireCodecSpec], populated at import time by the
#: decorators below. Runtime consumers: the HDS005 WireBudget (budget
#: lookup by tag) and tests/test_wire_audit.py (closure + fuzz
#: parametrization). The static rules never read this — they collect
#: the same decorators from the AST, so linting never imports the
#: code it scans.
WIRE_CODECS: dict = {}

#: tag -> max_bytes for budget-only entries (object-frame seams with no
#: byte codec of their own, e.g. the overlay's partial-aggregate frames
#: whose wire size is *estimated* and charged at ingress).
WIRE_BUDGETS: dict = {}

_ENCODE_PREFIXES = ("encode", "marshal")
_DECODE_PREFIXES = ("decode", "unmarshal")


def _infer_role(obj) -> str:
    if isinstance(obj, type):
        return "both"
    name = getattr(obj, "__name__", "")
    leaf = name.lstrip("_")
    if any(leaf.startswith(p) for p in _DECODE_PREFIXES):
        return "decode"
    if any(leaf.startswith(p) for p in _ENCODE_PREFIXES):
        return "encode"
    return "both"


def wire_codec(*, tag: str, max_bytes: int, version: int = 1,
               role: str = None):
    """Register a wire codec endpoint under the frame-family ``tag``.

    Apply to an ``encode_*`` / ``marshal_*`` function, its matching
    ``decode_*`` / ``unmarshal_*``, or ONCE to a class that carries the
    ``marshal``/``unmarshal`` pair as methods. ``max_bytes`` is the
    decode byte budget HDS005 enforces per frame of this family (the
    surge MaxBytes analogue, declared where the format is defined
    instead of implied by call sites). ``role`` is inferred from the
    name when omitted. Pure marker at call time: returns the object
    unchanged apart from an ``__hd_wire_codec__`` attribute.
    """
    if max_bytes <= 0:
        raise ValueError(f"wire_codec max_bytes must be positive: {max_bytes}")

    def deco(obj):
        spec = WireCodecSpec(
            tag=str(tag),
            max_bytes=int(max_bytes),
            version=int(version),
            role=role if role is not None else _infer_role(obj),
            name=getattr(obj, "__name__", "?"),
            module=getattr(obj, "__module__", "?"),
        )
        try:
            obj.__hd_wire_codec__ = spec
        except (AttributeError, TypeError):  # slotted callables
            pass
        WIRE_CODECS.setdefault(spec.tag, []).append(spec)
        return obj

    return deco


def wire_entry(fn=None):
    """Mark ``fn`` as a wire entry point: its byte-typed parameters are
    untrusted (authored by a potentially Byzantine peer). HD007/HD008
    seed their taint lattice from these markers in addition to the
    intrinsic socket-receive sources, so handlers that take already-
    framed payloads (inbox pumps, replay loaders) stay in the audited
    set. Pure marker, usable bare or called."""
    if fn is None:
        return wire_entry
    try:
        fn.__hd_wire_entry__ = True
    except (AttributeError, TypeError):  # builtins / slotted callables
        pass
    return fn


def declare_wire_budget(tag: str, max_bytes: int) -> None:
    """Declare a decode budget for a frame family with no byte codec of
    its own (object-frame seams: the ingress handler estimates the wire
    size and charges it via the sanitizer's ``wire_charge``)."""
    if max_bytes <= 0:
        raise ValueError(f"wire budget must be positive: {max_bytes}")
    WIRE_BUDGETS[str(tag)] = int(max_bytes)


def wire_budget_for(tag: str):
    """The declared ``max_bytes`` for ``tag`` (codec registrations win
    over budget-only declarations), or None when the tag is unknown."""
    specs = WIRE_CODECS.get(tag)
    if specs:
        return min(s.max_bytes for s in specs)
    return WIRE_BUDGETS.get(tag)


def device_fetch(x, *, why: str = ""):
    """THE annotated device→host sync point.

    Materializes ``x`` (a jax array, a device-backed buffer, or
    anything ``np.asarray`` accepts) on the host and returns a numpy
    array. ``why`` is a free-form justification that lives at the call
    site for reviewers; it is not interpreted.

    HD001 recognizes this call and exempts its subtree — the point is
    not to forbid syncs but to make every one of them a named,
    searchable decision.
    """
    import numpy as np

    if _fetch_observer is not None:
        _fetch_observer(why)
    probe = _fetch_probe
    if probe is not None:
        probe.fetch_begin(why)
        try:
            return np.asarray(x)
        finally:
            probe.fetch_end(why)
    return np.asarray(x)
