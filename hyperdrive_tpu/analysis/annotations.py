"""Source annotations the lint rules key on.

``@hot_path`` marks a function outside the path-scoped hot set
(``ops/``, ``tallyflush.py``, ``batch.py``, ``harness/sim.py``) as a
throughput-critical leg: HD001 then audits its body for implicit
host↔device syncs exactly as it audits the scoped files.

``device_fetch`` is the ONE blessed device→host materialization point.
A sync that is genuinely required (a verify mask the host automaton
must branch on, a warmup result that forces compilation) goes through
it; HD001 treats anything inside a ``device_fetch(...)`` call as
accounted-for. Keeping every deliberate sync behind one name makes the
cost grep-able: ``grep -rn device_fetch hyperdrive_tpu`` IS the sync
budget.
"""

from __future__ import annotations

__all__ = [
    "hot_path",
    "async_scope",
    "drain_point",
    "device_fetch",
    "set_fetch_observer",
    "set_fetch_probe",
]

#: Optional callback invoked with the ``why`` string on every
#: device_fetch — the flight recorder's tap (obs/recorder.py). Module
#: global, not thread-local: the sim installs it for the duration of an
#: observed run and removes it in a finally; the default None keeps the
#: fetch path at one global load.
_fetch_observer = None


def set_fetch_observer(cb) -> None:
    """Install (or, with None, remove) the device_fetch observer."""
    global _fetch_observer
    _fetch_observer = cb


#: Optional timing probe bracketing the materialization itself — the
#: device-telemetry tap (obs/devtel.py) installs it for the duration of
#: one coalesced launch so the launch's wall time decomposes into a
#: sync share. Same discipline as the observer: module global, default
#: None, one load + None check on the untapped path.
_fetch_probe = None


def set_fetch_probe(probe) -> None:
    """Install (or, with None, remove) the device_fetch timing probe —
    an object with ``fetch_begin(why)`` / ``fetch_end(why)`` hooks."""
    global _fetch_probe
    _fetch_probe = probe


def hot_path(fn=None):
    """Mark ``fn`` as a throughput-critical leg for HD001.

    Usable bare (``@hot_path``) or called (``@hot_path()``). Pure
    marker: returns ``fn`` unchanged apart from a ``__hd_hot_path__``
    attribute, so it composes with jit/caching decorators and costs
    nothing at call time.
    """
    if fn is None:
        return hot_path
    try:
        fn.__hd_hot_path__ = True
    except (AttributeError, TypeError):  # builtins / slotted callables
        pass
    return fn


def async_scope(fn=None):
    """Mark ``fn`` as devsched-managed async code for HD006.

    Inside an async scope (this marker, or the path-scoped
    ``devsched/`` package, or a ``# hdlint: scope=async`` pragma),
    futures are the only allowed device-access idiom: a raw blocking
    :func:`device_fetch` would re-serialize the pipeline the scope
    exists to overlap, so HD006 flags it unless the enclosing function
    is a declared :func:`drain_point`. Pure marker like
    :func:`hot_path`: usable bare or called, zero call-time cost.
    """
    if fn is None:
        return async_scope
    try:
        fn.__hd_async_scope__ = True
    except (AttributeError, TypeError):  # builtins / slotted callables
        pass
    return fn


def drain_point(fn=None):
    """Mark ``fn`` as a devsched drain point: the ONE place an async
    scope is allowed to block (resolve futures, materialize masks).
    HD006 exempts the marked function's body — blocking is the point
    of a drain, exactly as ``device_fetch`` is the point of a sync.
    """
    if fn is None:
        return drain_point
    try:
        fn.__hd_drain_point__ = True
    except (AttributeError, TypeError):  # builtins / slotted callables
        pass
    return fn


def device_fetch(x, *, why: str = ""):
    """THE annotated device→host sync point.

    Materializes ``x`` (a jax array, a device-backed buffer, or
    anything ``np.asarray`` accepts) on the host and returns a numpy
    array. ``why`` is a free-form justification that lives at the call
    site for reviewers; it is not interpreted.

    HD001 recognizes this call and exempts its subtree — the point is
    not to forbid syncs but to make every one of them a named,
    searchable decision.
    """
    import numpy as np

    if _fetch_observer is not None:
        _fetch_observer(why)
    probe = _fetch_probe
    if probe is not None:
        probe.fetch_begin(why)
        try:
            return np.asarray(x)
        finally:
            probe.fetch_end(why)
    return np.asarray(x)
