"""hdlint engine: file loading, scope resolution, suppressions, reporting.

A *scope* names a slice of the repo a rule cares about:

* ``hot``    — host↔device sync discipline (HD001): ``ops/``,
  ``tallyflush.py``, ``batch.py``, ``harness/sim.py``; elsewhere only
  functions marked ``@hot_path``.
* ``digest`` — determinism feeding commit digests / wire bytes (HD003):
  ``codec.py``, ``process.py``, ``harness/sim.py``.
* ``ops``    — device kernel dtype discipline (HD004): ``ops/``.
* ``async``  — devsched future discipline (HD006): ``devsched/``;
  elsewhere only functions marked ``@async_scope``.

Scopes resolve from the file path; a file outside the path set can opt
in with a pragma comment (used by the fixture corpus)::

    # hdlint: scope=hot,digest,ops

Suppressions attach to the flagged line or the line directly above::

    # hdlint: disable=HD003 replay order is fixed upstream
    for h in maybe_a_set: ...

The reason text is part of the syntax: ``--strict`` reports any
suppression that omits it (as HD000), so waivers stay auditable.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

__all__ = [
    "Finding",
    "Suppression",
    "FileContext",
    "iter_python_files",
    "lint_paths",
]

SUPPRESS_RE = re.compile(
    r"#\s*hdlint:\s*disable=(?P<codes>[A-Z][A-Z0-9]*(?:\s*,\s*[A-Z][A-Z0-9]*)*)"
    r"(?:\s+(?P<reason>\S.*))?"
)
SCOPE_RE = re.compile(r"#\s*hdlint:\s*scope=(?P<scopes>[a-z]+(?:\s*,\s*[a-z]+)*)")

VALID_SCOPES = frozenset({"hot", "digest", "ops", "async"})

_HOT_SUFFIXES = ("/tallyflush.py", "/batch.py", "/harness/sim.py")
_DIGEST_SUFFIXES = ("/codec.py", "/process.py", "/harness/sim.py")

_SKIP_DIRS = frozenset({"__pycache__", ".git", ".jax_cache", "fixtures"})


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass
class Suppression:
    line: int
    rules: frozenset
    reason: str
    used: bool = field(default=False, compare=False)

    def covers(self, rule: str) -> bool:
        return rule in self.rules


class FileContext:
    """One parsed source file: AST + pragmas, handed to every rule."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        #: line -> list[Suppression]
        self.suppressions: dict[int, list] = {}
        self.forced_scopes: set = set()
        self._scan_comments()
        self.scopes = self._path_scopes() | self.forced_scopes

    # ------------------------------------------------------------- comments

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [
                (t.start[0], t.string)
                for t in tokens
                if t.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return
        for line, text in comments:
            m = SUPPRESS_RE.search(text)
            if m:
                codes = frozenset(
                    c.strip() for c in m.group("codes").split(",") if c.strip()
                )
                sup = Suppression(line, codes, (m.group("reason") or "").strip())
                self.suppressions.setdefault(line, []).append(sup)
            m = SCOPE_RE.search(text)
            if m:
                self.forced_scopes |= {
                    s.strip()
                    for s in m.group("scopes").split(",")
                    if s.strip() in VALID_SCOPES
                }

    # --------------------------------------------------------------- scopes

    def _path_scopes(self) -> set:
        p = self.path.replace(os.sep, "/")
        scopes: set = set()
        in_ops = "/ops/" in p or p.startswith("ops/")
        if in_ops or any(p.endswith(s) for s in _HOT_SUFFIXES):
            scopes.add("hot")
        if any(p.endswith(s) for s in _DIGEST_SUFFIXES):
            scopes.add("digest")
        if in_ops:
            scopes.add("ops")
        if "/devsched/" in p or p.startswith("devsched/"):
            scopes.add("async")
        return scopes

    # --------------------------------------------------------- suppressions

    def suppressed(self, finding: Finding) -> bool:
        """A finding is waived by a matching suppression on its own line
        or on the line directly above (the comment-above idiom)."""
        for line in (finding.line, finding.line - 1):
            for sup in self.suppressions.get(line, ()):
                if sup.covers(finding.rule):
                    sup.used = True
                    return True
        return False

    def suppression_issues(self) -> list:
        """Reasonless suppressions, reported under HD000 in --strict."""
        issues = []
        for line, sups in sorted(self.suppressions.items()):
            for sup in sups:
                if not sup.reason:
                    issues.append(
                        Finding(
                            "HD000",
                            self.path,
                            line,
                            "suppression without a reason: append a "
                            "justification after the rule code(s)",
                        )
                    )
        return issues


def iter_python_files(paths) -> list:
    """Expand files/directories into a sorted list of .py files.

    Skips caches, VCS internals, and any directory named ``fixtures``
    (the known-bad lint corpus must never leak into a default repo
    scan — tests point at it explicitly)."""
    out = []
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                out.append(path)
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(
                d for d in dirs
                if d not in _SKIP_DIRS and not d.startswith(".")
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    out.append(os.path.join(root, name))
    return sorted(set(out))


def lint_paths(paths, rules, strict: bool = False):
    """Run ``rules`` over ``paths``.

    Rules come in two shapes: per-file rules expose ``check(ctx)`` and
    run once per parsed file; package rules (the HD007–HD010 wire
    dataflow set) expose ``check_package(ctxs)`` and run ONCE over the
    full parsed file set, because their properties — taint crossing
    module boundaries, codec-pair completeness — do not decompose per
    file. Both kinds yield plain :class:`Finding`\\ s, and suppressions
    apply identically: a package finding is waived by a pragma in the
    file it points at.

    Returns ``(findings, errors)``: surviving findings sorted by
    location, and non-lint problems (unreadable / unparsable files) as
    strings. ``strict`` adds HD000 findings for reasonless
    suppressions."""
    findings: list = []
    errors: list = []
    ctxs: list = []
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            errors.append(f"{path}: unreadable: {e}")
            continue
        try:
            ctxs.append(FileContext(path, source))
        except SyntaxError as e:
            errors.append(f"{path}: syntax error: {e}")
    by_path = {ctx.path: ctx for ctx in ctxs}
    file_rules = [r for r in rules if hasattr(r, "check")]
    package_rules = [r for r in rules if hasattr(r, "check_package")]
    for ctx in ctxs:
        raw: list = []
        for rule in file_rules:
            raw.extend(rule.check(ctx))
        findings.extend(f for f in set(raw) if not ctx.suppressed(f))
    raw = []
    for rule in package_rules:
        raw.extend(rule.check_package(ctxs))
    for f in set(raw):
        ctx = by_path.get(f.path)
        if ctx is None or not ctx.suppressed(f):
            findings.append(f)
    if strict:
        for ctx in ctxs:
            findings.extend(ctx.suppression_issues())
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings, errors
