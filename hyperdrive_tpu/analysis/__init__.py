"""hdlint: repo-specific static analysis + runtime consensus sanitizer.

Two halves, one contract (see ANALYSIS.md for the full catalog):

* **Static** (``python -m hyperdrive_tpu.analysis``): AST rules HD001..
  HD004 guard the properties the JAX port's headline numbers rest on —
  hot paths free of silent host↔device syncs (HD001) and jit retrace
  hazards (HD002), digest-feeding code free of nondeterministic
  iteration (HD003), ops kernels free of dtype-width drift (HD004).
* **Runtime** (:mod:`hyperdrive_tpu.analysis.sanitizer`): invariant
  checks HDS001..HDS004 interposed on the Process DI seams and the
  DeviceTallyFlusher tally view, toggled by ``HD_SANITIZE`` (tier-1
  tests enable it by default via conftest).

This module stays import-light (no jax, no numpy at import time): it is
imported by :mod:`hyperdrive_tpu.replica` on every construction.
"""

from hyperdrive_tpu.analysis.annotations import device_fetch, hot_path
from hyperdrive_tpu.analysis.sanitizer import SanitizerError

__all__ = ["device_fetch", "hot_path", "SanitizerError"]
