// Native host-side runtime for the TPU batch verifier.
//
// The TPU kernel (hyperdrive_tpu/ops/ed25519_jax.py) consumes packed limb
// tensors; producing them requires per-signature work that is bit-twiddly
// and branchy — exactly what the host should do, and exactly what pure
// Python does ~100x too slowly: Ed25519 point decompression (one field
// exponentiation per point), SHA-512 challenge scalars, reduction mod the
// group order, and 13-bit limb / 4-bit nibble packing.
//
// This file is a self-contained C++ implementation of that pipeline with a
// plain C ABI (ctypes-friendly). Semantics are bit-for-bit identical to the
// Python oracle (hyperdrive_tpu/crypto/ed25519.py, RFC 8032 decoding rules
// including the x2 == 0 edge cases); differential tests enforce parity.
//
// Field arithmetic: GF(2^255 - 19) as 5 x 51-bit limbs in uint64, products
// via unsigned __int128 (standard radix-51 representation).

#include <cstdint>
#include <cstring>
#include <mutex>

typedef unsigned __int128 u128;
typedef uint64_t u64;
typedef uint32_t u32;
typedef uint8_t u8;

namespace {

// ------------------------------------------------------------------ fe25519

constexpr u64 MASK51 = ((u64)1 << 51) - 1;

struct Fe {
  u64 v[5];
};

inline Fe fe_zero() { return Fe{{0, 0, 0, 0, 0}}; }
inline Fe fe_one() { return Fe{{1, 0, 0, 0, 0}}; }

inline Fe fe_add(const Fe &a, const Fe &b) {
  Fe r;
  for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + b.v[i];
  return r;
}

// a - b with a pre-bias of 2p (limb-wise dominating), keeping limbs
// non-negative; inputs must have limbs < 2^52.
inline Fe fe_sub(const Fe &a, const Fe &b) {
  Fe r;
  r.v[0] = a.v[0] + 0xFFFFFFFFFFFDAULL - b.v[0];
  r.v[1] = a.v[1] + 0xFFFFFFFFFFFFEULL - b.v[1];
  r.v[2] = a.v[2] + 0xFFFFFFFFFFFFEULL - b.v[2];
  r.v[3] = a.v[3] + 0xFFFFFFFFFFFFEULL - b.v[3];
  r.v[4] = a.v[4] + 0xFFFFFFFFFFFFEULL - b.v[4];
  return r;
}

inline void fe_carry(Fe &r) {
  u64 c;
  c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
  c = r.v[1] >> 51; r.v[1] &= MASK51; r.v[2] += c;
  c = r.v[2] >> 51; r.v[2] &= MASK51; r.v[3] += c;
  c = r.v[3] >> 51; r.v[3] &= MASK51; r.v[4] += c;
  c = r.v[4] >> 51; r.v[4] &= MASK51; r.v[0] += c * 19;
  c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
}

inline Fe fe_mul(const Fe &a, const Fe &b) {
  u128 t0, t1, t2, t3, t4;
  u64 a0 = a.v[0], a1 = a.v[1], a2 = a.v[2], a3 = a.v[3], a4 = a.v[4];
  u64 b0 = b.v[0], b1 = b.v[1], b2 = b.v[2], b3 = b.v[3], b4 = b.v[4];
  u64 b1_19 = b1 * 19, b2_19 = b2 * 19, b3_19 = b3 * 19, b4_19 = b4 * 19;

  t0 = (u128)a0 * b0 + (u128)a1 * b4_19 + (u128)a2 * b3_19 +
       (u128)a3 * b2_19 + (u128)a4 * b1_19;
  t1 = (u128)a0 * b1 + (u128)a1 * b0 + (u128)a2 * b4_19 +
       (u128)a3 * b3_19 + (u128)a4 * b2_19;
  t2 = (u128)a0 * b2 + (u128)a1 * b1 + (u128)a2 * b0 +
       (u128)a3 * b4_19 + (u128)a4 * b3_19;
  t3 = (u128)a0 * b3 + (u128)a1 * b2 + (u128)a2 * b1 + (u128)a3 * b0 +
       (u128)a4 * b4_19;
  t4 = (u128)a0 * b4 + (u128)a1 * b3 + (u128)a2 * b2 + (u128)a3 * b1 +
       (u128)a4 * b0;

  Fe r;
  u64 c;
  r.v[0] = (u64)t0 & MASK51; c = (u64)(t0 >> 51);
  t1 += c;
  r.v[1] = (u64)t1 & MASK51; c = (u64)(t1 >> 51);
  t2 += c;
  r.v[2] = (u64)t2 & MASK51; c = (u64)(t2 >> 51);
  t3 += c;
  r.v[3] = (u64)t3 & MASK51; c = (u64)(t3 >> 51);
  t4 += c;
  r.v[4] = (u64)t4 & MASK51; c = (u64)(t4 >> 51);
  r.v[0] += c * 19;
  c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
  return r;
}

inline Fe fe_sqr(const Fe &a) { return fe_mul(a, a); }

// Canonical little-endian 32 bytes (value in [0, p)).
inline void fe_tobytes(u8 out[32], const Fe &a) {
  Fe t = a;
  fe_carry(t);
  // Fully reduce: add 19, propagate, then drop bit 255 (classic trick).
  u64 q = (t.v[0] + 19) >> 51;
  q = (t.v[1] + q) >> 51;
  q = (t.v[2] + q) >> 51;
  q = (t.v[3] + q) >> 51;
  q = (t.v[4] + q) >> 51;
  t.v[0] += 19 * q;
  u64 c;
  c = t.v[0] >> 51; t.v[0] &= MASK51; t.v[1] += c;
  c = t.v[1] >> 51; t.v[1] &= MASK51; t.v[2] += c;
  c = t.v[2] >> 51; t.v[2] &= MASK51; t.v[3] += c;
  c = t.v[3] >> 51; t.v[3] &= MASK51; t.v[4] += c;
  t.v[4] &= MASK51;

  u64 w0 = t.v[0] | (t.v[1] << 51);
  u64 w1 = (t.v[1] >> 13) | (t.v[2] << 38);
  u64 w2 = (t.v[2] >> 26) | (t.v[3] << 25);
  u64 w3 = (t.v[3] >> 39) | (t.v[4] << 12);
  memcpy(out, &w0, 8);
  memcpy(out + 8, &w1, 8);
  memcpy(out + 16, &w2, 8);
  memcpy(out + 24, &w3, 8);
}

inline Fe fe_frombytes(const u8 in[32]) {
  u64 w0, w1, w2, w3;
  memcpy(&w0, in, 8);
  memcpy(&w1, in + 8, 8);
  memcpy(&w2, in + 16, 8);
  memcpy(&w3, in + 24, 8);
  Fe r;
  r.v[0] = w0 & MASK51;
  r.v[1] = ((w0 >> 51) | (w1 << 13)) & MASK51;
  r.v[2] = ((w1 >> 38) | (w2 << 26)) & MASK51;
  r.v[3] = ((w2 >> 25) | (w3 << 39)) & MASK51;
  r.v[4] = (w3 >> 12) & MASK51;  // drops bit 255 — callers handle the sign
  return r;
}

inline bool fe_iszero(const Fe &a) {
  u8 b[32];
  fe_tobytes(b, a);
  u8 acc = 0;
  for (int i = 0; i < 32; i++) acc |= b[i];
  return acc == 0;
}

inline bool fe_eq(const Fe &a, const Fe &b) {
  u8 x[32], y[32];
  fe_tobytes(x, a);
  fe_tobytes(y, b);
  return memcmp(x, y, 32) == 0;
}

inline bool fe_isodd(const Fe &a) {
  u8 b[32];
  fe_tobytes(b, a);
  return b[0] & 1;
}

// a^(2^n) in place helper.
inline Fe fe_nsqr(Fe a, int n) {
  for (int i = 0; i < n; i++) a = fe_sqr(a);
  return a;
}

// a^(p-5)/8 = a^(2^252 - 3), the exponent of the combined sqrt-division
// trick; standard curve25519 addition chain.
Fe fe_pow22523(const Fe &z) {
  Fe z2 = fe_sqr(z);               // 2
  Fe z8 = fe_nsqr(z2, 2);          // 8
  Fe z9 = fe_mul(z, z8);           // 9
  Fe z11 = fe_mul(z2, z9);         // 11
  Fe z22 = fe_sqr(z11);            // 22
  Fe z_5_0 = fe_mul(z9, z22);      // 2^5 - 2^0
  Fe z_10_0 = fe_mul(fe_nsqr(z_5_0, 5), z_5_0);
  Fe z_20_0 = fe_mul(fe_nsqr(z_10_0, 10), z_10_0);
  Fe z_40_0 = fe_mul(fe_nsqr(z_20_0, 20), z_20_0);
  Fe z_50_0 = fe_mul(fe_nsqr(z_40_0, 10), z_10_0);
  Fe z_100_0 = fe_mul(fe_nsqr(z_50_0, 50), z_50_0);
  Fe z_200_0 = fe_mul(fe_nsqr(z_100_0, 100), z_100_0);
  Fe z_250_0 = fe_mul(fe_nsqr(z_200_0, 50), z_50_0);
  return fe_mul(fe_nsqr(z_250_0, 2), z);  // 2^252 - 3
}

// a^(p-2), for the x2 = u * v^(p-2) edge-case-exact decompression.
Fe fe_invert(const Fe &z) {
  Fe z2 = fe_sqr(z);
  Fe z8 = fe_nsqr(z2, 2);
  Fe z9 = fe_mul(z, z8);
  Fe z11 = fe_mul(z2, z9);
  Fe z22 = fe_sqr(z11);
  Fe z_5_0 = fe_mul(z9, z22);
  Fe z_10_0 = fe_mul(fe_nsqr(z_5_0, 5), z_5_0);
  Fe z_20_0 = fe_mul(fe_nsqr(z_10_0, 10), z_10_0);
  Fe z_40_0 = fe_mul(fe_nsqr(z_20_0, 20), z_20_0);
  Fe z_50_0 = fe_mul(fe_nsqr(z_40_0, 10), z_10_0);
  Fe z_100_0 = fe_mul(fe_nsqr(z_50_0, 50), z_50_0);
  Fe z_200_0 = fe_mul(fe_nsqr(z_100_0, 100), z_100_0);
  Fe z_250_0 = fe_mul(fe_nsqr(z_200_0, 50), z_50_0);
  return fe_mul(fe_nsqr(z_250_0, 5), z11);  // 2^255 - 21 = p - 2
}

// Curve constant d = -121665/121666 mod p (value below computed offline and
// verified by the differential tests against the Python oracle).
const Fe FE_D = {{0x34dca135978a3ULL, 0x1a8283b156ebdULL, 0x5e7a26001c029ULL,
                  0x739c663a03cbbULL, 0x52036cee2b6ffULL}};
// sqrt(-1) mod p.
const Fe FE_SQRTM1 = {{0x61b274a0ea0b0ULL, 0xd5a5fc8f189dULL,
                       0x7ef5e9cbd0c60ULL, 0x78595a6804c9eULL,
                       0x2b8324804fc1dULL}};
// p as raw little-endian bytes, for the canonical y < p check.
const u8 P_BYTES[32] = {0xed, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
                        0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
                        0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
                        0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x7f};

// Little-endian compare of 32-byte values: a < b.
inline bool lt_le32(const u8 a[32], const u8 b[32]) {
  for (int i = 31; i >= 0; i--) {
    if (a[i] != b[i]) return a[i] < b[i];
  }
  return false;
}

// RFC 8032 point decoding, matching the Python oracle exactly:
//   y = enc & (2^255-1); sign = enc >> 255; reject y >= p;
//   x2 = (y^2 - 1) * (d y^2 + 1)^(p-2);
//   if x2 == 0: sign -> reject, else x = 0;
//   else x = x2^((p+3)/8) (via the 22523 chain), fixed up with sqrt(-1);
//   reject if x^2 != x2; flip parity to match sign.
// Returns false if decoding fails; else writes affine x, y.
bool point_decompress(const u8 in[32], Fe &x, Fe &y) {
  u8 ybytes[32];
  memcpy(ybytes, in, 32);
  int sign = ybytes[31] >> 7;
  ybytes[31] &= 0x7f;
  if (!lt_le32(ybytes, P_BYTES)) return false;  // non-canonical y
  y = fe_frombytes(ybytes);

  Fe y2 = fe_sqr(y);
  Fe u = fe_sub(y2, fe_one());      // y^2 - 1
  Fe v = fe_add(fe_mul(FE_D, y2), fe_one());  // d y^2 + 1
  Fe x2 = fe_mul(u, fe_invert(v));  // matches Python: v==0 -> x2 = 0

  if (fe_iszero(x2)) {
    if (sign) return false;
    x = fe_zero();
    return true;
  }

  // Candidate root: x = x2^((p+3)/8) = x2 * x2^((p-5)/8).
  x = fe_mul(x2, fe_pow22523(x2));
  Fe xx = fe_sqr(x);
  if (!fe_eq(xx, x2)) {
    x = fe_mul(x, FE_SQRTM1);
    xx = fe_sqr(x);
    if (!fe_eq(xx, x2)) return false;
  }
  if ((int)fe_isodd(x) != sign) {
    x = fe_sub(fe_zero(), x);
    fe_carry(x);
  }
  return true;
}

// ------------------------------------------------------------------ sha512

const u64 K512[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

inline u64 rotr64(u64 x, int n) { return (x >> n) | (x << (64 - n)); }

struct Sha512 {
  u64 h[8];
  u8 buf[128];
  u64 total;
  int buflen;

  Sha512() {
    h[0] = 0x6a09e667f3bcc908ULL; h[1] = 0xbb67ae8584caa73bULL;
    h[2] = 0x3c6ef372fe94f82bULL; h[3] = 0xa54ff53a5f1d36f1ULL;
    h[4] = 0x510e527fade682d1ULL; h[5] = 0x9b05688c2b3e6c1fULL;
    h[6] = 0x1f83d9abfb41bd6bULL; h[7] = 0x5be0cd19137e2179ULL;
    total = 0;
    buflen = 0;
  }

  void block(const u8 *p) {
    u64 w[80];
    for (int i = 0; i < 16; i++) {
      w[i] = ((u64)p[8 * i] << 56) | ((u64)p[8 * i + 1] << 48) |
             ((u64)p[8 * i + 2] << 40) | ((u64)p[8 * i + 3] << 32) |
             ((u64)p[8 * i + 4] << 24) | ((u64)p[8 * i + 5] << 16) |
             ((u64)p[8 * i + 6] << 8) | (u64)p[8 * i + 7];
    }
    for (int i = 16; i < 80; i++) {
      u64 s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7);
      u64 s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    u64 a = h[0], b = h[1], c = h[2], d = h[3];
    u64 e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 80; i++) {
      u64 S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
      u64 ch = (e & f) ^ (~e & g);
      u64 t1 = hh + S1 + ch + K512[i] + w[i];
      u64 S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
      u64 maj = (a & b) ^ (a & c) ^ (b & c);
      u64 t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const u8 *p, size_t n) {
    total += n;
    while (n > 0) {
      size_t take = 128 - buflen;
      if (take > n) take = n;
      memcpy(buf + buflen, p, take);
      buflen += take;
      p += take;
      n -= take;
      if (buflen == 128) {
        block(buf);
        buflen = 0;
      }
    }
  }

  void final(u8 out[64]) {
    u64 bits = total * 8;
    u8 pad = 0x80;
    update(&pad, 1);
    u8 z = 0;
    while (buflen != 112) update(&z, 1);
    u8 len[16] = {0};
    for (int i = 0; i < 8; i++) len[15 - i] = (u8)(bits >> (8 * i));
    update(len, 16);
    for (int i = 0; i < 8; i++) {
      for (int j = 0; j < 8; j++) out[8 * i + j] = (u8)(h[i] >> (56 - 8 * j));
    }
  }
};

// ------------------------------------------------------------- scalars mod L

// L = 2^252 + 27742317777372353535851937790883648493, little-endian words.
const u64 L_WORDS[4] = {0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL,
                        0x0000000000000000ULL, 0x1000000000000000ULL};

// r < L on 4 LE words.
inline bool sc_lt_l(const u64 r[4]) {
  for (int i = 3; i >= 0; i--) {
    if (r[i] != L_WORDS[i]) return r[i] < L_WORDS[i];
  }
  return false;
}

// Binary long division: 512-bit (8 LE words) mod L -> 4 LE words.
// ~512 cheap word ops per call; exactness over speed (this is a few percent
// of the packing cost; the exponentiations dominate).
//
// Constant-time: signing reduces the secret nonce r and challenge products
// through here, so the per-bit conditional subtract is a branch-free masked
// select — the instruction trace is identical for every input.
void sc_mod_l_512(const u64 x[8], u64 out[4]) {
  u64 r[4] = {0, 0, 0, 0};
  for (int bit = 511; bit >= 0; bit--) {
    // r = (r << 1) | x_bit
    u64 top = r[3] >> 63;
    r[3] = (r[3] << 1) | (r[2] >> 63);
    r[2] = (r[2] << 1) | (r[1] >> 63);
    r[1] = (r[1] << 1) | (r[0] >> 63);
    r[0] = (r[0] << 1) | ((x[bit >> 6] >> (bit & 63)) & 1);
    // top can only be set transiently right after shifting; since r < L <
    // 2^253 before each shift, r_new < 2^254, so top is always 0 — but the
    // masked subtract below is what maintains that invariant.
    u64 t[4], borrow = 0;
    for (int i = 0; i < 4; i++) {
      u128 d = (u128)r[i] - L_WORDS[i] - borrow;
      t[i] = (u64)d;
      borrow = (u64)(d >> 64) & 1;
    }
    // Use t iff the subtraction did not borrow (r >= L) or a bit shifted
    // out (top): mask = all-ones when subtracting.
    u64 mask = 0 - (top | (borrow ^ 1));
    for (int i = 0; i < 4; i++) r[i] ^= mask & (r[i] ^ t[i]);
  }
  memcpy(out, r, 32);
}

// ------------------------------------------------------------ limb packing

// 32-byte LE value -> 20 x 13-bit int32 limbs.
inline void pack_limbs13(const u8 bytes[32], int32_t *out) {
  u8 padded[34];
  memcpy(padded, bytes, 32);
  padded[32] = padded[33] = 0;
  for (int i = 0; i < 20; i++) {
    int bitpos = 13 * i;
    int byte = bitpos >> 3;
    int off = bitpos & 7;
    u32 v = (u32)padded[byte] | ((u32)padded[byte + 1] << 8) |
            ((u32)padded[byte + 2] << 16);
    out[i] = (int32_t)((v >> off) & 0x1FFF);
  }
}

// 32-byte LE scalar -> 64 x 4-bit nibbles (int32).
inline void pack_nibbles(const u8 bytes[32], int32_t *out) {
  for (int i = 0; i < 32; i++) {
    out[2 * i] = bytes[i] & 0xF;
    out[2 * i + 1] = bytes[i] >> 4;
  }
}

// ------------------------------------------------- decompressed-point cache
//
// Validator sets are small (hundreds) while batches are huge; pubkey
// decompression repeats endlessly. A tiny open-addressing cache keyed by the
// 32 raw bytes eliminates it. R points are per-signature (never cached).

struct CacheEntry {
  u8 key[32];
  u8 valid;    // entry holds a successful decompression
  u8 occupied;
  Fe x, y;
};

constexpr int CACHE_SLOTS = 1 << 12;  // 4096 entries, plenty for one set
CacheEntry g_cache[CACHE_SLOTS];
// ctypes releases the GIL during hd_pack_batch, and each replica may run on
// its own thread — all cache reads/writes happen under this mutex (the
// guarded work is a memcmp/memcpy; the expensive decompression of a missed
// key runs outside the lock).
std::mutex g_cache_mu;

inline u32 cache_hash(const u8 key[32]) {
  u32 h;
  memcpy(&h, key, 4);  // pubkeys are uniformly random — low bytes suffice
  return h & (CACHE_SLOTS - 1);
}

// Returns 1 valid / 0 invalid, filling x, y on success.
int cached_decompress(const u8 key[32], Fe &x, Fe &y) {
  u32 slot = cache_hash(key);
  int free_slot = -1;
  {
    std::lock_guard<std::mutex> lock(g_cache_mu);
    for (int probe = 0; probe < 8; probe++) {
      int idx = (slot + probe) & (CACHE_SLOTS - 1);
      CacheEntry &e = g_cache[idx];
      if (!e.occupied) {
        free_slot = idx;
        break;
      }
      if (memcmp(e.key, key, 32) == 0) {
        if (!e.valid) return 0;
        x = e.x;
        y = e.y;
        return 1;
      }
    }
  }
  bool ok = point_decompress(key, x, y);
  if (free_slot >= 0) {
    std::lock_guard<std::mutex> lock(g_cache_mu);
    CacheEntry &e = g_cache[free_slot];
    if (!e.occupied) {  // another thread may have claimed it meanwhile
      memcpy(e.key, key, 32);
      e.valid = ok ? 1 : 0;
      if (ok) {
        e.x = x;
        e.y = y;
      }
      e.occupied = 1;
    }
  }
  return ok ? 1 : 0;
}

// ------------------------------------------------------- point arithmetic
//
// Extended homogeneous coordinates (X, Y, Z, T) with x = X/Z, y = Y/Z,
// T = XY/Z on -x^2 + y^2 = 1 + d x^2 y^2 — the same unified a = -1
// formulas as the Python oracle and the TPU kernel.

struct Pt {
  Fe x, y, z, t;
};

const Fe FE_2D = {{0x69b9426b2f159ULL, 0x35050762add7aULL, 0x3cf44c0038052ULL,
                   0x6738cc7407977ULL, 0x2406d9dc56dffULL}};  // 2d mod p

inline Pt pt_identity() {
  return Pt{fe_zero(), fe_one(), fe_one(), fe_zero()};
}

Pt pt_add(const Pt &p, const Pt &q) {
  Fe a = fe_mul(fe_sub(p.y, p.x), fe_sub(q.y, q.x));
  Fe b = fe_mul(fe_add(p.y, p.x), fe_add(q.y, q.x));
  Fe c = fe_mul(fe_mul(p.t, FE_2D), q.t);
  Fe zz = fe_mul(p.z, q.z);
  Fe d = fe_add(zz, zz);
  Fe e = fe_sub(b, a);
  Fe f = fe_sub(d, c);
  Fe g = fe_add(d, c);
  Fe h = fe_add(b, a);
  return Pt{fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h)};
}

Pt pt_double(const Pt &p) { return pt_add(p, p); }

inline void fe_cmov(Fe &r, const Fe &a, u64 mask) {
  for (int i = 0; i < 5; i++) r.v[i] ^= mask & (r.v[i] ^ a.v[i]);
}

inline void pt_cmov(Pt &r, const Pt &a, u64 mask) {
  fe_cmov(r.x, a.x, mask);
  fe_cmov(r.y, a.y, mask);
  fe_cmov(r.z, a.z, mask);
  fe_cmov(r.t, a.t, mask);
}

// Scalar multiplication, 4-bit fixed windows (Horner from the top digit):
// ~252 doublings + 63 additions + a 16-entry table. One ladder serves both
// trust models; only the table-lookup step differs:
//
// - kConstTime=false: direct indexed lookup. For public scalars only
//   (verification: s, k are attacker-known).
// - kConstTime=true: reads all 16 entries and selects with branch-free
//   masked moves, so neither the memory trace nor the branch pattern
//   depends on the scalar. For secret scalars (signing / key derivation).
//   The field arithmetic itself (fe_mul etc.) is already constant-time
//   (fixed-shape u64 limb schoolbook, no secret branches), and the only
//   branch in the ladder is on the loop index.
template <bool kConstTime>
Pt pt_scalar_mul_impl(const u8 scalar_le[32], const Pt &base) {
  Pt table[16];
  table[0] = pt_identity();
  for (int i = 1; i < 16; i++) table[i] = pt_add(table[i - 1], base);
  Pt acc = pt_identity();
  for (int i = 31; i >= 0; i--) {
    for (int half = 1; half >= 0; half--) {
      u64 digit = (u64)((scalar_le[i] >> (4 * half)) & 0xF);
      if (!(i == 31 && half == 1)) {  // loop-index branch, not secret
        acc = pt_double(acc);
        acc = pt_double(acc);
        acc = pt_double(acc);
        acc = pt_double(acc);
      }
      if constexpr (kConstTime) {
        Pt entry = table[0];
        for (u64 j = 1; j < 16; j++) {
          u64 eq = digit ^ j;  // 0 iff j == digit
          u64 mask = (u64)(((eq | (0 - eq)) >> 63) ^ 1) * ~0ULL;
          pt_cmov(entry, table[j], mask);
        }
        acc = pt_add(acc, entry);
      } else {
        acc = pt_add(acc, table[digit]);
      }
    }
  }
  return acc;
}

Pt pt_scalar_mul(const u8 scalar_le[32], const Pt &base) {
  return pt_scalar_mul_impl<false>(scalar_le, base);
}

Pt pt_scalar_mul_ct(const u8 scalar_le[32], const Pt &base) {
  return pt_scalar_mul_impl<true>(scalar_le, base);
}

// Projective equality: X1 Z2 == X2 Z1 && Y1 Z2 == Y2 Z1.
bool pt_equal(const Pt &p, const Pt &q) {
  return fe_eq(fe_mul(p.x, q.z), fe_mul(q.x, p.z)) &&
         fe_eq(fe_mul(p.y, q.z), fe_mul(q.y, p.z));
}

// Base point B (y = 4/5, even x), affine limbs precomputed offline and
// cross-checked by the differential tests.
const Pt PT_BASE = {
    {{0x62d608f25d51aULL, 0x412a4b4f6592aULL, 0x75b7171a4b31dULL,
      0x1ff60527118feULL, 0x216936d3cd6e5ULL}},
    {{0x6666666666658ULL, 0x4ccccccccccccULL, 0x1999999999999ULL,
      0x3333333333333ULL, 0x6666666666666ULL}},
    {{1, 0, 0, 0, 0}},
    {{0x68ab3a5b7dda3ULL, 0xeea2a5eadbbULL, 0x2af8df483c27eULL,
      0x332b375274732ULL, 0x67875f0fd78b7ULL}},
};

inline void pt_compress(u8 out[32], const Pt &p) {
  Fe zinv = fe_invert(p.z);
  Fe x = fe_mul(p.x, zinv);
  Fe y = fe_mul(p.y, zinv);
  fe_tobytes(out, y);
  out[31] |= (u8)(fe_isodd(x) << 7);
}

// s = (a + b * c) mod L on little-endian 32-byte scalars (all < L).
void sc_muladd(u8 out[32], const u8 a[32], const u8 b[32], const u8 c[32]) {
  // Schoolbook 256x256 -> 512-bit product of b*c, plus a, then mod L.
  u64 bw[4], cw[4], aw[4];
  memcpy(bw, b, 32);
  memcpy(cw, c, 32);
  memcpy(aw, a, 32);
  u64 prod[8] = {0};
  for (int i = 0; i < 4; i++) {
    u128 carry = 0;
    for (int j = 0; j < 4; j++) {
      carry += (u128)bw[i] * cw[j] + prod[i + j];
      prod[i + j] = (u64)carry;
      carry >>= 64;
    }
    prod[i + 4] = (u64)carry;
  }
  u128 carry = 0;
  for (int i = 0; i < 4; i++) {
    carry += (u128)prod[i] + aw[i];
    prod[i] = (u64)carry;
    carry >>= 64;
  }
  // Fixed-shape carry propagation (no early exit): the inputs are the
  // secret nonce and secret-key products, so whether the carry ripples
  // must not show in the branch pattern.
  for (int i = 4; i < 8; i++) {
    carry += prod[i];
    prod[i] = (u64)carry;
    carry >>= 64;
  }
  u64 r[4];
  sc_mod_l_512(prod, r);
  memcpy(out, r, 32);
}

// Full RFC 8032 verification of one signature (host CPU path).
bool verify_one(const u8 pub[32], const u8 *msg, size_t msg_len,
                const u8 sig[64]) {
  Fe ax, ay;
  if (!cached_decompress(pub, ax, ay)) return false;
  Fe rx, ry;
  if (!point_decompress(sig, rx, ry)) return false;
  u64 s_words[4];
  memcpy(s_words, sig + 32, 32);
  if (!sc_lt_l(s_words)) return false;

  Sha512 h;
  h.update(sig, 32);
  h.update(pub, 32);
  h.update(msg, msg_len);
  u8 kh[64];
  h.final(kh);
  u64 kw[8], kr[4];
  memcpy(kw, kh, 64);
  sc_mod_l_512(kw, kr);
  u8 kbytes[32];
  memcpy(kbytes, kr, 32);

  Pt a{ax, ay, fe_one(), fe_mul(ax, ay)};
  Pt r{rx, ry, fe_one(), fe_mul(rx, ry)};
  Pt sb = pt_scalar_mul(sig + 32, PT_BASE);
  Pt ka = pt_scalar_mul(kbytes, a);
  Pt rka = pt_add(r, ka);
  return pt_equal(sb, rka);
}

}  // namespace

// ------------------------------------------------------------------- C ABI

extern "C" {

// Self-test hook: decompress one point; returns 1 valid / 0 invalid and
// writes canonical affine x||y bytes (32+32).
int hd_decompress(const u8 *in, u8 *xy_out) {
  Fe x, y;
  if (!point_decompress(in, x, y)) return 0;
  fe_tobytes(xy_out, x);
  fe_tobytes(xy_out + 32, y);
  return 1;
}

// SHA-512 of a buffer (self-test hook).
void hd_sha512(const u8 *in, size_t n, u8 *out64) {
  Sha512 h;
  h.update(in, n);
  h.final(out64);
}

// 512-bit LE bytes mod L -> 32 LE bytes (self-test hook).
void hd_mod_l(const u8 *in64, u8 *out32) {
  u64 x[8];
  memcpy(x, in64, 64);
  u64 r[4];
  sc_mod_l_512(x, r);
  memcpy(out32, r, 32);
}

// Derive the public key (compressed point) from a 32-byte seed.
void hd_public_from_seed(const u8 *seed, u8 *pub_out) {
  u8 h[64];
  Sha512 sh;
  sh.update(seed, 32);
  sh.final(h);
  h[0] &= 248;
  h[31] &= 127;
  h[31] |= 64;
  pt_compress(pub_out, pt_scalar_mul_ct(h, PT_BASE));
}

// RFC 8032 Ed25519 signing: out = R (32B) || s (32B LE). ``pub_opt`` may
// carry the caller's cached public key (it is always derivable from the
// seed, but deriving costs a full base-point scalar multiplication —
// callers that hold a KeyPair skip it); pass NULL to derive.
void hd_sign(const u8 *seed, const u8 *pub_opt, const u8 *msg, size_t msg_len,
             u8 *sig_out) {
  u8 h[64];
  Sha512 sh;
  sh.update(seed, 32);
  sh.final(h);
  u8 a_scalar[32];
  memcpy(a_scalar, h, 32);
  a_scalar[0] &= 248;
  a_scalar[31] &= 127;
  a_scalar[31] |= 64;
  u8 pub[32];
  if (pub_opt) {
    memcpy(pub, pub_opt, 32);
  } else {
    pt_compress(pub, pt_scalar_mul_ct(a_scalar, PT_BASE));
  }

  // r = SHA-512(prefix || msg) mod L.
  Sha512 hr;
  hr.update(h + 32, 32);
  hr.update(msg, msg_len);
  u8 rh[64];
  hr.final(rh);
  u64 rw[8], rr[4];
  memcpy(rw, rh, 64);
  sc_mod_l_512(rw, rr);
  u8 rbytes[32];
  memcpy(rbytes, rr, 32);
  pt_compress(sig_out, pt_scalar_mul_ct(rbytes, PT_BASE));

  // k = SHA-512(R || A || msg) mod L.
  Sha512 hk;
  hk.update(sig_out, 32);
  hk.update(pub, 32);
  hk.update(msg, msg_len);
  u8 kh[64];
  hk.final(kh);
  u64 kw[8], kr[4];
  memcpy(kw, kh, 64);
  sc_mod_l_512(kw, kr);
  u8 kbytes[32];
  memcpy(kbytes, kr, 32);

  // s = (r + k * a) mod L. The clamped a is < 2^255 but not < L; reduce it
  // first so sc_muladd's inputs satisfy its contract.
  u64 aw8[8] = {0}, ar[4];
  memcpy(aw8, a_scalar, 32);
  sc_mod_l_512(aw8, ar);
  u8 abytes[32];
  memcpy(abytes, ar, 32);
  sc_muladd(sig_out + 32, rbytes, kbytes, abytes);
}

// Batch verification on the host CPU (the wire-speed fallback when no
// device is attached). Layout mirrors hd_pack_batch; out[i] = 1 iff item i
// is well-formed and its signature verifies.
int hd_verify_batch(const u8 *pubs, const u8 *digests, const int32_t *digest_lens,
                    int dstride, const u8 *sigs, const u8 *in_ok, int n,
                    u8 *out) {
  for (int i = 0; i < n; i++) {
    out[i] = 0;
    if (in_ok && !in_ok[i]) continue;
    out[i] = verify_one(pubs + 32 * i, digests + (size_t)dstride * i,
                        (size_t)digest_lens[i], sigs + 64 * i)
                 ? 1
                 : 0;
  }
  return 0;
}

// Single-shot verify (self-test hook / small paths).
int hd_verify_one(const u8 *pub, const u8 *msg, size_t msg_len, const u8 *sig) {
  return verify_one(pub, msg, msg_len, sig) ? 1 : 0;
}

// Reset the pubkey decompression cache (e.g. between unrelated tests).
void hd_cache_clear(void) {
  std::lock_guard<std::mutex> lock(g_cache_mu);
  memset(g_cache, 0, sizeof(g_cache));
}

// The batch packer. For each of n items (pub[i*32..], digest[i*dstride..]
// of length digest_lens[i], sig[i*64..]) with in_ok[i] != 0:
//   - decompress A (cached) and R; range-check s < L;
//   - compute k = SHA-512(R || A || digest) mod L;
//   - write -A (limbs of x(-A), y, t = x*y), R (x, y), s and k nibbles into
//     row i of the output arrays;
//   - prevalid[i] = 1.
// Rows that fail any host check (or have in_ok[i] == 0) are left untouched
// (callers pre-zero the buffers) with prevalid[i] = 0.
// Output layouts match Ed25519BatchHost.pack: limb arrays are int32
// [*, 20] rows, nibble arrays int32 [*, 64] rows.
int hd_pack_batch(const u8 *pubs, const u8 *digests, const int32_t *digest_lens,
                  int dstride, const u8 *sigs, const u8 *in_ok, int n,
                  int32_t *ax, int32_t *ay, int32_t *at, int32_t *rx,
                  int32_t *ry, int32_t *s_nib, int32_t *k_nib, u8 *prevalid) {
  for (int i = 0; i < n; i++) {
    prevalid[i] = 0;
    if (in_ok && !in_ok[i]) continue;
    const u8 *pub = pubs + 32 * i;
    const u8 *digest = digests + (size_t)dstride * i;
    const u8 *sig = sigs + 64 * i;

    Fe ax_f, ay_f;
    if (!cached_decompress(pub, ax_f, ay_f)) continue;
    Fe rx_f, ry_f;
    if (!point_decompress(sig, rx_f, ry_f)) continue;

    u64 s_words[4];
    memcpy(s_words, sig + 32, 32);
    if (!sc_lt_l(s_words)) continue;

    // k = SHA-512(R || A || M) mod L.
    Sha512 h;
    h.update(sig, 32);
    h.update(pub, 32);
    h.update(digest, (size_t)digest_lens[i]);
    u8 kh[64];
    h.final(kh);
    u64 kw[8];
    memcpy(kw, kh, 64);
    u64 kr[4];
    sc_mod_l_512(kw, kr);
    u8 kbytes[32];
    memcpy(kbytes, kr, 32);

    // Negate A: x -> p - x (0 stays 0 — fe_sub + carry is canonicalized by
    // fe_tobytes below).
    Fe nax = fe_sub(fe_zero(), ax_f);
    Fe nat = fe_mul(nax, ay_f);

    u8 b[32];
    fe_tobytes(b, nax);
    pack_limbs13(b, ax + (size_t)i * 20);
    fe_tobytes(b, ay_f);
    pack_limbs13(b, ay + (size_t)i * 20);
    fe_tobytes(b, nat);
    pack_limbs13(b, at + (size_t)i * 20);
    fe_tobytes(b, rx_f);
    pack_limbs13(b, rx + (size_t)i * 20);
    fe_tobytes(b, ry_f);
    pack_limbs13(b, ry + (size_t)i * 20);
    pack_nibbles(sig + 32, s_nib + (size_t)i * 64);
    pack_nibbles(kbytes, k_nib + (size_t)i * 64);
    prevalid[i] = 1;
  }
  return 0;
}

// The wire packer: the host half of the device-decompression verify path
// (hyperdrive_tpu/ops/ed25519_wire.py). Point decompression — the
// expensive field exponentiations that dominate hd_pack_batch — moves to
// the device; this loop keeps only the cheap checks and the challenge
// hash. For each item with in_ok[i] != 0:
//   - reject non-canonical y encodings of A and R (y >= p, sign masked);
//   - range-check s < L;
//   - compute k = SHA-512(R || A || digest) mod L;
//   - copy pub/R/s/k into 32-byte rows of the four output arrays.
// Rows failing any check keep prevalid[i] = 0 (buffers pre-zeroed by the
// caller). Throughput is hash+mod-L bound: no Fe math at all.
int hd_pack_wire(const u8 *pubs, const u8 *digests, const int32_t *digest_lens,
                 int dstride, const u8 *sigs, const u8 *in_ok, int n,
                 u8 *a_rows, u8 *r_rows, u8 *s_rows, u8 *k_rows,
                 u8 *prevalid) {
  for (int i = 0; i < n; i++) {
    prevalid[i] = 0;
    if (in_ok && !in_ok[i]) continue;
    const u8 *pub = pubs + 32 * i;
    const u8 *sig = sigs + 64 * i;

    u8 ymasked[32];
    memcpy(ymasked, pub, 32);
    ymasked[31] &= 0x7f;
    if (!lt_le32(ymasked, P_BYTES)) continue;
    memcpy(ymasked, sig, 32);
    ymasked[31] &= 0x7f;
    if (!lt_le32(ymasked, P_BYTES)) continue;

    u64 s_words[4];
    memcpy(s_words, sig + 32, 32);
    if (!sc_lt_l(s_words)) continue;

    Sha512 h;
    h.update(sig, 32);
    h.update(pub, 32);
    h.update(digests + (size_t)dstride * i, (size_t)digest_lens[i]);
    u8 kh[64];
    h.final(kh);
    u64 kw[8], kr[4];
    memcpy(kw, kh, 64);
    sc_mod_l_512(kw, kr);

    memcpy(a_rows + (size_t)32 * i, pub, 32);
    memcpy(r_rows + (size_t)32 * i, sig, 32);
    memcpy(s_rows + (size_t)32 * i, sig + 32, 32);
    memcpy(k_rows + (size_t)32 * i, kr, 32);
    prevalid[i] = 1;
  }
  return 0;
}

}  // extern "C"
