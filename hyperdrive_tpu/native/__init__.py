"""ctypes binding for the native host runtime (hd_native.cc).

The shared library is compiled on demand with g++ (no pip, no pybind11) and
cached next to the source, keyed by a hash of the source text so edits
trigger a rebuild. Everything degrades gracefully: if a toolchain is
missing or the compile fails, :func:`load` returns None and callers fall
back to the pure-Python path (``HD_NO_NATIVE=1`` forces the fallback).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

__all__ = ["load", "available", "NativePacker"]

_SRC = os.path.join(os.path.dirname(__file__), "hd_native.cc")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")

_lock = threading.Lock()
_lib = None
_lib_err: str | None = None


def _compile() -> str:
    with open(_SRC, "rb") as fh:
        tag = hashlib.sha256(fh.read()).hexdigest()[:16]
    so_path = os.path.join(_BUILD_DIR, f"libhd_native-{tag}.so")
    if os.path.exists(so_path):
        return so_path
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = so_path + f".tmp.{os.getpid()}"
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        subprocess.run(
            base[:2] + ["-march=native"] + base[2:],
            check=True,
            capture_output=True,
        )
    except (subprocess.CalledProcessError, OSError):
        subprocess.run(base, check=True, capture_output=True)
    os.replace(tmp, so_path)  # atomic: concurrent builders race benignly
    return so_path


def load():
    """Returns the loaded CDLL, or None if native is unavailable."""
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    with _lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        if os.environ.get("HD_NO_NATIVE"):
            _lib_err = "disabled by HD_NO_NATIVE"
            return None
        try:
            lib = ctypes.CDLL(_compile())
        except subprocess.CalledProcessError as e:
            stderr = (e.stderr or b"").decode("utf-8", "replace").strip()
            _lib_err = f"native build failed: {e}: {stderr[-500:]}"
            return None
        except Exception as e:  # missing g++, bad toolchain, load error
            _lib_err = f"native build failed: {e}"
            return None
        lib.hd_pack_batch.restype = ctypes.c_int
        lib.hd_pack_wire.restype = ctypes.c_int
        lib.hd_decompress.restype = ctypes.c_int
        lib.hd_sha512.restype = None
        lib.hd_mod_l.restype = None
        lib.hd_cache_clear.restype = None
        lib.hd_public_from_seed.restype = None
        lib.hd_sign.restype = None
        lib.hd_verify_batch.restype = ctypes.c_int
        lib.hd_verify_one.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def last_error() -> str | None:
    """Why native is unavailable (None if it loaded or wasn't tried)."""
    return _lib_err


_packer = None
_packer_failed = False


def instance():
    """Shared NativePacker, or None when native is unavailable — the one
    place fallback policy lives (callers: verifier, keys, batch host)."""
    global _packer, _packer_failed
    if _packer is None and not _packer_failed:
        try:
            _packer = NativePacker()
        except RuntimeError:
            _packer_failed = True
    return _packer


def _u8ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _marshal_items(items):
    """Marshal (pub32, payload, sig64) triples into the contiguous buffers
    the C ABI consumes: (pubs, payloads, payload_lens, payload_stride,
    sigs, in_ok). Wrong-length pubs/sigs get in_ok=0; payloads may be any
    length. Shared by packing and host batch verification so the two paths
    can never diverge.

    Fast path: when every row is well-formed and payloads share one length
    (consensus digests are always 32 bytes), the buffers are built with
    three byte-joins instead of a per-row numpy loop — the loop was ~40% of
    end-to-end pack cost at 100k+ windows.
    """
    n = len(items)
    if n and all(
        len(p) == 32 and len(s) == 64 and len(m) == len(items[0][1])
        for p, m, s in items
    ):
        mlen = len(items[0][1])
        stride = mlen or 1
        pubs = np.frombuffer(
            b"".join(p for p, _, _ in items), dtype=np.uint8
        ).reshape(n, 32)
        sigs = np.frombuffer(
            b"".join(s for _, _, s in items), dtype=np.uint8
        ).reshape(n, 64)
        if mlen:
            payloads = np.frombuffer(
                b"".join(m for _, m, _ in items), dtype=np.uint8
            ).reshape(n, mlen)
        else:
            payloads = np.zeros((n, 1), dtype=np.uint8)
        lens = np.full(n, mlen, dtype=np.int32)
        in_ok = np.ones(n, dtype=np.uint8)
        return pubs, payloads, lens, stride, sigs, in_ok

    stride = max((len(m) for _, m, _ in items), default=1) or 1
    pubs = np.zeros((n, 32), dtype=np.uint8)
    payloads = np.zeros((n, stride), dtype=np.uint8)
    lens = np.zeros(n, dtype=np.int32)
    sigs = np.zeros((n, 64), dtype=np.uint8)
    in_ok = np.zeros(n, dtype=np.uint8)
    for i, (pub, payload, sig) in enumerate(items):
        if len(pub) != 32 or len(sig) != 64:
            continue
        pubs[i] = np.frombuffer(pub, dtype=np.uint8)
        if payload:
            payloads[i, : len(payload)] = np.frombuffer(payload, dtype=np.uint8)
        lens[i] = len(payload)
        sigs[i] = np.frombuffer(sig, dtype=np.uint8)
        in_ok[i] = 1
    return pubs, payloads, lens, stride, sigs, in_ok


class NativePacker:
    """Batch Ed25519 packing through the native library.

    Same contract as the Python loop in ``Ed25519BatchHost.pack``: given
    parallel (pub, digest, sig) byte arrays, fill the kernel's limb/nibble
    tensors and a prevalidity mask.
    """

    def __init__(self):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError(_lib_err or "native library unavailable")

    def pack_into(
        self,
        items,
        ax: np.ndarray,
        ay: np.ndarray,
        at: np.ndarray,
        rx: np.ndarray,
        ry: np.ndarray,
        s_nib: np.ndarray,
        k_nib: np.ndarray,
    ) -> np.ndarray:
        """items: sequence of (pub, digest, sig) byte triples (digests may
        be any length; pub/sig must be 32/64 bytes). Writes row i of each
        output array for every item that passes host checks; returns the
        bool prevalid mask (length = len(items))."""
        n = len(items)
        pubs, digests, digest_lens, dstride, sigs, in_ok = _marshal_items(items)
        prevalid = np.zeros(n, dtype=np.uint8)
        self._lib.hd_pack_batch(
            _u8ptr(pubs),
            _u8ptr(digests),
            _i32ptr(digest_lens),
            ctypes.c_int(dstride),
            _u8ptr(sigs),
            _u8ptr(in_ok),
            ctypes.c_int(n),
            _i32ptr(ax),
            _i32ptr(ay),
            _i32ptr(at),
            _i32ptr(rx),
            _i32ptr(ry),
            _i32ptr(s_nib),
            _i32ptr(k_nib),
            _u8ptr(prevalid),
        )
        return prevalid.astype(bool)

    def pack_wire_into(
        self,
        items,
        a_rows: np.ndarray,
        r_rows: np.ndarray,
        s_rows: np.ndarray,
        k_rows: np.ndarray,
    ) -> np.ndarray:
        """Wire-path packing (device-side decompression): writes 32-byte
        rows (pub, R, s, k) for every item passing the host range checks;
        returns the bool prevalid mask. Same item contract as
        :meth:`pack_into`."""
        n = len(items)
        pubs, digests, digest_lens, dstride, sigs, in_ok = _marshal_items(items)
        prevalid = np.zeros(n, dtype=np.uint8)
        self._lib.hd_pack_wire(
            _u8ptr(pubs),
            _u8ptr(digests),
            _i32ptr(digest_lens),
            ctypes.c_int(dstride),
            _u8ptr(sigs),
            _u8ptr(in_ok),
            ctypes.c_int(n),
            _u8ptr(a_rows),
            _u8ptr(r_rows),
            _u8ptr(s_rows),
            _u8ptr(k_rows),
            _u8ptr(prevalid),
        )
        return prevalid.astype(bool)

    # ------------------------------------------------------ self-test hooks

    def decompress(self, data: bytes):
        """Mirror of crypto.ed25519.point_decompress for differential tests:
        returns (x, y) ints or None."""
        out = np.zeros(64, dtype=np.uint8)
        buf = np.frombuffer(data, dtype=np.uint8) if len(data) == 32 else None
        if buf is None:
            return None
        ok = self._lib.hd_decompress(_u8ptr(np.ascontiguousarray(buf)), _u8ptr(out))
        if not ok:
            return None
        x = int.from_bytes(out[:32].tobytes(), "little")
        y = int.from_bytes(out[32:].tobytes(), "little")
        return x, y

    def sha512(self, data: bytes) -> bytes:
        buf = np.frombuffer(data, dtype=np.uint8) if data else np.zeros(0, np.uint8)
        out = np.zeros(64, dtype=np.uint8)
        self._lib.hd_sha512(
            _u8ptr(np.ascontiguousarray(buf)), ctypes.c_size_t(len(data)), _u8ptr(out)
        )
        return out.tobytes()

    def mod_l(self, data64: bytes) -> int:
        buf = np.frombuffer(data64, dtype=np.uint8)
        out = np.zeros(32, dtype=np.uint8)
        self._lib.hd_mod_l(_u8ptr(np.ascontiguousarray(buf)), _u8ptr(out))
        return int.from_bytes(out.tobytes(), "little")

    def cache_clear(self) -> None:
        self._lib.hd_cache_clear()

    # -------------------------------------------------------- sign / verify

    def public_from_seed(self, seed: bytes) -> bytes:
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        out = np.zeros(32, dtype=np.uint8)
        buf = np.frombuffer(seed, dtype=np.uint8)
        self._lib.hd_public_from_seed(_u8ptr(np.ascontiguousarray(buf)), _u8ptr(out))
        return out.tobytes()

    def sign(self, seed: bytes, msg: bytes, pub: bytes | None = None) -> bytes:
        """Sign ``msg``. Passing the (derivable) cached ``pub`` skips one of
        the three base-point scalar multiplications."""
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        if pub is not None and len(pub) != 32:
            raise ValueError("pub must be 32 bytes")
        out = np.zeros(64, dtype=np.uint8)
        sbuf = np.ascontiguousarray(np.frombuffer(seed, dtype=np.uint8))
        mbuf = (
            np.ascontiguousarray(np.frombuffer(msg, dtype=np.uint8))
            if msg
            else np.zeros(0, np.uint8)
        )
        pbuf = (
            _u8ptr(np.ascontiguousarray(np.frombuffer(pub, dtype=np.uint8)))
            if pub is not None
            else None
        )
        self._lib.hd_sign(
            _u8ptr(sbuf), pbuf, _u8ptr(mbuf), ctypes.c_size_t(len(msg)), _u8ptr(out)
        )
        return out.tobytes()

    def verify(self, pub: bytes, msg: bytes, sig: bytes) -> bool:
        if len(pub) != 32 or len(sig) != 64:
            return False
        pbuf = np.ascontiguousarray(np.frombuffer(pub, dtype=np.uint8))
        mbuf = (
            np.ascontiguousarray(np.frombuffer(msg, dtype=np.uint8))
            if msg
            else np.zeros(0, np.uint8)
        )
        sbuf = np.ascontiguousarray(np.frombuffer(sig, dtype=np.uint8))
        return bool(
            self._lib.hd_verify_one(
                _u8ptr(pbuf), _u8ptr(mbuf), ctypes.c_size_t(len(msg)), _u8ptr(sbuf)
            )
        )

    def verify_batch(self, items) -> np.ndarray:
        """items: sequence of (pub, msg, sig); returns bool[n] of results.
        Host-CPU batch verification (no device involved)."""
        n = len(items)
        pubs, msgs, lens, dstride, sigs, in_ok = _marshal_items(items)
        out = np.zeros(n, dtype=np.uint8)
        self._lib.hd_verify_batch(
            _u8ptr(pubs),
            _u8ptr(msgs),
            _i32ptr(lens),
            ctypes.c_int(dstride),
            _u8ptr(sigs),
            _u8ptr(in_ok),
            ctypes.c_int(n),
            _u8ptr(out),
        )
        return out.astype(bool)
