"""Pluggable vote-signature verification.

The reference assumes authentication happens outside the library
(process/process.go:95-98, mq/mq.go:85-86). This framework makes it a
first-class, injectable seam on the replica's drain loop: a Verifier
receives a whole window of queued messages and returns an accept mask.

- :class:`NullVerifier` — accept everything; byte-compatible with the
  reference's trust model (authentication fully external). The default.
- :class:`HostVerifier` — per-message Ed25519 verification on the host,
  the "pure-host path" the benchmarks baseline against.
- The TPU batch verifier lives in :mod:`hyperdrive_tpu.ops.ed25519_jax`
  and satisfies the same protocol; host and device verifiers must agree
  accept/reject bit-for-bit (differentially tested).
"""

from __future__ import annotations

import time
from typing import Protocol, Sequence, runtime_checkable

from hyperdrive_tpu.crypto import ed25519

__all__ = ["Verifier", "NullVerifier", "HostVerifier", "AdaptiveVerifier"]


@runtime_checkable
class Verifier(Protocol):
    def verify_batch(self, window: Sequence) -> Sequence[bool]:
        """Return one accept/reject per message in the window."""
        ...


class NullVerifier:
    """Trusts the transport (the reference's model)."""

    def verify_batch(self, window):
        return [True] * len(window)


class HostVerifier:
    """Host-CPU Ed25519 verification of each message's detached signature,
    with the sender's public key as the verification key.

    Uses the native C++ batch path (hyperdrive_tpu.native, ~35x the pure-
    Python oracle) when the toolchain allows, falling back to per-message
    Python verification. Both agree bit-for-bit (differentially tested).
    """

    def __init__(self):
        from hyperdrive_tpu import native

        self._native = native.instance()

    def verify_batch(self, window):
        if self._native is not None:
            # Signatures pass through unchanged: the native marshaller
            # length-checks and marks wrong-length signatures invalid, so
            # rejection is deterministic and identical to the Python path
            # (never substitute a zero signature — with an adversarial
            # small-order pubkey a zero signature can *verify*).
            items = [
                (msg.sender, msg.digest(), msg.signature) for msg in window
            ]
            mask = self._native.verify_batch(items)
            return [
                bool(ok) and bool(msg.signature)
                for ok, msg in zip(mask, window)
            ]
        return [
            bool(msg.signature)
            and ed25519.verify(msg.sender, msg.digest(), msg.signature)
            for msg in window
        ]

    def verify_signatures(self, items):
        """Raw (pub, digest, sig) triples -> bool mask (sliceable,
        per-element assignable); the aggregated-batch entry point shared
        with TpuBatchVerifier so harness drivers can swap host and device
        backends freely."""
        if self._native is not None:
            return self._native.verify_batch(items)
        return [ed25519.verify(pub, digest, sig) for pub, digest, sig in items]


class AdaptiveVerifier:
    """Routes each window to the host or the device backend by size.

    The latency/throughput tension of SURVEY.md §7.3(2): a device launch
    has a fixed dispatch+transfer overhead but far higher sustained
    throughput, so small windows (a lone propose, a timeout-round trickle)
    verify faster on the host while vote storms belong on the device. The
    crossover is measured, not guessed: the first window at least as large
    as ``calibrate_at`` is timed through BOTH backends (their verdicts also
    cross-checked), and the per-signature rates + device overhead solve for
    the break-even size. Until calibration, windows route by the
    provisional ``crossover`` guess.

    Both backends implement the same ``verify_signatures`` contract and
    must agree bit-for-bit, so routing is a pure performance decision.
    """

    def __init__(
        self,
        device=None,
        host=None,
        crossover: int = 192,
        calibrate_at: int = 384,
    ):
        if device is None:
            from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

            device = TpuBatchVerifier()
        self.device = device
        self.host = host if host is not None else HostVerifier()
        self.crossover = int(crossover)
        self.calibrate_at = int(calibrate_at)
        self.calibrated = False
        #: Self-describing calibration record once measured — keys
        #: ``host_sigs_per_s``, ``device_sigs_per_s``,
        #: ``device_overhead_s`` (the single-launch time, i.e. dispatch +
        #: transfer, in seconds — NOT a rate) — exposed for benchmark
        #: reporting.
        self.rates = None

    @staticmethod
    def _median_time(fn, reps: int = 3):
        """Median-of-``reps`` timing: one jittered sample (tunnel hiccup,
        scheduler preemption) cannot set the rate a calibration bakes in
        for the rest of the process."""
        out = None
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn()
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2], out

    def recalibrate(self) -> None:
        """Forget the measured crossover; the next window at least
        ``calibrate_at`` large re-measures both legs. Call after anything
        that changes the latency regime (device contention ended, link
        changed, process migrated)."""
        self.calibrated = False

    def _calibrate(self, items):
        # Warm BOTH device shapes first so XLA compilation isn't billed as
        # launch overhead (the kernel compiles once per bucket shape; the
        # tiny probe below typically lands in a different bucket than the
        # full window).
        self.device.verify_signatures(items)
        self.device.verify_signatures(items[:1])
        t_dev_full, mask_dev = self._median_time(
            lambda: self.device.verify_signatures(items)
        )
        # A tiny launch isolates the fixed overhead (dispatch + transfer).
        t_dev_one, _ = self._median_time(
            lambda: self.device.verify_signatures(items[:1])
        )
        t_host, mask_host = self._median_time(
            lambda: self.host.verify_signatures(items)
        )
        if list(mask_dev) != list(mask_host):
            raise RuntimeError(
                "host and device verifiers disagree during calibration — "
                "refusing to route on performance while correctness differs"
            )
        n = len(items)
        host_rate = n / t_host if t_host > 0 else float("inf")
        # Marginal device cost per signature: the difference between the
        # full and single-item launches. When both land in the same padded
        # bucket the difference is ~0 (the launch is overhead-dominated) —
        # clamp at zero and report the sustained rate instead, which is
        # what the full launch actually achieved.
        dev_per_sig = max(t_dev_full - t_dev_one, 0.0) / max(n - 1, 1)
        dev_rate = n / t_dev_full if t_dev_full > 0 else float("inf")
        # Break-even: n/host_rate == overhead + n*dev_per_sig.
        denom = 1.0 / host_rate - dev_per_sig
        self.crossover = (
            int(t_dev_one / denom) + 1 if denom > 0 else 1 << 30
        )
        self.rates = {
            "host_sigs_per_s": host_rate,
            "device_sigs_per_s": dev_rate,
            "device_overhead_s": t_dev_one,
        }
        self.calibrated = True
        return mask_dev

    def verify_signatures(self, items):
        if not self.calibrated and len(items) >= self.calibrate_at:
            return self._calibrate(list(items))
        backend = self.device if len(items) >= self.crossover else self.host
        return backend.verify_signatures(items)

    def verify_batch(self, window):
        items = [(m.sender, m.digest(), m.signature) for m in window]
        mask = self.verify_signatures(items)
        return [bool(ok) and bool(m.signature) for ok, m in zip(mask, window)]
