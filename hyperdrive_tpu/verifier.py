"""Pluggable vote-signature verification.

The reference assumes authentication happens outside the library
(process/process.go:95-98, mq/mq.go:85-86). This framework makes it a
first-class, injectable seam on the replica's drain loop: a Verifier
receives a whole window of queued messages and returns an accept mask.

- :class:`NullVerifier` — accept everything; byte-compatible with the
  reference's trust model (authentication fully external). The default.
- :class:`HostVerifier` — per-message Ed25519 verification on the host,
  the "pure-host path" the benchmarks baseline against.
- The TPU batch verifier lives in :mod:`hyperdrive_tpu.ops.ed25519_jax`
  and satisfies the same protocol; host and device verifiers must agree
  accept/reject bit-for-bit (differentially tested).
"""

from __future__ import annotations

from typing import Protocol, Sequence, runtime_checkable

from hyperdrive_tpu.crypto import ed25519

__all__ = ["Verifier", "NullVerifier", "HostVerifier"]


@runtime_checkable
class Verifier(Protocol):
    def verify_batch(self, window: Sequence) -> Sequence[bool]:
        """Return one accept/reject per message in the window."""
        ...


class NullVerifier:
    """Trusts the transport (the reference's model)."""

    def verify_batch(self, window):
        return [True] * len(window)


class HostVerifier:
    """Host-CPU Ed25519 verification of each message's detached signature,
    with the sender's public key as the verification key.

    Uses the native C++ batch path (hyperdrive_tpu.native, ~35x the pure-
    Python oracle) when the toolchain allows, falling back to per-message
    Python verification. Both agree bit-for-bit (differentially tested).
    """

    def __init__(self):
        from hyperdrive_tpu import native

        self._native = native.instance()

    def verify_batch(self, window):
        if self._native is not None:
            # Signatures pass through unchanged: the native marshaller
            # length-checks and marks wrong-length signatures invalid, so
            # rejection is deterministic and identical to the Python path
            # (never substitute a zero signature — with an adversarial
            # small-order pubkey a zero signature can *verify*).
            items = [
                (msg.sender, msg.digest(), msg.signature) for msg in window
            ]
            mask = self._native.verify_batch(items)
            return [
                bool(ok) and bool(msg.signature)
                for ok, msg in zip(mask, window)
            ]
        return [
            bool(msg.signature)
            and ed25519.verify(msg.sender, msg.digest(), msg.signature)
            for msg in window
        ]

    def verify_signatures(self, items):
        """Raw (pub, digest, sig) triples -> bool mask (sliceable,
        per-element assignable); the aggregated-batch entry point shared
        with TpuBatchVerifier so harness drivers can swap host and device
        backends freely."""
        if self._native is not None:
            return self._native.verify_batch(items)
        return [ed25519.verify(pub, digest, sig) for pub, digest, sig in items]
