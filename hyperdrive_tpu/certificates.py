"""Aggregate quorum certificates: constant-size commit proofs.

Once 2f+1 precommits for a value have been verified, re-gossiping those
2f+1 signatures (64 bytes each — ~11 KB at n=256, ~44 KB at n=1024) to
prove the commit is pure waste: the quorum is a fact the verifier
already established in one batched launch. A
:class:`QuorumCertificate` compresses the proof to a constant-size
record — height, round, value digest, signer bitmap, and a binding to
the batch-verification transcript that established the quorum — that
the settle path, :class:`~hyperdrive_tpu.tallyflush.DeviceTallyFlusher`,
and :class:`~hyperdrive_tpu.parallel.multihost.ShardVerifyService` carry
and re-verify in O(1) (PAPERS.md: "Scalable BFT Consensus Mechanism
Through Aggregated Signature Gossip"). The certificate chain is also the
seam epoch-transition proofs hang off (ROADMAP item 4) and what a Handel
overlay would gossip instead of vote sets (item 2).

Trust model: the binding is an integrity commitment, not an aggregate
signature — it proves the certificate's fields are exactly what the
emitting replica committed after its verifier's batched launch accepted
the 2f+1 precommits (the RLC transcript digest from
``TpuBatchVerifier.last_transcript`` rides inside it). Tampering with
any field breaks the binding; substituting a whole forged certificate
requires forging the emitting seam itself, which is the same trust a
re-gossiped signature set places in the local verifier. A BLS-style
self-verifying aggregate would drop that residual trust and slots into
the same field.

Wire format (codec.py, canonical):

    u64 height | u32 round | bytes32 value_digest |
    raw bitmap (u32 length prefix) | bytes32 transcript | bytes32 binding

Size is 112 bytes + n/8 for the signer bitmap: 144 B at n=256, 176 B at
n=512, 240 B at n=1024 — flat against the ~64n bytes of the signature
set it replaces (the "O(1) in validator count" claim of the paper trail;
the bitmap is the only term that moves, at 1/512th the slope).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from hyperdrive_tpu.codec import Reader, SerdeError, Writer
from hyperdrive_tpu.obs.recorder import NULL_BOUND

__all__ = [
    "QuorumCertificate",
    "Certifier",
    "marshal_certificate",
    "unmarshal_certificate",
    "certificate_size",
]

#: Domain separator for the binding hash (versioned: a format change must
#: not collide with old bindings).
_BINDING_TAG = b"hd-qc-v1"


@dataclass(frozen=True)
class QuorumCertificate:
    """One committed (height, round, value) plus the quorum that proved it.

    ``value_digest`` is sha256 of the committed value (values are
    variable-length; the digest keeps the record constant-size).
    ``signers`` is the bitmap of precommit signatories in whitelist
    order; ``transcript`` binds the batch-verification launch that
    established the quorum (b"" * 32 when the verifier exposes none —
    the unsigned/lock-step harness paths). ``binding`` commits to every
    other field; :meth:`Certifier.verify` recomputes it.
    """

    height: int
    round: int
    value_digest: bytes
    signers: bytes
    transcript: bytes
    binding: bytes

    def signer_count(self) -> int:
        return sum(bin(b).count("1") for b in self.signers)


def _binding(height, round, value_digest, signers, transcript) -> bytes:
    h = hashlib.sha256()
    h.update(_BINDING_TAG)
    h.update(int(height).to_bytes(8, "little"))
    h.update(int(round).to_bytes(4, "little"))
    h.update(value_digest)
    h.update(len(signers).to_bytes(2, "little"))
    h.update(signers)
    h.update(transcript)
    return h.digest()


def marshal_certificate(cert: QuorumCertificate, w: Writer) -> None:
    w.u64(cert.height)
    w.u32(cert.round)
    w.bytes32(cert.value_digest)
    w.raw(cert.signers)
    w.bytes32(cert.transcript)
    w.bytes32(cert.binding)


def unmarshal_certificate(r: Reader) -> QuorumCertificate:
    height = r.u64()
    rnd = r.u32()
    value_digest = r.bytes32()
    signers = r.raw()
    if len(signers) > 4096:
        raise SerdeError(f"signer bitmap too wide: {len(signers)} bytes")
    transcript = r.bytes32()
    binding = r.bytes32()
    return QuorumCertificate(
        height=height,
        round=rnd,
        value_digest=value_digest,
        signers=signers,
        transcript=transcript,
        binding=binding,
    )


def certificate_size(n_validators: int) -> int:
    """Marshalled bytes for an n-validator certificate (the bench's
    O(1)-in-n measurement helper)."""
    w = Writer()
    marshal_certificate(
        QuorumCertificate(
            height=0,
            round=0,
            value_digest=bytes(32),
            signers=bytes(-(-n_validators // 8)),
            transcript=bytes(32),
            binding=bytes(32),
        ),
        w,
    )
    return len(w.data())


class Certifier:
    """Per-replica certificate emitter + O(1) re-verifier.

    Plugs into the :class:`~hyperdrive_tpu.process.Process` commit seam:
    when L49 fires with 2f+1 precommits, the process hands over the
    signer set and the certifier mints the certificate, binding the
    verifier's last batch transcript (``transcript_source``: a callable
    returning bytes — e.g. ``lambda: verifier.last_transcript`` — or
    None for transcript-less paths). Emitted certificates are kept per
    height (``certs``) and surfaced through the ``cert.emit`` /
    ``cert.verify`` obs events (OBSERVABILITY.md).
    """

    def __init__(self, signatories, f: int, transcript_source=None,
                 obs=None):
        self.signatories = list(signatories)
        self._pos = {s: i for i, s in enumerate(self.signatories)}
        self.f = int(f)
        self.transcript_source = transcript_source
        self.obs = obs if obs is not None else NULL_BOUND
        #: height -> QuorumCertificate, in emission order.
        self.certs: dict = {}
        #: Verification outcomes (observability/tests).
        self.verified = 0
        self.rejected = 0

    # ------------------------------------------------------------- emission

    def observe_commit(self, height, round, value, signers):
        """Mint the certificate for one committed (height, round, value).

        ``signers``: the precommit signatories counted toward the 2f+1
        quorum (whitelist members; unknown signatories are ignored —
        they were never counted by the grid either)."""
        bitmap = bytearray(-(-len(self.signatories) // 8))
        for s in signers:
            i = self._pos.get(s)
            if i is not None:
                bitmap[i >> 3] |= 1 << (i & 7)
        transcript = b""
        if self.transcript_source is not None:
            transcript = self.transcript_source() or b""
        if len(transcript) != 32:
            transcript = hashlib.sha256(transcript).digest() if transcript \
                else bytes(32)
        value_digest = hashlib.sha256(value).digest()
        signers_b = bytes(bitmap)
        cert = QuorumCertificate(
            height=int(height),
            round=int(round),
            value_digest=value_digest,
            signers=signers_b,
            transcript=transcript,
            binding=_binding(
                height, round, value_digest, signers_b, transcript
            ),
        )
        self.certs[int(height)] = cert
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "cert.emit", int(height), int(round),
                cert.value_digest.hex()[:16],
            )
        return cert

    # ----------------------------------------------------------- re-verify

    def verify(self, cert: QuorumCertificate) -> bool:
        """O(1) acceptance: quorum weight, bitmap width, and binding
        integrity — no signature is re-checked and no vote set is
        re-gossiped. Emits ``cert.verify`` with the outcome."""
        ok = (
            len(cert.signers) == -(-len(self.signatories) // 8)
            and cert.signer_count() >= 2 * self.f + 1
            and len(cert.value_digest) == 32
            and cert.binding
            == _binding(
                cert.height, cert.round, cert.value_digest, cert.signers,
                cert.transcript,
            )
        )
        if ok:
            self.verified += 1
        else:
            self.rejected += 1
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "cert.verify", cert.height, cert.round,
                "ok" if ok else "reject",
            )
        return ok

    # ------------------------------------------------------------- rotation

    def rotate(self, signatories, f: int) -> None:
        """Epoch hot-swap (epochs.py): install the next committee's
        whitelist order and quorum threshold. Emitted certificates are
        kept — the chain stays continuous across the transition; only
        bitmap indexing for NEW emissions follows the new order."""
        self.signatories = list(signatories)
        self._pos = {s: i for i, s in enumerate(self.signatories)}
        self.f = int(f)

    # ------------------------------------------------------------- chaining

    def certificate_for(self, height):
        return self.certs.get(int(height))

    def chain_digest(self) -> str:
        """Canonical digest over the emitted certificate chain — the
        cross-replica / pipelined-vs-sequential equality handle (the
        certificate sibling of ``SimulationResult.commit_digest``)."""
        h = hashlib.sha256()
        for height in sorted(self.certs):
            c = self.certs[height]
            h.update(int(height).to_bytes(8, "little"))
            h.update(c.value_digest)
            h.update(c.signers)
        return h.hexdigest()

    def reset(self) -> None:
        """Crash-restart hook: a revived replica re-emits from its
        checkpoint; stale certificates must not survive the restore."""
        self.certs.clear()
