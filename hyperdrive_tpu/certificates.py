"""Aggregate quorum certificates: constant-size commit proofs.

Once 2f+1 precommits for a value have been verified, re-gossiping those
2f+1 signatures (64 bytes each — ~11 KB at n=256, ~44 KB at n=1024) to
prove the commit is pure waste: the quorum is a fact the verifier
already established in one batched launch. A
:class:`QuorumCertificate` compresses the proof to a constant-size
record — height, round, value digest, signer bitmap, and a binding to
the batch-verification transcript that established the quorum — that
the settle path, :class:`~hyperdrive_tpu.tallyflush.DeviceTallyFlusher`,
and :class:`~hyperdrive_tpu.parallel.multihost.ShardVerifyService` carry
and re-verify in O(1) (PAPERS.md: "Scalable BFT Consensus Mechanism
Through Aggregated Signature Gossip"). The certificate chain is also the
seam epoch-transition proofs hang off (ROADMAP item 4) and what a Handel
overlay would gossip instead of vote sets (item 2).

Trust model — two tiers. The *binding* is an integrity commitment, not
an aggregate signature: it proves the certificate's fields are exactly
what the emitting replica committed after its verifier's batched launch
accepted the 2f+1 precommits (the RLC transcript digest from
``TpuBatchVerifier.last_transcript`` rides inside it). Tampering with
any field breaks the binding, but trusting it means trusting the
emitting seam. The optional **BLS aggregate signature** (``agg_sig``,
48 bytes compressed G1) drops that residual trust entirely: each
counted signer's BLS partial over the canonical commit message
(:func:`bls_commit_message`) is aggregated — on device via the
:mod:`~hyperdrive_tpu.ops.g1` bitmask kernel, or on host — and a light
client holding only the committee's public keys re-verifies the quorum
with :func:`verify_bls_certificate`: one pairing product, zero
transcript trust, zero vote-set gossip.

Wire format (codec.py, canonical):

    u64 height | u32 round | bytes32 value_digest |
    raw bitmap (u32 length prefix) | bytes32 transcript | bytes32 binding |
    raw agg_sig (empty or 48 B)

Size is 116 bytes + n/8 for the signer bitmap (+48 when the BLS
aggregate rides along): 148/196 B at n=256, 244/292 B at n=1024 — flat
against the ~64n bytes of the signature set it replaces (the "O(1) in
validator count" claim of the paper trail; the bitmap is the only term
that moves, at 1/512th the slope).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from hyperdrive_tpu.analysis.annotations import wire_codec
from hyperdrive_tpu.codec import Reader, SerdeError, Writer
from hyperdrive_tpu.obs.recorder import NULL_BOUND

__all__ = [
    "QuorumCertificate",
    "Certifier",
    "marshal_certificate",
    "unmarshal_certificate",
    "certificate_size",
    "bls_commit_message",
    "verify_bls_certificate",
]

#: Domain separator for the binding hash (versioned: a format change must
#: not collide with old bindings). Certificates without a BLS aggregate
#: keep the v1 tag and preimage byte-for-byte; the aggregate-carrying
#: form commits to the extra field under its own tag.
_BINDING_TAG = b"hd-qc-v1"
_BINDING_TAG_BLS = b"hd-qc-v2-bls"

#: Domain separator for the message BLS partials sign. Deliberately
#: covers only (height, round, value_digest) — the consensus fact — so a
#: light client can recompute it from the certificate alone.
_BLS_MSG_TAG = b"hd-bls-commit-v1"


def bls_commit_message(height: int, round: int, value_digest: bytes) -> bytes:
    """The canonical byte string a committee member BLS-signs to endorse
    one committed (height, round, value). Same-message across the
    committee, which is what makes rogue-key-safe *same-message*
    aggregation applicable (every signer is a whitelisted identity with
    a deterministically derived key — no adversarial key registration)."""
    return (
        _BLS_MSG_TAG
        + int(height).to_bytes(8, "little")
        + int(round).to_bytes(4, "little")
        + bytes(value_digest)
    )


@dataclass(frozen=True)
class QuorumCertificate:
    """One committed (height, round, value) plus the quorum that proved it.

    ``value_digest`` is sha256 of the committed value (values are
    variable-length; the digest keeps the record constant-size).
    ``signers`` is the bitmap of precommit signatories in whitelist
    order; ``transcript`` binds the batch-verification launch that
    established the quorum (b"" * 32 when the verifier exposes none —
    the unsigned/lock-step harness paths). ``binding`` commits to every
    other field; :meth:`Certifier.verify` recomputes it.
    """

    height: int
    round: int
    value_digest: bytes
    signers: bytes
    transcript: bytes
    binding: bytes
    #: Compressed BLS12-381 G1 aggregate signature over
    #: :func:`bls_commit_message` (48 bytes), or b"" on the
    #: transcript-bound-only path.
    agg_sig: bytes = b""

    def signer_count(self) -> int:
        return sum(bin(b).count("1") for b in self.signers)


def _binding(height, round, value_digest, signers, transcript,
             agg_sig: bytes = b"") -> bytes:
    h = hashlib.sha256()
    if agg_sig:
        h.update(_BINDING_TAG_BLS)
    else:
        h.update(_BINDING_TAG)
    h.update(int(height).to_bytes(8, "little"))
    h.update(int(round).to_bytes(4, "little"))
    h.update(value_digest)
    h.update(len(signers).to_bytes(2, "little"))
    h.update(signers)
    h.update(transcript)
    if agg_sig:
        h.update(agg_sig)
    return h.digest()


@wire_codec(tag="cert.quorum", max_bytes=8192)
def marshal_certificate(cert: QuorumCertificate, w: Writer) -> None:
    w.u64(cert.height)
    w.u32(cert.round)
    w.bytes32(cert.value_digest)
    w.raw(cert.signers)
    w.bytes32(cert.transcript)
    w.bytes32(cert.binding)
    w.raw(cert.agg_sig)


@wire_codec(tag="cert.quorum", max_bytes=8192)
def unmarshal_certificate(r: Reader) -> QuorumCertificate:
    height = r.u64()
    rnd = r.u32()
    value_digest = r.bytes32()
    signers = r.raw()
    if len(signers) > 4096:
        raise SerdeError(f"signer bitmap too wide: {len(signers)} bytes")
    transcript = r.bytes32()
    binding = r.bytes32()
    agg_sig = r.raw()
    if len(agg_sig) not in (0, 48):
        raise SerdeError(f"bad aggregate signature length: {len(agg_sig)}")
    return QuorumCertificate(
        height=height,
        round=rnd,
        value_digest=value_digest,
        signers=signers,
        transcript=transcript,
        binding=binding,
        agg_sig=agg_sig,
    )


def certificate_size(n_validators: int, with_bls: bool = False) -> int:
    """Marshalled bytes for an n-validator certificate (the bench's
    O(1)-in-n measurement helper). ``with_bls`` adds the 48-byte
    aggregate-signature field the BLS path carries."""
    w = Writer()
    marshal_certificate(
        QuorumCertificate(
            height=0,
            round=0,
            value_digest=bytes(32),
            signers=bytes(-(-n_validators // 8)),
            transcript=bytes(32),
            binding=bytes(32),
            agg_sig=bytes(48) if with_bls else b"",
        ),
        w,
    )
    return len(w.data())


def verify_bls_certificate(cert: QuorumCertificate, pubkeys,
                           quorum: "int | None" = None) -> bool:
    """Light-client verification: accept the certificate on the strength
    of its BLS aggregate alone — no transcript, no binding, no trust in
    the emitting replica.

    ``pubkeys``: the committee's G2 public keys in whitelist order, as
    96-byte compressed blobs or affine Fp2 pairs. The signer bitmap
    selects which keys participate; ``quorum`` (default: reject nothing
    on weight — pass 2f+1 to enforce) gates the signer count. One
    pairing product regardless of committee size."""
    from hyperdrive_tpu.crypto import bls

    if len(cert.agg_sig) != 48 or len(cert.value_digest) != 32:
        return False
    if len(cert.signers) != -(-len(pubkeys) // 8):
        return False
    if quorum is not None and cert.signer_count() < quorum:
        return False
    try:
        sig = bls.g1_decompress(cert.agg_sig)
    except Exception:
        return False
    selected = []
    for i, pk in enumerate(pubkeys):
        if not cert.signers[i >> 3] >> (i & 7) & 1:
            continue
        if isinstance(pk, (bytes, bytearray)):
            try:
                pk = bls.g2_decompress(bytes(pk))
            except Exception:
                return False
        selected.append(pk)
    # Trailing bits past the committee width must be clear.
    for i in range(len(pubkeys), 8 * len(cert.signers)):
        if cert.signers[i >> 3] >> (i & 7) & 1:
            return False
    if not selected:
        return False
    msg = bls_commit_message(cert.height, cert.round, cert.value_digest)
    return bls.verify_aggregate_same_message(selected, msg, sig)


class Certifier:
    """Per-replica certificate emitter + O(1) re-verifier.

    Plugs into the :class:`~hyperdrive_tpu.process.Process` commit seam:
    when L49 fires with 2f+1 precommits, the process hands over the
    signer set and the certifier mints the certificate, binding the
    verifier's last batch transcript (``transcript_source``: a callable
    returning bytes — e.g. ``lambda: verifier.last_transcript`` — or
    None for transcript-less paths). Emitted certificates are kept per
    height (``certs``) and surfaced through the ``cert.emit`` /
    ``cert.verify`` obs events (OBSERVABILITY.md).
    """

    def __init__(self, signatories, f: int, transcript_source=None,
                 obs=None, bls_keyring=None, bls_aggregate_fn=None):
        self.signatories = list(signatories)
        self._pos = {s: i for i, s in enumerate(self.signatories)}
        self.f = int(f)
        self.transcript_source = transcript_source
        self.obs = obs if obs is not None else NULL_BOUND
        #: Optional BLS committee keyring: signatory identity ->
        #: :class:`~hyperdrive_tpu.crypto.bls.BlsKeyPair`. When set,
        #: emitted certificates carry the 48-byte aggregate signature.
        #: (Harness shortcut: partials that would ride on precommit
        #: messages in a deployment are computed here from the shared
        #: deterministic keyring — same bytes either way.)
        self.bls_keyring = bls_keyring
        #: Aggregation backend: callable(list of affine G1 partials) ->
        #: affine G1 aggregate. Defaults to the host fold; the sim
        #: injects the device bitmask-tree kernel here.
        self._bls_aggregate_fn = bls_aggregate_fn
        #: height -> QuorumCertificate, in emission order.
        self.certs: dict = {}
        #: Verification outcomes (observability/tests).
        self.verified = 0
        self.rejected = 0

    def bls_pubkeys(self):
        """The committee's compressed G2 public keys in whitelist order
        (what a light client needs for :func:`verify_bls_certificate`),
        or None when no keyring is installed."""
        if self.bls_keyring is None:
            return None
        return [self.bls_keyring[s].pk_bytes for s in self.signatories]

    # ------------------------------------------------------------- emission

    def observe_commit(self, height, round, value, signers):
        """Mint the certificate for one committed (height, round, value).

        ``signers``: the precommit signatories counted toward the 2f+1
        quorum (whitelist members; unknown signatories are ignored —
        they were never counted by the grid either)."""
        bitmap = bytearray(-(-len(self.signatories) // 8))
        for s in signers:
            i = self._pos.get(s)
            if i is not None:
                bitmap[i >> 3] |= 1 << (i & 7)
        transcript = b""
        if self.transcript_source is not None:
            transcript = self.transcript_source() or b""
        if len(transcript) != 32:
            transcript = hashlib.sha256(transcript).digest() if transcript \
                else bytes(32)
        value_digest = hashlib.sha256(value).digest()
        signers_b = bytes(bitmap)
        agg_sig = self._bls_aggregate(
            height, round, value_digest, signers_b
        )
        cert = QuorumCertificate(
            height=int(height),
            round=int(round),
            value_digest=value_digest,
            signers=signers_b,
            transcript=transcript,
            binding=_binding(
                height, round, value_digest, signers_b, transcript, agg_sig
            ),
            agg_sig=agg_sig,
        )
        self.certs[int(height)] = cert
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "cert.emit", int(height), int(round),
                cert.value_digest.hex()[:16],
            )
        return cert

    def _bls_aggregate(self, height, round, value_digest,
                       signers_b: bytes) -> bytes:
        """Aggregate the counted signers' BLS partials over the commit
        message. Returns the 48-byte compressed aggregate, or b"" when
        no keyring is installed or a counted signer has no key (an
        aggregate that disagrees with the bitmap would be worse than
        none)."""
        if self.bls_keyring is None:
            return b""
        counted = []
        for i, s in enumerate(self.signatories):
            if signers_b[i >> 3] >> (i & 7) & 1:
                kp = self.bls_keyring.get(s)
                if kp is None:
                    return b""
                counted.append(kp)
        if not counted:
            return b""
        from hyperdrive_tpu.crypto import bls

        msg = bls_commit_message(height, round, value_digest)
        partials = [kp.sign(msg) for kp in counted]
        if self._bls_aggregate_fn is not None:
            agg = self._bls_aggregate_fn(partials)
        else:
            agg = bls.aggregate_signatures(partials)
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "bls.cert.agg", int(height), len(partials),
                "device" if self._bls_aggregate_fn is not None else "host",
            )
        return bls.g1_compress(agg)

    # ----------------------------------------------------------- re-verify

    def verify(self, cert: QuorumCertificate) -> bool:
        """O(1) acceptance: quorum weight, bitmap width, and binding
        integrity — no signature is re-checked and no vote set is
        re-gossiped. Emits ``cert.verify`` with the outcome."""
        ok = (
            len(cert.signers) == -(-len(self.signatories) // 8)
            and cert.signer_count() >= 2 * self.f + 1
            and len(cert.value_digest) == 32
            and cert.binding
            == _binding(
                cert.height, cert.round, cert.value_digest, cert.signers,
                cert.transcript, cert.agg_sig,
            )
        )
        if ok:
            self.verified += 1
        else:
            self.rejected += 1
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "cert.verify", cert.height, cert.round,
                "ok" if ok else "reject",
            )
        return ok

    # ------------------------------------------------------------- rotation

    def rotate(self, signatories, f: int, bls_keyring=None) -> None:
        """Epoch hot-swap (epochs.py): install the next committee's
        whitelist order and quorum threshold. Emitted certificates are
        kept — the chain stays continuous across the transition; only
        bitmap indexing for NEW emissions follows the new order. When a
        keyring is installed and none is supplied for the new committee,
        keys are re-derived deterministically from the identities (the
        same construction every component uses), so BLS emission
        survives churn."""
        self.signatories = list(signatories)
        self._pos = {s: i for i, s in enumerate(self.signatories)}
        self.f = int(f)
        if bls_keyring is not None:
            self.bls_keyring = bls_keyring
        elif self.bls_keyring is not None:
            from hyperdrive_tpu.crypto import bls

            for s in self.signatories:
                if s not in self.bls_keyring:
                    self.bls_keyring[s] = bls.bls_keypair_from_identity(s)

    # ------------------------------------------------------------- chaining

    def certificate_for(self, height):
        return self.certs.get(int(height))

    def chain_digest(self) -> str:
        """Canonical digest over the emitted certificate chain — the
        cross-replica / pipelined-vs-sequential equality handle (the
        certificate sibling of ``SimulationResult.commit_digest``)."""
        h = hashlib.sha256()
        for height in sorted(self.certs):
            c = self.certs[height]
            h.update(int(height).to_bytes(8, "little"))
            h.update(c.value_digest)
            h.update(c.signers)
        return h.hexdigest()

    def reset(self) -> None:
        """Crash-restart hook: a revived replica re-emits from its
        checkpoint; stale certificates must not survive the restore."""
        self.certs.clear()
