"""Per-replica device-tally flushing: the DEPLOYMENT shape of the vote
grid.

The harness settles a whole lockstep network in one aggregated launch
(:mod:`hyperdrive_tpu.harness.sim` — a simulation artifact: one process
owns every replica). A deployed replica instead owns its own n=1 grid
row (the "deployment (n = 1)" row of :class:`~hyperdrive_tpu.ops.votegrid.
VoteGrid`'s memory-budget table) and flushes at its own pace, driven by
its own event loop. This module is that composition: a
:class:`DeviceTallyFlusher` plugs into :class:`hyperdrive_tpu.replica.
Replica`'s ``flusher`` seam and, per flush pass,

1. drains the replica's eligible window from the sorted queue,
2. batch-verifies it through the injected Verifier (in the capstone
   deployment: :class:`~hyperdrive_tpu.ops.ed25519_wire.TpuWireVerifier`
   with a resident ValidatorTable — the grouped 69 B/lane challenge
   format, SHA-512 + mod-L + decompression + ladder on device),
3. inserts the survivors into the host automaton
   (:meth:`~hyperdrive_tpu.replica.Replica.ingest_insert_window`),
   scattering each *accepted* vote into the device grid,
4. runs ONE fused tally launch and hands the counts to the rule cascade
   (:meth:`~hyperdrive_tpu.replica.Replica.ingest_cascade_window`).

The rule cascade reads device counts where the grid covers the query and
falls back to the host counters elsewhere — bit-equal by contract,
enforceable per query with ``tally_check=CheckedTallyView``. The
reference has no analogue of any of this: its hot loops rescan Go maps
per message (/root/reference/process/process.go:574-579); this is the
north star's masked-reduction tally behind the replica's own inbox.
"""

from __future__ import annotations

import numpy as np

from hyperdrive_tpu.analysis.annotations import async_scope, device_fetch
from hyperdrive_tpu.obs.recorder import NULL_BOUND

__all__ = ["DeviceTallyFlusher"]


class DeviceTallyFlusher:
    """Owns one replica's device vote grid + batched verification flush.

    Single-writer: all methods must run on the owning replica's event
    loop thread (the same discipline as the Process itself — reference:
    process/process.go:100-101). Multiple local replicas each get their
    own flusher; they may share one Verifier (its launches are
    independent).

    ``validators``: the signatory list in whitelist order — defines the
    grid's validator axis. ``tally_check``: optional ``(view, proc) ->
    view`` wrapper (e.g. :class:`~hyperdrive_tpu.ops.votegrid.
    CheckedTallyView`) installed over every launch's TallyView.
    """

    def __init__(self, verifier, validators, r_slots: int = 8,
                 buckets: tuple = (256, 1024, 4096), tally_check=None,
                 pipeline_split: int = 512, obs=None, queue=None,
                 certifier=None):
        from hyperdrive_tpu.ops.votegrid import VoteGrid

        self.verifier = verifier
        #: Optional certificates.Certifier shared with the replica's
        #: Process: the settle path re-verifies each newly minted
        #: QuorumCertificate in O(1) (binding + quorum weight) instead of
        #: carrying the 2f+1-signature vote set forward. When the
        #: certifier has no transcript source yet, bind it to this
        #: flusher's verifier so certificates commit to the batch launch
        #: that established their quorum.
        self.certifier = certifier
        if certifier is not None and certifier.transcript_source is None:
            certifier.transcript_source = lambda: getattr(
                self.verifier, "last_transcript", b""
            )
        self.grid = VoteGrid(
            1, len(validators), r_slots=r_slots, buckets=buckets
        )
        self._pos = {s: i for i, s in enumerate(validators)}
        self._r_slots = r_slots
        self._buckets = buckets
        #: Epoch-keyed pubkey-table generation (epochs.py). Tags every
        #: queued verify command so the DeviceWorkQueue never coalesces
        #: windows from different validator-set generations into one
        #: launch — a drain spanning an epoch boundary splits instead.
        self.generation = 0
        if tally_check is None:
            # Sanitizer HDS004 (ANALYSIS.md): under HD_SANITIZE every
            # launch's device counts are cross-checked against the host
            # counters; callers that pass their own tally_check keep it.
            from hyperdrive_tpu.analysis.sanitizer import maybe_tally_check

            tally_check = maybe_tally_check()
        self.tally_check = tally_check
        self._height = None
        self._dirty: set = set()
        #: Flush passes that ran a tally launch (observability).
        self.launches = 0
        #: Double-buffered verify: a window at least this large splits in
        #: two, both halves' verify launches are enqueued up front, and
        #: the second half's device time runs UNDER the first half's host
        #: insert instead of ahead of it. Requires a verifier with an
        #: async entry point (``verify_signatures_begin``); others keep
        #: the single-launch schedule. 0 disables splitting.
        self.pipeline_split = int(pipeline_split)
        #: Rows ingested through the columnar fast path (observability —
        #: the wire-facing :meth:`settle_block` entry).
        self.fastpath_rows = 0
        #: Flight-recorder handle (obs/recorder.py; NULL_BOUND = off).
        self.obs = obs if obs is not None else NULL_BOUND
        #: Async device-work queue (:class:`hyperdrive_tpu.devsched.
        #: DeviceWorkQueue`). When set, :meth:`flush` stops blocking per
        #: window: each drained window becomes one submitted verify
        #: command and its settle (insert + tally + cascade) runs at the
        #: queue's next drain — where windows from EVERY flusher sharing
        #: the queue coalesce into one launch, so co-located replicas
        #: (and multihost tenants) split one sync floor instead of
        #: paying one each. None keeps the synchronous schedule.
        self.queue = queue
        #: Futures for submitted-but-unsettled windows, in submission
        #: order (crash-restart reset cancels them).
        self._inflight: list = []

    def warmup(self) -> None:
        """Compile the grid kernel (one empty scatter) before the replica
        starts — a deployment pays XLA compiles at boot, not inside its
        first consensus round where they would masquerade as network
        stalls and fire timeouts."""
        R = self.grid.R
        self.grid.update_and_tally(
            np.zeros((0, 4), dtype=np.int32),
            np.zeros((0, 8), dtype=np.int32),
            np.zeros(1, dtype=bool),
            np.zeros((1, R, 8), dtype=np.int32),
            np.zeros((1, R), dtype=bool),
            np.full(1, -1, dtype=np.int32),
            np.zeros((1, 8), dtype=np.int32),
            np.zeros(1, dtype=np.int32),
        )
        if hasattr(self.verifier, "warmup"):
            self.verifier.warmup()

    def reset(self, replica=None) -> None:
        """Crash-restart recovery hook (:meth:`hyperdrive_tpu.replica.
        Replica.restore` calls this): cancel every in-flight settle — a
        revived replica restores from its checkpoint and must NOT apply
        its dead predecessor's submitted-but-unsettled windows on top —
        and drop the height claim so the next settle resets the grid
        plane instead of trusting pre-crash scatters."""
        for fut in self._inflight:
            fut.cancel()
        self._inflight.clear()
        self._height = None
        self._dirty = set()
        if self.certifier is not None:
            self.certifier.reset()

    def rotate_validators(self, validators, generation=None) -> None:
        """Install the next epoch's signatory list (whitelist order).

        Epoch-boundary hot swap: rebuilds the sender->column map, grows
        a fresh grid when the committee size changed (same-size
        committees reuse the allocation — the next settle's height move
        resets the plane anyway), and bumps :attr:`generation` so queued
        verify commands submitted after this point land in their own
        coalesced launch. In-flight windows keep their OLD generation
        tag: the queue settles them under the table they were signed
        against, never a mixed batch."""
        validators = list(validators)
        if generation is None:
            generation = self.generation + 1
        self.generation = int(generation)
        if len(validators) != self.grid.V:
            from hyperdrive_tpu.ops.votegrid import VoteGrid

            self.grid = VoteGrid(
                1, len(validators), r_slots=self._r_slots,
                buckets=self._buckets,
            )
        self._pos = {s: i for i, s in enumerate(validators)}
        # Pre-rotation scatters are meaningless under the new column
        # order; force the next settle to reset the grid plane.
        self._height = None
        self._dirty = set()

    @async_scope
    def _flush_async(self, replica) -> None:
        """The devsched flush schedule: drain windows NOW, settle at the
        queue's next drain. Each window's verify command goes onto the
        shared queue and its settle (mask filter + insert + tally +
        cascade) runs in the future's done-callback — by then the
        coalesced launch has verified every co-submitted window, so N
        flushing replicas paid ONE device sync. The settle reads the
        replica's state at drain time, which is the pipelining: the
        replica keeps stepping (next height's propose/prevote) while its
        windows are in flight. No ``device_fetch`` here — the mask
        arrives resolved (HD006 enforces this discipline)."""
        queue = self.queue
        launcher = queue.verify_launcher(self.verifier)
        while True:
            window = replica.mq.drain_window(
                replica.proc.current_height, replica.opts.verify_window
            )
            if not window:
                return
            if self.obs is not NULL_BOUND:
                self.obs.emit(
                    "flush.launch",
                    replica.proc.current_height,
                    replica.proc.current_round,
                    len(window),
                )
            fut = queue.submit(
                launcher,
                [(m.sender, m.digest(), m.signature) for m in window],
                self.generation,
                origin=(
                    self.obs.replica
                    if self.obs is not NULL_BOUND else None
                ),
                rows=len(window),
            )
            self._inflight.append(fut)

            def settle(f, window=window, replica=replica):
                try:
                    self._inflight.remove(f)
                except ValueError:
                    pass
                # The launcher already applied the verifier's unsigned
                # filter; its verdicts ARE verify_batch's.
                # hdlint: disable=HD001 resolved futures hold a host list; the one device fetch happened inside the coalesced launch
                keep = [bool(ok) for ok in f.result()]
                self._settle(replica, [(window, None, lambda k=keep: k)])

            fut.add_done_callback(settle)

    def flush(self, replica) -> None:
        """Drain the replica's queue to quiescence (the reference flush
        contract, replica/replica.go:251-264), one verified + tallied
        window per pass.

        Double-buffered when the window is large and the verifier is
        async-capable: the window splits in half, BOTH halves' verify
        launches are enqueued up front, then the first half's mask is
        fetched and inserted into the host automaton while the second
        half is still verifying on device. The second fetch lands after
        ~an insert leg of overlap instead of after a dead sync wait. Both
        halves feed ONE tally launch + cascade, so commit behaviour is
        byte-identical to the single-launch schedule (the automaton sees
        the same rows in the same order).
        """
        if self.queue is not None:
            self._flush_async(replica)
            return
        begin = getattr(self.verifier, "verify_signatures_begin", None)
        while True:
            window = replica.mq.drain_window(
                replica.proc.current_height, replica.opts.verify_window
            )
            if not window:
                return
            if self.obs is not NULL_BOUND:
                self.obs.emit(
                    "flush.launch",
                    replica.proc.current_height,
                    replica.proc.current_round,
                    len(window),
                )
            if (
                begin is not None
                and self.pipeline_split > 0
                and len(window) >= max(2, self.pipeline_split)
            ):
                mid = len(window) // 2
                halves = (window[:mid], window[mid:])
                # Enqueue BOTH launches before materializing either mask:
                # half 2 verifies under half 1's fetch + host insert.
                pending = [
                    begin([(m.sender, m.digest(), m.signature) for m in h])
                    for h in halves
                ]
                self._settle(
                    replica,
                    [
                        (
                            h,
                            None,
                            lambda p=p, h=h: [
                                bool(ok) and bool(m.signature)
                                for ok, m in zip(
                                    device_fetch(
                                        p.mask(),
                                        why="half-window verdicts; the "
                                            "2nd half verifies under "
                                            "this fetch + insert",
                                    ),
                                    h,
                                )
                            ],
                        )
                        for h, p in zip(halves, pending)
                    ],
                )
            else:
                keep = self.verifier.verify_batch(window)
                self._settle(replica, [(window, None, lambda k=keep: k)])

    def settle_block(self, replica, block) -> None:
        """Wire-facing columnar settle: one verified + tallied pass over a
        :class:`~hyperdrive_tpu.batch.MessageBlock` window straight off
        the transport. Rows flow into the automaton through the columnar
        fast path (:meth:`~hyperdrive_tpu.replica.Replica.
        ingest_insert_window_cols`) — message objects materialize only
        for rows the automaton actually accepts or that trip a catcher,
        never for verify-rejected or duplicate rows. Bypasses the
        replica's queue: the caller owns windowing (this IS the window).
        """
        cols = block.columns()
        items = block.verify_items()
        begin = getattr(self.verifier, "verify_signatures_begin", None)
        if begin is not None:
            pending = begin(items)
            resolve = lambda: [  # noqa: E731
                bool(b)
                for b in device_fetch(pending.mask(),
                                      why="columnar settle verify mask")
            ]
        elif hasattr(self.verifier, "verify_signatures"):
            mask = self.verifier.verify_signatures(items)
            resolve = lambda: [bool(b) for b in mask]  # noqa: E731
        else:
            # Transport-trusting verifier (NullVerifier): accept whatever
            # carries a signature. Unsigned rows still drop — a wire row
            # without a signature is a framing defect, not a trust call.
            keep = [bool(sig) for _, _, sig in items]
            resolve = lambda: keep  # noqa: E731
        self.fastpath_rows += cols.n
        self._settle(replica, [(None, cols, resolve)])

    def _settle(self, replica, parts) -> None:
        """Insert every part (resolving each part's verify mask just
        before its insert leg — the double-buffer overlap point), union
        the insert plans, then run ONE tally launch + cascade. ``parts``:
        ``(window, cols, resolve_keep)`` triples; exactly one of
        ``window`` (message list) / ``cols`` (WindowColumns) is set."""
        from hyperdrive_tpu.batch import MessageBlock
        from hyperdrive_tpu.ops.tally import pack_value
        from hyperdrive_tpu.ops.votegrid import TallyView

        grid = self.grid
        R = grid.R
        proc = replica.proc

        # Reset the plane when the height moved since the grid was last
        # valid — computed BEFORE the insert phase so the hook's dirty
        # marks for the new height survive (inserts never move heights).
        reset = np.zeros(1, dtype=bool)
        h = proc.current_height
        if self._height != h:
            reset[0] = True
            self._height = h
            self._dirty = set()

        accepted: list = []
        dirty = self._dirty

        def on_accepted(msg, is_precommit):
            rnd = msg.round
            plane = 1 if is_precommit else 0
            if rnd < 0 or rnd >= R:
                # Outside the slot window: the view declines these rounds.
                return
            v = self._pos.get(msg.sender)
            if v is None:
                # Whitelisted sender beyond the grid's validator axis
                # (post-rotation): poison the round for this height.
                dirty.add((plane, rnd))
                return
            accepted.append((plane, msg))

        commit_rounds: set = set()
        vote_rounds: set = set()
        for window, cols, resolve in parts:
            keep = resolve()
            if cols is not None:
                part_plan = replica.ingest_insert_window_cols(
                    cols, keep, on_accepted
                )
            else:
                part_plan = replica.ingest_insert_window(
                    window, keep, on_accepted
                )
            commit_rounds |= part_plan[0]
            vote_rounds |= part_plan[1]
        plan = (commit_rounds, vote_rounds)

        # Launch inputs (n = 1): per-round matching targets are this
        # replica's proposal values post-insert; the L28 lane carries the
        # cross-round (valid_round, current proposal value) query.
        st = proc.state
        targets = np.zeros((1, R, 8), dtype=np.int32)
        tvalid = np.zeros((1, R), dtype=bool)
        l28_slot = np.full(1, -1, dtype=np.int32)
        l28_target = np.zeros((1, 8), dtype=np.int32)
        tmap: dict = {}
        for rnd, p in st.propose_logs.items():
            if 0 <= rnd < R:
                targets[0, rnd] = pack_value(p.value)
                tvalid[0, rnd] = True
                tmap[rnd] = p.value
        l28_val = b""
        cur = st.propose_logs.get(st.current_round)
        if cur is not None and 0 <= cur.valid_round < R:
            l28_slot[0] = cur.valid_round
            l28_target[0] = pack_value(cur.value)
            l28_val = cur.value

        if accepted:
            block = MessageBlock.from_messages([m for _, m in accepted])
            words = np.ascontiguousarray(block.rows["value"]).view("<i4")
            idx = np.array(
                [
                    (0, plane, m.round, self._pos[m.sender])
                    for plane, m in accepted
                ],
                dtype=np.int32,
            )
        else:
            words = np.zeros((0, 8), dtype=np.int32)
            idx = np.zeros((0, 4), dtype=np.int32)
        counts = grid.update_and_tally(
            idx, words, reset, targets, tvalid, l28_slot, l28_target,
            np.array([proc.f], dtype=np.int32),
        )
        self.launches += 1
        if self.obs is not NULL_BOUND:
            self.obs.emit("tally.launch", h, st.current_round, len(idx))
        view = TallyView(
            0, self._height, counts, R, tmap, int(l28_slot[0]), l28_val,
            dirty=dirty,
        )
        if self.tally_check is not None:
            view = self.tally_check(view, proc)
        h_before = proc.current_height
        replica.ingest_cascade_window(plan, view)
        if self.certifier is not None:
            # Any height the cascade just committed minted a certificate
            # (Process L49); re-check each one here in O(1) so a broken
            # emission seam fails the settle that produced it, not a
            # remote consumer rounds later.
            for ch in range(h_before, proc.current_height):
                cert = self.certifier.certificate_for(ch)
                if cert is not None:
                    self.certifier.verify(cert)
        if self.obs is not NULL_BOUND:
            self.obs.emit(
                "flush.settle", proc.current_height, proc.current_round
            )
