"""Timeout scheduling with linear round scaling.

Capability parity with the reference's ``timer/timer.go``: a
:class:`LinearTimer` schedules propose/prevote/precommit timeouts whose
duration grows linearly with the round (``timeout * (1 + round * scaling)``),
delivering a :class:`~hyperdrive_tpu.messages.Timeout` event to an injected
handler when the deadline passes.

Two implementations are provided:

- :class:`LinearTimer` — wall-clock, one daemon ``threading.Timer`` per
  scheduled timeout (the analogue of the reference's goroutine-per-timeout,
  timer/timer.go:88-92). For production-style use.
- :class:`VirtualTimer` — deterministic simulated time for the test/bench
  harness: deadlines go into a heap owned by a
  :class:`~hyperdrive_tpu.harness.sim.VirtualClock`; the simulator advances
  time explicitly, so runs are reproducible and fast. This is this
  framework's answer to the reference's real-sleep test timers.
"""

from __future__ import annotations

import random
import threading
from typing import Callable, Optional

from hyperdrive_tpu.messages import Timeout
from hyperdrive_tpu.types import Height, MessageType, Round

__all__ = ["LinearTimer", "VirtualTimer", "DEFAULT_TIMEOUT", "DEFAULT_TIMEOUT_SCALING"]

#: Default base timeout in seconds (reference: timer/opt.go:10-11).
DEFAULT_TIMEOUT = 20.0
#: Default linear scaling factor per round (reference: timer/opt.go:13-14).
DEFAULT_TIMEOUT_SCALING = 0.5

TimeoutHandler = Callable[[Timeout], None]


def _shaped_duration(
    timeout: float,
    scaling: float,
    round: Round,
    max_timeout: "float | None",
    jitter: float,
    rng: "random.Random | None",
) -> float:
    """The shared duration policy behind both timer implementations.

    Base law: ``timeout * (1 + round * scaling)`` (reference:
    timer/timer.go:120-122). Two optional shapers, both OFF by default
    so existing deployments and every recorded sim trajectory are
    untouched:

    - ``max_timeout`` caps the linear growth — unbounded, a long stall
      (a partition lasting many rounds) leaves replicas waiting
      arbitrarily long after conditions recover.
    - ``jitter`` stretches each duration by a uniform factor in
      ``[1, 1 + jitter)`` — identical deterministic timeouts expire in
      lockstep across replicas, synchronizing their round changes and
      re-proposals into colliding bursts; per-replica jitter (pass each
      replica its own seeded ``rng``) desynchronizes them.

    The cap applies BEFORE jitter, so the effective ceiling is
    ``max_timeout * (1 + jitter)`` and jitter keeps working (stays
    non-lockstep) even for capped rounds.
    """
    d = timeout + timeout * round * scaling
    if max_timeout is not None and d > max_timeout:
        d = max_timeout
    if jitter:
        d += d * jitter * (rng or random).random()
    return d


class LinearTimer:
    """Wall-clock timer: spawns a daemon thread per scheduled timeout."""

    def __init__(
        self,
        handle_timeout_propose: Optional[TimeoutHandler] = None,
        handle_timeout_prevote: Optional[TimeoutHandler] = None,
        handle_timeout_precommit: Optional[TimeoutHandler] = None,
        timeout: float = DEFAULT_TIMEOUT,
        timeout_scaling: float = DEFAULT_TIMEOUT_SCALING,
        max_timeout: "float | None" = None,
        jitter: float = 0.0,
        rng: "random.Random | None" = None,
    ):
        self._handle_propose = handle_timeout_propose
        self._handle_prevote = handle_timeout_prevote
        self._handle_precommit = handle_timeout_precommit
        self.timeout = timeout
        self.timeout_scaling = timeout_scaling
        self.max_timeout = max_timeout
        self.jitter = jitter
        self._rng = rng

    def duration_at(self, height: Height, round: Round) -> float:
        """Timeout duration for a (height, round)
        (reference: timer/timer.go:120-122), optionally capped and
        jittered — see :func:`_shaped_duration`."""
        return _shaped_duration(
            self.timeout,
            self.timeout_scaling,
            round,
            self.max_timeout,
            self.jitter,
            self._rng,
        )

    def _spawn(self, handler: TimeoutHandler, ty: MessageType, h: Height, r: Round):
        t = threading.Timer(
            self.duration_at(h, r),
            handler,
            args=(Timeout(message_type=ty, height=h, round=r),),
        )
        t.daemon = True
        t.start()

    def timeout_propose(self, height: Height, round: Round) -> None:
        if self._handle_propose is not None:
            self._spawn(self._handle_propose, MessageType.PROPOSE, height, round)

    def timeout_prevote(self, height: Height, round: Round) -> None:
        if self._handle_prevote is not None:
            self._spawn(self._handle_prevote, MessageType.PREVOTE, height, round)

    def timeout_precommit(self, height: Height, round: Round) -> None:
        if self._handle_precommit is not None:
            self._spawn(self._handle_precommit, MessageType.PRECOMMIT, height, round)


class VirtualTimer:
    """Simulated-time timer for the deterministic harness.

    Schedules deadlines on a clock object exposing
    ``schedule(delay: float, event: Timeout, handler) -> None``; the harness
    decides when virtual time advances and then invokes ``handler(event)``
    (or routes the event itself when ``handler`` is None).
    """

    def __init__(
        self,
        clock,
        handler: Optional[TimeoutHandler] = None,
        timeout: float = 1.0,
        timeout_scaling: float = DEFAULT_TIMEOUT_SCALING,
        max_timeout: "float | None" = None,
        jitter: float = 0.0,
        rng: "random.Random | None" = None,
    ):
        self._clock = clock
        self._handler = handler
        self.timeout = timeout
        self.timeout_scaling = timeout_scaling
        self.max_timeout = max_timeout
        self.jitter = jitter
        #: Jittered virtual timers MUST get a seeded per-replica rng or
        #: the harness's determinism (record/replay, fixed-seed digests)
        #: breaks; the harness owns that wiring.
        self._rng = rng

    def duration_at(self, height: Height, round: Round) -> float:
        return _shaped_duration(
            self.timeout,
            self.timeout_scaling,
            round,
            self.max_timeout,
            self.jitter,
            self._rng,
        )

    def _schedule(self, ty: MessageType, h: Height, r: Round) -> None:
        self._clock.schedule(
            self.duration_at(h, r),
            Timeout(message_type=ty, height=h, round=r),
            self._handler,
        )

    def timeout_propose(self, height: Height, round: Round) -> None:
        self._schedule(MessageType.PROPOSE, height, round)

    def timeout_prevote(self, height: Height, round: Round) -> None:
        self._schedule(MessageType.PREVOTE, height, round)

    def timeout_precommit(self, height: Height, round: Round) -> None:
        self._schedule(MessageType.PRECOMMIT, height, round)
