"""Contribution scoring: the overlay's Byzantine-robustness mechanism.

Handel's insight (arXiv:1906.05132 §4.3) is that an aggregation tree
does not need to *detect* Byzantine peers, only to *deprioritize*
them: every frame a peer contributes is scored by how much new signer
coverage it delivered, and peers whose frames are invalid, stale, or
simply absent drift to the back of every contact queue. The sim keeps
one network-wide score table (a real deployment scores per-observer;
collapsing to a shared table is a documented simplification that keeps
memory O(n) instead of O(n²) at 4096 validators and makes the
monitor's "no honest peer permanently demoted" invariant directly
checkable).

All arithmetic is integer — scores feed ranked fallback ordering,
which feeds message order, which feeds the commit digest, so a float
anywhere here would put platform rounding into consensus replay.

Demotion is advisory, never exclusion (the never-starve doctrine): a
demoted peer still has its frames processed and can still earn its way
back over the demotion threshold — chaos asserts that honest peers
demoted during a fault window recover after it heals.
"""

from __future__ import annotations

__all__ = ["ContributionScores", "CHARGE_WEIGHTS"]

#: Integer penalty per misbehavior class. Keys align with the shared
#: frame-classification vocabulary (load/frames.py) plus the two
#: overlay-only verdicts a classifier cannot see: ``invalid`` (the
#: DeviceWorkQueue verify mask rejected rows of the partial aggregate)
#: and ``withheld`` (a contacted peer sent nothing inside the level
#: window).
CHARGE_WEIGHTS = {
    "invalid": 6,
    "stale_generation": 2,
    "duplicate": 1,
    "withheld": 1,
}


class ContributionScores:
    """Network-wide integer reputation for overlay contributors."""

    def __init__(
        self,
        n: int,
        *,
        credit: int = 2,
        demote_at: int = -8,
        floor: int = -64,
        on_demote=None,
        on_recover=None,
    ):
        if demote_at <= floor:
            raise ValueError("demote_at must sit above the score floor")
        self.n = n
        self.credit_per_signer = int(credit)
        self.demote_at = int(demote_at)
        self.floor = int(floor)
        self.scores = [0] * n
        self.demoted: set = set()
        self.demotions = 0
        self.recoveries = 0
        self.charges = {k: 0 for k in CHARGE_WEIGHTS}
        self._on_demote = on_demote
        self._on_recover = on_recover

    # ------------------------------------------------------------ updates

    def credit_coverage(self, peer: int, new_signers: int) -> int:
        """Reward ``peer`` for a frame that delivered ``new_signers``
        previously-unseen valid signatures to its receiver."""
        if new_signers <= 0:
            return self.scores[peer]
        s = self.scores[peer] + self.credit_per_signer * new_signers
        self.scores[peer] = s
        if peer in self.demoted and s > self.demote_at:
            self.demoted.discard(peer)
            self.recoveries += 1
            if self._on_recover is not None:
                self._on_recover(peer, s)
        return s

    def charge(self, peer: int, cls: str) -> int:
        """Debit ``peer`` for a misbehavior class; clamps at the floor
        so a long fault window stays recoverable in bounded credit."""
        weight = CHARGE_WEIGHTS[cls]
        self.charges[cls] += 1
        s = max(self.floor, self.scores[peer] - weight)
        self.scores[peer] = s
        if s <= self.demote_at and peer not in self.demoted:
            self.demoted.add(peer)
            self.demotions += 1
            if self._on_demote is not None:
                self._on_demote(peer, s, cls)
        return s

    def rehabilitate(self, amount: int) -> None:
        """Time-based amnesty: pull every nonzero score ``amount``
        toward zero. Called once per committed height, it bounds how
        long any verdict — fair or not — stays on the books. The
        asymmetry that makes this safe: a peer silenced by a partition
        is indistinguishable from a withholder to its observers, but it
        stops accruing charges the moment the fault heals, so amnesty
        plus fresh contribution credit restores it in
        O(depth / heal_rate) heights — while an actively-Byzantine peer
        re-earns its debt every slot faster than amnesty forgives it
        (invalid frames cost ``6`` per observer vs one amnesty step per
        committed height)."""
        if amount <= 0:
            return
        for p in range(self.n):
            s = self.scores[p]
            if s < 0:
                s = min(0, s + amount)
            elif s > 0:
                s = max(0, s - amount)
            else:
                continue
            self.scores[p] = s
            if p in self.demoted and s > self.demote_at:
                self.demoted.discard(p)
                self.recoveries += 1
                if self._on_recover is not None:
                    self._on_recover(p, s)

    # ------------------------------------------------------------ queries

    def is_demoted(self, peer: int) -> bool:
        return peer in self.demoted

    def ranked(self, exclude: int = -1) -> list:
        """All peers best-first: score desc, demoted last, index as the
        deterministic tiebreak. Feeds the ranked direct-gossip fallback
        — demoted peers are *last*, not absent (never-starve)."""
        return sorted(
            (p for p in range(self.n) if p != exclude),
            key=lambda p: (p in self.demoted, -self.scores[p], p),
        )

    def snapshot(self) -> dict:
        return {
            "demoted": sorted(self.demoted),
            "demotions": self.demotions,
            "recoveries": self.recoveries,
            "charges": dict(self.charges),
            "min": min(self.scores) if self.scores else 0,
            "max": max(self.scores) if self.scores else 0,
        }
