"""The aggregation overlay runtime: dissemination between replica and sim.

This is the layer ISSUE 12's tentpole names: replicas broadcast votes
into it instead of all-to-all fan-out, and it moves them along the
seeded binomial tree (:mod:`.topology`) as **partial-aggregate frames**
— one frame carries a contributor's whole coverage of a (kind, height,
round) slot as a signer bitmask over a network-global deduplicated
vote table, so frame size is O(1) object-wise and the sim charges one
``delivery_cost`` per frame, not per constituent vote. That pricing is
the scalability claim made measurable: virtual commit latency counts
frames, frames per slot are O(n log n) against all-to-all's O(n²),
and BENCH_r09 plots exactly that ratio.

Determinism contract (lock-step): every decision the runtime makes —
contact order, wave escalation, fallback ranking, Byzantine fault
draws — is a function of the sim seed, the epoch anchor chain, and
the delivery order the sim already records. Constituent votes are
delivered to replicas *per message* and recorded as plain ``(to,
vote)`` tuples, so a dump replays through the ordinary record-driven
path with no overlay at all: topology, frames, and ticks are
reconstruction detail, never record format.

Robustness mechanics (Handel, arXiv:1906.05132):

- **Contribution scoring** (:mod:`.score`): every frame is credited by
  new-signer coverage delivered; invalid rows from the device verify
  mask, stale-generation extras (classified by the *shared*
  ``load/frames.py`` helper — the same predicate the AdmissionGate
  sheds on, so the two ingresses cannot drift), and withheld level
  windows are charged to the **contributing peer**, never the signer.
- **Windowed level ticks with fast-path completion**: levels activate
  by tick index (windowed) or instantly when the previous level's
  block completes (fast path — the happy-path cascade never waits).
- **Never-starve fallback**: when waves exhaust on a dark level the
  node direct-gossips its aggregate to score-ranked peers, demoted
  peers last but never excluded.
- **Verification dedup**: each vote is device-verified once
  network-wide (``verified`` mask), batched per frame through the
  :class:`~hyperdrive_tpu.devsched.queue.DeviceWorkQueue` with
  ``generation=level`` so an aggregation level coalesces naturally and
  the per-row verdict mask isolates culprits.
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass

from hyperdrive_tpu.analysis.annotations import declare_wire_budget
from hyperdrive_tpu.analysis.sanitizer import wire_charge
from hyperdrive_tpu.load.frames import STALE_GENERATION, classify_frame
from hyperdrive_tpu.messages import Precommit, Prevote

from .score import ContributionScores
from .topology import Topology

#: HDS005 budget for one partial-aggregate frame, as it would cost on a
#: wire: header + committee-wide mask + 48-byte BLS aggregate + one
#: full-envelope extra per committee member (the worst legal frame under
#: the on_frame shape caps). Object frames charge an ESTIMATE of this
#: footprint at ingress — the sanitizer fires only if the caps and this
#: budget drift apart.
declare_wire_budget("overlay.partial", 1 << 20)
#: Wire-size estimate for one extras envelope (signed vote riding
#: outside the table): 8 + 8 + 32 + 32 + 64 plus framing slack.
_EXTRA_WIRE_BYTES = 160

__all__ = [
    "OverlayConfig",
    "OverlayFaults",
    "OverlayFrame",
    "OverlayTick",
    "OverlayRuntime",
]

_PREVOTE, _PRECOMMIT = 1, 2
_VOTE_TAG = {Prevote: _PREVOTE, Precommit: _PRECOMMIT}
_VOTE_CLS = {_PREVOTE: Prevote, _PRECOMMIT: Precommit}

#: Seed salt for the Byzantine-contributor RNG ("OVLY"), disjoint from
#: the chaos ("CHOS") and churn ("EPOC") salts so composed fault plans
#: never share a stream.
_BYZ_SALT = 0x4F564C59


@dataclass(frozen=True)
class OverlayFaults:
    """Byzantine-contributor behavior for overlay chaos runs.

    Members of ``byzantine`` keep voting honestly (they are *signers*
    in good standing — the attack surface is the dissemination role):
    they withhold frames on the listed levels and replace a seeded
    fraction of the rest with garbage partial aggregates (empty
    coverage plus fabricated votes that fail device verification, a
    ``stale_rate`` slice of them signed under retired identities so
    the stale-generation charge path is exercised end-to-end).
    """

    byzantine: tuple = ()
    withhold_levels: tuple = ()
    garbage_rate: float = 0.35
    stale_rate: float = 0.25

    def validate(self, n: int) -> None:
        f = n // 3
        bad = sorted(set(int(b) for b in self.byzantine))
        if len(bad) != len(self.byzantine):
            raise ValueError("duplicate byzantine contributor indices")
        if any(b < 0 or b >= n for b in bad):
            raise ValueError(f"byzantine contributor out of range for n={n}")
        if len(bad) > f:
            raise ValueError(
                f"{len(bad)} byzantine contributors exceeds f={f} for n={n}"
            )
        if any(l < 0 for l in self.withhold_levels):
            raise ValueError("withhold levels must be >= 0")
        if not 0.0 <= self.garbage_rate <= 1.0:
            raise ValueError("garbage_rate must be within [0, 1]")
        if not 0.0 <= self.stale_rate <= 1.0:
            raise ValueError("stale_rate must be within [0, 1]")


@dataclass(frozen=True)
class OverlayConfig:
    """``Simulation(overlay=OverlayConfig(...))`` — dissemination knobs.

    ``level_window=None`` auto-scales the tick window to
    ``2 * n * delivery_cost``: the shared virtual clock advances once
    per frame network-wide, so a window that does not scale with n
    would fire withhold charges at honest peers whose frames are merely
    still in the global queue.
    """

    fanout: int = 2
    max_waves: int = 3
    fallback_fanout: int = 2
    level_window: float | None = None
    #: Deliver at most quorum (2f+1) constituent votes per (replica,
    #: value) — enough for every protocol rule, and the reason replica
    #: ingest work stays O(quorum) instead of O(n) at 4096.
    #: Batch a frame's constituents through ``handle_coalesced``
    #: instead of per-message ``handle`` — reserved for unrecorded
    #: mega-committee benches; per-message is the replay-exact default.
    coalesce_ingest: bool = False
    faults: OverlayFaults | None = None
    credit: int = 2
    demote_at: int = -8
    score_floor: int = -64
    #: Per-committed-height amnesty: every nonzero score moves this
    #: many points toward zero (ContributionScores.rehabilitate). This
    #: is what makes demotion recoverable after a long fault window —
    #: a partitioned peer looks exactly like a withholder to every
    #: observer and racks up charges for the whole window, so without
    #: time-based forgiveness the hole can exceed what contribution
    #: credit alone can refill before the run ends.
    heal_rate: int = 6
    #: Frames carry real BLS partial aggregates: each signer's G1
    #: partial over its vote digest enters the global table alongside
    #: the vote, every frame's mask is accompanied by the 48-byte
    #: compressed sum of the covered partials, and the receiver
    #: recomputes that sum (batched through the device queue's G1-sum
    #: launcher, generation=level) BEFORE merging coverage — a garbled
    #: partial aggregate charges its contributor at the merge level,
    #: without ever reaching the signature batch-verify.
    bls_partials: bool = False

    def validate(self, n: int) -> None:
        if self.fanout < 1 or self.fallback_fanout < 1:
            raise ValueError("overlay fanout values must be >= 1")
        if self.max_waves < 1:
            raise ValueError("overlay max_waves must be >= 1")
        if self.level_window is not None and self.level_window <= 0.0:
            raise ValueError("overlay level_window must be positive")
        if self.heal_rate < 0:
            raise ValueError("overlay heal_rate must be >= 0")
        if self.faults is not None:
            self.faults.validate(n)


class OverlayFrame:
    """One partial-aggregate message: contributor ``src``'s coverage of
    ``slot`` as a signer bitmask, plus any out-of-table ``extras``
    (only Byzantine injection produces those). ``agg`` is the 48-byte
    compressed BLS partial aggregate over the mask's covered partials
    (``bls_partials`` runs; None otherwise). Never recorded."""

    __slots__ = ("src", "slot", "level", "mask", "extras", "reciprocal",
                 "fallback", "agg")

    def __init__(self, src, slot, level, mask, extras=(),
                 reciprocal=False, fallback=False, agg=None):
        self.src = src
        self.slot = slot
        self.level = level
        self.mask = mask
        self.extras = extras
        self.reciprocal = reciprocal
        self.fallback = fallback
        self.agg = agg

    @property
    def height(self):
        return self.slot[1]


class OverlayTick:
    """A node's per-slot level-window timer, riding the sim's virtual
    clock like a Timeout (and pruned by height the same way)."""

    __slots__ = ("slot", "height")

    def __init__(self, slot):
        self.slot = slot
        self.height = slot[1]


class _SlotState:
    """All per-(kind, height, round) dissemination state."""

    __slots__ = ("votes", "all_mask", "verified", "cov", "t0", "tick_idx",
                 "armed", "done", "fb_pos", "waves", "dcount", "heard",
                 "charged", "recip", "frames_seen", "bls")

    def __init__(self, n: int, levels: int):
        self.votes: dict = {}          # signer slot -> verified-or-own vote
        self.bls: dict = {}            # signer slot -> BLS partial (G1 affine)
        self.all_mask = 0              # union of table bits
        self.verified = 0              # bits verified once network-wide
        self.cov = [0] * n             # per-node coverage bitmask
        self.t0 = [None] * n           # activation time per node
        self.tick_idx = [0] * n
        self.armed = [False] * n
        self.done = [False] * n
        self.fb_pos = [0] * n
        self.waves: dict = {}          # node -> per-level wave pointer
        self.dcount: dict = {}         # node -> {value: delivered count}
        self.heard: dict = {}          # node -> set of contributors heard
        self.charged: dict = {}        # node -> peers already withhold-charged
        self.recip: dict = {}          # node -> peers already reciprocated
        self.frames_seen: dict = {}    # node -> exact frames seen (dup charge)

    def wave_of(self, node: int, levels: int) -> list:
        w = self.waves.get(node)
        if w is None:
            w = self.waves[node] = [0] * (levels + 1)
        return w


def _bits(mask: int):
    while mask:
        low = mask & -mask
        yield low.bit_length() - 1
        mask ^= low


class OverlayRuntime:
    """One sim's overlay instance; the sim owns delivery and the clock,
    the runtime owns topology, coverage, scoring, and fault injection."""

    def __init__(
        self,
        config: OverlayConfig,
        *,
        n: int,
        seed: int,
        anchor: bytes,
        identities,
        quorum: int,
        delivery_cost: float,
        enqueue,          # (to, frame) -> sim queue append
        schedule,         # (delay, tick, owner) -> clock schedule
        now,              # () -> virtual time
        deliver,          # (to, [votes]) -> record + replica ingest
        alive,            # shared sim liveness list
        order_pos,        # shared identity -> slot index map
        retired,          # shared retired identity -> first stale height
        verifier=None,    # HostVerifier for dedup verification (sign mode)
        sched=None,       # DeviceWorkQueue (required when verifier is set)
        obs=None,
        registry=None,
        bls_keyring=None,  # identity -> BlsKeyPair (bls_partials runs)
    ):
        config.validate(n)
        self.config = config
        self.n = n
        self.seed = int(seed)
        self.quorum = int(quorum)
        self.epoch = 0
        self.topo = Topology(seed, anchor, identities)
        self.window = (
            config.level_window
            if config.level_window is not None
            else 2.0 * n * delivery_cost
        )
        self._enqueue = enqueue
        self._schedule = schedule
        self._now = now
        self._deliver = deliver
        self._alive = alive
        self._order_pos = order_pos
        self._retired = retired
        self._verifier = verifier
        self._sched = sched
        if verifier is not None and sched is None:
            raise ValueError("overlay verification requires a device queue")
        self._obs = obs
        self._reg = registry
        #: BLS partial-aggregate plumbing (config.bls_partials): the
        #: shared committee keyring signs each own-vote's digest into
        #: the global table; masked partial sums ride every frame. With
        #: a device queue the sums run through the G1SumLauncher
        #: (generation=level, so one level's merges coalesce); without
        #: one they fold on host — byte-identical aggregates either way.
        self._bls_keyring = bls_keyring
        self._bls_launcher = None
        if bls_keyring is not None and sched is not None:
            from hyperdrive_tpu.ops.g1 import G1SumLauncher

            width = 1
            while width < max(n, 1):
                width *= 2
            self._bls_launcher = G1SumLauncher(width)
        self._byz_rng = random.Random((self.seed << 1) ^ _BYZ_SALT)
        self._faults = config.faults
        self._byz = frozenset(self._faults.byzantine) if self._faults else frozenset()
        self._withhold = frozenset(self._faults.withhold_levels) if self._faults else frozenset()
        self.scores = ContributionScores(
            n,
            credit=config.credit,
            demote_at=config.demote_at,
            floor=config.score_floor,
            on_demote=self._on_demote,
            on_recover=self._on_recover,
        )
        self._slots: dict = {}
        self._floor = 0
        # Commit floor at each peer's most recent charge: the monitor's
        # permanent-demotion check only fires once enough floor has
        # advanced past this point that rehabilitation should have
        # recovered the peer.
        self._last_charge_floor: dict = {}
        self._garbage_ctr = 0
        # Accounting (overlay_snapshot / bench / obs report rows).
        self.frames_sent = 0
        self.frames_reciprocal = 0
        self.frames_fallback = 0
        self.frames_garbage = 0
        self.frames_withheld = 0
        self.votes_delivered = 0
        self.verify_rows = 0
        self.level_timeouts = 0
        self.fallback_engaged = 0
        self.windows_exhausted = 0
        self.rekeys = 0
        self.bls_partials_attached = 0
        self.bls_partial_rejects = 0
        #: Frames rejected at the shape caps (mask wider than the
        #: committee, extras flood) before any state was touched.
        self.frame_rejects = 0

    # -------------------------------------------------------------- events

    def _emit(self, kind, node, slot, detail=None):
        if self._obs is not None:
            self._obs.emit(kind, node, slot[1], slot[2], detail)

    def _count(self, name, k=1):
        if self._reg is not None:
            self._reg.count(name, k)

    def _on_demote(self, peer, score, cls):
        self._count("overlay.demotions")
        if self._obs is not None:
            self._obs.emit("overlay.demote", peer, 0, 0, f"{cls}:{score}")

    def _on_recover(self, peer, score):
        self._count("overlay.recoveries")
        if self._obs is not None:
            self._obs.emit("overlay.recover", peer, 0, 0, str(score))

    # ------------------------------------------------------------ lifecycle

    def rekey(self, anchor: bytes, identities, epoch: int) -> None:
        """Epoch boundary: rebuild the tree off the new anchor digest and
        rotated identity set. Coverage masks are slot-indexed, so
        in-flight slots carry across; only positions re-key."""
        self.topo = Topology(self.seed, anchor, identities)
        self.epoch = int(epoch)
        self.rekeys += 1
        self._count("overlay.rekeys")
        if self._obs is not None:
            self._obs.emit("overlay.rekey", -1, 0, 0,
                           f"epoch={epoch}:{self.topo.digest().hex()[:12]}")

    def note_commit(self, height: int) -> None:
        """Advance the slot floor: votes for heights below ``height - 1``
        can no longer change any honest replica (catch-up resyncs
        laggards; the overlay has no retransmission duty, matching the
        protocol's no-retransmission doctrine)."""
        floor = height - 1
        if floor <= self._floor:
            return
        # One amnesty step per height the floor actually advances over
        # (note_commit arrives once per replica per height; the floor
        # guard above dedupes). Integer, network-wide, replay-safe.
        self.scores.rehabilitate((floor - self._floor) *
                                 self.config.heal_rate)
        self._floor = floor
        dead = [s for s in self._slots if s[1] < floor]
        for s in dead:
            del self._slots[s]

    # --------------------------------------------------------------- ingress

    def on_broadcast(self, node: int, vote) -> None:
        """A replica's own vote enters the overlay (the sim already
        queued its self-delivery): seed the table, activate the node's
        tree participation for the slot."""
        tag = _VOTE_TAG.get(type(vote))
        if tag is None or vote.height < self._floor:
            return
        slot = (tag, vote.height, vote.round)
        st = self._slot(slot)
        idx = self._order_pos.get(vote.sender)
        if idx is not None and idx not in st.votes:
            st.votes[idx] = vote
            bit = 1 << idx
            st.all_mask |= bit
            if self._bls_keyring is not None:
                # The signer's BLS partial enters the global table with
                # the vote (in a deployment it rides the vote message).
                # Bits added through the Byzantine extras path never
                # gain a partial, so per bit the has-partial status is
                # fixed at insertion — sender and receiver of any frame
                # always sum the identical subset.
                kp = self._bls_keyring.get(vote.sender)
                if kp is not None:
                    st.bls[idx] = kp.sign(vote.digest())
            # NOT marked verified: the signer trusts its own vote (its
            # replica ingests it directly), but the first frame carrying
            # it to anyone else pays the one network-wide device
            # verification, batched with the rest of that frame's new
            # coverage under generation=level.
            st.cov[node] |= bit
            st.done[node] = False
        self._touch(st, slot, node)
        self._arm(st, slot, node)

    def on_frame(self, to: int, frame: OverlayFrame) -> None:
        # Byzantine frame-shape caps, enforced before ANY state mutation:
        # a mask wider than the committee or an extras flood is a typed
        # rejection scored against the contributor — never an unbounded
        # merge, never a crash.
        if (frame.mask < 0 or frame.mask.bit_length() > self.n
                or len(frame.extras) > self.n):
            self.frame_rejects += 1
            self._count("overlay.frame.reject")
            self._charge(frame.src, "invalid", frame.slot, to)
            return
        # HDS005: charge the frame's estimated wire footprint against
        # the declared overlay budget (object seam — no byte decode).
        wire_charge(
            "overlay.partial",
            16 + (frame.mask.bit_length() + 7) // 8 + 48
            + _EXTRA_WIRE_BYTES * len(frame.extras),
        )
        slot = frame.slot
        st = self._slots.get(slot)
        if st is None:
            if slot[1] < self._floor:
                return
            st = self._slot(slot)
        src = frame.src
        st.heard.setdefault(to, set()).add(src)
        self._touch(st, slot, to)

        # Byzantine extras: votes riding outside the global table. The
        # shared classifier (load/frames.py) is the stale-generation
        # authority here, exactly as it is for the AdmissionGate.
        for v in frame.extras:
            cls, _ = classify_frame(v, retired=self._retired)
            if cls is STALE_GENERATION:
                self._charge(src, "stale_generation", slot, to)
                continue
            idx = self._order_pos.get(v.sender)
            if idx is None or not self._verify_extra(v, frame.level, to):
                self._charge(src, "invalid", slot, to)
                continue
            if idx not in st.votes:
                st.votes[idx] = v
                st.all_mask |= 1 << idx
                st.verified |= 1 << idx

        # Coverage claims with no table backing are lies, not lag: the
        # table strictly precedes any mask bit a correct peer can send.
        phantom = frame.mask & ~st.all_mask
        if phantom:
            self._charge(src, "invalid", slot, to)
        # Merge-level BLS check: recompute the masked partial sum and
        # compare against the frame's aggregate BEFORE any coverage is
        # merged or any signature batch-verified. A garbled partial
        # aggregate charges the CONTRIBUTOR here and drops the frame —
        # the poisoned aggregate never propagates and never costs a
        # verify launch.
        if self._bls_keyring is not None and frame.mask:
            expect = self._bls_masked_sum(
                st, frame.mask & st.all_mask, frame.level, to
            )
            if frame.agg != expect:
                self.bls_partial_rejects += 1
                self._count("overlay.bls.reject")
                if self._obs is not None:
                    self._obs.emit("bls.partial.reject", to, slot[1],
                                   slot[2], f"src={src}:lvl={frame.level}")
                self._charge(src, "invalid", slot, to)
                return
        new = frame.mask & st.all_mask & ~st.cov[to]
        if new:
            pending = new & ~st.verified
            if pending and self._verifier is not None:
                ok = self._verify_mask(st, pending, frame.level, to)
                bad = pending & ~ok
                for _ in _bits(bad):
                    self._charge(src, "invalid", slot, to)
                st.verified |= ok
                new &= ~bad
        if new:
            self._deliver_new(to, st, slot, new)
            st.cov[to] |= new
            self.scores.credit_coverage(src, new.bit_count())
            self._emit("overlay.frame", to, slot,
                       f"src={src}:lvl={frame.level}:new={new.bit_count()}")
            st.done[to] = False
            self._advance(to, st, slot)
            self._arm(st, slot, to)
        elif not frame.fallback and not frame.reciprocal:
            # Redundant coverage is normal tree behavior — only an
            # *exact* repeat of a TREE frame this node already saw is
            # spam. Fallback and reciprocal frames are exempt: they are
            # the designed-redundancy rescue paths, and a node stuck
            # behind a partition re-advertises the same aggregate every
            # window until someone pushes it the gap — charging that
            # would demote exactly the peers the never-starve doctrine
            # exists to rescue.
            key = (src, frame.level, frame.mask, bool(frame.extras))
            seen = st.frames_seen.setdefault(to, set())
            if key in seen:
                self._charge(src, "duplicate", slot, to)
            else:
                seen.add(key)
        if not frame.reciprocal:
            self._reciprocate(to, src, st, slot, frame)

    def on_tick(self, node: int, tick: OverlayTick) -> None:
        slot = tick.slot
        st = self._slots.get(slot)
        if st is None:
            return
        st.armed[node] = False
        if not self._alive[node] or st.done[node] or slot[1] < self._floor:
            return
        k = st.tick_idx[node]
        st.tick_idx[node] = k + 1
        waves = st.wave_of(node, self.topo.levels)
        incomplete = False
        exhausted = True
        for lvl in range(1, self.topo.levels + 1):
            if self._complete(node, st, lvl):
                continue
            incomplete = True
            if waves[lvl] == 0:
                if lvl <= k + 2:
                    # Windowed activation: level lvl opens at tick lvl-2
                    # even if lower levels are dark (Handel's parallel
                    # levels — a stalled level never serializes the tree).
                    self._send_wave(node, st, slot, lvl, 0)
                    waves[lvl] = 1
                exhausted = False
            elif waves[lvl] <= self.config.max_waves:
                self.level_timeouts += 1
                self._count("overlay.timeouts")
                self._emit("overlay.level.timeout", node, slot,
                           f"lvl={lvl}:wave={waves[lvl]}")
                self._charge_withheld(node, st, slot, lvl, waves[lvl] - 1)
                self._send_wave(node, st, slot, lvl, waves[lvl])
                waves[lvl] += 1
                exhausted = False
        missing_known = st.cov[node] != st.all_mask
        if incomplete and exhausted and missing_known:
            self.windows_exhausted += 1
            # Every wave spent, the node still lacks votes the network
            # holds: ranked direct gossip advertises its aggregate so a
            # reciprocal push can fill the gap (never-starve).
            self._fallback(node, st, slot)
        if not incomplete or (exhausted and not missing_known):
            # Tree complete, or the node holds everything the network
            # knows and has no waves left to spend — go idle; a frame
            # bearing new coverage re-arms it.
            st.done[node] = True
        else:
            self._arm(st, slot, node)

    # ------------------------------------------------------------- internals

    def _slot(self, slot) -> _SlotState:
        st = self._slots.get(slot)
        if st is None:
            st = self._slots[slot] = _SlotState(self.n, self.topo.levels)
        return st

    def _touch(self, st: _SlotState, slot, node: int) -> None:
        if st.t0[node] is None:
            st.t0[node] = self._now()
            self._advance(node, st, slot)
            self._arm(st, slot, node)

    def _arm(self, st: _SlotState, slot, node: int) -> None:
        if not st.armed[node]:
            st.armed[node] = True
            self._schedule(self.window, OverlayTick(slot), node)

    def _complete(self, node: int, st: _SlotState, level: int) -> bool:
        bm = self.topo.block_mask(node, level)
        return st.cov[node] & bm == bm

    def _advance(self, node: int, st: _SlotState, slot) -> None:
        """Fast-path completion: the instant level ``l-1``'s block is
        whole, open level ``l`` without waiting for its tick window."""
        waves = st.wave_of(node, self.topo.levels)
        for lvl in range(1, self.topo.levels + 1):
            if waves[lvl] == 0 and (lvl == 1 or self._complete(node, st, lvl - 1)):
                self._send_wave(node, st, slot, lvl, 0)
                waves[lvl] = 1
            if not self._complete(node, st, lvl):
                break

    def _send_wave(self, node: int, st: _SlotState, slot, level: int,
                   wave: int) -> None:
        fo = self.config.fanout
        contacts = self.topo.contacts(node, level, (wave + 1) * fo)
        for peer in contacts[wave * fo:(wave + 1) * fo]:
            self._send_frame(node, peer, st, slot, level)

    def _send_frame(self, node: int, peer: int, st: _SlotState, slot,
                    level: int, reciprocal=False, fallback=False) -> None:
        if peer == node:
            return
        if node in self._byz:
            if level in self._withhold:
                self.frames_withheld += 1
                self._count("overlay.withheld_by_fault")
                return
            if self._byz_rng.random() < self._faults.garbage_rate:
                self._send_garbage(node, peer, slot, level)
                return
        mask = st.cov[node]
        if not mask:
            return
        agg = None
        if self._bls_keyring is not None:
            agg = self._bls_masked_sum(st, mask, level, node)
            self.bls_partials_attached += 1
        frame = OverlayFrame(node, slot, level, mask,
                             reciprocal=reciprocal, fallback=fallback,
                             agg=agg)
        self.frames_sent += 1
        self._count("overlay.frames")
        if reciprocal:
            self.frames_reciprocal += 1
            self._count("overlay.frames.reciprocal")
        if fallback:
            self.frames_fallback += 1
            self._count("overlay.frames.fallback")
        self._enqueue(peer, frame)

    def _send_garbage(self, node: int, peer: int, slot, level: int) -> None:
        """A Byzantine partial aggregate: zero real coverage, fabricated
        votes that the device verify mask will reject row-by-row — or,
        on BLS runs, a frame claiming the contributor's REAL coverage
        under a corrupted partial aggregate, which the receiver's
        merge-level sum check must catch before any verify launch."""
        st = self._slots.get(slot)
        if (self._bls_keyring is not None and st is not None
                and st.cov[node] and self._byz_rng.random() < 0.5):
            mask = st.cov[node]
            good = self._bls_masked_sum(st, mask, level, node)
            bad = (bytes([good[0] ^ 0x01]) + good[1:]) if good \
                else b"\xff" * 48
            frame = OverlayFrame(node, slot, level, mask, agg=bad)
            self.frames_sent += 1
            self.frames_garbage += 1
            self._count("overlay.frames")
            self._count("overlay.frames.garbage")
            self._enqueue(peer, frame)
            return
        self._garbage_ctr += 1
        cls = _VOTE_CLS[slot[0]]
        stale = None
        if self._retired and self._byz_rng.random() < self._faults.stale_rate:
            # Replay under a retired identity: exercises the shared
            # stale-generation classifier, not the verify mask.
            stale = min(self._retired)
        sender = stale if stale is not None else hashlib.sha256(
            b"hd-overlay-garbage" + self._garbage_ctr.to_bytes(8, "little")
        ).digest()
        value = hashlib.sha256(
            b"hd-overlay-garbage-value" + self._garbage_ctr.to_bytes(8, "little")
        ).digest()
        fake = cls(height=slot[1], round=slot[2], value=value,
                   sender=sender, signature=b"\x00" * 64)
        frame = OverlayFrame(node, slot, level, 0, extras=(fake,))
        self.frames_sent += 1
        self.frames_garbage += 1
        self._count("overlay.frames")
        self._count("overlay.frames.garbage")
        self._enqueue(peer, frame)

    def _reciprocate(self, to: int, src: int, st: _SlotState, slot,
                     frame: OverlayFrame) -> None:
        """Bidirectional exchange (Handel sessions are two-way): if the
        receiver holds coverage the sender's mask lacks, push it back —
        once per (receiver, sender, slot) — so a node whose own contact
        waves go dark is still fed by everyone who contacts *it*."""
        if src == to:
            return
        extra = st.cov[to] & ~frame.mask
        if not extra:
            return
        done = st.recip.setdefault(to, set())
        if src in done:
            return
        done.add(src)
        self._send_frame(to, src, st, slot, frame.level, reciprocal=True)

    def _fallback(self, node: int, st: _SlotState, slot) -> None:
        """Ranked direct gossip once every wave is spent: never-starve.
        Demoted peers rank last but stay reachable; the cursor walks the
        whole ring so repeated fallbacks cover different peers."""
        ranked = self.scores.ranked(exclude=node)
        if not ranked:
            return
        self.fallback_engaged += 1
        self._count("overlay.fallback")
        self._emit("overlay.fallback", node, slot, f"pos={st.fb_pos[node]}")
        pos = st.fb_pos[node]
        for _ in range(min(self.config.fallback_fanout, len(ranked))):
            peer = ranked[pos % len(ranked)]
            pos += 1
            self._send_frame(node, peer, st, slot, 0, fallback=True)
        st.fb_pos[node] = pos

    def _charge_withheld(self, node: int, st: _SlotState, slot, level: int,
                         wave: int) -> None:
        fo = self.config.fanout
        contacts = self.topo.contacts(node, level, (wave + 1) * fo)
        heard = st.heard.get(node, ())
        charged = st.charged.setdefault(node, set())
        for peer in contacts[wave * fo:(wave + 1) * fo]:
            if peer not in heard and peer not in charged:
                charged.add(peer)
                self._charge(peer, "withheld", slot, node)

    def _charge(self, peer: int, cls: str, slot, observer: int) -> None:
        self.scores.charge(peer, cls)
        self._last_charge_floor[peer] = self._floor
        self._count("overlay." + cls)
        kind = {
            "invalid": "overlay.invalid",
            "stale_generation": "overlay.stale",
            "duplicate": "overlay.duplicate",
            "withheld": "overlay.withhold",
        }[cls]
        self._emit(kind, observer, slot, f"peer={peer}")

    def _deliver_new(self, to: int, st: _SlotState, slot, new: int) -> None:
        """Materialize newly-covered votes from the global table and hand
        them to the replica, capped at quorum per (replica, value)."""
        dc = st.dcount.setdefault(to, {})
        out = []
        for idx in _bits(new):
            v = st.votes[idx]
            c = dc.get(v.value, 0)
            if c < self.quorum:
                dc[v.value] = c + 1
                out.append(v)
        if out:
            self.votes_delivered += len(out)
            self._count("overlay.votes.delivered", len(out))
            self._deliver(to, out)

    # ---------------------------------------------------------- verification

    def _bls_masked_sum(self, st: _SlotState, mask: int, level: int,
                        origin: int) -> bytes:
        """Compressed G1 sum of the table partials covered by ``mask``
        (bits without a partial — extras-path insertions — are excluded
        on both the sending and receiving side, so the subset is always
        identical). Device-batched through the queue when a launcher is
        installed; host fold otherwise."""
        pts = [st.bls[i] for i in _bits(mask) if i in st.bls]
        from hyperdrive_tpu.crypto import bls

        if not pts:
            return b""
        if self._bls_launcher is not None:
            fut = self._sched.submit(
                self._bls_launcher, pts,
                generation=level, origin=origin, rows=len(pts),
            )
            self._sched.drain()
            agg = fut.result()
        else:
            agg = bls.aggregate_signatures(pts)
        return bls.g1_compress(agg)

    def _verify_mask(self, st: _SlotState, pending: int, level: int,
                     origin: int) -> int:
        idxs = list(_bits(pending))
        rows = [
            (st.votes[i].sender, st.votes[i].digest(), st.votes[i].signature)
            for i in idxs
        ]
        self.verify_rows += len(rows)
        self._count("overlay.verify.rows", len(rows))
        fut = self._sched.submit(
            self._sched.verify_launcher(self._verifier), rows,
            generation=level, origin=origin, rows=len(rows),
        )
        self._sched.drain()
        mask = fut.result()
        ok = 0
        for pos, idx in enumerate(idxs):
            if mask[pos]:
                ok |= 1 << idx
        return ok

    def _verify_extra(self, vote, level: int, origin: int) -> bool:
        if self._verifier is None:
            return False  # unsigned runs cannot authenticate off-table votes
        self.verify_rows += 1
        self._count("overlay.verify.rows", 1)
        fut = self._sched.submit(
            self._sched.verify_launcher(self._verifier),
            [(vote.sender, vote.digest(), vote.signature)],
            generation=level, origin=origin, rows=1,
        )
        self._sched.drain()
        return bool(fut.result()[0])

    def verify_propose(self, propose) -> bool:
        """Shared-verifier propose check (replicas run verifier=None in
        overlay mode; one network-wide verification replaces n)."""
        if self._verifier is None:
            return True
        return bool(self._verify_extra(propose, 0, -1))

    # ------------------------------------------------------------- queries

    def honest_demoted(self) -> list:
        """Non-Byzantine peers currently demoted — the monitor's
        'no honest peer permanently demoted' invariant reads this."""
        return sorted(self.scores.demoted - set(self._byz))

    def snapshot(self) -> dict:
        return {
            "epoch": self.epoch,
            "topology": self.topo.digest().hex(),
            "levels": self.topo.levels,
            "window": self.window,
            "frames": self.frames_sent,
            "frames_reciprocal": self.frames_reciprocal,
            "frames_fallback": self.frames_fallback,
            "frames_garbage": self.frames_garbage,
            "frames_withheld": self.frames_withheld,
            "votes_delivered": self.votes_delivered,
            "verify_rows": self.verify_rows,
            "level_timeouts": self.level_timeouts,
            "fallback_engaged": self.fallback_engaged,
            "windows_exhausted": self.windows_exhausted,
            "rekeys": self.rekeys,
            "bls_partials": self._bls_keyring is not None,
            "bls_partials_attached": self.bls_partials_attached,
            "bls_partial_rejects": self.bls_partial_rejects,
            "live_slots": len(self._slots),
            "scores": self.scores.snapshot(),
            "honest_demoted": self.honest_demoted(),
            "byzantine": sorted(self._byz),
        }
