"""Seeded binomial aggregation-tree topology (Handel, arXiv:1906.05132).

The overlay's peer structure is a *pure function* of ``(seed, epoch
anchor digest, validator set)`` — no Python ``hash()``, no process
state, no wall clock — so every replica, every process, and a replay
reconstructing the run from a dump derive byte-identical trees
(property-tested across subprocesses). Keying off the epoch anchor
digest (:mod:`hyperdrive_tpu.epochs`) makes churn re-key tree positions
at every boundary for free: the anchor chains the committed boundary
value, so the epoch-e tree is unpredictable before epoch e-1 commits —
an adversary cannot pre-position around its future level assignment.

Shape (Handel / "verification-priority" style): nodes are permuted
into ranks by a seeded Fisher–Yates walk over a counter-mode SHA-256
stream; rank space is padded to ``N = 2**ceil(log2 n)``. At level
``l`` (1-based), a node's **partner half** is the sibling
``2**(l-1)``-rank block of its own within the enclosing ``2**l``
block: completing level ``l`` means holding every vote in that ``2**l``
block, after which the node's aggregate is worth sending one level up.
Contact order within a partner half is an independent seeded shuffle
per (rank, level) — Handel's VP ordering — so a withholding partner is
routed around by the next wave instead of stalling the level.
"""

from __future__ import annotations

import hashlib

__all__ = ["Topology"]

_DOMAIN = b"hd-overlay-v1"
_MASK64 = (1 << 64) - 1


class _HashStream:
    """Deterministic uniform ints from counter-mode SHA-256."""

    __slots__ = ("_key", "_ctr", "_buf", "_off")

    def __init__(self, key: bytes):
        self._key = key
        self._ctr = 0
        self._buf = b""
        self._off = 0

    def _u64(self) -> int:
        if self._off >= len(self._buf):
            self._buf = hashlib.sha256(
                self._key + self._ctr.to_bytes(8, "little")
            ).digest()
            self._ctr += 1
            self._off = 0
        v = int.from_bytes(self._buf[self._off : self._off + 8], "little")
        self._off += 8
        return v

    def below(self, bound: int) -> int:
        """Uniform draw in [0, bound) via rejection sampling (unbiased,
        unlike a bare modulo)."""
        if bound <= 1:
            return 0
        limit = ((1 << 64) // bound) * bound
        while True:
            v = self._u64()
            if v < limit:
                return v % bound


class _ContactShuffle:
    """Lazily-extended seeded shuffle of one partner half.

    A node only ever walks the first ``waves * fanout`` contacts of a
    level, so the full Fisher–Yates permutation of a 2048-rank half is
    never materialized beyond the prefix actually consumed. Extending
    the prefix never re-draws: contact k is fixed the moment it is
    first read, which is what lets withhold charges name exactly the
    peers a wave contacted."""

    __slots__ = ("_pool", "_stream", "_done")

    def __init__(self, pool: list, key: bytes):
        self._pool = pool
        self._stream = _HashStream(key)
        self._done = 0

    def prefix(self, k: int) -> list:
        pool = self._pool
        k = min(k, len(pool))
        while self._done < k:
            i = self._done
            j = i + self._stream.below(len(pool) - i)
            pool[i], pool[j] = pool[j], pool[i]
            self._done += 1
        return pool[:k]

    def __len__(self) -> int:
        return len(self._pool)


class Topology:
    """One epoch's aggregation tree over ``n`` validator slots.

    ``rank[i]`` is slot i's position in the padded rank space;
    ``order[r]`` inverts it (None for padding ranks). Everything else
    is derived lazily and cached — block masks and contact shuffles
    are touched only for the (node, level) pairs a run actually
    exercises.
    """

    def __init__(self, seed: int, anchor: bytes, identities):
        ids = list(identities)
        n = len(ids)
        if n < 1:
            raise ValueError("topology needs at least one validator")
        self.n = n
        self.seed = int(seed)
        self.anchor = bytes(anchor)
        h = hashlib.sha256()
        for ident in ids:
            h.update(len(ident).to_bytes(2, "little"))
            h.update(ident)
        self.set_digest = h.digest()
        self._root = hashlib.sha256(
            _DOMAIN
            + (self.seed & _MASK64).to_bytes(8, "little")
            + self.anchor
            + self.set_digest
        ).digest()
        #: Padded rank-space size and level count: level l spans
        #: 2**l-rank blocks, so the top level is log2(N).
        self.size = 1 << (n - 1).bit_length() if n > 1 else 1
        self.levels = self.size.bit_length() - 1
        # Seeded Fisher–Yates over the REAL slots; padding ranks (>= n
        # after permutation of rank space) stay empty. Permute rank
        # assignments: slot -> rank over the full padded space so the
        # empty ranks move too (a fixed empty suffix would make the top
        # block systematically sparse).
        stream = _HashStream(self._root + b"perm")
        ranks = list(range(self.size))
        for i in range(self.size - 1, 0, -1):
            j = stream.below(i + 1)
            ranks[i], ranks[j] = ranks[j], ranks[i]
        #: slot i -> rank.
        self.rank = ranks[:n]
        #: rank -> slot (None = padding).
        self.order: list = [None] * self.size
        for slot, r in enumerate(self.rank):
            self.order[r] = slot
        self._contacts: dict = {}
        self._block_masks: dict = {}

    # ------------------------------------------------------------ identity

    def digest(self) -> bytes:
        """Commitment to the whole tree: the rank permutation under the
        derivation root. Two topologies agree iff their digests do —
        the cross-process purity property test compares exactly this."""
        h = hashlib.sha256(self._root)
        for r in self.rank:
            h.update(r.to_bytes(4, "little"))
        return h.digest()

    # ------------------------------------------------------------- queries

    def partner_half(self, slot: int, level: int) -> list:
        """The slots in ``slot``'s sibling half at ``level`` (the ranks
        it must obtain to complete the level), unshuffled, rank order."""
        r = self.rank[slot]
        low = level - 1
        base = ((r >> level) << level) | ((1 - ((r >> low) & 1)) << low)
        out = []
        for p in range(base, base + (1 << low)):
            s = self.order[p]
            if s is not None:
                out.append(s)
        return out

    def contacts(self, slot: int, level: int, k: int) -> list:
        """First ``k`` contacts of ``slot``'s level-``level`` partner
        half, in the node's seeded VP order. Stable under extension."""
        key = (slot, level)
        sh = self._contacts.get(key)
        if sh is None:
            sh = _ContactShuffle(
                self.partner_half(slot, level),
                self._root
                + b"order"
                + self.rank[slot].to_bytes(4, "little")
                + level.to_bytes(2, "little"),
            )
            self._contacts[key] = sh
        return sh.prefix(k)

    def block_mask(self, slot: int, level: int) -> int:
        """Bitmask (over slots) of the full ``2**level`` rank block
        containing ``slot`` — coverage ⊇ mask means the level is
        complete and the aggregate is ready for level + 1."""
        r = self.rank[slot] >> level
        key = (level, r)
        m = self._block_masks.get(key)
        if m is None:
            m = 0
            base = r << level
            for p in range(base, base + (1 << level)):
                s = self.order[p]
                if s is not None:
                    m |= 1 << s
            self._block_masks[key] = m
        return m

    def level_groups(self, level: int) -> list:
        """Partition of slots into ``2**level``-rank blocks — the
        natural grain for partitions that slice the tree along level
        boundaries (:meth:`FaultPlan.overlay` draws its groups here)."""
        groups: list = []
        for base in range(0, self.size, 1 << level):
            g = [
                self.order[p]
                for p in range(base, base + (1 << level))
                if self.order[p] is not None
            ]
            if g:
                groups.append(tuple(g))
        return groups
