"""Byzantine-resilient aggregation overlay (ISSUE 12 tentpole).

A dissemination layer between :mod:`hyperdrive_tpu.replica` and the
harness: votes travel a seeded binomial aggregation tree as partial-
aggregate frames instead of all-to-all fan-out, with contribution
scoring for Byzantine robustness. See ``ROBUSTNESS.md`` ("Aggregation
doctrine") for the operational invariants and ``runtime.py`` for the
determinism contract.

Public surface:

- :class:`OverlayConfig` — ``Simulation(overlay=OverlayConfig(...))``.
- :class:`OverlayFaults` — Byzantine-contributor chaos knobs, composed
  by ``FaultPlan.overlay``.
- :class:`Topology` — the pure (seed, anchor, validator set) → tree
  function; property-tested for cross-process identity.
- :class:`ContributionScores` — the integer scoring/demotion table.
- :class:`OverlayRuntime` / :class:`OverlayFrame` / :class:`OverlayTick`
  — harness-facing internals (the sim's delivery loop intercepts frame
  and tick objects by type).
"""

from hyperdrive_tpu.overlay.runtime import (
    OverlayConfig,
    OverlayFaults,
    OverlayFrame,
    OverlayRuntime,
    OverlayTick,
)
from hyperdrive_tpu.overlay.score import CHARGE_WEIGHTS, ContributionScores
from hyperdrive_tpu.overlay.topology import Topology

__all__ = [
    "OverlayConfig",
    "OverlayFaults",
    "OverlayFrame",
    "OverlayRuntime",
    "OverlayTick",
    "Topology",
    "ContributionScores",
    "CHARGE_WEIGHTS",
]
