"""Quorum certificates: codec roundtrips, forgery rejection, O(1) size,
and the consensus seams that mint and re-verify them.

The certificate replaces re-gossiping 2f+1 signatures with a constant-
size record; everything here checks the two properties that make that
sound: the binding commits to every field (any tamper rejects), and the
emission seams (Process L49, the settle path, the sim) agree on the
chain they minted.
"""

import hashlib

import pytest

from hyperdrive_tpu.certificates import (
    Certifier,
    QuorumCertificate,
    certificate_size,
    marshal_certificate,
    unmarshal_certificate,
)
from hyperdrive_tpu.codec import Reader, SerdeError, Writer
from hyperdrive_tpu.harness.sim import Simulation


def _mk_certifier(n=7, f=2, transcript=b"\x5a" * 32):
    return Certifier(
        [bytes([i]) * 32 for i in range(n)],
        f,
        transcript_source=(lambda: transcript) if transcript else None,
    )


# ------------------------------------------------------------------ codec


def test_roundtrip_property(rng):
    for _ in range(64):
        n = rng.randint(1, 1024)
        cert = QuorumCertificate(
            height=rng.randint(0, 2**63 - 1),
            round=rng.randint(0, 2**31 - 1),
            value_digest=rng.randbytes(32),
            signers=rng.randbytes(-(-n // 8)),
            transcript=rng.randbytes(32),
            binding=rng.randbytes(32),
        )
        w = Writer()
        marshal_certificate(cert, w)
        r = Reader(w.data())
        assert unmarshal_certificate(r) == cert
        assert r.done()


def test_truncated_and_oversize_blobs_reject(rng):
    cert = _mk_certifier().observe_commit(3, 1, b"value", [])
    w = Writer()
    marshal_certificate(cert, w)
    blob = w.data()
    for cut in (0, 1, len(blob) // 2, len(blob) - 1):
        with pytest.raises(SerdeError):
            unmarshal_certificate(Reader(blob[:cut]))
    # A bitmap length claiming more than any validator set we size for.
    w2 = Writer()
    w2.u64(1)
    w2.u32(0)
    w2.bytes32(bytes(32))
    w2.raw(bytes(8192))
    w2.bytes32(bytes(32))
    w2.bytes32(bytes(32))
    with pytest.raises(SerdeError):
        unmarshal_certificate(Reader(w2.data()))


def test_size_is_constant_in_validator_count():
    # The acceptance criterion: bytes at n=256/512/1024 move only by the
    # bitmap (n/8), i.e. 1/512th the slope of the 64n-byte signature set
    # the certificate replaces.
    s256, s512, s1024 = (certificate_size(n) for n in (256, 512, 1024))
    assert s512 - s256 == 256 // 8
    assert s1024 - s512 == 512 // 8
    assert s1024 < 256  # vs ~44 KB of 2f+1 signatures at n=1024


# -------------------------------------------------------------- emit/verify


def test_emit_then_verify_accepts():
    c = _mk_certifier()
    sigs = c.signatories
    cert = c.observe_commit(9, 2, b"block-nine", sigs[:5])
    assert cert.signer_count() == 5
    assert cert.value_digest == hashlib.sha256(b"block-nine").digest()
    assert cert.transcript == b"\x5a" * 32
    assert c.verify(cert)
    assert c.certificate_for(9) is cert
    assert c.verified == 1 and c.rejected == 0


def test_unknown_signers_do_not_count():
    c = _mk_certifier()
    cert = c.observe_commit(
        1, 0, b"v", [b"\xee" * 32, c.signatories[0]]
    )
    assert cert.signer_count() == 1


def test_forged_certificates_reject():
    c = _mk_certifier()
    sigs = c.signatories
    cert = c.observe_commit(4, 0, b"honest", sigs[:5])

    def forged(**kw):
        fields = dict(
            height=cert.height,
            round=cert.round,
            value_digest=cert.value_digest,
            signers=cert.signers,
            transcript=cert.transcript,
            binding=cert.binding,
        )
        fields.update(kw)
        return QuorumCertificate(**fields)

    assert c.verify(cert)
    # Tampering with ANY bound field breaks the binding.
    assert not c.verify(forged(height=cert.height + 1))
    assert not c.verify(forged(round=cert.round + 1))
    assert not c.verify(forged(value_digest=b"\x01" * 32))
    assert not c.verify(forged(transcript=b"\x02" * 32))
    assert not c.verify(forged(signers=bytes([0xFF])))
    # A re-bound forgery with too few signers fails the quorum check.
    thin = c.observe_commit(5, 0, b"thin", sigs[:4])
    assert not c.verify(thin)
    # Wrong bitmap width (different validator set size) rejects.
    other = Certifier([bytes([i]) * 32 for i in range(20)], 2)
    wide = other.observe_commit(4, 0, b"honest", other.signatories[:7])
    assert not c.verify(wide)


def test_sub_32_byte_transcript_is_hashed_to_width():
    c = _mk_certifier(transcript=None)
    c.transcript_source = lambda: b"short"
    cert = c.observe_commit(1, 0, b"v", c.signatories[:5])
    assert cert.transcript == hashlib.sha256(b"short").digest()
    c.transcript_source = lambda: b""
    cert2 = c.observe_commit(2, 0, b"v", c.signatories[:5])
    assert cert2.transcript == bytes(32)


def test_chain_digest_orders_by_height_and_resets():
    a = _mk_certifier()
    b = _mk_certifier()
    sigs = a.signatories
    a.observe_commit(1, 0, b"one", sigs[:5])
    a.observe_commit(2, 0, b"two", sigs[:5])
    b.observe_commit(2, 0, b"two", sigs[:5])
    b.observe_commit(1, 0, b"one", sigs[:5])
    assert a.chain_digest() == b.chain_digest()
    b.observe_commit(3, 0, b"three", sigs[:5])
    assert a.chain_digest() != b.chain_digest()
    b.reset()
    assert not b.certs


# ----------------------------------------------------------- consensus seams


def test_sim_certificates_match_commits_across_replicas():
    sim = Simulation(n=4, target_height=6, certificates=True)
    result = sim.run()
    assert result.completed
    # Every replica minted the same certificate chain.
    assert result.cert_digests is not None
    assert len(set(result.cert_digests)) == 1
    # Each certificate's value digest is the committed value's digest,
    # its quorum weight clears 2f+1, and it re-verifies in O(1).
    for i, certifier in enumerate(sim.certifiers):
        for h, cert in certifier.certs.items():
            want = hashlib.sha256(result.commits[i][h]).digest()
            assert cert.value_digest == want
            assert cert.signer_count() >= 2 * sim.f + 1
            assert certifier.verify(cert)


def test_sim_certificate_chain_is_deterministic():
    kw = dict(n=4, target_height=5, seed=11, certificates=True)
    assert (
        Simulation(**kw).run().cert_digests
        == Simulation(**kw).run().cert_digests
    )


def test_pipelined_certificates_equal_sequential():
    # The devsched acceptance cross-check: gated/speculative commits must
    # mint the same certificate chain the blocking schedule mints.
    kw = dict(
        n=4, target_height=6, seed=7, sign=True, burst=True,
        certificates=True,
    )
    seq = Simulation(**kw).run()
    pipe = Simulation(pipeline_heights=True, **kw).run()
    assert seq.completed and pipe.completed
    assert seq.commit_digest() == pipe.commit_digest()
    assert seq.cert_digests == pipe.cert_digests


def test_tallyflush_binds_verifier_transcript_and_reverifies():
    from hyperdrive_tpu.tallyflush import DeviceTallyFlusher
    from hyperdrive_tpu.verifier import NullVerifier

    validators = [bytes([i]) * 32 for i in range(4)]
    certifier = Certifier(validators, f=1)
    flusher = DeviceTallyFlusher(
        NullVerifier(), validators, certifier=certifier
    )
    # The flusher bound its verifier as the transcript source.
    assert certifier.transcript_source is not None
    assert certifier.transcript_source() == b""
    # And reset() clears the chain with the other volatile state.
    certifier.observe_commit(1, 0, b"v", validators[:3])
    flusher.reset()
    assert not certifier.certs


def test_multihost_accept_certificate_registry():
    from hyperdrive_tpu.parallel.multihost import ShardVerifyService
    from hyperdrive_tpu.verifier import NullVerifier

    svc = ShardVerifyService(NullVerifier())
    validators = [bytes([i]) * 32 for i in range(7)]
    certifier = svc.certifier(validators, f=2)
    cert = certifier.observe_commit(3, 0, b"shard-val", validators[:5])
    assert svc.accept_certificate("tenant-a", certifier, cert)
    assert svc.certificates["tenant-a"][3] is cert
    bad = QuorumCertificate(
        cert.height, cert.round, b"\x09" * 32, cert.signers,
        cert.transcript, cert.binding,
    )
    assert not svc.accept_certificate("tenant-a", certifier, bad)
    assert 3 in svc.certificates["tenant-a"]


def test_cert_obs_events_emitted():
    from hyperdrive_tpu.obs.recorder import EVENT_KINDS, Recorder

    rec = Recorder(capacity=256)
    c = Certifier(
        [bytes([i]) * 32 for i in range(4)], 1, obs=rec.scoped(0)
    )
    cert = c.observe_commit(2, 1, b"v", c.signatories[:3])
    c.verify(cert)
    c.verify(
        QuorumCertificate(
            cert.height, cert.round, cert.value_digest, cert.signers,
            b"\x01" * 32, cert.binding,
        )
    )
    kinds = [e.kind for e in rec.snapshot()]
    assert kinds.count("cert.emit") == 1
    assert kinds.count("cert.verify") == 2
    assert {"cert.emit", "cert.verify"} <= EVENT_KINDS
    outcomes = [
        e.detail for e in rec.snapshot() if e.kind == "cert.verify"
    ]
    assert outcomes == ["ok", "reject"]


# -------------------------------------------------------------------- BLS


@pytest.fixture(scope="module")
def bls_ids():
    return [bytes([i]) * 32 for i in range(7)]


@pytest.fixture(scope="module")
def bls_keyring(bls_ids):
    from hyperdrive_tpu.crypto import bls

    return {s: bls.bls_keypair_from_identity(s) for s in bls_ids}


def _bls_certifier(bls_ids, bls_keyring, **kw):
    return Certifier(
        bls_ids, 2, transcript_source=lambda: b"\x5a" * 32,
        bls_keyring=bls_keyring, **kw,
    )


def test_bls_certificate_mints_aggregate_and_verifies(bls_ids, bls_keyring):
    from hyperdrive_tpu.certificates import verify_bls_certificate

    c = _bls_certifier(bls_ids, bls_keyring)
    cert = c.observe_commit(3, 1, b"block-three", bls_ids[:5])
    assert len(cert.agg_sig) == 48
    assert c.verify(cert)
    # The light client holds only the committee pubkeys — no transcript,
    # no verifier state, no trust in the minting replica.
    assert verify_bls_certificate(cert, c.bls_pubkeys(), quorum=5)


def test_bls_certificate_tamper_rejects(bls_ids, bls_keyring):
    from hyperdrive_tpu.certificates import verify_bls_certificate

    c = _bls_certifier(bls_ids, bls_keyring)
    cert = c.observe_commit(3, 1, b"block-three", bls_ids[:5])
    pks = c.bls_pubkeys()
    flipped = QuorumCertificate(
        cert.height, cert.round,
        bytes([cert.value_digest[0] ^ 1]) + cert.value_digest[1:],
        cert.signers, cert.transcript, cert.binding, cert.agg_sig,
    )
    assert not verify_bls_certificate(flipped, pks)
    # An extra bitmap bit claims a signer whose partial is not in the
    # aggregate: pairing mismatch.
    bm = bytearray(cert.signers)
    bm[0] ^= 0b0100000
    extra = QuorumCertificate(
        cert.height, cert.round, cert.value_digest, bytes(bm),
        cert.transcript, cert.binding, cert.agg_sig,
    )
    assert not verify_bls_certificate(extra, pks)
    # Quorum gate: the same certificate under a stricter threshold.
    assert not verify_bls_certificate(cert, pks, quorum=6)


def test_bls_certificate_wire_roundtrip_and_size(bls_ids, bls_keyring):
    c = _bls_certifier(bls_ids, bls_keyring)
    cert = c.observe_commit(3, 1, b"block-three", bls_ids[:5])
    w = Writer()
    marshal_certificate(cert, w)
    assert unmarshal_certificate(Reader(w.data())) == cert
    # 48 bytes of signature material on top of the plain certificate,
    # at every committee width.
    for n in (256, 1024, 4096):
        assert (certificate_size(n, with_bls=True)
                == certificate_size(n) + 48)


def test_bls_certificate_bad_agg_sig_length_rejects(bls_ids, bls_keyring):
    c = _bls_certifier(bls_ids, bls_keyring)
    cert = c.observe_commit(3, 1, b"block-three", bls_ids[:5])
    w = Writer()
    marshal_certificate(
        QuorumCertificate(
            cert.height, cert.round, cert.value_digest, cert.signers,
            cert.transcript, cert.binding, cert.agg_sig + b"\x00",
        ),
        w,
    )
    with pytest.raises(SerdeError):
        unmarshal_certificate(Reader(w.data()))


def test_bls_binding_is_v1_compatible_without_keyring(bls_ids):
    # No keyring -> empty agg_sig and the EXACT v1 binding preimage, so
    # pre-BLS verifiers and stored certificates stay byte-compatible.
    plain = Certifier(bls_ids, 2, transcript_source=lambda: b"\x5a" * 32)
    cert = plain.observe_commit(3, 1, b"block-three", bls_ids[:5])
    assert cert.agg_sig == b""
    assert plain.verify(cert)
    w = Writer()
    marshal_certificate(cert, w)
    assert unmarshal_certificate(Reader(w.data())) == cert


def test_bls_device_aggregation_matches_host(bls_ids, bls_keyring):
    from hyperdrive_tpu.certificates import verify_bls_certificate
    from hyperdrive_tpu.ops import g1 as g1k

    host = _bls_certifier(bls_ids, bls_keyring)
    dev = _bls_certifier(
        bls_ids, bls_keyring,
        bls_aggregate_fn=lambda pts: g1k.aggregate_points(pts, width=8),
    )
    hcert = host.observe_commit(3, 1, b"block-three", bls_ids[:5])
    dcert = dev.observe_commit(3, 1, b"block-three", bls_ids[:5])
    assert dcert == hcert  # byte-identical, aggregation route invisible
    assert verify_bls_certificate(dcert, dev.bls_pubkeys(), quorum=5)


def test_bls_rotate_rederives_churned_keys(bls_ids, bls_keyring):
    from hyperdrive_tpu.certificates import verify_bls_certificate

    c = _bls_certifier(bls_ids, bls_keyring)
    new_ids = bls_ids[2:] + [bytes([99]) * 32]
    c.rotate(new_ids, f=2)
    cert = c.observe_commit(4, 0, b"block-four", new_ids[:5])
    assert len(cert.agg_sig) == 48
    assert verify_bls_certificate(cert, c.bls_pubkeys(), quorum=5)


def test_bls_cert_obs_event_emitted(bls_ids, bls_keyring):
    from hyperdrive_tpu.obs.recorder import EVENT_KINDS, Recorder

    rec = Recorder(capacity=64)
    c = Certifier(
        bls_ids, 2, transcript_source=lambda: b"\x5a" * 32,
        bls_keyring=bls_keyring, obs=rec.scoped(0),
    )
    c.observe_commit(3, 1, b"block-three", bls_ids[:5])
    kinds = [e.kind for e in rec.snapshot()]
    assert kinds.count("bls.cert.agg") == 1
    assert "bls.cert.agg" in EVENT_KINDS


def test_sim_bls_certificates_digest_neutral():
    base = Simulation(n=4, target_height=3, seed=5, timeout=1.0)
    bres = base.run(max_steps=100_000)
    sim = Simulation(
        n=4, target_height=3, seed=5, timeout=1.0, bls_certificates=True
    )
    sres = sim.run(max_steps=100_000)
    assert sres.commit_digest() == bres.commit_digest()
    assert all(
        len(cert.agg_sig) == 48
        for c in sim.certifiers for cert in c.certs.values()
    )
    assert any(c.certs for c in sim.certifiers)
