"""Backend resolution: one rule, shared by the verifier and the mesh."""

import numpy as np
import pytest

import jax

from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier
from hyperdrive_tpu.ops.ed25519_pallas import pallas_backend_ok, resolve_backend


def test_resolve_passthrough_and_validation():
    assert resolve_backend("pallas") == "pallas"
    assert resolve_backend("xla") == "xla"
    with pytest.raises(ValueError):
        resolve_backend("cuda")


def test_auto_on_cpu_devices_is_xla():
    # conftest pins the suite to the CPU backend: both sentinels resolve
    # to the XLA kernel, for the process default and for explicit devices.
    for sentinel in (None, "auto"):
        assert resolve_backend(sentinel) == "xla"
    assert not pallas_backend_ok(np.array(jax.devices()))
    assert resolve_backend(None, devices=np.array(jax.devices())) == "xla"


def test_verifier_reports_backend():
    v = TpuBatchVerifier(buckets=(64,))
    assert v.backend == "xla"  # CPU test environment
    v2 = TpuBatchVerifier(buckets=(64,), backend="xla")
    assert v2.backend == "xla"
    with pytest.raises(ValueError):
        TpuBatchVerifier(buckets=(64,), backend="bogus")
