"""Backend resolution: one rule, shared by the verifier and the mesh."""

import numpy as np
import pytest

import jax

from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier
from hyperdrive_tpu.ops.ed25519_pallas import pallas_backend_ok, resolve_backend


class _FakeLeg:
    """Backend stub whose nth call costs delays[n] (then the last delay
    forever) — a deterministic latency script for calibration tests."""

    def __init__(self, delays):
        import time as _t

        self._t = _t
        self.delays = list(delays)
        self.calls = 0

    def verify_signatures(self, items):
        d = self.delays[min(self.calls, len(self.delays) - 1)]
        self.calls += 1
        self._t.sleep(d)
        return [True] * len(items)


def test_adaptive_calibration_median_survives_one_outlier():
    # A single jittered sample must not flip routing: the device's first
    # TIMED full-window rep is a 60x outlier, but the median-of-3 ignores
    # it, so the computed crossover matches a clean run's to within the
    # margin the two remaining clean samples allow.
    from hyperdrive_tpu.verifier import AdaptiveVerifier

    items = [(bytes(32), bytes(32), bytes(64))] * 64

    def run(outlier: float):
        # Device call order: warm full, warm tiny, timed full x3,
        # timed tiny x3. The outlier lands on the first timed full rep.
        dev = _FakeLeg(
            [0.0, 0.0, outlier, 0.004, 0.004, 0.002, 0.002, 0.002]
        )
        host = _FakeLeg([0.008])
        av = AdaptiveVerifier(device=dev, host=host, calibrate_at=64)
        av.verify_signatures(items)
        assert av.calibrated
        return av.crossover

    clean = run(0.004)
    jittered = run(0.24)
    assert jittered == pytest.approx(clean, rel=0.5)
    # Sanity: a crossover from the outlier sample would be wildly larger
    # (device "slower" than host at every size -> effectively infinite).
    assert jittered < 10_000


def test_adaptive_recalibrate_remeasures():
    from hyperdrive_tpu.verifier import AdaptiveVerifier

    items = [(bytes(32), bytes(32), bytes(64))] * 64
    dev = _FakeLeg([0.0])
    host = _FakeLeg([0.002])
    av = AdaptiveVerifier(device=dev, host=host, calibrate_at=64)
    av.verify_signatures(items)
    assert av.calibrated
    first_calls = dev.calls
    av.verify_signatures(items)  # routed, no re-measurement burst
    assert dev.calls <= first_calls + 1
    av.recalibrate()
    assert not av.calibrated
    av.verify_signatures(items)
    assert av.calibrated
    assert dev.calls > first_calls + 1


def test_resolve_passthrough_and_validation():
    assert resolve_backend("pallas") == "pallas"
    assert resolve_backend("xla") == "xla"
    with pytest.raises(ValueError):
        resolve_backend("cuda")


def test_auto_on_cpu_devices_is_xla():
    # conftest pins the suite to the CPU backend: both sentinels resolve
    # to the XLA kernel, for the process default and for explicit devices.
    for sentinel in (None, "auto"):
        assert resolve_backend(sentinel) == "xla"
    assert not pallas_backend_ok(np.array(jax.devices()))
    assert resolve_backend(None, devices=np.array(jax.devices())) == "xla"


def test_verifier_reports_backend():
    v = TpuBatchVerifier(buckets=(64,))
    assert v.backend == "xla"  # CPU test environment
    v2 = TpuBatchVerifier(buckets=(64,), backend="xla")
    assert v2.backend == "xla"
    with pytest.raises(ValueError):
        TpuBatchVerifier(buckets=(64,), backend="bogus")
