"""GF(2^255-19) limb arithmetic: differential tests against Python ints.

Every operation must agree bit-for-bit with bignum arithmetic mod p, and
every public result must satisfy the normalization invariant (limbs in
[0, 2^13], value < 2^256).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperdrive_tpu.ops import fe25519 as fe

P = fe.P_INT

# Jitted wrappers: eager-mode dispatch of 60-op limb pipelines is ~100x
# slower than compiled execution; tests go through these.
jadd = jax.jit(fe.add)
jsub = jax.jit(fe.sub)
jmul = jax.jit(fe.mul)
jinv = jax.jit(fe.inv)
jcanon = jax.jit(fe.canonical)
jmul_small = jax.jit(fe.mul_small, static_argnums=1)

EDGE_VALUES = [
    0,
    1,
    2,
    19,
    P - 1,
    P,
    P + 1,
    2 * P - 1,
    (1 << 255) - 1,
    (1 << 256) - 1,
    (1 << 255) + 12345,
    0x0123456789ABCDEF_0123456789ABCDEF_0123456789ABCDEF_0123456789ABCDEF,
]


def rand_vals(rng, k):
    return [rng.getrandbits(256) for _ in range(k)]


def check_invariant(arr):
    a = np.asarray(arr)
    assert a.dtype == np.int32
    assert (a >= 0).all()
    # Normalized limbs carry fold slack bounded by SLACK_MAX (see the
    # module doc); 20 * SLACK_MAX^2 still fits int32, so this is the real
    # invariant.
    assert (a <= fe.SLACK_MAX).all()
    if a.ndim == 1:
        assert fe.from_limbs(a) < 1 << 256
    else:
        flat = a.reshape(-1, fe.N_LIMBS)
        for row in flat:
            assert fe.from_limbs(row) < 1 << 256


def test_to_from_roundtrip(rng):
    for v in EDGE_VALUES + rand_vals(rng, 50):
        v %= 1 << 260
        assert fe.from_limbs(fe.to_limbs(v)) == v


def test_add_matches_bignum(rng):
    vals = EDGE_VALUES + rand_vals(rng, 30)
    a = jnp.asarray(fe.to_limbs([x % (1 << 256) for x in vals]))
    b = jnp.asarray(fe.to_limbs([(x * 7 + 13) % (1 << 256) for x in vals]))
    out = jadd(a, b)
    check_invariant(out)
    for i, x in enumerate(vals):
        got = fe.from_limbs(np.asarray(out)[i]) % P
        want = ((x % (1 << 256)) + ((x * 7 + 13) % (1 << 256))) % P
        assert got == want


def test_sub_matches_bignum(rng):
    vals = EDGE_VALUES + rand_vals(rng, 30)
    other = [(x * 31 + 5) % (1 << 256) for x in vals]
    a = jnp.asarray(fe.to_limbs([x % (1 << 256) for x in vals]))
    b = jnp.asarray(fe.to_limbs(other))
    out = jsub(a, b)
    check_invariant(out)
    for i, x in enumerate(vals):
        got = fe.from_limbs(np.asarray(out)[i]) % P
        want = ((x % (1 << 256)) - other[i]) % P
        assert got == want


def test_mul_matches_bignum(rng):
    vals = EDGE_VALUES + rand_vals(rng, 30)
    other = [(x * 131 + 7) % (1 << 256) for x in vals]
    a = jnp.asarray(fe.to_limbs([x % (1 << 256) for x in vals]))
    b = jnp.asarray(fe.to_limbs(other))
    out = jmul(a, b)
    check_invariant(out)
    for i, x in enumerate(vals):
        got = fe.from_limbs(np.asarray(out)[i]) % P
        want = ((x % (1 << 256)) * other[i]) % P
        assert got == want
    # Worst-case column accumulation: both operands with every limb at the
    # invariant maximum (the binding case for _reduce_cols's bound walk).
    worst = jnp.broadcast_to(
        jnp.full((fe.N_LIMBS,), fe.SLACK_MAX, dtype=jnp.int32),
        (4, fe.N_LIMBS),
    )
    wv = fe.from_limbs(np.asarray(worst)[0])
    wout = jmul(worst, worst)
    check_invariant(wout)
    assert fe.from_limbs(np.asarray(wout)[0]) % P == (wv * wv) % P


def test_sqr_matches_bignum(rng):
    jsqr = jax.jit(fe.sqr)
    vals = [v % (1 << 256) for v in EDGE_VALUES + rand_vals(rng, 30)]
    a = jnp.asarray(fe.to_limbs(vals))
    out = jsqr(a)
    check_invariant(out)
    for i, x in enumerate(vals):
        assert fe.from_limbs(np.asarray(out)[i]) % P == (x * x) % P
    # Worst-case column accumulation: all limbs at the invariant maximum.
    worst = jnp.broadcast_to(
        jnp.full((fe.N_LIMBS,), fe.SLACK_MAX, dtype=jnp.int32),
        (4, fe.N_LIMBS),
    )
    wv = fe.from_limbs(np.asarray(worst)[0])
    got = fe.from_limbs(np.asarray(jsqr(worst))[0]) % P
    assert got == (wv * wv) % P


def test_mul_small_matches_bignum(rng):
    vals = [v % (1 << 256) for v in EDGE_VALUES + rand_vals(rng, 10)]
    a = jnp.asarray(fe.to_limbs(vals))
    for k in (0, 1, 2, 19, 608, 121665, (1 << 17) - 1):
        out = jmul_small(a, k)
        check_invariant(out)
        for i, x in enumerate(vals):
            assert fe.from_limbs(np.asarray(out)[i]) % P == (x * k) % P


def test_repeated_mul_stays_stable(rng):
    # Invariant preservation over long chains (the scalar-mult workload).
    x = rng.getrandbits(255) % P
    a = jnp.asarray(fe.to_limbs(x))
    acc_int = x
    for _ in range(100):
        a = jmul(a, a)
        acc_int = (acc_int * acc_int) % P
        check_invariant(a)
    assert fe.from_limbs(np.asarray(jcanon(a))) == acc_int


def test_inv_matches_fermat(rng):
    vals = [v % P for v in rand_vals(rng, 5) + [1, 2, P - 1]]
    a = jnp.asarray(fe.to_limbs(vals))
    out = jinv(a)
    check_invariant(out)
    for i, x in enumerate(vals):
        assert fe.from_limbs(np.asarray(out)[i]) % P == pow(x, P - 2, P)


def test_canonical_full_reduction(rng):
    vals = [v % (1 << 256) for v in EDGE_VALUES + rand_vals(rng, 30)]
    a = jnp.asarray(fe.to_limbs(vals))
    out = jcanon(a)
    arr = np.asarray(out)
    for i, x in enumerate(vals):
        got = fe.from_limbs(arr[i])
        assert got == x % P
        assert got < P


def test_eq_across_representations(rng):
    x = rng.getrandbits(250)
    a = jnp.asarray(fe.to_limbs(x))
    b = jnp.asarray(fe.to_limbs(x + P))  # same element, different rep
    c = jnp.asarray(fe.to_limbs((x + 1) % P))
    assert bool(fe.eq(a, b))
    assert not bool(fe.eq(a, c))
    assert bool(fe.is_zero(jnp.asarray(fe.to_limbs(P))))
    assert not bool(fe.is_zero(jnp.asarray(fe.to_limbs(1))))


def test_ops_are_jit_and_vmap_transparent(rng):
    vals = [v % (1 << 255) for v in rand_vals(rng, 8)]
    a = jnp.asarray(fe.to_limbs(vals))
    b = jnp.asarray(fe.to_limbs(list(reversed(vals))))

    jit_mul = jax.jit(fe.mul)
    np.testing.assert_array_equal(np.asarray(jit_mul(a, b)), np.asarray(fe.mul(a, b)))

    vmul = jax.vmap(fe.mul)
    np.testing.assert_array_equal(np.asarray(vmul(a, b)), np.asarray(fe.mul(a, b)))


def test_batch_shapes(rng):
    vals = [[rng.getrandbits(255) for _ in range(3)] for _ in range(2)]
    a = jnp.asarray(fe.to_limbs(vals))  # [2, 3, 20]
    out = jmul(a, a)
    assert out.shape == (2, 3, fe.N_LIMBS)
    for i in range(2):
        for j in range(3):
            assert (
                fe.from_limbs(np.asarray(out)[i, j]) % P
                == (vals[i][j] * vals[i][j]) % P
            )
