"""Replica structured-log contract (utils/log.py): the driver actually
logs what SURVEY.md §5 faulted the reference for never logging — commits,
height resyncs, byzantine evidence — as grep-able key=value lines on the
``hyperdrive_tpu`` logger, with configuration left to the embedding app.
"""

import hashlib
import logging

from hyperdrive_tpu.messages import Prevote
from hyperdrive_tpu.replica import ResetHeight

from test_replica import build_network


def _messages(caplog, needle, level=None):
    return [
        r.getMessage()
        for r in caplog.records
        if needle in r.getMessage()
        and (level is None or r.levelno == level)
    ]


def test_commit_logged_with_height_round_value_kv(caplog):
    caplog.set_level(logging.INFO, logger="hyperdrive_tpu")
    _, replicas, commits = build_network(4)
    for r in replicas:
        r.start()
    assert commits[0], "sanity: loopback network committed"
    lines = _messages(caplog, "commit ", logging.INFO)
    assert lines, "committer instrumentation logged nothing"
    # kv() renders height=/round=/value= with the value hex-abbreviated.
    line = lines[0]
    assert "height=" in line and "round=" in line and "value=" in line
    assert not any(len(tok.split("=", 1)[1]) > 16
                   for tok in line.split() if tok.startswith("value="))


def test_height_resync_logged_with_from_to_kv(caplog):
    caplog.set_level(logging.INFO, logger="hyperdrive_tpu")
    sigs, replicas, _ = build_network(4)
    r0 = replicas[0]
    r0.start()
    caplog.clear()
    r0.handle(ResetHeight(height=100, signatories=tuple(sigs)))
    lines = _messages(caplog, "reset height", logging.INFO)
    assert len(lines) == 1
    assert "to_height=100" in lines[0]
    assert "from_height=" in lines[0]
    assert "rotating=True" in lines[0]


def test_equivocation_logged_as_warning_with_kind_and_sender(caplog):
    caplog.set_level(logging.INFO, logger="hyperdrive_tpu")
    sigs, replicas, _ = build_network(4)
    r0 = replicas[0]
    for r in replicas:
        r.start()
    caplog.clear()
    h, rnd = r0.current_height(), r0.proc.current_round
    # Two conflicting prevotes from one signatory at the same (h, r):
    # whichever vote that sender already holds, at least one conflicts.
    for tag in (b"fork-a", b"fork-b"):
        r0.handle(Prevote(
            height=h, round=rnd,
            value=hashlib.sha256(tag).digest(), sender=sigs[1],
        ))
    lines = _messages(caplog, "byzantine evidence", logging.WARNING)
    assert lines, "double prevote was not logged"
    assert "kind=double_prevote" in lines[0]
    assert f"sender={sigs[1].hex()[:16]}" in lines[0]


def test_quiet_logger_costs_nothing_at_default_level(caplog):
    # get_logger attaches only a NullHandler; at WARNING (the stdlib
    # default), the INFO commit lines are never rendered — kv() is
    # guarded by isEnabledFor at the call site.
    caplog.set_level(logging.WARNING, logger="hyperdrive_tpu")
    _, replicas, commits = build_network(4)
    for r in replicas:
        r.start()
    assert commits[0]
    assert _messages(caplog, "commit ", logging.INFO) == []
