"""BLS12-381 device path: fp381 field arithmetic and the G1 kernels
against the pure-Python host reference, plus the aggregate-signature
protocol layer.

The discipline mirrors tests/test_msm.py: every device result is pinned
to the serial host arithmetic (crypto/bls.py) on random inputs, with
the degenerate cases the complete RCB16 formulas must absorb branch-
free — P+P, P+(-P), P+O, O+O, identity rows, zero scalars, masked-out
lanes — exercised explicitly. The compressed generator doubles as a
conformance anchor: it must land on the standard ZCash-format encoding
of the BLS12-381 G1 generator, so the field, curve constants, and
compression agree with every other implementation of the curve
(PARITY.md "BLS aggregation").
"""

import random

import numpy as np
import pytest

from hyperdrive_tpu.crypto import bls
from hyperdrive_tpu.ops import fp381 as fp
from hyperdrive_tpu.ops import g1 as g1k

_N = 8


@pytest.fixture(scope="module")
def rng():
    return random.Random(0xB15)


@pytest.fixture(scope="module")
def points(rng):
    return [bls.g1_mul(bls.G1_GEN, rng.randrange(1, bls.R_ORDER))
            for _ in range(_N)]


def _host_masked_sum(points, mask):
    acc = None
    for p, m in zip(points, mask):
        if m and p is not None:
            acc = p if acc is None else bls.g1_add(acc, p)
    return acc


# --------------------------------------------------------------- field


def test_fp381_matches_python_ints(rng):
    xs = [rng.randrange(fp.P_INT) for _ in range(_N)]
    ys = [rng.randrange(fp.P_INT) for _ in range(_N)]
    a = np.stack([fp.to_mont(x) for x in xs])
    b = np.stack([fp.to_mont(y) for y in ys])
    assert fp.from_mont(fp.mul(a, b)) == [
        x * y % fp.P_INT for x, y in zip(xs, ys)
    ]
    assert fp.from_mont(fp.sqr(a)) == [x * x % fp.P_INT for x in xs]
    # canonical() leaves the Montgomery domain (x-bar/R), so unpack the
    # result with from_limbs, not from_mont.
    assert fp.from_limbs(fp.canonical(fp.add(a, b))) == [
        (x + y) % fp.P_INT for x, y in zip(xs, ys)
    ]
    assert fp.from_limbs(fp.canonical(fp.sub(a, b))) == [
        (x - y) % fp.P_INT for x, y in zip(xs, ys)
    ]
    assert fp.from_limbs(fp.canonical(fp.neg(a))) == [
        (-x) % fp.P_INT for x in xs
    ]
    assert fp.from_mont(fp.mul_small(a, 12)) == [
        12 * x % fp.P_INT for x in xs
    ]


def test_fp381_mont_roundtrip_edges():
    for v in (0, 1, 2, fp.P_INT - 1, fp.P_INT - 2, (fp.P_INT - 1) // 2):
        assert fp.from_mont(fp.to_mont(v)) == v
        assert fp.from_limbs(fp.to_limbs(v)) == v


def test_fp381_mul_chain_stays_in_invariant(rng):
    # The G1 formulas feed sums of up to 8 field elements back into mul
    # (pdbl's 8*Y^2 term); a chain of scaled adds between muls must not
    # overflow the signed-redundancy envelope.
    x = rng.randrange(fp.P_INT)
    a = fp.to_mont(x)
    acc = a
    for _ in range(3):  # 8x growth per round via three doublings
        acc = fp.add(acc, acc)
    assert fp.from_mont(fp.mul(acc, a)) == 8 * x * x % fp.P_INT


# --------------------------------------------------------------- curve


def test_generator_compresses_to_standard_encoding():
    # The ZCash-format compressed G1 generator — agreeing with this
    # 48-byte string means the field prime, curve constants, Montgomery
    # encode/decode, and compression all match the published curve.
    assert bls.g1_compress(bls.G1_GEN).hex() == (
        "97f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
        "6c55e83ff97a1aeffb3af00adb22c6bb"
    )
    assert bls.g1_compress(None)[0] == 0xC0  # infinity flag


def test_padd_matches_host_pairwise(points):
    import jax

    px = g1k.pack_points(points)
    qx = g1k.pack_points(points[1:] + points[:1])
    got = g1k.unpack_points(*jax.jit(g1k.padd)(px, qx))
    for i in range(_N):
        assert got[i] == bls.g1_add(points[i], points[(i + 1) % _N])


def test_padd_complete_formula_edges(points):
    import jax

    p = g1k.pack_points(points)
    neg = g1k.pack_points([bls.g1_neg(q) for q in points])
    ident = g1k.pack_points([None] * _N)
    padd = jax.jit(g1k.padd)
    # P + P must fall into the doubling case with the same instructions
    got = g1k.unpack_points(*padd(p, p))
    assert got == [bls.g1_double(q) for q in points]
    # P + (-P) = O
    assert all(q is None for q in g1k.unpack_points(*padd(p, neg)))
    # P + O = P, O + O = O
    assert g1k.unpack_points(*padd(p, ident)) == points
    assert all(q is None for q in g1k.unpack_points(*padd(ident, ident)))


def test_pdbl_matches_host(points):
    import jax

    pdbl = jax.jit(g1k.pdbl)
    got = g1k.unpack_points(*pdbl(g1k.pack_points(points)))
    assert got == [bls.g1_double(q) for q in points]
    ident = g1k.pack_points([None] * _N)
    assert all(q is None for q in g1k.unpack_points(*pdbl(ident)))


def test_recode_scalars_digits_reconstruct(rng):
    ks = [rng.randrange(bls.R_ORDER) for _ in range(4)] + [0, 1]
    digits = g1k.recode_scalars(ks)
    assert digits.shape == (g1k.G1_WINDOWS, len(ks))
    assert int(abs(digits).max()) <= 8
    for j, k in enumerate(ks):
        assert sum(
            int(digits[w, j]) << (4 * w) for w in range(g1k.G1_WINDOWS)
        ) == k


def test_recode_scalars_rejects_oversize():
    with pytest.raises(ValueError):
        g1k.recode_scalars([1 << 255])


@pytest.mark.slow  # the CI bls-parity smoke runs this exact differential
def test_g1_msm_matches_host(rng, points):
    import jax

    ks = [rng.randrange(bls.R_ORDER) for _ in range(_N)]
    ks[0] = 0
    px, py, pz = g1k.pack_points(points)
    kern = jax.jit(g1k.g1_msm_kernel)
    got = g1k.unpack_points(*kern(px, py, pz, g1k.recode_scalars(ks)))[0]
    acc = None
    for p, k in zip(points, ks):
        acc = bls.g1_add(acc, bls.g1_mul(p, k))
    assert got == acc
    # all-zero scalars -> identity
    zero = g1k.recode_scalars([0] * _N)
    assert g1k.unpack_points(*kern(px, py, pz, zero))[0] is None


@pytest.mark.parametrize("n", [1, 5, 8])
def test_aggregate_tree_matches_host_fold(rng, points, n):
    sub = points[:n]
    mask = [rng.randrange(2) for _ in range(n)]
    got = g1k.aggregate_points(
        [p if m else None for p, m in zip(sub, mask)]
    )
    assert got == _host_masked_sum(sub, mask)


def test_aggregate_tree_all_masked_out_is_identity(points):
    assert g1k.aggregate_points([None] * 5) is None


def test_aggregate_pads_to_fixed_width(points):
    # width > len(points): identity padding must not change the sum
    got = g1k.aggregate_points(points[:3], width=8)
    assert got == _host_masked_sum(points[:3], [1, 1, 1])


def test_g1sum_launcher_batches_one_launch(points):
    from hyperdrive_tpu.devsched.queue import DeviceWorkQueue

    queue = DeviceWorkQueue()
    launcher = g1k.G1SumLauncher(width=8)
    futs = [
        queue.submit(launcher, points[i : i + 4], generation=0,
                     rows=4)
        for i in range(0, _N, 4)
    ]
    queue.drain()
    got = [f.result() for f in futs]
    assert got == [
        _host_masked_sum(points[i : i + 4], [1] * 4)
        for i in range(0, _N, 4)
    ]
    assert launcher.launched == 1  # both payloads coalesced into one


# ------------------------------------------------------------ protocol


def test_sign_aggregate_verify_and_forgery(points):
    kps = [bls.bls_keypair_from_identity(b"bls-%d" % i) for i in range(3)]
    msg = b"hd-bls-commit"
    agg = bls.aggregate_signatures([kp.sign(msg) for kp in kps])
    pks = [kp.pk for kp in kps]
    assert bls.verify_aggregate_same_message(pks, msg, agg)
    assert not bls.verify_aggregate_same_message(pks, b"forged", agg)


def test_device_aggregate_equals_host_aggregate():
    kps = [bls.bls_keypair_from_identity(b"agg-%d" % i) for i in range(5)]
    sigs = [kp.sign(b"m") for kp in kps]
    host = bls.g1_compress(bls.aggregate_signatures(sigs))
    dev = bls.g1_compress(g1k.aggregate_points(sigs))
    assert host == dev


def test_pinned_self_generated_vectors():
    # Frozen outputs of this repo's own keygen/sign path: any change to
    # the HKDF keygen, hash-to-curve, or compression is a wire break
    # for every stored certificate and must show up here first.
    kp = bls.bls_keypair_from_identity(b"hd-bls-test-vector")
    assert kp.pk_bytes.hex() == (
        "b725489b6c05dfba5b0c10621913bb19637f12524da91b1a25f47af5beea8b8e"
        "7a8a15c47e88011a74b87475f0ff5a700355255a31f99eddd2b7fca74c490eaf"
        "eebde28317f903f45ddc8accca0d363a5cc6cc6dde41b1bcefabc48a55fa6f8d"
    )
    sig = kp.sign(b"hd-bls-test-message")
    assert bls.g1_compress(sig).hex() == (
        "931b8317b8c284f1450455c4d9ac1f173d09884622265fc89370510b22a8d5c9"
        "4210a8423d57d2465727a8d98c250a65"
    )


def test_compress_decompress_round_trip(points):
    for p in points + [None]:
        assert bls.g1_decompress(bls.g1_compress(p)) == p
