"""Codec: budget enforcement, roundtrips, fuzz-no-panic.

Mirrors the reference's serde test contract (process/*_test.go): random
byte blobs must error, never crash; undersized budgets must error;
marshal->unmarshal must be the identity.
"""

import random

import pytest

from hyperdrive_tpu.codec import MAX_BYTES, Reader, SerdeError, Writer


def test_scalar_roundtrip(rng):
    for _ in range(200):
        w = Writer()
        u8 = rng.randint(0, 255)
        u16 = rng.randint(0, 0xFFFF)
        u32 = rng.randint(0, 0xFFFFFFFF)
        u64 = rng.randint(0, (1 << 64) - 1)
        i8 = rng.randint(-128, 127)
        i64 = rng.randint(-(1 << 63), (1 << 63) - 1)
        b32 = rng.randbytes(32)
        raw = rng.randbytes(rng.randint(0, 64))
        flag = rng.random() < 0.5
        w.u8(u8); w.u16(u16); w.u32(u32); w.u64(u64)
        w.i8(i8); w.i64(i64); w.bytes32(b32); w.raw(raw); w.bool(flag)
        r = Reader(w.data())
        assert r.u8() == u8
        assert r.u16() == u16
        assert r.u32() == u32
        assert r.u64() == u64
        assert r.i8() == i8
        assert r.i64() == i64
        assert r.bytes32() == b32
        assert r.raw() == raw
        assert r.bool() is flag
        assert r.done()


def test_write_budget_enforced():
    w = Writer(rem=7)
    with pytest.raises(SerdeError):
        w.u64(1)
    w = Writer(rem=8)
    w.u64(1)  # exactly fits
    with pytest.raises(SerdeError):
        w.u8(1)


def test_read_budget_enforced():
    data = Writer()
    data.u64(42)
    r = Reader(data.data(), rem=7)
    with pytest.raises(SerdeError):
        r.u64()


def test_read_underflow_raises():
    r = Reader(b"\x01\x02")
    with pytest.raises(SerdeError):
        r.u32()


def test_bad_bool_rejected():
    r = Reader(b"\x02")
    with pytest.raises(SerdeError):
        r.bool()


def test_raw_length_is_budgeted():
    # A length prefix claiming 4GiB must die on the budget, not allocate.
    w = Writer()
    w.u32(0xFFFFFFFF)
    r = Reader(w.data(), rem=1024)
    with pytest.raises(SerdeError):
        r.raw()


def test_fuzz_never_crashes(rng):
    for _ in range(500):
        blob = rng.randbytes(rng.randint(0, 128))
        r = Reader(blob, rem=256)
        try:
            while True:
                op = rng.randint(0, 7)
                if op == 0:
                    r.u8()
                elif op == 1:
                    r.u64()
                elif op == 2:
                    r.i64()
                elif op == 3:
                    r.bytes32()
                elif op == 4:
                    r.raw()
                elif op == 5:
                    r.bool()
                elif op == 6:
                    r.u32()
                else:
                    r.u16()
        except SerdeError:
            pass  # errors are the contract; crashes are not


def test_bytes32_wrong_length():
    w = Writer()
    with pytest.raises(SerdeError):
        w.bytes32(b"\x00" * 31)


def test_default_budget_is_bounded():
    assert 0 < MAX_BYTES <= 64 * 1024 * 1024
